"""Benchmark: continuous-batching decode throughput on one chip.

Measures BASELINE.md config 2 (single-chip continuous batching) with a
Llama-3.2-1B-shaped model (random bf16 weights — the environment has no
network egress, so no checkpoints; throughput is weight-content-independent).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N/2000}
vs_baseline is against the north-star 2000 output tok/s/chip target
(BASELINE.json; the reference itself publishes no numbers — BASELINE.md).

Env knobs: BENCH_BATCH (64), BENCH_PROMPT (128), BENCH_NEW (128),
BENCH_BLOCK (64 burst / 16 when BENCH_RATE_RPS>0, decode steps per
device block), BENCH_PIPELINE (1,
blocks in flight), BENCH_PREFILL_BATCH (16, rows per batched prefill
program), BENCH_PREFILL_BUDGET (8192, prefill tokens per engine step),
BENCH_RATE_RPS (0; >0 switches to steady-state serving mode — requests
arrive at this rate and TTFT is measured from arrival, the number the
p50<200ms target is about), BENCH_IMPL (auto|pallas|xla decode attention),
BENCH_COMPARE (default 1 on hardware: measure BOTH attention impls,
report the better with both numbers in the line; 0 = single BENCH_IMPL
run), BENCH_FORCE_CPU=1 (tiny-model smoke mode), BENCH_CPU_FULL=1
(BASELINE.md config 1: the REAL BENCH_MODEL on the CPU backend, batch 1,
greedy single-request decode, f32 — the CPU-backend baseline config is
measurable with no TPU at all; defaults clamp to prompt 64 / 32 new
tokens so a 1-core run finishes in minutes),
BENCH_INIT_TIMEOUT_S (180).

Scale knobs (BASELINE.json's metric is tok/s/chip AT 8B — measure it):
BENCH_MODEL (any models/configs.py preset; default llama-3.2-1b),
BENCH_QUANT (none|int8|int4 — weight-only; int8 fits 8B on one v5e:
  BENCH_MODEL=llama-3-8b BENCH_QUANT=int8 BENCH_BATCH=32 python bench.py),
BENCH_HBM_GBPS (819, v5e HBM bandwidth for the roofline estimate printed
alongside every hardware run: roofline tok/s = batch * BW / weight
bytes — the weight-read bound a decode step cannot beat),
BENCH_SHARED_PREFIX (0; >0 = first K prompt tokens identical across
  requests, so later requests reuse the prefix pages — the TTFT delta vs
  0 measures the prefix cache, and records carry the allocator hit rate),
BENCH_DRAFT (none|same|self-int8|self-int4 — speculative decoding with a
  draft sharing the target's weights ("same": acceptance 1.0 ceiling) or a
  quantized copy of them ("self-int*": honest sub-1.0 acceptance from
  quantization disagreement, a real self-speculation config),
BENCH_GAMMA (4, draft tokens per speculation round),
BENCH_MEASURE_WARMUP=1 (measure cold first-request TTFT vs a warmed
engine's first request vs steady-state — quantifies engine.warmup()'s
compile amortization instead of asserting it).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time


@functools.lru_cache(maxsize=1)
def _git_rev() -> str:
    """Short commit id stamped into every record so a number can always
    be traced to the exact tree that produced it; empty when git is
    unavailable (the record must never fail over provenance). Cached —
    the rev cannot change within a run, and a wedged git must not stall
    every emission."""
    try:
        import subprocess

        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            text=True, stderr=subprocess.DEVNULL, timeout=5,
        ).strip()
    except Exception:
        return ""


def _emit(obj) -> None:
    rev = _git_rev()
    if rev:
        obj.setdefault("rev", rev)
    print(json.dumps(obj), flush=True)


_MODEL_SLUGS = {
    "llama-3.2-1b": "llama1b",
    "llama-3-8b": "llama8b",
    "llama-3-70b": "llama70b",
    "mistral-7b": "mistral7b",
    "qwen2-7b": "qwen7b",
    "gemma2-9b": "gemma9b",
    "mixtral-8x7b": "mixtral",
}


def bench_handoff() -> None:
    """KV-handoff microbench (BENCH_HANDOFF=1; ISSUE 4): sweep sequence
    length x channel x wire_quant x export mode on the tiny CPU fixture,
    emitting one JSON line per config with the STALL (decode pause the
    migrated sequence observes: switchover -> import seated) split from
    the END-TO-END handoff time (which the streamed export mostly
    overlaps with decoding), plus post-quantization bytes moved.

    Engine-level on purpose: two LLMEngine instances and the real
    channel/export/import code paths, no HTTP jitter — the serving-path
    rerun lives in `tools/disagg_smoke.py --bench`.

    Knobs: BENCH_HANDOFF_LENS ("128,400,1024" token sequence lengths),
    BENCH_HANDOFF_REPS (5)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.disagg import make_channel

    import jax.numpy as jnp

    lens = [int(x) for x in os.environ.get(
        "BENCH_HANDOFF_LENS", "128,400,1024").split(",") if x.strip()]
    reps = int(os.environ.get("BENCH_HANDOFF_REPS", "5"))
    ps = 8
    max_pages = -(-(max(lens) + 256) // ps)
    paged = PagedCacheConfig(num_pages=2 * max_pages + 64, page_size=ps,
                             max_pages_per_seq=max_pages)
    params = llama.init_params(jax.random.PRNGKey(0), TINY,
                               dtype=jnp.float32)

    def mk():
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(64, 256), paged=paged),
            dtype=jnp.float32,
        )

    rng = np.random.default_rng(0)

    def prefill(engine, rid, n, budget=512):
        ids = rng.integers(1, min(TINY.vocab_size, 250), size=n).tolist()
        engine.add_request(rid, ids, SamplingParams(
            max_tokens=budget, temperature=0.0), prefill_only=True)
        while not engine.handoff_ready_ids():
            engine.step()

    def one_monolithic(src, dst, chan, rid, n, wq):
        prefill(src, rid, n)
        t0 = time.monotonic()
        exp = src.export_handoff(rid, wire_quant=wq)
        # stall == e2e for the stop-the-world export
        wired = chan.transfer(exp)
        dst.import_sequence(wired)
        t1 = time.monotonic()
        dst.abort(rid)
        return {"stall_s": t1 - t0, "e2e_s": t1 - t0,
                "bytes": exp.kv_bytes(), "chunks": 0}

    def one_streamed(src, dst, chan, rid, n, wq):
        # the serving pipeline's two-phase flow, inline: prefix
        # serializes AND imports on the target during the overlap
        # window; the stall is only the switchover delta
        prefill(src, rid, n)
        t_begin = time.monotonic()
        session = src.export_handoff_begin(rid, chunk_pages=8, wire_quant=wq)
        assert session is not None, "streamed export refused"
        src.step()  # the overlap window: the sequence decodes a block
        src.export_handoff_pump(session)
        wired_prefix = chan.transfer_chunks(rid, wq, session.chunks)
        isess = dst.import_stream_open(rid, len(session.prefix_pages))
        dst.import_stream_add(isess, wired_prefix)
        src.step()  # more overlap while the target absorbs the prefix
        exp, _outputs = src.export_handoff_finish(session)
        assert exp is not None, "sequence resolved in place mid-bench"
        tail = exp.kv_chunks[len(session.chunks):]
        wired = chan.transfer_commit(exp, tail)
        dst.import_stream_commit(isess, wired)
        t1 = time.monotonic()
        dst.abort(rid)
        return {"stall_s": t1 - exp.stalled_at, "e2e_s": t1 - t_begin,
                "bytes": exp.kv_bytes(), "chunks": len(exp.kv_chunks or [])}

    for n in lens:
        src, dst = mk(), mk()
        seq = 0
        for chan_name in ("inproc", "protowire"):
            chan = make_channel(chan_name)
            for wq in ("none", "int8"):
                for mode, fn in (("monolithic", one_monolithic),
                                 ("streamed", one_streamed)):
                    stalls, e2es, rec = [], [], None
                    for r in range(reps + 1):
                        seq += 1
                        rec = fn(src, dst, chan, f"h{seq}", n, wq)
                        if r:  # rep 0 warms compile caches
                            stalls.append(rec["stall_s"])
                            e2es.append(rec["e2e_s"])
                    _emit({
                        "metric": "kv_handoff_stall_ms_tiny_cpu",
                        "value": round(float(np.median(stalls)) * 1e3, 3),
                        "unit": "ms",
                        "vs_baseline": 0.0,
                        "seq_len": n,
                        "channel": chan_name,
                        "wire_quant": wq,
                        "mode": mode,
                        "e2e_ms": round(float(np.median(e2es)) * 1e3, 3),
                        "bytes": rec["bytes"],
                        "chunks": rec["chunks"],
                        "reps": reps,
                    })


def bench_prefix() -> None:
    """Tiered prefix-cache microbench (BENCH_PREFIX=1; ISSUE 5): a
    repeated-prefix workload (one long shared system prefix + unique
    tails, interleaved with short unique "churn" traffic that cycles the
    HBM page pool) measured AFTER an eviction cycle — the regime where
    the HBM-only prefix cache is worthless because the pool already
    recycled the shared pages.

    Per swept config it emits one JSON line with the probe request's
    median TTFT and prefill-tokens-recomputed (prompt length minus the
    pages matched in either tier):

    - mode "cold": never-seen prefix (full prefill — the floor);
    - mode "hbm_only": host_tier_bytes=0 — after churn the prefix pages
      are gone, so this re-pays ~full prefill;
    - mode "tiered": host tier on, swept over budget (generous: holds
      the whole working set / tight: forces front-biased partial
      retention) x storage quant (none | int8).

    Engine-level on purpose (two tiers + the real match/reload path, no
    HTTP jitter). Knobs: BENCH_PREFIX_REPS (5), BENCH_PREFIX_PAGES (24
    shared-prefix pages), BENCH_PREFIX_CHURN (10 unique churn prompts
    per rep)."""
    import gc

    # single-threaded XLA CPU: the thread pool's scheduling jitter on a
    # small host is ±2x PER REP on identical work, drowning the
    # tiered-vs-HBM-only TTFT deltas; one thread is slower but tight
    # (must be set before jax initializes)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    reps = int(os.environ.get("BENCH_PREFIX_REPS", "5"))
    prefix_pages = int(os.environ.get("BENCH_PREFIX_PAGES", "24"))
    churn_n = int(os.environ.get("BENCH_PREFIX_CHURN", "10"))
    # ~8x TINY's prefill compute (4x layers, 2x width) with only 4x the
    # KV bytes: on TINY itself dispatch noise is the same order as the
    # whole prefill, so the recompute savings the tier buys would drown
    # in jitter; at this scale compute dominates and TTFT separates
    # cleanly while the bench stays CI-runnable on CPU
    mcfg = TINY.with_overrides(
        name="tiny-4l", hidden_size=128, intermediate_size=512,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
    )
    ps = 8
    churn_pages = 4
    tail = ps  # unique tail tokens after the shared prefix
    prompt_len = prefix_pages * ps + tail
    # pool sized so one churn phase cycles it past the shared prefix:
    # barely larger than the longest prompt, as a loaded server runs
    paged = PagedCacheConfig(
        num_pages=prefix_pages + 8,
        page_size=ps,
        max_pages_per_seq=prefix_pages + 4,
    )
    params = llama.init_params(jax.random.PRNGKey(0), mcfg,
                               dtype=jnp.float32)
    # page bytes in the f32 pool (k+v), for budget sweeps in page units
    page_bytes = (mcfg.num_layers * ps * mcfg.num_kv_heads * mcfg.head_dim
                  * 4 * 2)
    budgets = {
        # holds the shared prefix AND the churn heads comfortably
        "generous": (prefix_pages + churn_pages * churn_n + 8) * page_bytes,
        # smaller than the shared prefix itself: front-biased retention
        # keeps the chain HEAD, so the probe still skips half the prefill
        "tight": (prefix_pages // 2) * page_bytes,
    }
    rng = np.random.default_rng(7)
    hi = min(mcfg.vocab_size, 250)

    def mk(host_bytes=0, quant="none"):
        return LLMEngine(
            params, mcfg, ByteTokenizer(),
            EngineConfig(max_batch=2, prefill_buckets=(64, 128, 256),
                         paged=paged, host_tier_bytes=host_bytes,
                         host_tier_quant=quant),
            dtype=jnp.float32,
        )

    seq = [0]

    def run(engine, ids, max_tokens=2):
        """Submit one request, drain it, return TTFT seconds."""
        seq[0] += 1
        rid = f"p{seq[0]}"
        t0 = time.perf_counter()
        engine.add_request(rid, ids, SamplingParams(
            max_tokens=max_tokens, temperature=0.0))
        ttft = None
        while engine.has_work():
            for out in engine.step():
                if ttft is None and out.token_id is not None:
                    ttft = time.perf_counter() - t0
        assert ttft is not None
        return ttft

    def compile_warm(engine):
        """Walk every prefill bucket + decode so no measured rep pays
        XLA compile, then drop every cache the warmers left behind."""
        run(engine, rng.integers(1, hi, size=prompt_len).tolist())
        run(engine, rng.integers(1, hi, size=prompt_len // 2).tolist())
        run(engine, rng.integers(1, hi, size=churn_pages * ps).tolist())
        engine.evict_cache(0.0, drop_host_tier=True)

    def probe(engine, prefix_ids):
        """One measured repeated-prefix request after a churn cycle (GC
        held off so a collection pause cannot land inside the TTFT)."""
        s0 = engine.cache_stats()
        host0 = engine.host_tier_stats() or {"hit_pages": 0}
        ids = prefix_ids + rng.integers(1, hi, size=tail).tolist()
        gc.collect()
        gc.disable()
        try:
            ttft = run(engine, ids)
        finally:
            gc.enable()
        s1 = engine.cache_stats()
        host1 = engine.host_tier_stats() or {"hit_pages": 0}
        hbm_pages = s1.hits - s0.hits
        host_pages = host1["hit_pages"] - host0["hit_pages"]
        reloads = engine.drain_reload_durations()
        return {
            "ttft_s": ttft,
            "recompute_tokens": len(ids) - (hbm_pages + host_pages) * ps,
            "hbm_pages": hbm_pages,
            "host_pages": host_pages,
            "reload_ms": round(sum(reloads) * 1e3, 3),
        }

    def churn(engine):
        for _ in range(churn_n):
            run(engine, rng.integers(
                1, hi, size=churn_pages * ps - 2).tolist())

    def measure(mode, host_bytes=0, quant="none", budget_name=None):
        engine = mk(host_bytes=host_bytes, quant=quant)
        compile_warm(engine)
        prefix_ids = rng.integers(1, hi, size=prefix_pages * ps).tolist()
        recs = []
        if mode == "cold":
            for _ in range(reps):
                # never-repeated prefix: every probe is a full prefill
                fresh = rng.integers(1, hi, size=prefix_pages * ps).tolist()
                churn(engine)
                recs.append(probe(engine, fresh))
        else:
            run(engine, prefix_ids
                + rng.integers(1, hi, size=tail).tolist())  # warm
            # one unmeasured cycle: the tier's chain protection needs a
            # first match to mark the prefix chain as re-used traffic
            # (steady state is what repeated-prefix serving runs in)
            churn(engine)
            probe(engine, prefix_ids)
            for _ in range(reps):
                churn(engine)  # cycle the pool: HBM prefix evicted
                recs.append(probe(engine, prefix_ids))
        s = engine.cache_stats()
        host = engine.host_tier_stats()
        _emit({
            "metric": "prefix_probe_ttft_ms_cpu",
            "value": round(
                float(np.median([r["ttft_s"] for r in recs])) * 1e3, 3),
            "unit": "ms",
            "vs_baseline": 0.0,
            "mode": mode,
            **({"host_budget": budget_name,
                "host_budget_bytes": host_bytes,
                "host_quant": quant} if host_bytes else {}),
            "prompt_len": prompt_len,
            "recompute_tokens": int(np.median(
                [r["recompute_tokens"] for r in recs])),
            "matched_hbm_pages": int(np.median(
                [r["hbm_pages"] for r in recs])),
            "matched_host_pages": int(np.median(
                [r["host_pages"] for r in recs])),
            "reload_ms": float(np.median(
                [r["reload_ms"] for r in recs])),
            "evictions": s.evictions,
            **({"host_tier_pages": host["pages"],
                "host_tier_bytes": host["bytes"],
                "host_offloads": host["offloads"],
                "host_evictions": host["evictions"]}
               if host is not None else {}),
            "reps": reps,
        })

    measure("cold")
    measure("hbm_only")
    for budget_name, budget in budgets.items():
        for quant in ("none", "int8"):
            measure("tiered", host_bytes=budget, quant=quant,
                    budget_name=budget_name)


def bench_peerfetch() -> None:
    """Fleet peer-fetch microbench (BENCH_PEERFETCH=1; ISSUE 8): a
    repeated-prefix request lands on a COLD replica while a warm peer
    holds the matched chain. Per swept config — prefix depth (pages) x
    wire quant — the probe's TTFT is measured under each of the cost
    model's three options (docs/CACHING.md "Fleet-wide prefix
    sharing"):

    - mode "recompute": the cold replica prefills the whole prompt (the
      floor the fetch must beat);
    - mode "fetch": the cold replica peer-fetches the chain from the
      warm peer (export -> protowire channel -> import_prefix) and
      prefills only the tail; TTFT INCLUDES the whole fetch;
    - mode "route_warm": the warm replica serves it in place (HBM
      prefix hit — the ceiling fetch cannot beat).

    Engine-level on purpose (the real export/channel/import code paths,
    no HTTP jitter), single-threaded XLA + GC held off and the tiny-4l
    model, exactly like BENCH_PREFIX — at TINY scale dispatch noise
    drowns the prefill-recompute savings being measured. Knobs:
    BENCH_PEERFETCH_REPS (5), BENCH_PEERFETCH_DEPTHS ("8,16,24")."""
    import gc

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
        chain_hashes,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.disagg import make_channel

    reps = int(os.environ.get("BENCH_PEERFETCH_REPS", "5"))
    depths = [int(x) for x in os.environ.get(
        "BENCH_PEERFETCH_DEPTHS", "8,16,24").split(",") if x.strip()]
    mcfg = TINY.with_overrides(
        name="tiny-4l", hidden_size=128, intermediate_size=512,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
    )
    ps = 8
    tail = ps
    max_depth = max(depths)
    paged = PagedCacheConfig(
        num_pages=2 * max_depth + 16,
        page_size=ps,
        max_pages_per_seq=max_depth + 4,
    )
    params = llama.init_params(jax.random.PRNGKey(0), mcfg,
                               dtype=jnp.float32)
    rng = np.random.default_rng(11)
    hi = min(mcfg.vocab_size, 250)
    chan = make_channel("protowire")

    def mk():
        return LLMEngine(
            params, mcfg, ByteTokenizer(),
            EngineConfig(
                max_batch=2,
                prefill_buckets=(16, 64, 128, 256),
                paged=paged, native_allocator=False,
                # the whole swept chain must be visible to the fetch
                digest_depth=max_depth,
            ),
            dtype=jnp.float32,
        )

    seq = [0]

    def run(engine, ids, max_tokens=2):
        seq[0] += 1
        rid = f"pf{seq[0]}"
        t0 = time.perf_counter()
        engine.add_request(rid, ids, SamplingParams(
            max_tokens=max_tokens, temperature=0.0))
        ttft = None
        while engine.has_work():
            for out in engine.step():
                if ttft is None and out.token_id is not None:
                    ttft = time.perf_counter() - t0
        assert ttft is not None
        return ttft

    def compile_warm(engine):
        for n in (max_depth * ps + tail, 2 * ps, tail + ps):
            run(engine, rng.integers(1, hi, size=n).tolist())
        engine.evict_cache(0.0)

    for depth in depths:
        prefix_ids = rng.integers(1, hi, size=depth * ps).tolist()
        warm, cold = mk(), mk()
        compile_warm(warm)
        compile_warm(cold)
        run(warm, prefix_ids + rng.integers(1, hi, size=tail).tolist())
        for wq in ("none", "int8"):
            recs = {"recompute": [], "fetch": [], "route_warm": []}
            fetch_ms, fetch_bytes = [], 0
            for r in range(reps + 1):
                probe = prefix_ids + rng.integers(1, hi,
                                                  size=tail).tolist()
                hashes = chain_hashes(probe, ps,
                                      max_pages=(len(probe) - 1) // ps)
                gc.collect()
                gc.disable()
                try:
                    # recompute floor: the cold replica starts empty
                    cold.evict_cache(0.0)
                    t_rec = run(cold, probe)
                    # fetch: export -> wire -> import -> prefill tail;
                    # TTFT includes the whole fetch
                    cold.evict_cache(0.0)
                    t0 = time.perf_counter()
                    served, chunks = warm.export_prefix_chunks(
                        hashes, chunk_pages=8, wire_quant=wq)
                    wired = chan.transfer_chunks(f"b{seq[0]}", wq, chunks)
                    cold.import_prefix(probe[: served * ps], wired)
                    t_fetch_done = time.perf_counter() - t0
                    t_fet = t_fetch_done + run(cold, probe)
                    # warm ceiling: the peer serves it in place
                    t_warm = run(warm, probe)
                finally:
                    gc.enable()
                if r:  # rep 0 warms compile caches
                    recs["recompute"].append(t_rec)
                    recs["fetch"].append(t_fet)
                    recs["route_warm"].append(t_warm)
                    fetch_ms.append(t_fetch_done * 1e3)
                    fetch_bytes = sum(len(c.payload) for c in wired)
                assert served == (len(probe) - 1) // ps, served
            for mode in ("recompute", "fetch", "route_warm"):
                _emit({
                    "metric": "peerfetch_ttft_ms_cpu",
                    "value": round(
                        float(np.median(recs[mode])) * 1e3, 3),
                    "unit": "ms",
                    "vs_baseline": 0.0,
                    "mode": mode,
                    "prefix_pages": depth,
                    "prompt_len": depth * ps + tail,
                    "wire_quant": wq,
                    **({"fetch_ms": round(float(np.median(fetch_ms)), 3),
                        "fetch_bytes": fetch_bytes}
                       if mode == "fetch" else {}),
                    "reps": reps,
                })


def bench_mixed() -> None:
    """Ragged mixed-batch step microbench (BENCH_MIXED=1; ISSUE 12): a
    mixed long-prompt/chat workload on ONE unified engine — chat rows
    decode continuously while a burst of long prompts arrives — measured
    under the MIXED step (engine.mixed_step_tokens > 0: one ragged
    dispatch per iteration serving decode rows + prefill chunks) vs the
    QUANTUM-INTERLEAVE baseline it replaces (prefill quanta dispatched
    between decode blocks, stalling every in-flight decode for their
    duration).

    Per swept config it emits one JSON line per mode with the chat rows'
    TBT max/p99 observed DURING the prompt burst (the number the mixed
    step exists to flatten), overall tokens/s at the fixed geometry, and
    ``tokens_identical`` — whether the two modes emitted bit-identical
    token streams (greedy workload; the acceptance criterion).

    Engine-level on purpose (no HTTP jitter), single-threaded XLA + the
    tiny-4l model exactly like BENCH_PREFIX — at TINY scale dispatch
    noise drowns the stall being measured. Knobs: BENCH_MIXED_REPS (3),
    BENCH_MIXED_PROMPTS ("64,128" burst prompt lengths),
    BENCH_MIXED_TOKENS (24, the packed width)."""
    import gc

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    reps = int(os.environ.get("BENCH_MIXED_REPS", "3"))
    prompt_lens = [int(x) for x in os.environ.get(
        "BENCH_MIXED_PROMPTS", "128,256").split(",") if x.strip()]
    mixed_tokens = int(os.environ.get("BENCH_MIXED_TOKENS", "24"))
    n_burst = int(os.environ.get("BENCH_MIXED_BURST", "4"))
    mcfg = TINY.with_overrides(
        name="tiny-4l", hidden_size=128, intermediate_size=512,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
    )
    ps = 8
    n_chat = 3
    chat_len, chat_tokens = ps, 64
    max_pages = -(-(max(prompt_lens) + 64) // ps)
    paged = PagedCacheConfig(
        num_pages=(n_chat + n_burst + 2) * max_pages, page_size=ps,
        max_pages_per_seq=max_pages,
    )
    params = llama.init_params(jax.random.PRNGKey(0), mcfg,
                               dtype=jnp.float32)
    rng = np.random.default_rng(23)
    hi = min(mcfg.vocab_size, 250)

    def mk(mixed: bool):
        return LLMEngine(
            params, mcfg, ByteTokenizer(),
            EngineConfig(
                max_batch=n_chat + n_burst,
                prefill_buckets=(32, 64, 128, 256),
                paged=paged, decode_block_size=4, pipeline_depth=1,
                mixed_step_tokens=mixed_tokens if mixed else 0,
            ),
            dtype=jnp.float32,
        )

    def run_once(engine, chats, prompts):
        """Seat the chat rows, fire the prompt burst, record every chat
        token's wall-clock instant until the burst's prompts finish and
        the chats hit their budget. Returns (events, toks, elapsed)."""
        toks = {}
        times = {f"c{i}": [] for i in range(n_chat)}
        for i, ids in enumerate(chats):
            engine.add_request(f"c{i}", ids, SamplingParams(
                max_tokens=chat_tokens, temperature=0.0))
        # chats seated and decoding before the burst lands
        while not all(times[r] for r in times):
            for out in engine.step():
                if out.token_id is not None:
                    toks.setdefault(out.request_id, []).append(out.token_id)
                    if out.request_id in times:
                        times[out.request_id].append(time.perf_counter())
        t0 = time.perf_counter()
        for i, ids in enumerate(prompts):
            engine.add_request(f"p{i}", ids, SamplingParams(
                max_tokens=4, temperature=0.0))
        produced = 0
        while engine.has_work():
            for out in engine.step():
                if out.token_id is not None:
                    produced += 1
                    toks.setdefault(out.request_id, []).append(out.token_id)
                    if out.request_id in times:
                        times[out.request_id].append(time.perf_counter())
        elapsed = time.perf_counter() - t0
        # TBT of the in-flight chats across the burst window: gaps
        # between consecutive observed tokens from the burst's landing
        # on — anchored at each chat's LAST pre-burst token, so the gap
        # that spans the prompt admission (the stall the mixed step
        # exists to flatten) is measured, not dropped
        tbts = []
        for r, ts in times.items():
            before = [t for t in ts if t < t0]
            after = [t for t in ts if t >= t0]
            anchored = before[-1:] + after
            tbts.extend(np.diff(anchored).tolist())
        return tbts, toks, produced / elapsed

    for n in prompt_lens:
        chats = [rng.integers(1, hi, size=chat_len).tolist()
                 for _ in range(n_chat)]
        prompts = [rng.integers(1, hi, size=n).tolist()
                   for _ in range(n_burst)]
        results = {}
        for mode, mixed in (("quantum", False), ("mixed", True)):
            engine = mk(mixed)
            all_tbts, toks, tput = [], None, []
            for r in range(reps + 1):
                gc.collect()
                gc.disable()
                try:
                    tbts, toks, tp = run_once(engine, chats, prompts)
                finally:
                    gc.enable()
                for rid in list(toks):
                    engine.abort(rid)
                # drop the prefix cache: a warm repeat would skip the
                # very prefill whose stall is being measured
                engine.evict_cache(0.0, drop_host_tier=True)
                if r:  # rep 0 warms compile caches
                    all_tbts.extend(tbts)
                    tput.append(tp)
            results[mode] = {
                "tbt_max_ms": float(np.max(all_tbts)) * 1e3,
                "tbt_p99_ms": float(np.percentile(all_tbts, 99)) * 1e3,
                "tokens_per_sec": float(np.median(tput)),
                "toks": toks,
            }
        identical = results["mixed"]["toks"] == results["quantum"]["toks"]
        for mode in ("quantum", "mixed"):
            r = results[mode]
            _emit({
                "metric": "mixed_step_tbt_p99_ms_cpu",
                "value": round(r["tbt_p99_ms"], 3),
                "unit": "ms",
                "vs_baseline": 0.0,
                "mode": mode,
                "prompt_len": n,
                "burst_prompts": n_burst,
                "chat_rows": n_chat,
                "mixed_step_tokens": mixed_tokens if mode == "mixed" else 0,
                "tbt_max_ms": round(r["tbt_max_ms"], 3),
                "tokens_per_sec": round(r["tokens_per_sec"], 2),
                "tokens_identical": identical,
                "reps": reps,
            })
        if not identical:
            print("BENCH_MIXED: token streams DIVERGED between modes",
                  file=sys.stderr)
            sys.exit(3)


def bench_loop() -> None:
    """Run-to-completion looped decode microbench (BENCH_LOOP=1; ISSUE
    19): a mixed long-prompt/chat workload on ONE unified engine, swept
    over {fixed-K, loop_to_completion} x {plain decode, mixed step at
    K-block fusion}. Per config it emits one JSON line per mode with

    - ``dispatches_per_decode_token`` on the mode's decode-serving path
      (the acceptance number: at K=8 the fused looped mixed step must
      spend >= 4x fewer mixed dispatches per decode token than the
      per-token fixed mixed step),
    - overall tokens/s at the fixed geometry, and
    - ``tokens_identical`` — greedy streams bit-identical to the
      fixed-path baseline of the same workload.

    Engine-level on purpose (no HTTP jitter), single-threaded XLA + the
    tiny-4l model exactly like BENCH_MIXED — at TINY scale a dispatch
    boundary costs more than the flops it frames, which is precisely the
    host-sync overhead kernel looping removes. Knobs: BENCH_LOOP_REPS
    (3), BENCH_LOOP_K (8, decode_block_size = the fusion width),
    BENCH_LOOP_PROMPTS ("128" burst prompt lengths),
    BENCH_LOOP_TOKENS (24, the packed mixed width)."""
    import gc

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    reps = int(os.environ.get("BENCH_LOOP_REPS", "3"))
    k_block = int(os.environ.get("BENCH_LOOP_K", "8"))
    prompt_lens = [int(x) for x in os.environ.get(
        "BENCH_LOOP_PROMPTS", "128").split(",") if x.strip()]
    mixed_tokens = int(os.environ.get("BENCH_LOOP_TOKENS", "24"))
    n_burst = 4
    mcfg = TINY.with_overrides(
        name="tiny-4l", hidden_size=128, intermediate_size=512,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
    )
    ps = 8
    n_chat = 3
    # the chat budget must OUTLIVE the prompt-loading window in the
    # fused mode (K decode tokens per dispatch): a chat that runs dry
    # mid-burst leaves later mixed dispatches with no decode rows,
    # muddying the per-path dispatch ratio being measured; the prompt
    # rows themselves stop after 4 tokens so the mixed window stays
    # dominated by the long-lived chats
    chat_len, chat_tokens = ps, 256
    max_pages = -(-(max(prompt_lens) + chat_tokens + 8) // ps)
    paged = PagedCacheConfig(
        num_pages=(n_chat + n_burst + 2) * max_pages, page_size=ps,
        max_pages_per_seq=max_pages,
    )
    params = llama.init_params(jax.random.PRNGKey(0), mcfg,
                               dtype=jnp.float32)
    rng = np.random.default_rng(23)
    hi = min(mcfg.vocab_size, 250)

    def mk(loop: bool, mixed: bool):
        return LLMEngine(
            params, mcfg, ByteTokenizer(),
            EngineConfig(
                max_batch=n_chat + n_burst,
                prefill_buckets=(32, 64, 128, 256),
                paged=paged, decode_block_size=k_block, pipeline_depth=1,
                mixed_step_tokens=mixed_tokens if mixed else 0,
                loop_to_completion=loop, loop_max_steps=256,
            ),
            dtype=jnp.float32,
        )

    def run_once(engine, chats, prompts):
        """Seat the chats, fire the prompt burst, drain. Returns
        (toks, decode_tokens/s, decode-path dispatches, decode tokens)
        for the burst window on."""
        sc0 = engine.step_clock_stats()["kinds"]
        d0 = {k: v["dispatches"] for k, v in sc0.items()}
        toks = {}
        n_req = len(chats) + len(prompts)
        for i, ids in enumerate(chats):
            engine.add_request(f"c{i}", ids, SamplingParams(
                max_tokens=chat_tokens, temperature=0.0))
        for i, ids in enumerate(prompts):
            engine.add_request(f"p{i}", ids, SamplingParams(
                max_tokens=4, temperature=0.0))
        t0 = time.perf_counter()
        produced = 0
        while engine.has_work():
            for out in engine.step():
                if out.token_id is not None:
                    produced += 1
                    toks.setdefault(out.request_id, []).append(out.token_id)
        elapsed = time.perf_counter() - t0
        sc = engine.step_clock_stats()["kinds"]
        # dispatches on the decode-serving path: every launch that
        # advanced decode rows (prefill-only launches excluded)
        decode_kinds = ("decode_block", "mixed", "loop")
        disp = sum(sc[k]["dispatches"] - d0.get(k, 0)
                   for k in decode_kinds if k in sc)
        decode_toks = produced - n_req  # prefill samples each first token
        ms = engine.mixed_stats()
        return toks, produced / elapsed, disp, decode_toks, ms

    for n in prompt_lens:
        chats = [rng.integers(1, hi, size=chat_len).tolist()
                 for _ in range(n_chat)]
        prompts = [rng.integers(1, hi, size=n).tolist()
                   for _ in range(n_burst)]
        results = {}
        modes = (
            ("fixed", False, False),
            ("loop", True, False),
            ("fixed+mixed", False, True),
            ("loop+mixed", True, True),
        )
        for mode, loop, mixed in modes:
            engine = mk(loop, mixed)
            tput, last = [], None
            for r in range(reps + 1):
                gc.collect()
                gc.disable()
                try:
                    last = run_once(engine, chats, prompts)
                finally:
                    gc.enable()
                toks, tp, disp, decode_toks, ms = last
                for rid in list(toks):
                    engine.abort(rid)
                engine.evict_cache(0.0, drop_host_tier=True)
                if r:  # rep 0 warms compile caches
                    tput.append(tp)
            toks, _, disp, decode_toks, ms = last
            results[mode] = {
                "toks": toks,
                "tokens_per_sec": float(np.median(tput)),
                "dispatches_per_decode_token": disp / max(1, decode_toks),
                "decode_tokens": decode_toks,
                # the acceptance ratio: mixed dispatches per decode
                # token ADVANCED BY THE MIXED PATH (cumulative over the
                # reps — every rep runs the identical workload)
                "mixed_dispatches_per_decode_token": (
                    ms["steps"] / max(1, ms["decode_tokens"])
                    if ms else None),
            }
        ok = True
        for mode in ("loop", "fixed+mixed", "loop+mixed"):
            if results[mode]["toks"] != results["fixed"]["toks"]:
                ok = False
        for mode, loop, mixed in modes:
            r = results[mode]
            _emit({
                "metric": "loop_dispatches_per_decode_token_cpu",
                "value": round(r["dispatches_per_decode_token"], 4),
                "unit": "dispatches/token",
                "vs_baseline": 0.0,
                "mode": mode,
                "k_block": k_block,
                "prompt_len": n,
                "burst_prompts": n_burst,
                "chat_rows": n_chat,
                "mixed_step_tokens": mixed_tokens if mixed else 0,
                "decode_tokens": r["decode_tokens"],
                "tokens_per_sec": round(r["tokens_per_sec"], 2),
                "mixed_dispatches_per_decode_token": (
                    round(r["mixed_dispatches_per_decode_token"], 4)
                    if r["mixed_dispatches_per_decode_token"] is not None
                    else None),
                "tokens_identical": ok,
                "reps": reps,
            })
        if not ok:
            print("BENCH_LOOP: token streams DIVERGED between modes",
                  file=sys.stderr)
            sys.exit(3)
        fused = results["loop+mixed"]["mixed_dispatches_per_decode_token"]
        base = results["fixed+mixed"]["mixed_dispatches_per_decode_token"]
        if fused > base / 4.0:
            print(
                "BENCH_LOOP: mixed-path dispatch collapse below 4x "
                f"({base:.3f} -> {fused:.3f} per decode token)",
                file=sys.stderr)
            sys.exit(4)


def bench_telem() -> None:
    """Telemetry-overhead microbench (BENCH_TELEM=1; ISSUE 14): decode
    tokens/s through a REAL EngineRunner with the performance-telemetry
    plane ON — MetricsCollector (step-clock delta reports + windowed
    digests) plus FlightRecorder with an armed SLO — vs OFF (metrics
    and recorder both None, the identity-check fast path). CPU anchor
    like the other microbenches (single-threaded XLA, tiny-4l, greedy);
    at TINY scale the host-side per-step cost is a LARGER share of the
    step than on real silicon, so the measured overhead upper-bounds
    production. Acceptance: <= 2% decode tokens/s cost.

    Knobs: BENCH_TELEM_REPS (5), BENCH_TELEM_ROWS (4 concurrent
    requests), BENCH_TELEM_TOKENS (192 decode tokens per request)."""
    import gc
    import threading

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_multi_thread_eigen=false"
        + " intra_op_parallelism_threads=1"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.flightrec import (
        FlightRecorder,
    )
    from distributed_inference_server_tpu.serving.metrics import (
        MetricsCollector,
    )
    from distributed_inference_server_tpu.serving.runner import (
        EngineRunner,
        ServerRequest,
    )
    from distributed_inference_server_tpu.serving.teledigest import (
        SloSettings,
    )

    reps = int(os.environ.get("BENCH_TELEM_REPS", "5"))
    rows = int(os.environ.get("BENCH_TELEM_ROWS", "4"))
    tokens = int(os.environ.get("BENCH_TELEM_TOKENS", "192"))
    mcfg = TINY.with_overrides(
        name="tiny-4l", hidden_size=128, intermediate_size=512,
        num_layers=4, num_heads=8, num_kv_heads=4, head_dim=16,
    )
    ps = 8
    max_pages = -(-(16 + tokens + ps) // ps)
    paged = PagedCacheConfig(num_pages=(rows + 2) * max_pages,
                             page_size=ps, max_pages_per_seq=max_pages)
    params = llama.init_params(jax.random.PRNGKey(0), mcfg,
                               dtype=jnp.float32)
    rng = np.random.default_rng(14)
    hi = min(mcfg.vocab_size, 250)
    prompts = [[int(t) for t in rng.integers(1, hi, size=16)]
               for _ in range(rows)]

    def factory():
        return LLMEngine(
            params, mcfg, ByteTokenizer(),
            EngineConfig(max_batch=rows, prefill_buckets=(16, 32),
                         paged=paged, decode_block_size=8,
                         warmup_compile=False),
            dtype=jnp.float32,
        )

    class _Sink:
        def __init__(self):
            self.tokens = 0
            self.ev = threading.Event()

        def on_token(self, token_id, text, token_index, logprob=None):
            if token_id is not None:
                self.tokens += 1

        def on_done(self, finish_reason, usage):
            self.ev.set()

        def on_error(self, message, code):
            self.ev.set()

    def run_batch(runner, tag: str) -> float:
        sinks = []
        reqs = []
        for i, prompt in enumerate(prompts):
            sink = _Sink()
            sinks.append(sink)
            reqs.append(ServerRequest(
                f"{tag}-{i}", list(prompt),
                SamplingParams(max_tokens=tokens, temperature=0.0),
                sink))
        t0 = time.perf_counter()
        runner.submit(reqs)
        for sink in sinks:
            assert sink.ev.wait(300.0), "bench request wedged"
        wall = time.perf_counter() - t0
        emitted = sum(s.tokens for s in sinks)
        assert emitted >= rows * (tokens - 1), emitted
        return emitted / wall

    results = {"off": [], "on": []}
    runners = {}
    metrics_on = MetricsCollector()
    recorder_on = FlightRecorder(
        metrics=metrics_on,
        slo=SloSettings(ttft_ms=60_000.0, tbt_p99_ms=60_000.0))
    runners["off"] = EngineRunner("bench-off", factory, None)
    runners["on"] = EngineRunner("bench-on", factory, metrics_on,
                                 recorder=recorder_on)
    try:
        for mode, runner in runners.items():
            runner.start(wait_ready=True)
            run_batch(runner, f"warm-{mode}")  # compile + warm path
        gc.disable()
        try:
            for rep in range(reps):
                # alternate order so drift penalizes neither mode
                order = (["off", "on"] if rep % 2 == 0
                         else ["on", "off"])
                for mode in order:
                    results[mode].append(
                        run_batch(runners[mode], f"r{rep}-{mode}"))
        finally:
            gc.enable()
    finally:
        for runner in runners.values():
            runner.shutdown()

    med_off = sorted(results["off"])[reps // 2]
    med_on = sorted(results["on"])[reps // 2]
    overhead = (med_off - med_on) / med_off * 100.0
    for mode in ("off", "on"):
        print(json.dumps({
            "bench": "telem_overhead", "mode": mode,
            "decode_tokens_per_sec_median": round(
                sorted(results[mode])[reps // 2], 1),
            "runs": [round(x, 1) for x in results[mode]],
            "rows": rows, "tokens": tokens, "reps": reps,
        }))
    print(json.dumps({
        "bench": "telem_overhead", "mode": "summary",
        "overhead_pct": round(overhead, 2),
        "budget_pct": 2.0,
        "within_budget": overhead <= 2.0,
    }))
    # sanity: the ON plane actually recorded — a vacuously fast
    # telemetry path that records nothing would be a broken bench
    perf = metrics_on.perf.wire_digests()
    assert "step_ms.decode_block" in perf, sorted(perf)
    assert "ttft_ms" in perf
    counts, _ = metrics_on.slo_counts()
    assert sum(counts.get("default", {}).values()) >= rows * reps


def bench_latent() -> None:
    """Latent-KV codec microbench (BENCH_LATENT=1; ISSUE 20, TPLA
    stage (a)): sweep rank x wire encoding (none/int8/latent/
    latent_int8) over the three KV byte paths on the tiny CPU fixture —

    - disagg handoff: monolithic export -> import; stall + payload bytes;
    - peer prefix fetch: export_prefix_chunks bytes for a warm chain;
    - host-tier reload: churn the prefix into the tier, re-prefill, and
      read the engine's reload timer + stored tier bytes;

    each emitting one JSON line with ``tokens_identical`` — greedy
    decode of the moved sequence must match the never-moved reference
    at the swept rank (the acceptance tolerance harness; a latent rank
    that flips a token shows up as tokens_identical=false, not a
    silently worse number).

    Knobs: BENCH_LATENT_RANKS ("4,8"; rank sweep for the latent wires —
    none/int8 are rank-independent and run once at rank 0),
    BENCH_LATENT_REPS (3)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
        chain_hashes,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    ranks = [int(x) for x in os.environ.get(
        "BENCH_LATENT_RANKS", "4,8").split(",") if x.strip()]
    reps = int(os.environ.get("BENCH_LATENT_REPS", "3"))
    ps = 4
    params = llama.init_params(jax.random.PRNGKey(0), TINY,
                               dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompt = [1 + int(t) for t in rng.integers(0, 200, 88)]  # 22 pages
    hashes = chain_hashes(prompt, ps, max_pages=(len(prompt) - 1) // ps)

    def mk(rank, num_pages=96, **over):
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(8, 128),
                         paged=PagedCacheConfig(num_pages=num_pages,
                                                page_size=ps,
                                                max_pages_per_seq=32),
                         latent_rank=rank, native_allocator=False, **over),
            dtype=jnp.float32,
        )

    def run(engine, rid, ids, max_tokens=8):
        engine.add_request(rid, ids, SamplingParams(
            max_tokens=max_tokens, temperature=0.0))
        toks = []
        while engine.has_work():
            for o in engine.step():
                if o.token_id is not None:
                    toks.append(o.token_id)
        return toks

    ref = mk(0)
    want = run(ref, "ref", prompt)
    sweep = [("none", 0), ("int8", 0)] + [
        (wq, r) for r in ranks for wq in ("latent", "latent_int8")]

    for wq, rank in sweep:
        # path 1: handoff (stall = export -> import seated)
        stalls, nbytes, identical = [], 0, True
        src, dst = mk(rank), mk(rank)
        for rep in range(reps + 1):
            rid = f"{wq}{rank}h{rep}"
            got = []
            src.add_request(rid, prompt, SamplingParams(
                max_tokens=8, temperature=0.0), prefill_only=True)
            while src.has_work() and not src.handoff_ready_ids():
                for o in src.step():
                    if o.token_id is not None:
                        got.append(o.token_id)
            t0 = time.monotonic()
            exp = src.export_handoff(rid, wire_quant=wq)
            dst.import_sequence(exp)
            t1 = time.monotonic()
            while dst.has_work():
                for o in dst.step():
                    if o.token_id is not None:
                        got.append(o.token_id)
            identical &= got == want
            nbytes = len(exp.kv)
            if rep:  # rep 0 warms compile caches
                stalls.append(t1 - t0)
        _emit({
            "metric": "kv_latent_handoff_stall_ms_tiny_cpu",
            "value": round(float(np.median(stalls)) * 1e3, 3),
            "unit": "ms", "vs_baseline": 0.0, "wire_quant": wq,
            "rank": rank, "bytes": nbytes, "tokens_identical": identical,
            "reps": reps,
        })

        # path 2: peer prefix fetch (bytes on the wire + token identity)
        warm = mk(rank)
        run(warm, "warm", prompt)
        depth, chunks = warm.export_prefix_chunks(hashes, chunk_pages=2,
                                                  wire_quant=wq)
        target = mk(rank)
        target.import_prefix(prompt[: depth * ps], chunks)
        _emit({
            "metric": "kv_latent_fetch_bytes_tiny_cpu",
            "value": sum(len(c.payload) for c in chunks),
            "unit": "bytes", "vs_baseline": 0.0, "wire_quant": wq,
            "rank": rank, "pages": depth,
            "tokens_identical": run(target, "probe", prompt) == want,
        })

        # path 3: host-tier reload (stored tier encoding = the wire);
        # the pool holds ONE resident sequence (22-page prompt + decode)
        # plus a little headroom, so churn demotes the warm prefix
        tier = mk(rank, num_pages=30, host_tier_bytes=1 << 22,
                  host_tier_quant=wq)
        run(tier, "seed", prompt)
        for i in range(6):  # churn the 12-page pool: the prefix demotes
            run(tier, f"churn{i}",
                rng.integers(100, 200, size=7).tolist(), max_tokens=2)
        tier.host_tier.flush()
        tier.drain_reload_durations()
        got = run(tier, "probe", prompt)
        reloads = tier.drain_reload_durations()
        st = tier.host_tier_stats() or {}
        _emit({
            "metric": "kv_latent_hosttier_reload_ms_tiny_cpu",
            "value": round(sum(reloads) * 1e3, 3),
            "unit": "ms", "vs_baseline": 0.0, "wire_quant": wq,
            "rank": rank, "tier_bytes": st.get("bytes", 0),
            "tier_pages": st.get("pages", 0),
            "hit_pages": st.get("hit_pages", 0),
            "tokens_identical": got == want,
        })


def main() -> None:
    if os.environ.get("BENCH_HANDOFF") == "1":
        bench_handoff()
        return
    if os.environ.get("BENCH_LATENT") == "1":
        bench_latent()
        return
    if os.environ.get("BENCH_TELEM") == "1":
        bench_telem()
        return
    if os.environ.get("BENCH_MIXED") == "1":
        bench_mixed()
        return
    if os.environ.get("BENCH_LOOP") == "1":
        bench_loop()
        return
    if os.environ.get("BENCH_PREFIX") == "1":
        bench_prefix()
        return
    if os.environ.get("BENCH_PEERFETCH") == "1":
        bench_peerfetch()
        return
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    cpu_full = os.environ.get("BENCH_CPU_FULL") == "1"
    model_name = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    quant = os.environ.get("BENCH_QUANT", "none")
    slug = _MODEL_SLUGS.get(
        model_name, "".join(c for c in model_name if c.isalnum())
    )
    if force_cpu:
        metric = "decode_tokens_per_sec_tiny_cpu"
    elif cpu_full:
        # BASELINE.md config 1: real model, CPU backend, single request
        metric = f"decode_tokens_per_sec_{slug}_f32_cpu_single"
    else:
        metric = "decode_tokens_per_sec_%s_%s" % (
            slug, quant if quant != "none" else "bf16"
        )
    batch = int(os.environ.get("BENCH_BATCH", "1" if cpu_full else "64"))
    prompt_len = int(os.environ.get(
        "BENCH_PROMPT", "64" if cpu_full else "128"
    ))
    new_tokens = int(os.environ.get(
        "BENCH_NEW", "32" if cpu_full else "128"
    ))
    rate_rps = float(os.environ.get("BENCH_RATE_RPS", "0"))
    # 64 measured best on-chip r4 for burst throughput (2187 tok/s vs
    # 2120 at 16, 1B bf16) — but in steady-state rate mode the host
    # blocks a full fixed-length device block per _process_block, so a
    # large block quantum (~64 x 29 ms) would dominate the TTFT being
    # measured; rate mode keeps the small block unless overridden
    block = int(os.environ.get(
        "BENCH_BLOCK", "16" if rate_rps > 0 else "64"
    ))
    pipeline = int(os.environ.get("BENCH_PIPELINE", "1"))
    prefill_batch = int(os.environ.get("BENCH_PREFILL_BATCH", "16"))
    prefill_budget = int(os.environ.get("BENCH_PREFILL_BUDGET", "8192"))
    impl = os.environ.get("BENCH_IMPL", "auto")
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "180"))
    # speculative decoding: "same" shares the target's weight arrays
    # (acceptance 1.0 — mechanism proof / ceiling), "self-int8"/"self-int4"
    # draft with a quantized copy of the SAME weights — a genuinely
    # cheaper forward whose argmax mostly-but-not-always agrees with the
    # bf16 target, i.e. an honest sub-1.0 acceptance measurable with
    # random weights (no checkpoint download exists in this environment)
    draft_mode = os.environ.get("BENCH_DRAFT", "none")
    gamma = int(os.environ.get("BENCH_GAMMA", "4"))
    kv_quant = os.environ.get("BENCH_KV_QUANT", "none")
    # shared-prefix mode: every request's first K prompt tokens are
    # identical, so requests after the first reuse the prefix pages
    # (content-addressed page sharing — reference Req 4.1/Property 9);
    # the TTFT delta vs BENCH_SHARED_PREFIX=0 is the prefix cache's
    # measured value, and the record carries the allocator's hit rate
    shared_prefix = int(os.environ.get("BENCH_SHARED_PREFIX", "0"))
    if force_cpu and cpu_full:
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "BENCH_FORCE_CPU and BENCH_CPU_FULL are mutually "
                     "exclusive (tiny smoke vs real-model CPU baseline)",
        })
        sys.exit(2)
    if cpu_full and batch != 1:
        # the metric name says _single; a batched run under it would lie
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "BENCH_CPU_FULL is the single-request baseline "
                     f"(config 1); BENCH_BATCH must be 1, got {batch}",
        })
        sys.exit(2)
    if cpu_full and quant != "none":
        # BASELINE config 1 is the f32 CPU baseline; a quantized run
        # under the _f32_cpu_single metric name would lie
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "BENCH_CPU_FULL is the f32 CPU baseline (config 1); "
                     "BENCH_QUANT must be none",
        })
        sys.exit(2)
    if shared_prefix < 0:
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"BENCH_SHARED_PREFIX must be >= 0, got {shared_prefix}",
        })
        sys.exit(2)
    if shared_prefix > 0 and os.environ.get("BENCH_MEASURE_WARMUP") == "1":
        # the warmup path builds its own unshared prompts; a record
        # labelled _prefixK for a run that shared nothing would lie
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "BENCH_SHARED_PREFIX and BENCH_MEASURE_WARMUP are "
                     "mutually exclusive (warmup prompts are unshared)",
        })
        sys.exit(2)
    # validation happens here (fail in milliseconds, before weight init)
    if kv_quant not in ("none", "int8"):
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"unknown BENCH_KV_QUANT {kv_quant!r}; known: none|int8",
        })
        sys.exit(2)
    # suffixes attach immediately after their own validation so every
    # later error record (unknown draft, relay down, watchdog, bad
    # model) carries the already-validated config it was measuring;
    # kv/spec are clamp-INDEPENDENT (force_cpu never alters them) —
    # only _prefixK waits for the post-clamp prompt_len/page_size
    if kv_quant != "none":
        metric += "_kv" + kv_quant
    if draft_mode not in ("none", "same", "self-int8", "self-int4"):
        # validate at parse time: an unknown value must fail in
        # milliseconds, not after minutes of 8B weight init inside a
        # hardware-window step budget
        _emit({
            "metric": metric, "value": 0.0, "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": f"unknown BENCH_DRAFT {draft_mode!r}; "
                     "known: none|same|self-int8|self-int4",
        })
        sys.exit(2)
    if draft_mode != "none":
        metric += "_spec_" + draft_mode.replace("self-", "self")

    # Fail fast when the tunnel is not even listening (dead relay): the
    # axon backend dials localhost relay ports; refused connections mean
    # no chip this boot — report immediately instead of hanging the
    # watchdog out. (Inline copy of tools/_relay.py's gate: the driver
    # runs bench.py standalone, so no tools/ import here — keep the
    # port set in sync with tools/_relay.RELAY_PORTS.)
    if (not force_cpu and not cpu_full
            and os.environ.get("JAX_PLATFORMS", "") == "axon"):
        import socket

        relay_ports = (8082, 8083, 8087, 8092)
        alive = False
        for p in relay_ports:
            try:
                socket.create_connection(("127.0.0.1", p), timeout=2).close()
                alive = True
                break
            except OSError:
                continue
        if not alive:
            _emit({
                "metric": metric,
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "TPU tunnel down (relay ports refused "
                         f"{relay_ports}); no device this boot",
            })
            sys.exit(2)

    # Watchdog: the single real TPU chip sits behind a one-process tunnel;
    # if another process holds the claim, backend init blocks forever.
    init_done = threading.Event()

    def _watchdog():
        if not init_done.wait(init_timeout):
            _emit({
                "metric": metric,
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"device backend init exceeded {init_timeout}s "
                         "(TPU tunnel busy?)",
            })
            os._exit(2)

    threading.Thread(target=_watchdog, daemon=True).start()

    import jax

    if force_cpu or cpu_full:
        jax.config.update("jax_platforms", "cpu")
    # persistent XLA compile cache (same policy as the server's):
    # hardware windows are short and flaky — the r4 b256 step died to
    # compile time a previous attempt had already paid. hw_window.sh
    # sets JAX_COMPILATION_CACHE_DIR so every tool shares one cache.
    from distributed_inference_server_tpu.utils.compile_cache import (
        setup_compile_cache,
    )

    setup_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    ) if "JAX_COMPILATION_CACHE_DIR" not in os.environ else None)
    devices = jax.devices()
    init_done.set()
    platform = devices[0].platform

    import jax.numpy as jnp
    import numpy as np

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY, get_config
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.ops.quant import (
        init_random_quantized,
    )

    if force_cpu:
        cfg, dtype = TINY, jnp.float32
        prompt_len, new_tokens = min(prompt_len, 16), min(new_tokens, 16)
        # clamp the block too: warmup() needs max_seq_len (64 here) to
        # cover block+1 steps, or every warmup request is skipped and the
        # smoke mode silently stops exercising the warmup machinery
        block = min(block, 8)
        paged = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)
        buckets = (32, 64)
    else:
        try:
            cfg = get_config(model_name)
        except KeyError as e:
            # keep the always-emit-JSON contract of the other error paths
            _emit({
                "metric": metric, "value": 0.0, "unit": "tokens/s",
                "vs_baseline": 0.0, "error": str(e),
            })
            sys.exit(2)
        # CPU-backend baseline (config 1) runs f32 — oneDNN's fast path;
        # bf16 matmuls take a slow emulation route on CPU
        dtype = jnp.float32 if cpu_full else jnp.bfloat16
        pages_per_seq = -(-(prompt_len + new_tokens + 16) // 16)
        paged = PagedCacheConfig(
            num_pages=(batch + 2) * pages_per_seq + 16,
            page_size=16,
            max_pages_per_seq=pages_per_seq,
        )
        buckets = (prompt_len, max(256, prompt_len))

    shared_prefix = min(shared_prefix, prompt_len)
    if shared_prefix > 0:
        metric += f"_prefix{shared_prefix}"
        # the post-prefix residual chunk needs its OWN prefill bucket:
        # without it the residual pads up to the full prompt bucket and
        # runs the exact same device program as an unshared prompt,
        # reducing the measured "prefix cache benefit" to host-side page
        # bookkeeping noise. Prefix matching shares whole PAGES only, so
        # the real residual is prompt_len minus the matched full pages —
        # and when every page would match, the engine holds one back
        # (the divergence page), leaving a one-page residual.
        matched = (shared_prefix // paged.page_size) * paged.page_size
        resid = prompt_len - matched
        if resid <= 0:
            resid = paged.page_size
        buckets = tuple(sorted(set(buckets) | {resid}))

    if quant != "none":
        # quantized leaves are created directly (no dense intermediate):
        # 8B bf16 (~16 GB) would not fit one v5e chip, 8B int8 (~8 GB) does
        params = init_random_quantized(
            jax.random.PRNGKey(0), cfg, quant, dtype=dtype
        )
    else:
        params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=dtype)
    jax.block_until_ready(params)

    draft_params = None
    if draft_mode == "same":
        draft_params = params  # shared arrays: no extra weight HBM
    elif draft_mode in ("self-int8", "self-int4"):
        if quant != "none":
            _emit({
                "metric": metric, "value": 0.0, "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "BENCH_DRAFT=self-int* requires BENCH_QUANT=none "
                         "(the draft is quantized FROM the bf16 target)",
            })
            sys.exit(2)
        from distributed_inference_server_tpu.ops.quant import (
            quantize_params,
        )
        draft_params = quantize_params(params, draft_mode[len("self-"):])
        jax.block_until_ready(draft_params)

    # HBM roofline: every decode step reads every weight byte once, so
    # steps/s <= BW / weight_bytes and tok/s <= batch * steps/s
    weight_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(params)
    )
    hbm_gbps = float(os.environ.get("BENCH_HBM_GBPS", "819"))
    roofline = batch * hbm_gbps * 1e9 / max(1, weight_bytes)
    rng = np.random.default_rng(0)

    def mk_engine(use_impl: str) -> "LLMEngine":
        # single construction site: warmup mode and throughput mode must
        # measure the SAME engine configuration
        kw = {}
        if draft_params is not None:
            from distributed_inference_server_tpu.engine.speculative import (
                SpecConfig,
            )

            kw = dict(
                draft_params=draft_params, draft_cfg=cfg,
                spec=SpecConfig(num_draft_tokens=gamma),
            )
        return LLMEngine(
            params, cfg, ByteTokenizer(),
            EngineConfig(
                max_batch=batch, prefill_buckets=buckets, paged=paged,
                attention_impl=use_impl, decode_block_size=block,
                pipeline_depth=pipeline, prefill_batch=prefill_batch,
                prefill_token_budget=prefill_budget, kv_quant=kv_quant,
            ),
            dtype=dtype,
            **kw,
        )

    warmup_metric = metric.replace(
        "decode_tokens_per_sec", "warmup_first_request_ttft"
    )
    if os.environ.get("BENCH_MEASURE_WARMUP") == "1":
        # Quantify the warmup machinery (engine.warmup docstring claims
        # first-request compile ~20-40s on TPU; VERDICT r2 weak #9 — the
        # benefit was never measured): cold first-request TTFT (pays
        # tracing + XLA compile) vs the same engine's second request vs
        # a warmed engine's FIRST request. No persistent compile cache is
        # set here, so each engine's compiles are honest.
        seq = [0]

        def first_ttft(engine) -> float:
            seq[0] += 1
            ids = rng.integers(
                1, min(cfg.vocab_size, 250), size=prompt_len
            ).tolist()
            t0 = time.perf_counter()
            engine.add_request(
                f"wu{seq[0]}", ids,
                SamplingParams(max_tokens=8, temperature=0.0),
            )
            ttft = None
            while engine.has_work():
                for out in engine.step():
                    if ttft is None and out.token_id is not None:
                        ttft = time.perf_counter() - t0
            assert ttft is not None
            return ttft

        try:
            cold_engine = mk_engine(impl)
            cold = first_ttft(cold_engine)
            steady = first_ttft(cold_engine)
            # release the first engine's KV pool + executables before
            # building the second: at 8B-int8 two live engines would
            # overshoot one chip's HBM
            del cold_engine
            warmed_engine = mk_engine(impl)
            t0 = time.perf_counter()
            warmed_engine.warmup()
            warmup_s = time.perf_counter() - t0
            warmed = first_ttft(warmed_engine)
        except Exception as e:  # same always-emit contract as run paths
            _emit({
                "metric": warmup_metric, "value": 0.0, "unit": "s",
                "vs_baseline": 0.0, "attention_impl": impl,
                "error": str(e).split("\n")[0][:200],
            })
            sys.exit(3)
        _emit({
            "metric": warmup_metric,
            "value": round(warmed, 4),
            "unit": "s",
            # >= 1 means the <200ms first-token target is met (matching
            # the throughput emissions' higher-is-better convention)
            "vs_baseline": round(0.2 / max(warmed, 1e-9), 4),
            "platform": platform,
            "model": cfg.name,
            **({"quant": quant} if quant != "none" else {}),
            "cold_first_ttft_s": round(cold, 4),
            "steady_ttft_s": round(steady, 4),
            "warmup_duration_s": round(warmup_s, 4),
            "compile_cost_amortized_s": round(cold - warmed, 4),
        })
        return

    def run_once(use_impl: str) -> dict:
        engine = mk_engine(use_impl)

        hi = min(cfg.vocab_size, 250)
        prefix_ids = rng.integers(
            1, hi, size=min(shared_prefix, prompt_len)
        ).tolist()

        def add(rid: str, n_new: int):
            ids = prefix_ids + rng.integers(
                1, hi, size=prompt_len - len(prefix_ids)
            ).tolist()
            engine.add_request(rid, ids, SamplingParams(
                max_tokens=n_new, temperature=0.0, top_p=1.0))

        def drain(t_start=None, first_token_at=None):
            tokens = 0
            while engine.has_work():
                for out in engine.step():
                    if out.token_id is not None:
                        tokens += 1
                        if first_token_at is not None and \
                                out.request_id not in first_token_at:
                            first_token_at[out.request_id] = (
                                time.perf_counter() - t_start)
            return tokens

        # warm-up at FULL length: decode gather windows are bucketed by
        # live page count, so a full-length generation walks (and
        # compiles) every bucket the timed run will hit
        add("warmup", new_tokens)
        drain()

        ttfts = {}
        if rate_rps > 0.0:
            # steady-state serving mode: requests arrive at rate_rps
            # (uniform spacing) and TTFT is measured from each request's
            # ARRIVAL — the continuous-batching number the p50<200ms
            # north star is about, not the all-at-once cold burst below
            total = batch * 2  # enough arrivals to reach steady state
            arrival_at = {f"r{i}": i / rate_rps for i in range(total)}
            pending = sorted(arrival_at, key=arrival_at.get)
            produced = 0
            t0 = time.perf_counter()
            while pending or engine.has_work():
                now = time.perf_counter() - t0
                while pending and arrival_at[pending[0]] <= now:
                    add(pending.pop(0), new_tokens)
                outs = engine.step()
                for out in outs:
                    if out.token_id is not None:
                        produced += 1
                        rid = out.request_id
                        if rid not in ttfts:
                            ttfts[rid] = (
                                time.perf_counter() - t0 - arrival_at[rid]
                            )
                if not outs:
                    # nothing surfaced this pass — sleep toward the next
                    # arrival instead of hot-spinning the host between
                    # events (the spin perturbs the TTFT being measured);
                    # a device block may still be in flight, so cap the
                    # nap well under a block's service time
                    wait = (
                        arrival_at[pending[0]] - (time.perf_counter() - t0)
                        if pending else 0.005
                    )
                    if engine.has_work():
                        wait = min(wait, 0.001)
                    if wait > 0:
                        time.sleep(min(0.005, wait))
            elapsed = time.perf_counter() - t0
        else:
            for i in range(batch):
                add(f"r{i}", new_tokens)
            t0 = time.perf_counter()
            produced = drain(t0, ttfts)
            elapsed = time.perf_counter() - t0
        ttft_sorted = sorted(ttfts.values())
        cache = None
        if shared_prefix > 0:
            cs = engine.cache_stats()
            cache = {
                "hits": cs.hits,
                "misses": cs.misses,
                "hit_rate": round(
                    cs.hits / max(1, cs.hits + cs.misses), 4
                ),
            }
        spec = None
        ss = engine.spec_stats()
        if ss is not None:
            spec = {
                "gamma": ss["num_draft_tokens"],
                "acceptance_rate": ss["acceptance_rate"],
                # emitted tokens per TARGET forward (incl. the bonus
                # token) — the speculative speedup factor
                "tokens_per_target_forward": ss["estimated_speedup"],
                "enabled": ss["enabled"],
            }
        return {
            "tput": produced / elapsed,
            "total_tokens": produced,
            "spec": spec,
            "cache": cache,
            "elapsed_s": round(elapsed, 3),
            "p50_ttft_s": round(
                ttft_sorted[len(ttft_sorted) // 2], 3
            ) if ttft_sorted else 0.0,
            "p99_ttft_s": round(
                ttft_sorted[min(len(ttft_sorted) - 1,
                                int(0.99 * len(ttft_sorted)))], 3,
            ) if ttft_sorted else 0.0,
        }

    extra = {}
    # compare defaults ON for hardware runs — but an explicit BENCH_IMPL
    # means "measure exactly this path", so it turns compare off unless
    # BENCH_COMPARE=1 is also explicit
    compare = os.environ.get(
        "BENCH_COMPARE",
        "0" if force_cpu or cpu_full or "BENCH_IMPL" in os.environ
        else "1",
    )
    if compare == "1":
        # measure BOTH attention impls (default on hardware); report the
        # better one and carry the comparison in the same line (VERDICT
        # r1: "auto" must be justified by a number). A failing impl —
        # e.g. a Mosaic rejection on a forced Pallas path — records 0
        # with its error instead of sinking the whole bench.
        results = {}
        for i in ("xla", "pallas"):
            try:
                results[i] = run_once(i)
            except Exception as e:
                results[i] = {"tput": 0.0, "total_tokens": 0,
                              "elapsed_s": 0.0, "p50_ttft_s": 0.0,
                              "p99_ttft_s": 0.0}
                extra[f"{i}_error"] = str(e).split("\n")[0][:200]
        impl = max(results, key=lambda i: results[i]["tput"])
        r = results[impl]
        extra.update({
            "xla_tokens_per_sec": round(results["xla"]["tput"], 2),
            "pallas_tokens_per_sec": round(results["pallas"]["tput"], 2),
        })
        if all(res["tput"] == 0.0 for res in results.values()):
            # both paths died: emit an explicit error record (matching
            # the tunnel-down/watchdog contract) and exit nonzero
            _emit({
                "metric": metric,
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "error": "both attention impls failed", **extra,
            })
            sys.exit(3)
    else:
        try:
            r = run_once(impl)
        except Exception as e:
            # same structured-error contract as the tunnel-down /
            # both-failed paths: always emit a JSON record
            _emit({
                "metric": metric,
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "attention_impl": impl,
                "error": str(e).split("\n")[0][:200],
            })
            sys.exit(3)

    tput = r["tput"]
    _emit({
        # steady-state (arrival-limited) runs get their own metric name:
        # their throughput reflects offered load, not engine capacity,
        # and must not be trended against the burst-mode number
        "metric": metric + ("_steady" if rate_rps > 0 else ""),
        "value": round(tput, 2),
        "unit": "tokens/s",
        "vs_baseline": round(tput / 2000.0, 4),
        "platform": platform,
        "model": cfg.name,
        **({"quant": quant} if quant != "none" else {}),
        **({"kv_quant": kv_quant} if kv_quant != "none" else {}),
        **({"shared_prefix": shared_prefix, "prefix_cache": r["cache"]}
           if r.get("cache") else {}),
        **({"draft": draft_mode, "spec": r["spec"]}
           if r.get("spec") else {}),
        "weight_bytes": weight_bytes,
        # the roofline is an HBM-bandwidth bound — meaningless for CPU
        # rows (smoke/config-1), where emitting it would hand consumers
        # a nonsense value/roofline ratio
        **({"roofline_tokens_per_sec": round(roofline, 1)}
           if platform != "cpu" else {}),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "decode_block": block,
        "pipeline_depth": pipeline,
        "attention_impl": impl,
        "total_tokens": r["total_tokens"],
        "elapsed_s": r["elapsed_s"],
        "p50_ttft_s": r["p50_ttft_s"],
        "p99_ttft_s": r["p99_ttft_s"],
        **({"rate_rps": rate_rps} if rate_rps > 0 else {}),
        **extra,
    })


if __name__ == "__main__":
    main()
