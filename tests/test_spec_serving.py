"""Speculative decoding through the full serving path (Req 12 end-to-end):
a server whose engines carry a draft model serves /generate with greedy
bit-exactness vs a plain server, and exposes speculation metrics in
/server/stats and /metrics."""

from __future__ import annotations

import asyncio

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import EngineConfig
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.engine.speculative import SpecConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=192, page_size=8, max_pages_per_seq=32)
_ECFG = EngineConfig(
    max_batch=4, prefill_buckets=(16, 64), paged=_PAGED,
    decode_block_size=3,
)


def _factory(with_draft: bool):
    def make():
        import jax

        from distributed_inference_server_tpu.engine.engine import LLMEngine

        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)
        draft = (
            llama.init_params(jax.random.PRNGKey(7), TINY, dtype=jnp.float32)
            if with_draft else None
        )
        return LLMEngine(
            params, TINY, ByteTokenizer(), _ECFG, dtype=jnp.float32,
            draft_params=draft,
            draft_cfg=TINY if with_draft else None,
            spec=SpecConfig(num_draft_tokens=3) if with_draft else None,
        )

    return make


@pytest.fixture(scope="module")
def spec_server():
    srv = InferenceServer(
        _factory(True), ByteTokenizer(), model_name="tiny-spec",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


@pytest.fixture(scope="module")
def plain_server():
    srv = InferenceServer(
        _factory(False), ByteTokenizer(), model_name="tiny-plain",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server: InferenceServer, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def _gen(prompt):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": prompt, "max_tokens": 10, "temperature": 0.0},
        )
        assert resp.status == 200
        return (await resp.json())["choices"][0]["text"]

    return go


def test_spec_generate_greedy_exact(spec_server, plain_server):
    for prompt in ("hello world", "speculate!"):
        spec_text = _run(spec_server, _gen(prompt))
        plain_text = _run(plain_server, _gen(prompt))
        assert spec_text == plain_text, prompt


def test_spec_stats_and_metrics_exposed(spec_server):
    async def go(client):
        # generate something so the tracker has data
        await client.post(
            "/generate",
            json={"prompt": "warm", "max_tokens": 8, "temperature": 0.0},
        )
        stats = await (await client.get("/server/stats")).json()
        ws = stats["worker_statuses"]
        assert ws and "speculation" in ws[0]
        spec = ws[0]["speculation"]
        assert {"acceptance_rate", "estimated_speedup", "enabled",
                "num_draft_tokens"} <= set(spec)
        assert spec["num_draft_tokens"] == 3
        metrics_text = await (await client.get("/metrics")).text()
        assert "speculation_acceptance_rate" in metrics_text
        assert "speculation_enabled" in metrics_text

    _run(spec_server, go)


def test_plain_server_has_no_speculation_fields(plain_server):
    async def go(client):
        stats = await (await client.get("/server/stats")).json()
        assert all(
            "speculation" not in w for w in stats["worker_statuses"]
        )

    _run(plain_server, go)


def test_admin_speculation_reset(spec_server):
    """POST /admin/speculation {"action": "reset"} clears the trackers
    fleet-wide and re-enables speculation."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        client = TestClient(TestServer(spec_server.build_app()))
        await client.start_server()
        try:
            # poison the greedy pattern's tracker into disabled state
            from distributed_inference_server_tpu.engine.speculative import (
                spec_signature,
            )
            from distributed_inference_server_tpu.engine.engine import (
                SamplingParams,
            )

            sig = spec_signature(SamplingParams(temperature=0.0))
            for runner in spec_server.scheduler.engines():
                t = runner._engine.spec_trackers
                for _ in range(t.cfg.window):
                    t.update(sig, 0, 4)
                t.disable(sig)  # force, bypass cooldown
            resp = await client.post("/admin/speculation",
                                     json={"action": "reset"})
            body = await resp.json()
            assert resp.status == 200 and body["engines_reset"] >= 1
            await asyncio.sleep(0.2)  # reset posted to the engine thread
            # a generation keeps the engine thread draining its inbox
            r = await client.post("/generate", json={
                "prompt": "after reset", "max_tokens": 2,
                "temperature": 0.0})
            assert r.status == 200
            for runner in spec_server.scheduler.engines():
                assert runner._engine.spec_trackers.all_enabled
            bad = await client.post("/admin/speculation",
                                    json={"action": "nope"})
            assert bad.status == 400
        finally:
            await client.close()

    asyncio.run(go())
