"""Tensor-parallel tests on the 8-virtual-device CPU mesh (SURVEY.md §4.3):
mesh construction, sharding-rule structure, TP-vs-single-device numerical
equivalence of the forward pass, and a TP engine generating end-to-end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.parallel import (
    MeshSpec,
    largest_tp,
    llama_param_specs,
    make_mesh,
    shard_params,
    tp_mesh,
    validate_tp,
)


class TestMesh:
    def test_make_mesh_axes(self):
        mesh = make_mesh(MeshSpec(tensor=4, data=2))
        assert mesh.shape["tensor"] == 4
        assert mesh.shape["data"] == 2
        assert mesh.shape["expert"] == 1

    def test_auto_axis(self):
        mesh = make_mesh(MeshSpec(tensor=4, data=0))
        assert mesh.shape["data"] == 2  # 8 devices / 4

    def test_two_auto_axes_rejected(self):
        with pytest.raises(ValueError):
            make_mesh(MeshSpec(tensor=0, data=0))

    def test_too_many_devices_rejected(self):
        with pytest.raises(ValueError):
            make_mesh(MeshSpec(tensor=16))

    def test_largest_tp(self):
        assert largest_tp(8, 4) == 4
        assert largest_tp(8, 8) == 8
        assert largest_tp(4, 8) == 4
        assert largest_tp(3, 8) == 1

    def test_validate_tp(self):
        validate_tp(TINY, 2)
        with pytest.raises(ValueError):
            validate_tp(TINY, 16)  # doesn't divide kv heads
        with pytest.raises(ValueError):
            validate_tp(TINY, 0)


class TestParamSpecs:
    def test_spec_tree_matches_param_tree(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        specs = llama_param_specs(TINY)
        from jax.sharding import PartitionSpec

        pt = jax.tree_util.tree_structure(params)
        st = jax.tree_util.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        assert pt == st

    def test_shard_params_places_on_mesh(self):
        mesh = tp_mesh(2)
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        sharded = shard_params(params, mesh, TINY)
        wq = sharded["layers"]["wq"]
        # column-parallel: last dim split over 2 devices
        assert wq.sharding.shard_shape(wq.shape)[-1] == wq.shape[-1] // 2


class TestTPEquivalence:
    def test_paged_forward_matches_single_device(self):
        """TP=2 logits == unsharded logits (same weights, f32)."""
        cfg = TINY
        params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        B, T = 2, 8
        num_slots, smax = 64, 16
        pool = jnp.zeros((cfg.num_layers, num_slots, cfg.num_kv_heads,
                          cfg.head_dim), jnp.float32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                 cfg.vocab_size)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        # row b owns slots [16b, 16b+16)
        write_slots = (positions + 16 * jnp.arange(B)[:, None]).astype(jnp.int32)
        gather = (jnp.arange(smax)[None, :] + 16 * jnp.arange(B)[:, None]
                  ).astype(jnp.int32)
        kv_valid = jnp.full((B,), T, jnp.int32)

        ref_logits, ref_k, _ = llama.paged_forward(
            params, cfg, ids, positions, pool, pool, write_slots, gather,
            kv_valid,
        )

        mesh = tp_mesh(2)
        sharded_params = shard_params(params, mesh, cfg)
        from jax.sharding import NamedSharding

        from distributed_inference_server_tpu.parallel import kv_pool_spec

        pool_sh = NamedSharding(mesh, kv_pool_spec())
        pool_tp = jax.device_put(pool, pool_sh)

        tp_logits, tp_k, _ = jax.jit(
            lambda p, pk, pv: llama.paged_forward(
                p, cfg, ids, positions, pk, pv, write_slots, gather, kv_valid
            )
        )(sharded_params, pool_tp, pool_tp)

        np.testing.assert_allclose(
            np.asarray(ref_logits), np.asarray(tp_logits), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(ref_k), np.asarray(tp_k), rtol=2e-4, atol=2e-4
        )


    def test_vocab_parallel_unembedding_untied(self):
        """VERDICT r2 missing #4: the unembedding is vocab-parallel. An
        UNTIED config's lm_head [H, V] is physically split on 'tensor'
        (each shard holds V/tp columns) and TP logits still bit-match the
        unsharded path — GSPMD inserts whatever gather/reduce the
        consumer needs."""
        cfg = TINY.with_overrides(name="tiny-untied",
                                  tie_word_embeddings=False)
        params = llama.init_params(jax.random.PRNGKey(3), cfg, jnp.float32)
        B, T = 2, 8
        num_slots, smax = 64, 16
        pool = jnp.zeros((cfg.num_layers, num_slots, cfg.num_kv_heads,
                          cfg.head_dim), jnp.float32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                 cfg.vocab_size)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T)).astype(jnp.int32)
        write_slots = (positions + 16 * jnp.arange(B)[:, None]
                       ).astype(jnp.int32)
        gather = (jnp.arange(smax)[None, :] + 16 * jnp.arange(B)[:, None]
                  ).astype(jnp.int32)
        kv_valid = jnp.full((B,), T, jnp.int32)

        ref_logits, _, _ = llama.paged_forward(
            params, cfg, ids, positions, pool, pool, write_slots, gather,
            kv_valid,
        )

        mesh = tp_mesh(2)
        sharded_params = shard_params(params, mesh, cfg)
        # the lm_head leaf is REALLY vocab-split: V/2 columns per shard
        shards = sharded_params["lm_head"].addressable_shards
        assert {s.data.shape for s in shards} == {
            (cfg.hidden_size, cfg.vocab_size // 2)
        }

        from jax.sharding import NamedSharding

        from distributed_inference_server_tpu.parallel import kv_pool_spec

        pool_tp = jax.device_put(pool, NamedSharding(mesh, kv_pool_spec()))
        tp_logits, _, _ = jax.jit(
            lambda p, pk, pv: llama.paged_forward(
                p, cfg, ids, positions, pk, pv, write_slots, gather,
                kv_valid,
            )
        )(sharded_params, pool_tp, pool_tp)
        np.testing.assert_allclose(
            np.asarray(ref_logits), np.asarray(tp_logits),
            rtol=2e-4, atol=2e-4,
        )


class TestTPEngine:
    def test_tp_engine_matches_unsharded_greedy(self):
        cfg = TINY
        paged = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)
        ecfg = EngineConfig(max_batch=2, prefill_buckets=(16,), paged=paged)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        tok = ByteTokenizer()

        def generate(engine):
            engine.add_request(
                "r", tok.encode("parallel!"),
                SamplingParams(max_tokens=8, temperature=0.0),
            )
            text = []
            while engine.has_work():
                for out in engine.step():
                    text.append(out.text)
            return "".join(text)

        plain = generate(LLMEngine(params, cfg, tok, ecfg, dtype=jnp.float32))
        tp = generate(
            LLMEngine(params, cfg, tok, ecfg, dtype=jnp.float32,
                      mesh=tp_mesh(2))
        )
        assert plain == tp


class TestTPServingE2E:
    """VERDICT r1 weak #5: the engine's TP + Pallas path must be driven
    through the SERVER, not only engine-level — full HTTP spine over a
    tensor=2 mesh with the shard_map-wrapped kernels (interpret mode)."""

    def test_http_generate_over_tp_pallas_engine(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.server import (
            InferenceServer,
        )

        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)

        def factory():
            return LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(
                    max_batch=2, prefill_buckets=(16, 64),
                    paged=PagedCacheConfig(num_pages=64, page_size=8,
                                           max_pages_per_seq=8),
                    attention_impl="pallas",
                ),
                dtype=jnp.float32, mesh=tp_mesh(2),
            )

        srv = InferenceServer(
            factory, ByteTokenizer(), model_name="tiny-tp",
            num_engines=1, auto_restart=False,
        )
        srv.start()
        try:
            async def main():
                client = TestClient(TestServer(srv.build_app()))
                await client.start_server()
                try:
                    resp = await client.post("/generate", json={
                        "prompt": "served over a tensor-parallel mesh",
                        "max_tokens": 6, "temperature": 0.0,
                    })
                    body = await resp.json()
                    assert resp.status == 200, body
                    assert body["usage"]["completion_tokens"] == 6
                    h = await client.get("/health")
                    assert (await h.json())["status"] == "ok"
                finally:
                    await client.close()

            asyncio.run(main())
        finally:
            srv.shutdown(drain_timeout_s=5.0)
