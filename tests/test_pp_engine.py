"""Pipeline parallelism driven by the serving engine over the paged KV
cache (the 70B TP x PP north-star structure, BASELINE.md config 5): an
LLMEngine on a (stage, tensor) mesh must produce exactly the single-device
engine's greedy tokens, through admission, batched prefill, decode blocks,
and prefix reuse."""

import jax
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh

TOK = ByteTokenizer()

ECFG = EngineConfig(
    max_batch=2,
    prefill_buckets=(8, 32),
    paged=PagedCacheConfig(num_pages=32, page_size=4, max_pages_per_seq=8),
    decode_block_size=4,
)


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def run(engine, max_steps=400):
    results = {}
    for _ in range(max_steps):
        if not engine.has_work():
            break
        for out in engine.step():
            r = results.setdefault(out.request_id,
                                   {"tokens": [], "error": None})
            if out.token_id is not None:
                r["tokens"].append(out.token_id)
            if out.finished:
                r["error"] = out.error
    assert not engine.has_work()
    return results


GREEDY = SamplingParams(max_tokens=10, temperature=0.0)


@pytest.mark.parametrize("spec", [
    MeshSpec(stage=2),              # pure PP
    MeshSpec(stage=2, tensor=2),    # PP x TP composition
])
def test_engine_pp_matches_single_device(tiny_params, spec):
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    plain = LLMEngine(tiny_params, TINY, TOK, ECFG, dtype=jnp.float32)
    pp = LLMEngine(tiny_params, TINY, TOK, ECFG, dtype=jnp.float32,
                   mesh=make_mesh(spec))
    prompts = {f"r{i}": TOK.encode(f"pp prompt {i}") for i in range(3)}
    for rid, ids in prompts.items():
        plain.add_request(rid, ids, GREEDY)
        pp.add_request(rid, ids, GREEDY)
    expected = run(plain)
    got = run(pp)
    for rid in prompts:
        assert got[rid]["error"] is None
        assert got[rid]["tokens"] == expected[rid]["tokens"], rid


def test_engine_pp_microbatched(tiny_params):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual devices")
    ecfg = EngineConfig(
        max_batch=2,
        prefill_buckets=(8, 32),
        paged=PagedCacheConfig(num_pages=32, page_size=4,
                               max_pages_per_seq=8),
        decode_block_size=3,
        pp_microbatches=2,
        prefill_batch=2,
    )
    plain = LLMEngine(tiny_params, TINY, TOK, ECFG, dtype=jnp.float32)
    pp = LLMEngine(tiny_params, TINY, TOK, ecfg, dtype=jnp.float32,
                   mesh=make_mesh(MeshSpec(stage=2)))
    prompt = TOK.encode("microbatch")
    plain.add_request("r", prompt, GREEDY)
    pp.add_request("r", prompt, GREEDY)
    assert run(pp)["r"]["tokens"] == run(plain)["r"]["tokens"]


def test_engine_pp_validates_layer_divisibility(tiny_params):
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    with pytest.raises(ValueError, match="stages do not divide"):
        LLMEngine(tiny_params, TINY, TOK, ECFG, dtype=jnp.float32,
                  mesh=make_mesh(MeshSpec(stage=4)))


def test_engine_pp_with_speculative_draft(tiny_params):
    """Speculative decoding composes with pipeline parallelism: draft and
    target both pipeline over the stage axis, and greedy output stays
    bit-identical to the plain engine."""
    from distributed_inference_server_tpu.engine.speculative import (
        SpecConfig,
    )

    draft = llama.init_params(jax.random.PRNGKey(7), TINY,
                              dtype=jnp.float32)
    plain = LLMEngine(tiny_params, TINY, TOK, ECFG, dtype=jnp.float32)
    pp_spec = LLMEngine(
        tiny_params, TINY, TOK, ECFG, dtype=jnp.float32,
        mesh=make_mesh(MeshSpec(stage=2)),
        draft_params=draft, draft_cfg=TINY,
        spec=SpecConfig(num_draft_tokens=3),
    )
    prompts = {f"r{i}": TOK.encode(f"pp+spec {i}") for i in range(2)}
    for rid, ids in prompts.items():
        plain.add_request(rid, ids, GREEDY)
        pp_spec.add_request(rid, ids, GREEDY)
    expected = run(plain)
    got = run(pp_spec)
    for rid in prompts:
        assert got[rid]["error"] is None
        assert got[rid]["tokens"] == expected[rid]["tokens"], rid
    stats = pp_spec.spec_stats()
    assert stats is not None and stats["estimated_speedup"] >= 1.0
