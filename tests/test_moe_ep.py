"""Expert-parallel MoE: capacity-based dispatch (ops/moe.py) vs the
dense-compute reference, unsharded and sharded over an 8-device mesh.

The dense path (models/llama.py:_moe_mlp) is ground truth; the GShard-style
dispatch must agree exactly (same top-k softmax gating) whenever capacity
is ample, drop excess assignments when it is not, and partition over the
``expert`` mesh axis with identical numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY_MOE
from distributed_inference_server_tpu.ops.moe import expert_capacity, moe_mlp_ep
from distributed_inference_server_tpu.parallel import (
    MeshSpec,
    make_mesh,
    shard_params,
)

CFG = TINY_MOE


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def _layer0(params):
    return jax.tree_util.tree_map(lambda a: a[0], params["layers"])


def test_sparse_matches_dense_when_capacity_ample(params):
    layer = _layer0(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, CFG.hidden_size))
    dense = llama._moe_mlp(x, layer, CFG)
    N = x.shape[0] * x.shape[1]
    sparse = moe_mlp_ep(
        x, layer, CFG.num_experts, CFG.num_experts_per_tok,
        capacity=N * CFG.num_experts_per_tok,  # nothing can drop
    )
    np.testing.assert_allclose(
        np.asarray(sparse), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


def test_capacity_drops_excess_assignments(params):
    layer = _layer0(params)
    # One token per sequence: all tokens route identically enough that a
    # capacity of 1 must drop assignments for some tokens.
    x = jnp.broadcast_to(
        jax.random.normal(jax.random.PRNGKey(2), (1, 1, CFG.hidden_size)),
        (1, 8, CFG.hidden_size),
    )
    full = moe_mlp_ep(
        x, layer, CFG.num_experts, CFG.num_experts_per_tok, capacity=16
    )
    capped = moe_mlp_ep(
        x, layer, CFG.num_experts, CFG.num_experts_per_tok, capacity=1
    )
    # first token keeps its full output; later identical tokens lose theirs
    np.testing.assert_allclose(
        np.asarray(capped[0, 0]), np.asarray(full[0, 0]), rtol=1e-5, atol=1e-5
    )
    assert np.abs(np.asarray(capped[0, -1])).max() < np.abs(
        np.asarray(full[0, -1])
    ).max()
    # dropped assignment = zero contribution, never NaN
    assert np.isfinite(np.asarray(capped)).all()


def test_expert_capacity_floor():
    assert expert_capacity(1, 8, 2, 1.25) == 2  # floored at k
    assert expert_capacity(64, 8, 2, 1.0) == 16
    assert expert_capacity(64, 8, 2, 1.25) == 20


def test_ep_sharded_forward_matches_dense(params):
    """Full TINY_MOE forward on a (data=2, expert=4) mesh with EP dispatch
    vs the single-device dense-compute forward. Capacity factor is raised
    so no assignment drops (drops are exercised separately above)."""
    cfg = CFG.with_overrides(moe_capacity_factor=float(CFG.num_experts))
    mesh = make_mesh(MeshSpec(data=2, expert=4))
    B, T = 2, 8
    ids = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)

    logits_dense, _ = llama.forward(
        params, cfg, ids, positions,
        llama.KVCache.create(cfg, B, T, dtype=jnp.float32),
        positions, valid,
    )

    sharded = shard_params(params, mesh, cfg)
    with mesh:
        fwd = jax.jit(
            lambda p, i: llama.forward(
                p, cfg, i, positions,
                llama.KVCache.create(cfg, B, T, dtype=jnp.float32),
                positions, valid, moe_impl="ep",
            )[0]
        )
        logits_ep = fwd(sharded, ids)
    np.testing.assert_allclose(
        np.asarray(logits_ep), np.asarray(logits_dense), rtol=2e-4, atol=2e-4
    )


def test_ep_inserts_collectives(params):
    """The compiled EP forward on an expert-sharded mesh must contain an
    all-to-all or equivalent collective (the dispatch boundary)."""
    mesh = make_mesh(MeshSpec(expert=4))
    B, T = 1, 8
    ids = jnp.zeros((B, T), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    sharded = shard_params(params, mesh, CFG)
    with mesh:
        fn = jax.jit(
            lambda p, i: llama.forward(
                p, CFG, i, positions,
                llama.KVCache.create(CFG, B, T, dtype=jnp.float32),
                positions, valid, moe_impl="ep",
            )[0]
        )
        hlo = fn.lower(sharded, ids).compile().as_text()
    assert any(op in hlo for op in ("all-to-all", "all-gather", "all-reduce"))


def test_engine_serves_moe_on_expert_mesh(params):
    """End-to-end: TINY_MOE served by the continuous-batching engine on an
    expert=4 mesh (EP dispatch) produces the same greedy completion as the
    meshless dense-compute engine."""
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    cfg = CFG.with_overrides(moe_capacity_factor=float(CFG.num_experts))
    tok = ByteTokenizer()
    prompt = tok.encode("moe!")
    results = {}
    for mesh in (None, make_mesh(MeshSpec(expert=4))):
        eng = LLMEngine(
            params, cfg, tok,
            EngineConfig(
                max_batch=2, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=32, page_size=4,
                                       max_pages_per_seq=8),
            ),
            dtype=jnp.float32, mesh=mesh,
        )
        eng.add_request("r", prompt, SamplingParams(max_tokens=8, temperature=0.0))
        toks = []
        while eng.has_work():
            for o in eng.step():
                if o.token_id is not None:
                    toks.append(o.token_id)
        results["ep" if mesh else "dense"] = toks
    assert len(results["dense"]) == 8
    assert results["ep"] == results["dense"]
