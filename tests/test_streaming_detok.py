"""Incremental detokenization: multi-token UTF-8 characters stream as
the completed character, not as per-fragment U+FFFD replacement chars
(Property 13's token text is meant to be the decoded text delta;
previously every byte of a multi-byte char streamed as a literal '�')."""

import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
    _Seq,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer


def _engine():
    params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(
            max_batch=2, prefill_buckets=(16,),
            paged=PagedCacheConfig(num_pages=64, page_size=8,
                                   max_pages_per_seq=8),
        ),
        dtype=jnp.float32,
    )


def _seq():
    return _Seq("r", [1, 2, 3], SamplingParams(max_tokens=64))


class TestDecodePiece:
    def test_multibyte_char_held_then_completed(self):
        eng = _engine()
        s = _seq()
        b = "中".encode("utf-8")  # 3 bytes
        assert eng._decode_piece(s, b[0]) == ""
        assert eng._decode_piece(s, b[1]) == ""
        assert eng._decode_piece(s, b[2]) == "中"
        assert s.pending_ids == []

    def test_ascii_fast_path_unbuffered(self):
        eng = _engine()
        s = _seq()
        assert eng._decode_piece(s, ord("h")) == "h"
        assert s.pending_ids == []

    def test_mixed_emoji_then_ascii(self):
        eng = _engine()
        s = _seq()
        out = []
        for byte in "🙂!".encode("utf-8"):
            out.append(eng._decode_piece(s, byte))
        assert "".join(out) == "🙂!"
        assert all("�" not in p for p in out)

    def test_garbage_run_flushes_after_cap(self):
        """A genuinely undecodable run must not stall the stream: it
        flushes (replacement chars included) at the 8-token cap."""
        eng = _engine()
        s = _seq()
        pieces = [eng._decode_piece(s, 0xFF) for _ in range(8)]
        joined = "".join(pieces)
        assert joined.count("�") == 8  # nothing silently dropped
        assert s.pending_ids == []

    def test_finish_flushes_trailing_fragment(self):
        eng = _engine()
        s = _seq()
        b = "中".encode("utf-8")
        assert eng._decode_piece(s, b[0]) == ""
        eng._flush_pending_text(s)
        assert s.output_text == "�"  # best-effort at termination
        assert s.pending_ids == []


def test_stream_deltas_reconstruct_valid_utf8_exactly():
    """Driving the REAL byte stream of a valid UTF-8 text through the
    incremental decoder reproduces the text exactly — the concatenated
    stream deltas a client sees contain no replacement chars."""
    eng = _engine()
    s = _seq()
    text = "héllo 🙂 中文 done"
    got = "".join(eng._decode_piece(s, b) for b in text.encode("utf-8"))
    assert got == text
