"""Golden-fixture conformance against REAL HF artifacts (VERDICT r4 #6).

The fixtures under tests/fixtures/ were produced by Hugging Face tooling
(tools/make_golden_fixtures.py): `LlamaForCausalLM.save_pretrained`
wrote the checkpoint bytes, the `tokenizers` library wrote
tokenizer.json, and the golden logits / greedy continuation were
computed by the HF torch forward — an INDEPENDENT implementation of the
same model math. These tests are the first non-synthetic anchor for the
loader/tokenizer/forward stack (SURVEY §7.2 M1 "logits vs. HF
reference"; reference model-load capability ``design.md:324-332``).

Tolerances: both sides run float32; differences are op-ordering only
(XLA vs torch/oneDNN), observed ~1e-5 — asserted at 100x margin.
"""

import json
import os

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.loader import load_checkpoint
from distributed_inference_server_tpu.models.tokenizer import load_tokenizer

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
CKPT = os.path.join(FIXTURES, "tiny_llama_hf")


@pytest.fixture(scope="module")
def golden():
    return np.load(os.path.join(FIXTURES, "golden_tiny_llama.npz"))


@pytest.fixture(scope="module")
def loaded():
    return load_checkpoint(CKPT, dtype=jnp.float32)


def test_config_parses_hf_config_json(loaded):
    _, cfg = loaded
    assert cfg.vocab_size == 384
    assert cfg.hidden_size == 64
    assert cfg.num_layers == 2
    assert cfg.num_heads == 4
    assert cfg.num_kv_heads == 2
    assert cfg.head_dim == 16
    assert not cfg.tie_word_embeddings


def test_forward_matches_hf_logits(loaded, golden):
    """Prefill logits vs the HF torch forward, all prompts, all valid
    positions."""
    params, cfg = loaded
    ids = golden["input_ids"]
    mask = golden["attention_mask"]
    B, T = ids.shape
    cache = llama.KVCache.create(cfg, B, T, dtype=jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    valid = jnp.asarray(mask.sum(axis=1), jnp.int32)
    logits, _ = llama.forward(
        params, cfg, jnp.asarray(ids), positions, cache,
        write_pos=positions, kv_valid_len=valid,
    )
    got = np.asarray(logits)
    want = golden["logits"]
    sel = mask.astype(bool)
    diff = np.abs(got[sel] - want[sel]).max()
    assert diff < 1e-3, f"max |logit diff| {diff} vs HF"
    # argmax agreement at every valid position — the decision-relevant bit
    assert (got[sel].argmax(-1) == want[sel].argmax(-1)).all()


def test_greedy_generation_matches_hf(loaded, golden):
    """16-token greedy continuation vs HF `generate` (dense path)."""
    from distributed_inference_server_tpu.models.generate import greedy_generate

    params, cfg = loaded
    prompt = golden["greedy_prompt"].tolist()
    want = golden["greedy_out"].tolist()
    got = greedy_generate(params, cfg, prompt, max_new_tokens=16)
    # greedy_generate returns the NEW tokens only
    assert got == want[len(prompt):]


def test_engine_paged_greedy_matches_hf(loaded, golden):
    """The PAGED serving path (engine, page tables, continuous batching)
    reproduces the HF greedy continuation — the strongest end-to-end
    anchor: tokens → pages → paged attention → sampling."""
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig

    params, cfg = loaded
    prompt = golden["greedy_prompt"].tolist()
    want = golden["greedy_out"].tolist()
    # the checkpoint's OWN tokenizer: its eos (<|end_of_text|>=1) must not
    # collide with ordinary generated ids (ByteTokenizer's eos 257 is a
    # regular token in this vocab and HF happens to emit it)
    engine = LLMEngine(
        params, cfg, load_tokenizer(CKPT),
        EngineConfig(
            max_batch=2,
            prefill_buckets=(16,),
            paged=PagedCacheConfig(
                num_pages=32, page_size=4, max_pages_per_seq=16
            ),
        ),
        dtype=jnp.float32,
    )
    engine.add_request(
        "g", prompt, SamplingParams(max_tokens=16, temperature=0.0)
    )
    tokens = []
    for _ in range(200):
        if not engine.has_work():
            break
        for out in engine.step():
            if out.token_id is not None:
                tokens.append(out.token_id)
    assert tokens == want[len(prompt):]


def test_tokenizer_parity_with_hf_tokenizers(golden):
    """HFTokenizer over the committed tokenizer.json reproduces the
    `tokenizers` library's encodings/decodings exactly."""
    with open(os.path.join(FIXTURES, "golden_tok.json")) as f:
        g = json.load(f)
    tok = load_tokenizer(CKPT)
    assert tok.vocab_size == g["vocab_size"]
    for text, want_ids in g["encodings"].items():
        assert tok.encode(text, add_bos=False) == want_ids, text
    for text, want_text in g["decodings"].items():
        assert tok.decode(tok.encode(text, add_bos=False)) == want_text
    # checkpoint-shipped chat template travels with the tokenizer
    assert getattr(tok, "chat_template", None)


def test_fixture_generator_is_hf_not_ours():
    """Guard: the checkpoint fixture must remain HF-produced bytes — the
    metadata written by save_pretrained names transformers as producer.
    (Our own save path writing the fixture would reintroduce the shared
    saver/loader-bug blind spot this fixture exists to remove.)"""
    import struct

    path = os.path.join(CKPT, "model.safetensors")
    with open(path, "rb") as f:
        n = struct.unpack("<Q", f.read(8))[0]
        header = json.loads(f.read(n))
    assert header.get("__metadata__", {}).get("format") == "pt"


FAMILIES = [
    "tiny_mixtral_hf", "tiny_gemma2_hf", "tiny_qwen2_hf",
    "tiny_mistral_hf",
]


@pytest.mark.parametrize("family", FAMILIES)
def test_family_forward_matches_hf_logits(family):
    """Every model family's loader mapping + forward against its own
    HF-produced checkpoint and HF-torch golden logits: Mixtral
    (block_sparse_moe expert naming + routing), Gemma-2 (unit-offset
    sandwich norms folded at load, soft-capping, query_pre_attn_scalar,
    alternating sliding windows), Qwen2 (qkv bias), Mistral (uniform
    sliding window)."""
    ck = os.path.join(FIXTURES, family)
    params, cfg = load_checkpoint(ck, dtype=jnp.float32)
    g = np.load(os.path.join(FIXTURES, f"golden_{family}.npz"))
    ids = g["input_ids"]
    B, T = ids.shape
    cache = llama.KVCache.create(cfg, B, T, dtype=jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    logits, _ = llama.forward(
        params, cfg, jnp.asarray(ids), positions, cache,
        write_pos=positions, kv_valid_len=valid,
    )
    got = np.asarray(logits)
    want = g["logits"]
    diff = np.abs(got - want).max()
    assert diff < 2e-3, f"{family}: max |logit diff| {diff} vs HF"
    assert (got.argmax(-1) == want.argmax(-1)).mean() > 0.99, family


@pytest.mark.parametrize("family", FAMILIES)
def test_family_greedy_matches_hf(family):
    """Each family's DECODE path (cache layout, sliding windows,
    soft-caps, MoE routing at T=1) vs the HF greedy continuation."""
    from distributed_inference_server_tpu.models.generate import (
        greedy_generate,
    )

    ck = os.path.join(FIXTURES, family)
    params, cfg = load_checkpoint(ck, dtype=jnp.float32)
    g = np.load(os.path.join(FIXTURES, f"golden_{family}.npz"))
    prompt = g["input_ids"][0].tolist()
    want = g["greedy_out"].tolist()
    got = greedy_generate(params, cfg, prompt, max_new_tokens=8)
    assert got == want[len(prompt):], family


def test_loader_reconciles_tie_with_checkpoint_contents(tmp_path):
    """The checkpoint is ground truth for head tying: HF serializes tied
    models WITHOUT lm_head.weight and untied ones WITH it. A config.json
    whose tie flag disagrees (absent/null keys, hand-edited configs) is
    overridden instead of silently unembedding with the wrong matrix."""
    import shutil

    # start from the untied llama fixture; claim tied in config.json
    src = CKPT
    dst = tmp_path / "claims_tied"
    shutil.copytree(src, dst)
    cfgp = dst / "config.json"
    obj = json.loads(cfgp.read_text())
    obj["tie_word_embeddings"] = True  # lie: shards carry lm_head.weight
    cfgp.write_text(json.dumps(obj))
    params, cfg = load_checkpoint(str(dst), dtype=jnp.float32)
    assert not cfg.tie_word_embeddings  # checkpoint wins
    assert "lm_head" in params
