"""Multi-host backend tests (SURVEY §5 two-plane design; VERDICT r1:
"DCN / multi-host absent entirely"): the jax.distributed wrapper + hybrid
DCN x ICI mesh (data plane) and the cross-host HTTP router (control
plane), driven against two real in-process backend servers."""

from __future__ import annotations

import asyncio
import json

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import EngineConfig
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.parallel import MeshSpec
from distributed_inference_server_tpu.parallel.distributed import (
    DistributedConfig,
    global_batch_shard,
    hybrid_mesh,
    initialize,
)
from distributed_inference_server_tpu.serving.router import (
    Router,
    RouterConfig,
    build_router_app,
)
from distributed_inference_server_tpu.serving.server import InferenceServer


class TestDataPlane:
    def test_single_process_skips_initialize(self):
        assert initialize(DistributedConfig()) is False
        assert not DistributedConfig().enabled
        assert DistributedConfig(num_processes=4,
                                 coordinator_address="h:1234").enabled

    def test_hybrid_mesh_single_slice_collapses(self):
        mesh = hybrid_mesh(MeshSpec(tensor=2), dcn_spec=MeshSpec(data=4))
        assert mesh.shape["data"] == 4
        assert mesh.shape["tensor"] == 2
        assert mesh.shape["expert"] == 1

    def test_hybrid_mesh_defaults(self):
        mesh = hybrid_mesh(MeshSpec(tensor=4, data=2))
        assert mesh.shape["tensor"] == 4
        assert mesh.shape["data"] == 2

    def test_global_batch_shard_single(self):
        assert global_batch_shard(7) == (7, 0)


_PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)


def _factory():
    import jax

    from distributed_inference_server_tpu.engine.engine import LLMEngine

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(max_batch=2, prefill_buckets=(16,), paged=_PAGED),
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def backends():
    servers = []
    for name in ("host-a", "host-b"):
        srv = InferenceServer(
            _factory, ByteTokenizer(), model_name=name,
            num_engines=1, auto_restart=False,
        )
        srv.start()
        servers.append(srv)
    yield servers
    for srv in servers:
        srv.shutdown(drain_timeout_s=5.0)


def _run_router(backends, coro_fn, **router_kw):
    async def main():
        # two real backend HTTP servers on localhost ports
        test_servers = [TestServer(s.build_app()) for s in backends]
        for ts in test_servers:
            await ts.start_server()
        urls = [str(ts.make_url("/")).rstrip("/") for ts in test_servers]
        router = Router(RouterConfig(
            backends=urls,
            health_check_interval_s=0.2,
            **router_kw,
        ))
        client = TestClient(TestServer(build_router_app(router)))
        await client.start_server()
        try:
            return await coro_fn(client, router, urls)
        finally:
            await client.close()
            for ts in test_servers:
                await ts.close()

    return asyncio.run(main())


class TestRouter:
    def test_generate_via_router(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={
                "prompt": "hello fleet", "max_tokens": 6,
                "temperature": 0.0,
            })
            assert resp.status == 200
            body = await resp.json()
            assert body["usage"]["completion_tokens"] == 6
            assert sum(b.total for b in router.backends) == 1
        _run_router(backends, go)

    def test_round_robin_spreads_load(self, backends):
        async def go(client, router, urls):
            for _ in range(4):
                resp = await client.post("/generate", json={
                    "prompt": "spread", "max_tokens": 2,
                    "temperature": 0.0,
                })
                assert resp.status == 200
            counts = sorted(b.total for b in router.backends)
            assert counts == [2, 2]
        _run_router(backends, go, strategy="round_robin")

    def test_sse_stream_passthrough(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={
                "prompt": "stream me", "max_tokens": 4,
                "temperature": 0.0, "stream": True,
            })
            assert resp.status == 200
            assert resp.content_type == "text/event-stream"
            events = []
            async for line in resp.content:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    events.append(json.loads(line[6:]))
            kinds = [e["type"] for e in events]
            # 4 generated tokens arrive as >= 4 token events (the final
            # token is emitted as id + held-back-text flush, same as the
            # direct backend stream) followed by done
            assert kinds.count("token") >= 4
            assert kinds[-1] == "done"
            assert events[-1]["usage"]["completion_tokens"] == 4
        _run_router(backends, go)

    def test_dead_backend_failover(self, backends):
        async def go(client, router, urls):
            # poison one backend with an unreachable address
            router.backends[0].base_url = "http://127.0.0.1:1"
            resp = await client.post("/generate", json={
                "prompt": "failover", "max_tokens": 3,
                "temperature": 0.0,
            })
            assert resp.status == 200  # retried on the healthy backend
            assert not router.backends[0].healthy
            assert router.backends[0].last_error
        _run_router(backends, go)

    def test_all_dead_returns_503(self, backends):
        async def go(client, router, urls):
            for b in router.backends:
                b.healthy = False
            resp = await client.post("/generate", json={
                "prompt": "nope", "max_tokens": 1,
            })
            assert resp.status == 503
            body = await resp.json()
            assert body["error"]["code"] == "no_backend"
        _run_router(backends, go)

    def test_health_aggregation_and_recovery(self, backends):
        async def go(client, router, urls):
            resp = await client.get("/health")
            assert resp.status == 200
            body = await resp.json()
            assert body["status"] == "ok"
            assert len(body["backends"]) == 2
            # mark one unhealthy; the health loop reinstates it
            router.backends[0].healthy = False
            await asyncio.sleep(0.5)
            assert router.backends[0].healthy  # recovered by the loop
        _run_router(backends, go)

    def test_stats_aggregation(self, backends):
        async def go(client, router, urls):
            resp = await client.get("/server/stats")
            assert resp.status == 200
            body = await resp.json()
            assert set(body["backends"]) == set(urls)
            assert len(body["router"]) == 2
        _run_router(backends, go)

    def test_validation_errors_pass_through(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={"max_tokens": 1})
            assert resp.status == 400  # backend's validator error
            body = await resp.json()
            assert body["error"]["error_type"] == "invalid_request_error"
        _run_router(backends, go)

    def test_router_config_validation(self):
        with pytest.raises(ValueError):
            Router(RouterConfig(backends=[]))
        with pytest.raises(ValueError):
            Router(RouterConfig(backends=["http://x"], strategy="nope"))
