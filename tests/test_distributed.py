"""Multi-host backend tests (SURVEY §5 two-plane design; VERDICT r1:
"DCN / multi-host absent entirely"): the jax.distributed wrapper + hybrid
DCN x ICI mesh (data plane) and the cross-host HTTP router (control
plane), driven against two real in-process backend servers."""

from __future__ import annotations

import asyncio
import json

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import EngineConfig
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.parallel import MeshSpec
from distributed_inference_server_tpu.parallel.distributed import (
    DistributedConfig,
    global_batch_shard,
    hybrid_mesh,
    initialize,
)
from distributed_inference_server_tpu.serving.router import (
    Router,
    RouterConfig,
    build_router_app,
)
from distributed_inference_server_tpu.serving.server import InferenceServer


class TestDataPlane:
    def test_single_process_skips_initialize(self):
        assert initialize(DistributedConfig()) is False
        assert not DistributedConfig().enabled
        assert DistributedConfig(num_processes=4,
                                 coordinator_address="h:1234").enabled

    def test_hybrid_mesh_single_slice_collapses(self):
        mesh = hybrid_mesh(MeshSpec(tensor=2), dcn_spec=MeshSpec(data=4))
        assert mesh.shape["data"] == 4
        assert mesh.shape["tensor"] == 2
        assert mesh.shape["expert"] == 1

    def test_hybrid_mesh_defaults(self):
        mesh = hybrid_mesh(MeshSpec(tensor=4, data=2))
        assert mesh.shape["tensor"] == 4
        assert mesh.shape["data"] == 2

    def test_global_batch_shard_single(self):
        assert global_batch_shard(7) == (7, 0)


_PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)


def _factory():
    import jax

    from distributed_inference_server_tpu.engine.engine import LLMEngine

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(max_batch=2, prefill_buckets=(16,), paged=_PAGED),
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def backends():
    servers = []
    for name in ("host-a", "host-b"):
        srv = InferenceServer(
            _factory, ByteTokenizer(), model_name=name,
            num_engines=1, auto_restart=False,
        )
        srv.start()
        servers.append(srv)
    yield servers
    for srv in servers:
        srv.shutdown(drain_timeout_s=5.0)


def _run_router(backends, coro_fn, **router_kw):
    async def main():
        # two real backend HTTP servers on localhost ports
        test_servers = [TestServer(s.build_app()) for s in backends]
        for ts in test_servers:
            await ts.start_server()
        urls = [str(ts.make_url("/")).rstrip("/") for ts in test_servers]
        router = Router(RouterConfig(
            backends=urls,
            health_check_interval_s=0.2,
            **router_kw,
        ))
        client = TestClient(TestServer(build_router_app(router)))
        await client.start_server()
        try:
            return await coro_fn(client, router, urls)
        finally:
            await client.close()
            for ts in test_servers:
                await ts.close()

    return asyncio.run(main())


class TestRouter:
    def test_v1_alias_via_router(self, backends):
        """The OpenAI /v1 aliases proxy through the router 1:1; the
        backend applies the field translation ("stop" here)."""
        async def go(client, router, urls):
            resp = await client.post("/v1/completions", json={
                "prompt": "hello fleet", "max_tokens": 4,
                "temperature": 0.0, "stop": ["zz_never"],
            })
            assert resp.status == 200
            body = await resp.json()
            assert body["object"] == "text_completion"
            assert body["usage"]["completion_tokens"] == 4
        _run_router(backends, go)

    def test_generate_via_router(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={
                "prompt": "hello fleet", "max_tokens": 6,
                "temperature": 0.0,
            })
            assert resp.status == 200
            body = await resp.json()
            assert body["usage"]["completion_tokens"] == 6
            assert sum(b.total for b in router.backends) == 1
        _run_router(backends, go)

    def test_round_robin_spreads_load(self, backends):
        async def go(client, router, urls):
            for _ in range(4):
                resp = await client.post("/generate", json={
                    "prompt": "spread", "max_tokens": 2,
                    "temperature": 0.0,
                })
                assert resp.status == 200
            counts = sorted(b.total for b in router.backends)
            assert counts == [2, 2]
        _run_router(backends, go, strategy="round_robin")

    def test_sse_stream_passthrough(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={
                "prompt": "stream me", "max_tokens": 4,
                "temperature": 0.0, "stream": True,
            })
            assert resp.status == 200
            assert resp.content_type == "text/event-stream"
            events = []
            async for line in resp.content:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    events.append(json.loads(line[6:]))
            kinds = [e["type"] for e in events]
            # 4 generated tokens arrive as >= 4 token events (the final
            # token is emitted as id + held-back-text flush, same as the
            # direct backend stream) followed by done
            assert kinds.count("token") >= 4
            assert kinds[-1] == "done"
            assert events[-1]["usage"]["completion_tokens"] == 4
        _run_router(backends, go)

    def test_dead_backend_failover(self, backends):
        async def go(client, router, urls):
            # poison one backend with an unreachable address
            router.backends[0].base_url = "http://127.0.0.1:1"
            resp = await client.post("/generate", json={
                "prompt": "failover", "max_tokens": 3,
                "temperature": 0.0,
            })
            assert resp.status == 200  # retried on the healthy backend
            assert not router.backends[0].healthy
            assert router.backends[0].last_error
        _run_router(backends, go)

    def test_all_dead_returns_503(self, backends):
        async def go(client, router, urls):
            for b in router.backends:
                b.healthy = False
            resp = await client.post("/generate", json={
                "prompt": "nope", "max_tokens": 1,
            })
            assert resp.status == 503
            body = await resp.json()
            assert body["error"]["code"] == "no_backend"
        _run_router(backends, go)

    def test_health_aggregation_and_recovery(self, backends):
        async def go(client, router, urls):
            resp = await client.get("/health")
            assert resp.status == 200
            body = await resp.json()
            assert body["status"] == "ok"
            assert len(body["backends"]) == 2
            # mark one unhealthy; the health loop reinstates it
            router.backends[0].healthy = False
            await asyncio.sleep(0.5)
            assert router.backends[0].healthy  # recovered by the loop
        _run_router(backends, go)

    def test_stats_aggregation(self, backends):
        async def go(client, router, urls):
            resp = await client.get("/server/stats")
            assert resp.status == 200
            body = await resp.json()
            assert set(body["backends"]) == set(urls)
            assert len(body["router"]) == 2
        _run_router(backends, go)

    def test_validation_errors_pass_through(self, backends):
        async def go(client, router, urls):
            resp = await client.post("/generate", json={"max_tokens": 1})
            assert resp.status == 400  # backend's validator error
            body = await resp.json()
            assert body["error"]["error_type"] == "invalid_request_error"
        _run_router(backends, go)

    def test_router_config_validation(self):
        with pytest.raises(ValueError):
            Router(RouterConfig(backends=[]))
        with pytest.raises(ValueError):
            Router(RouterConfig(backends=["http://x"], strategy="nope"))


_WORKER_SRC = '''
"""One rank of the two-process jax.distributed smoke test (SURVEY §5:
the comm backend's real multi-process init path, not the single-process
skip). Run: python worker.py <rank> <port>"""
import sys

rank, port = int(sys.argv[1]), sys.argv[2]
import jax

jax.config.update("jax_platforms", "cpu")  # never touch the axon chip

from distributed_inference_server_tpu.parallel.distributed import (
    DistributedConfig,
    global_batch_shard,
    initialize,
    is_coordinator,
    process_count,
)

cfg = DistributedConfig(
    coordinator_address="127.0.0.1:" + port, num_processes=2,
    process_id=rank,
)
assert initialize(cfg), "initialize returned False"
assert initialize(cfg), "second initialize must be idempotent-True"
assert process_count() == 2
assert is_coordinator() == (rank == 0)
assert global_batch_shard(5) == ((3, 0) if rank == 0 else (2, 3))

import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

devs = jax.devices()
assert len(devs) == 2, devs  # global device view spans both processes
mesh = Mesh(np.array(devs), ("data",))
f = jax.jit(jax.shard_map(
    lambda x: lax.psum(x, "data"), mesh=mesh,
    in_specs=P("data"), out_specs=P(),
))
local = jnp.arange(2, dtype=jnp.float32) + 1  # global [1, 2], one per rank
out = np.asarray(f(local))
assert out.tolist() == [3.0], out  # summed ACROSS processes over the wire
print("WORKER%d OK" % rank)
'''


class TestTwoProcessDataPlane:
    def test_real_initialize_and_cross_process_psum(self, tmp_path):
        """Spawn two local CPU processes with a coordinator on localhost:
        ``initialize()`` really runs (not the single-process skip), the
        global device view spans both processes, and a psum over the
        'data' axis completes ACROSS the process boundary (VERDICT r2
        weak #6: multi-host init was the one piece no test executed)."""
        import os
        import socket
        import subprocess
        import sys

        worker = tmp_path / "dist_worker.py"
        worker.write_text(_WORKER_SRC)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(
            os.environ,
            PYTHONPATH=repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        # one local CPU device per process, whatever the suite's XLA_FLAGS
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(r), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env,
            )
            for r in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r} failed:\n{out[-2000:]}"
            assert f"WORKER{r} OK" in out
