"""Hand-rolled protobuf wire codec (serving/protowire.py) vs the REAL
protobuf runtime: dynamic descriptors replicate inference.proto's
representative messages and every encode/decode is cross-checked against
google.protobuf, plus golden wire bytes and the documented JSON-dict
translation rules (tagged-union TokenEvent, lowercase enum strings,
proto3 default filling)."""

from __future__ import annotations

import pytest

from distributed_inference_server_tpu.serving import protowire

descriptor_pb2 = pytest.importorskip("google.protobuf.descriptor_pb2")
from google.protobuf import descriptor_pool, message_factory  # noqa: E402

FD = descriptor_pb2.FieldDescriptorProto


def _field(name, number, ftype, label=FD.LABEL_OPTIONAL, type_name=None,
           proto3_optional=False, oneof_index=None):
    f = FD(name=name, number=number, type=ftype, label=label)
    if type_name:
        f.type_name = type_name
    if proto3_optional:
        f.proto3_optional = True
    if oneof_index is not None:
        f.oneof_index = oneof_index
    return f


@pytest.fixture(scope="module")
def msgs():
    """Dynamic protobuf classes mirroring inference.proto (the subset the
    differential tests use)."""
    fd = descriptor_pb2.FileDescriptorProto()
    fd.name = "inference_diff.proto"
    fd.package = "dis.tpu.test"
    fd.syntax = "proto3"

    role = fd.enum_type.add()
    role.name = "Role"
    for i, n in enumerate(
        ["ROLE_UNSPECIFIED", "SYSTEM", "USER", "ASSISTANT"]
    ):
        role.value.add(name=n, number=i)
    fin = fd.enum_type.add()
    fin.name = "FinishReason"
    for i, n in enumerate(
        ["FINISH_REASON_UNSPECIFIED", "STOP", "LENGTH", "STOP_SEQUENCE"]
    ):
        fin.value.add(name=n, number=i)
    pri = fd.enum_type.add()
    pri.name = "Priority"
    for i, n in enumerate(
        ["PRIORITY_UNSPECIFIED", "LOW", "NORMAL", "HIGH"]
    ):
        pri.value.add(name=n, number=i)

    gen = fd.message_type.add()
    gen.name = "GenerateRequest"
    gen.field.extend([
        _field("prompt", 1, FD.TYPE_STRING),
        _field("max_tokens", 2, FD.TYPE_UINT32, proto3_optional=True,
               oneof_index=0),
        _field("temperature", 3, FD.TYPE_FLOAT, proto3_optional=True,
               oneof_index=1),
        _field("top_p", 4, FD.TYPE_FLOAT, proto3_optional=True,
               oneof_index=2),
        _field("stop_sequences", 5, FD.TYPE_STRING,
               label=FD.LABEL_REPEATED),
        _field("stream", 6, FD.TYPE_BOOL),
        _field("priority", 7, FD.TYPE_ENUM,
               type_name=".dis.tpu.test.Priority", proto3_optional=True,
               oneof_index=3),
    ])
    for i, n in enumerate(
        ["_max_tokens", "_temperature", "_top_p", "_priority"]
    ):
        gen.oneof_decl.add(name=n)

    usage = fd.message_type.add()
    usage.name = "Usage"
    usage.field.extend([
        _field("prompt_tokens", 1, FD.TYPE_UINT32),
        _field("completion_tokens", 2, FD.TYPE_UINT32),
        _field("total_tokens", 3, FD.TYPE_UINT32),
    ])

    choice = fd.message_type.add()
    choice.name = "GenerateChoice"
    choice.field.extend([
        _field("text", 1, FD.TYPE_STRING),
        _field("index", 2, FD.TYPE_UINT32),
        _field("finish_reason", 3, FD.TYPE_ENUM,
               type_name=".dis.tpu.test.FinishReason"),
    ])

    resp = fd.message_type.add()
    resp.name = "GenerateResponse"
    resp.field.extend([
        _field("id", 1, FD.TYPE_STRING),
        _field("object", 2, FD.TYPE_STRING),
        _field("created", 3, FD.TYPE_INT64),
        _field("model", 4, FD.TYPE_STRING),
        _field("choices", 5, FD.TYPE_MESSAGE,
               type_name=".dis.tpu.test.GenerateChoice",
               label=FD.LABEL_REPEATED),
        _field("usage", 6, FD.TYPE_MESSAGE,
               type_name=".dis.tpu.test.Usage"),
    ])

    emb = fd.message_type.add()
    emb.name = "EmbeddingData"
    emb.field.extend([
        _field("object", 1, FD.TYPE_STRING),
        _field("embedding", 2, FD.TYPE_FLOAT, label=FD.LABEL_REPEATED),
        _field("index", 3, FD.TYPE_UINT32),
    ])

    tok = fd.message_type.add()
    tok.name = "TokenEvent"
    inner_tok = tok.nested_type.add()
    inner_tok.name = "Token"
    inner_tok.field.extend([
        _field("token", 1, FD.TYPE_STRING),
        _field("index", 2, FD.TYPE_UINT32),
        _field("logprob", 3, FD.TYPE_FLOAT, proto3_optional=True,
               oneof_index=0),
    ])
    inner_tok.oneof_decl.add(name="_logprob")
    inner_done = tok.nested_type.add()
    inner_done.name = "Done"
    inner_done.field.extend([
        _field("finish_reason", 1, FD.TYPE_ENUM,
               type_name=".dis.tpu.test.FinishReason"),
        _field("usage", 2, FD.TYPE_MESSAGE,
               type_name=".dis.tpu.test.Usage"),
    ])
    tok.field.extend([
        _field("token", 1, FD.TYPE_MESSAGE,
               type_name=".dis.tpu.test.TokenEvent.Token", oneof_index=0),
        _field("done", 2, FD.TYPE_MESSAGE,
               type_name=".dis.tpu.test.TokenEvent.Done", oneof_index=0),
    ])
    tok.oneof_decl.add(name="event")

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fd)
    names = ["GenerateRequest", "Usage", "GenerateChoice",
             "GenerateResponse", "EmbeddingData", "TokenEvent"]
    return {
        n: message_factory.GetMessageClass(
            pool.FindMessageTypeByName(f"dis.tpu.test.{n}")
        )
        for n in names
    }


class TestGoldenBytes:
    def test_simple_request_bytes(self):
        data = protowire.encode(
            "GenerateRequest", {"prompt": "hi", "max_tokens": 4}
        )
        # field 1 LEN "hi", field 2 VARINT 4
        assert data == b"\x0a\x02hi\x10\x04"

    def test_zero_scalars_stay_off_the_wire(self):
        assert protowire.encode("GenerateChoice",
                                {"text": "", "index": 0}) == b""
        assert protowire.encode("HealthRequest", {}) == b""

    def test_explicit_zero_on_optional_fields_is_emitted(self):
        # temperature 0 (greedy) must survive the wire — proto3 optional
        data = protowire.encode("GenerateRequest", {"temperature": 0.0})
        assert data == b"\x1d\x00\x00\x00\x00"  # field 3 FIXED32 0.0
        back = protowire.decode("GenerateRequest", data)
        assert back["temperature"] == 0.0
        # and an ABSENT optional stays absent (server default applies)
        assert "temperature" not in protowire.decode("GenerateRequest",
                                                     b"")


class TestDifferentialVsProtobufRuntime:
    """Bytes produced by protowire parse identically in google.protobuf
    and vice versa."""

    def test_request_roundtrip_through_runtime(self, msgs):
        obj = {"prompt": "héllo", "max_tokens": 32, "temperature": 0.5,
               "top_p": 0.9, "stop_sequences": ["\n", "###"],
               "stream": True, "priority": "high"}
        mine = protowire.encode("GenerateRequest", obj)
        theirs = msgs["GenerateRequest"].FromString(mine)
        assert theirs.prompt == "héllo"
        assert theirs.max_tokens == 32
        assert abs(theirs.temperature - 0.5) < 1e-6
        assert list(theirs.stop_sequences) == ["\n", "###"]
        assert theirs.stream is True
        assert theirs.priority == 3  # HIGH
        # runtime-serialized bytes decode to the same dict
        back = protowire.decode("GenerateRequest",
                                theirs.SerializeToString())
        assert back["prompt"] == "héllo"
        assert back["priority"] == "high"
        assert back["stop_sequences"] == ["\n", "###"]

    def test_response_with_nested_and_int64(self, msgs):
        obj = {
            "id": "cmpl-x", "object": "text_completion",
            "created": 1785450006, "model": "tiny",
            "choices": [
                {"text": "a", "index": 0, "finish_reason": "length"},
                {"text": "b", "index": 1, "finish_reason": "stop"},
            ],
            "usage": {"prompt_tokens": 3, "completion_tokens": 2,
                      "total_tokens": 5},
        }
        mine = protowire.encode("GenerateResponse", obj)
        theirs = msgs["GenerateResponse"].FromString(mine)
        assert theirs.created == 1785450006
        assert [c.text for c in theirs.choices] == ["a", "b"]
        assert theirs.choices[1].finish_reason == 1  # STOP
        assert theirs.usage.total_tokens == 5
        back = protowire.decode("GenerateResponse",
                                theirs.SerializeToString())
        assert back == obj

    def test_packed_floats_both_directions(self, msgs):
        obj = {"object": "embedding",
               "embedding": [0.0, 1.5, -2.25], "index": 7}
        mine = protowire.encode("EmbeddingData", obj)
        theirs = msgs["EmbeddingData"].FromString(mine)
        assert list(theirs.embedding) == [0.0, 1.5, -2.25]
        back = protowire.decode("EmbeddingData",
                                theirs.SerializeToString())
        assert back == obj

    def test_token_event_oneof(self, msgs):
        ev = {"type": "token", "token": "x", "index": 3,
              "logprob": -1.25}
        mine = protowire.encode("TokenEvent", ev)
        theirs = msgs["TokenEvent"].FromString(mine)
        assert theirs.WhichOneof("event") == "token"
        assert theirs.token.index == 3
        assert abs(theirs.token.logprob + 1.25) < 1e-6
        assert protowire.decode("TokenEvent",
                                theirs.SerializeToString()) == ev

        done = {"type": "done", "finish_reason": "stop",
                "usage": {"prompt_tokens": 1, "completion_tokens": 2,
                          "total_tokens": 3}}
        mine = protowire.encode("TokenEvent", done)
        theirs = msgs["TokenEvent"].FromString(mine)
        assert theirs.WhichOneof("event") == "done"
        assert protowire.decode("TokenEvent",
                                theirs.SerializeToString()) == done

    def test_logprob_absence_is_presence_not_zero(self, msgs):
        ev = {"type": "token", "token": "x", "index": 0}
        decoded = protowire.decode("TokenEvent",
                                   protowire.encode("TokenEvent", ev))
        assert "logprob" not in decoded
        # logprob 0.0 is a legal value distinct from absent
        ev0 = {"type": "token", "token": "x", "index": 0, "logprob": 0.0}
        assert protowire.decode(
            "TokenEvent", protowire.encode("TokenEvent", ev0)
        )["logprob"] == 0.0


class TestDecodeRobustness:
    def test_unknown_fields_skipped(self):
        # append an unknown field 99 (varint) to a valid message
        data = protowire.encode("Usage", {"prompt_tokens": 1})
        unknown = protowire._key(99, 0) + protowire._enc_varint(7)
        back = protowire.decode("Usage", data + unknown)
        assert back["prompt_tokens"] == 1

    def test_defaults_filled_for_responses(self):
        back = protowire.decode("GenerateChoice", b"")
        assert back == {"text": "", "index": 0, "finish_reason": None}
        assert protowire.decode("EmbeddingData", b"")["embedding"] == []

    def test_truncated_payload_raises(self):
        with pytest.raises(Exception):
            protowire.decode("Usage", b"\x08")  # key then no varint

    def test_unpacked_scalars_accepted(self):
        # some encoders emit repeated scalars unpacked; decode accepts
        import struct

        data = (protowire._key(2, 5) + struct.pack("<f", 1.0)
                + protowire._key(2, 5) + struct.pack("<f", 2.0))
        back = protowire.decode("EmbeddingData", data)
        assert back["embedding"] == [1.0, 2.0]
