"""Differential tests: native C++ components vs the canonical Python tier.

The same randomized operation sequences drive both implementations; every
observable (returned values, depths, stats, backpressure state) must be
identical. This is the conformance story for the native serving layer —
the Python modules carry the reference-derived property tests, and these
prove the C++ twins behave identically."""

import random

import pytest

from distributed_inference_server_tpu import native
from distributed_inference_server_tpu.core.errors import CacheFull, QueueFull
from distributed_inference_server_tpu.core.queue import (
    PriorityQueueManager,
    QueueConfig,
    QueuedRequest,
)
from distributed_inference_server_tpu.core.types import Priority
from distributed_inference_server_tpu.engine.kv_cache import (
    PageAllocator,
    PagedCacheConfig,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _req(i: int, prio: Priority, t: float):
    return QueuedRequest(id=f"r{i}", data=i, priority=prio, enqueued_at=t)


def test_queue_differential_random_ops():
    cfg = QueueConfig(high_watermark=30, low_watermark=15,
                      request_timeout_s=10.0, max_queue_size=60)
    py = PriorityQueueManager(cfg)
    cc = native.NativePriorityQueue(cfg)
    rnd = random.Random(0)
    now = 0.0
    seq = 0
    for _ in range(3000):
        op = rnd.random()
        now += rnd.random() * 0.5
        if op < 0.45:
            seq += 1
            prio = rnd.choice(list(Priority))
            r1 = _req(seq, prio, now)
            r2 = _req(seq, prio, now)
            outcomes = []
            for q, r in ((py, r1), (cc, r2)):
                try:
                    q.enqueue(r)
                    outcomes.append("ok")
                except QueueFull:
                    outcomes.append("full")
            assert outcomes[0] == outcomes[1], f"enqueue diverged at {seq}"
        elif op < 0.7:
            n = rnd.randint(1, 8)
            a = [r.id for r in py.dequeue_batch(n)]
            b = [r.id for r in cc.dequeue_batch(n)]
            assert a == b
        elif op < 0.8:
            a = py.dequeue_one()
            b = cc.dequeue_one()
            assert (a.id if a else None) == (b.id if b else None)
        elif op < 0.9:
            a = sorted(r.id for r in py.remove_expired(now))
            b = sorted(r.id for r in cc.remove_expired(now))
            assert a == b
        else:
            victim = f"r{rnd.randint(max(1, seq - 20), seq + 1)}"
            a = py.cancel(victim)
            b = cc.cancel(victim)
            assert (a.id if a else None) == (b.id if b else None)
        assert py.queue_depth() == cc.queue_depth()
        assert py.is_accepting() == cc.is_accepting()


def test_queue_backpressure_hysteresis_native():
    """Property 7 directly against the native queue."""
    cfg = QueueConfig(high_watermark=10, low_watermark=5,
                      request_timeout_s=30.0, max_queue_size=100)
    q = native.NativePriorityQueue(cfg)
    for i in range(10):
        q.enqueue(_req(i, Priority.NORMAL, 0.0))
    assert q.is_accepting()  # at watermark, not above
    q.enqueue(_req(99, Priority.NORMAL, 0.0))  # 11 > 10
    assert not q.is_accepting()  # crossed high watermark
    with pytest.raises(QueueFull):
        q.enqueue(_req(100, Priority.NORMAL, 0.0))
    while q.total_depth() >= 5:
        q.dequeue_one()
    assert q.is_accepting()  # released below low watermark


def test_allocator_differential_random_ops():
    cfg = PagedCacheConfig(num_pages=24, page_size=4, max_pages_per_seq=8)
    py = PageAllocator(cfg)
    cc = native.NativePageAllocator(cfg)
    rnd = random.Random(1)
    # sequences: token list + page ids currently held, mirrored across impls
    held_py = []  # list of (tokens, pages)
    held_cc = []
    for step in range(2000):
        op = rnd.random()
        if op < 0.35:  # admit a sequence: match prefix then allocate rest
            n_tokens = rnd.randint(1, 28)
            tokens = [rnd.randint(0, 5) for _ in range(n_tokens)]
            res = []
            for impl, held in ((py, held_py), (cc, held_cc)):
                shared, matched = impl.match_prefix(tokens)
                needed = -(-(n_tokens) // cfg.page_size) - len(shared)
                try:
                    fresh = impl.allocate(needed)
                    impl.publish(tokens, shared + fresh)
                    held.append((tokens, shared + fresh))
                    res.append(("ok", shared, matched, fresh))
                except CacheFull:
                    impl.release(shared)
                    res.append(("full", shared, matched, None))
            assert res[0] == res[1], f"admit diverged at step {step}"
        elif op < 0.75 and held_py:  # finish a sequence
            i = rnd.randrange(len(held_py))
            _, pages_py = held_py.pop(i)
            _, pages_cc = held_cc.pop(i)
            py.release(pages_py)
            cc.release(pages_cc)
        elif op < 0.85 and held_py:  # touch
            i = rnd.randrange(len(held_py))
            py.touch(held_py[i][1])
            cc.touch(held_cc[i][1])
        elif op < 0.95:
            frac = rnd.random()
            assert py.evict_below(frac) == cc.evict_below(frac)
        else:
            assert py.num_free() == cc.num_free()
        s_py, s_cc = py.stats(), cc.stats()
        assert (s_py.hits, s_py.misses, s_py.evictions, s_py.pages_free,
                s_py.pages_cached) == (
            s_cc.hits, s_cc.misses, s_cc.evictions, s_cc.pages_free,
            s_cc.pages_cached,
        ), f"stats diverged at step {step}"


def test_allocator_prefix_reuse_native():
    """Property 9 against the native allocator: identical prompts share
    full pages."""
    cfg = PagedCacheConfig(num_pages=16, page_size=4, max_pages_per_seq=8)
    a = native.NativePageAllocator(cfg)
    tokens = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    shared, matched = a.match_prefix(tokens)
    assert (shared, matched) == ([], 0)
    fresh = a.allocate(3)
    a.publish(tokens, fresh)
    shared2, matched2 = a.match_prefix(tokens)
    assert shared2 == fresh[:2]  # two FULL pages (8 of 9 tokens)
    assert matched2 == 8
    a.release(shared2)
    a.release(fresh)
    # all pages released -> cached, reclaimable
    assert a.num_free() == cfg.num_pages


def test_engine_runs_on_native_allocator():
    """End-to-end: the continuous-batching engine with the native page
    allocator produces the same tokens as with the Python allocator."""
    import jax
    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    tok = ByteTokenizer()
    results = {}
    for use_native in (False, True):
        eng = LLMEngine(
            params, TINY, tok,
            EngineConfig(
                max_batch=2, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=32, page_size=4,
                                       max_pages_per_seq=8),
                native_allocator=use_native,
            ),
            dtype=jnp.float32,
        )
        assert ("Native" in type(eng.allocator).__name__) == use_native
        eng.add_request("r", tok.encode("native!"),
                        SamplingParams(max_tokens=8, temperature=0.0))
        toks = []
        while eng.has_work():
            for o in eng.step():
                if o.token_id is not None:
                    toks.append(o.token_id)
        results[use_native] = toks
    assert results[True] == results[False]
    assert len(results[True]) == 8


# ---------------------------------------------------------------------------
# admission batcher (native/batcher.cpp vs serving/batcher.py)
# ---------------------------------------------------------------------------


def _batcher_pair(window_ms=50.0, max_batch=4, qcfg=None):
    from distributed_inference_server_tpu.serving.batcher import (
        AdmissionBatcher,
        BatcherConfig,
    )

    qcfg = qcfg or QueueConfig(high_watermark=100, low_watermark=50,
                               request_timeout_s=60.0, max_queue_size=200)
    bcfg = BatcherConfig(window_ms=window_ms, max_batch_size=max_batch)
    pyq = PriorityQueueManager(qcfg)
    ccq = native.NativePriorityQueue(qcfg)
    return (
        (pyq, AdmissionBatcher(pyq, bcfg)),
        (ccq, native.NativeAdmissionBatcher(ccq, bcfg)),
    )


def _ids(batch):
    return [r.id for r in batch.requests] if batch else None


def test_batcher_differential_random_ops():
    (pyq, pyb), (ccq, ccb) = _batcher_pair()
    rnd = random.Random(7)
    now = 0.0
    seq = 0
    for _ in range(2000):
        op = rnd.random()
        now += rnd.random() * 0.02
        if op < 0.5:
            seq += 1
            prio = rnd.choice(list(Priority))
            pyq.enqueue(_req(seq, prio, now))
            ccq.enqueue(_req(seq, prio, now))
        elif op < 0.85:
            assert _ids(pyb.poll(now)) == _ids(ccb.poll(now)), \
                f"poll diverged at step {seq}"
            assert pyb.pending_count() == ccb.pending_count()
        elif op < 0.95 and seq:
            rid = f"r{rnd.randint(max(1, seq - 5), seq)}"
            got = (pyb.cancel(rid) is not None,
                   ccb.cancel(rid) is not None)
            assert got[0] == got[1], f"cancel diverged on {rid}"
        else:
            assert _ids(pyb.flush(now)) == _ids(ccb.flush(now))
    assert _ids(pyb.flush(now)) == _ids(ccb.flush(now))


def test_batcher_window_expiry_native():
    (_, _), (ccq, ccb) = _batcher_pair(window_ms=50.0, max_batch=8)
    ccq.enqueue(_req(1, Priority.NORMAL, 0.0))
    assert ccb.poll(0.0) is None  # window opens, not expired
    assert ccb.pending_count() == 1
    assert ccb.poll(0.049) is None
    batch = ccb.poll(0.051)  # 51ms >= 50ms window
    assert _ids(batch) == ["r1"]
    assert ccb.pending_count() == 0


def test_batcher_size_dispatch_and_priority_order_native():
    (_, _), (ccq, ccb) = _batcher_pair(max_batch=3)
    ccq.enqueue(_req(1, Priority.LOW, 0.0))
    ccq.enqueue(_req(2, Priority.HIGH, 0.0))
    ccq.enqueue(_req(3, Priority.NORMAL, 0.0))
    batch = ccb.poll(0.0)  # size cap reached -> immediate dispatch
    assert _ids(batch) == ["r2", "r3", "r1"]  # strict priority order


def test_batcher_divisor_and_hot_reload_native():
    from distributed_inference_server_tpu.serving.batcher import BatcherConfig

    (_, _), (ccq, ccb) = _batcher_pair(max_batch=4)
    ccb.size_divisor = 2  # degradation ladder: effective cap 2
    for i in range(1, 4):
        ccq.enqueue(_req(i, Priority.NORMAL, 0.0))
    assert _ids(ccb.poll(0.0)) == ["r1", "r2"]
    ccb.size_divisor = 1
    ccb.config = BatcherConfig(window_ms=1.0, max_batch_size=4)
    assert ccb.poll(0.0) is None  # r3 pending, window reopened
    assert _ids(ccb.poll(0.01)) == ["r3"]  # 10ms >= 1ms window


def test_dispatcher_uses_native_batcher_with_native_queue():
    from distributed_inference_server_tpu.serving.dispatcher import (
        _make_batcher,
        _make_queue,
    )

    q = _make_queue(None, True)
    b = _make_batcher(q, None)
    assert isinstance(b, native.NativeAdmissionBatcher)
    q2 = _make_queue(None, False)
    b2 = _make_batcher(q2, None)
    from distributed_inference_server_tpu.serving.batcher import (
        AdmissionBatcher,
    )

    assert isinstance(b2, AdmissionBatcher)


# ---------------------------------------------------------------------------
# race detection (SURVEY §5): TSan-instrumented native stress harness
# ---------------------------------------------------------------------------


def _run_stress(target: str, env_extra=None):
    import os
    import subprocess

    d = os.path.dirname(os.path.abspath(native.__file__))
    build = subprocess.run(["make", "-C", d, target],
                           capture_output=True, timeout=300)
    if build.returncode != 0:
        pytest.skip(f"{target} build unavailable: "
                    f"{build.stderr.decode()[-200:]}")
    env = dict(os.environ, **(env_extra or {}))
    run = subprocess.run([os.path.join(d, target)], capture_output=True,
                         timeout=600, env=env)
    assert run.returncode == 0, (
        f"{target} failed:\n{run.stdout.decode()[-1000:]}\n"
        f"{run.stderr.decode()[-3000:]}"
    )
    assert b"stress OK" in run.stdout


def test_native_stress_tsan():
    """The whole native tier (queue + batcher + allocator) hammered from
    concurrent threads under ThreadSanitizer; any data race aborts."""
    _run_stress("stress_tsan", {"TSAN_OPTIONS": "halt_on_error=1"})


def test_native_stress_plain():
    _run_stress("stress_plain")


# ---------------------------------------------------------------------------
# validator (native/validator.cpp vs core/validator.py)
# ---------------------------------------------------------------------------


def _validators():
    from distributed_inference_server_tpu.core.validator import (
        RequestValidator,
        ValidatorConfig,
    )

    cfg = ValidatorConfig(max_context_tokens=64, max_output_tokens=32)
    return RequestValidator(cfg), native.NativeRequestValidator(cfg)


def _outcome(fn, req):
    try:
        return ("ok", type(fn(req).into_inner()).__name__)
    except Exception as e:  # compared by type AND message
        return (type(e).__name__, str(e))


def test_validator_differential_generate():
    from distributed_inference_server_tpu.core.models import GenerateRequest

    py, nat = _validators()
    rng = random.Random(7)
    texts = [
        "", " ", "\t\n", "ok", "x" * 255, "x" * 256, "x" * 257, "x" * 1000,
        "héllo wörld", "　", "    ", "a b", "🙂" * 70,
        "mixed 🙂 ascii and ünïcode",
    ]
    for _ in range(300):
        req = GenerateRequest(
            prompt=rng.choice(texts),
            max_tokens=rng.choice([-1, 0, 1, 32, 33, 4096]),
            temperature=rng.choice([-0.1, 0.0, 1.0, 2.0, 2.1]),
            top_p=rng.choice([-0.1, 0.0, 0.5, 1.0, 1.01]),
        )
        assert _outcome(py.validate_generate, req) == _outcome(
            nat.validate_generate, req
        ), req


def test_validator_differential_chat_and_embeddings():
    from distributed_inference_server_tpu.core.models import (
        ChatMessage,
        ChatRequest,
        EmbeddingsRequest,
        Role,
    )

    py, nat = _validators()
    rng = random.Random(11)
    contents = ["", "  ", "hello", "x" * 200, "ü" * 100, "　 "]
    for _ in range(200):
        msgs = [
            ChatMessage(role=Role.USER, content=rng.choice(contents))
            for _ in range(rng.randint(0, 4))
        ]
        req = ChatRequest(
            messages=msgs,
            max_tokens=rng.choice([1, 32, 64]),
            temperature=rng.choice([0.0, 1.0, 3.0]),
            top_p=1.0,
        )
        assert _outcome(py.validate_chat, req) == _outcome(
            nat.validate_chat, req
        ), req
    for _ in range(200):
        n = rng.randint(0, 4)
        inputs = [rng.choice(contents) for _ in range(n)]
        req = EmbeddingsRequest(input=inputs if n != 1 else inputs[0])
        assert _outcome(py.validate_embeddings, req) == _outcome(
            nat.validate_embeddings, req
        ), req


def test_validator_token_count_parity_unicode():
    py, nat = _validators()
    for s in ["", "a", "abc", "abcd", "abcde", "héllo", "🙂" * 9,
              "　" * 7, "mixed 🙂 text"]:
        assert py.token_count(s) == nat.token_count(s), s


def test_server_uses_native_validator_when_available():
    from distributed_inference_server_tpu.native import make_validator

    v = make_validator()
    assert type(v).__name__ == "NativeRequestValidator"


def test_validator_huge_max_tokens_not_wrapped():
    """ctypes c_int64 wraps out-of-range ints silently; a 2^64+32
    max_tokens must still be rejected exactly like the Python tier."""
    from distributed_inference_server_tpu.core.models import GenerateRequest

    py, nat = _validators()
    req = GenerateRequest(prompt="ok", max_tokens=2**64 + 32)
    assert _outcome(py.validate_generate, req) == _outcome(
        nat.validate_generate, req
    )


def test_validator_lone_surrogate_delegates():
    """json.loads produces lone-surrogate strings; UTF-8 encoding fails,
    so the native tier must delegate instead of raising
    UnicodeEncodeError (which the HTTP error middleware can't map)."""
    from distributed_inference_server_tpu.core.models import GenerateRequest

    py, nat = _validators()
    req = GenerateRequest(prompt="\ud800 hello", max_tokens=4)
    assert _outcome(py.validate_generate, req) == _outcome(
        nat.validate_generate, req
    )
