"""Unit/property tests for the serving spine's pure logic: admission
batcher (Properties 4-5), scheduler strategies (Properties 16-20),
dispatcher sweep/backpressure (Properties 7-8 at the serving boundary),
and SSE encoding (Properties 13-15 wire format).

Mirrors the reference's test strategy (SURVEY.md §4): property-based where
the spec gives a property, deterministic clocks everywhere.
"""

from __future__ import annotations

import time
from typing import List, Optional

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core.errors import QueueFull
from distributed_inference_server_tpu.core.models import FinishReason, TokenEvent, Usage
from distributed_inference_server_tpu.core.queue import (
    PriorityQueueManager,
    QueueConfig,
    QueuedRequest,
)
from distributed_inference_server_tpu.core.types import Priority
from distributed_inference_server_tpu.engine.engine import SamplingParams
from distributed_inference_server_tpu.serving.batcher import (
    AdmissionBatcher,
    BatcherConfig,
)
from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.scheduler import (
    AdaptiveScheduler,
    SchedulingStrategy,
    choose_engine,
)
from distributed_inference_server_tpu.serving.streamer import sse_encode


class RecordingSink:
    def __init__(self) -> None:
        self.tokens: List[str] = []
        self.done: Optional[FinishReason] = None
        self.usage: Optional[Usage] = None
        self.errors: List[tuple] = []

    def on_token(self, token_id, text, token_index, logprob=None) -> None:
        self.tokens.append(text)

    def on_done(self, finish_reason, usage) -> None:
        self.done = finish_reason
        self.usage = usage

    def on_error(self, message, code) -> None:
        self.errors.append((message, code))


def _req(rid: str = "r") -> ServerRequest:
    return ServerRequest(rid, [1, 2, 3], SamplingParams(), RecordingSink())


# ---------------------------------------------------------------------------
# Admission batcher — Properties 4-5 (design.md:704-714 [spec])
# ---------------------------------------------------------------------------


class TestAdmissionBatcher:
    def _mk(self, window_ms=50.0, max_batch=4):
        q: PriorityQueueManager = PriorityQueueManager(
            QueueConfig(high_watermark=10_000, low_watermark=5_000,
                        max_queue_size=20_000)
        )
        b = AdmissionBatcher(q, BatcherConfig(window_ms=window_ms,
                                              max_batch_size=max_batch))
        return q, b

    def test_size_trigger_dispatches_immediately(self):
        q, b = self._mk(window_ms=1e9, max_batch=4)
        t = 100.0
        for i in range(4):
            q.enqueue(QueuedRequest(id=f"r{i}", data=i))
        batch = b.poll(t)
        assert batch is not None and len(batch) == 4

    def test_window_trigger(self):
        q, b = self._mk(window_ms=50.0, max_batch=32)
        q.enqueue(QueuedRequest(id="r0", data=0))
        assert b.poll(100.0) is None  # window opens
        assert b.poll(100.049) is None
        batch = b.poll(100.051)
        assert batch is not None and len(batch) == 1

    def test_window_anchored_to_first_request(self):
        """A late-arriving request does not reset the window (Property 5:
        max one window of wait)."""
        q, b = self._mk(window_ms=50.0, max_batch=32)
        q.enqueue(QueuedRequest(id="r0", data=0))
        assert b.poll(100.0) is None
        q.enqueue(QueuedRequest(id="r1", data=1))
        assert b.poll(100.03) is None
        batch = b.poll(100.0501)
        assert batch is not None and len(batch) == 2

    def test_priority_order_within_batch(self):
        q, b = self._mk(window_ms=0.0, max_batch=10)
        q.enqueue(QueuedRequest(id="low", data=0, priority=Priority.LOW))
        q.enqueue(QueuedRequest(id="high", data=1, priority=Priority.HIGH))
        q.enqueue(QueuedRequest(id="norm", data=2, priority=Priority.NORMAL))
        batch = b.poll(1.0)
        assert [r.id for r in batch.requests] == ["high", "norm", "low"]

    @settings(max_examples=100, deadline=None)
    @given(
        n=st.integers(min_value=0, max_value=100),
        max_batch=st.integers(min_value=1, max_value=32),
    )
    def test_property4_batch_size_bounds(self, n: int, max_batch: int):
        """Property 4: every dispatched batch has 1 <= size <=
        max_batch_size (design.md:704-708 [spec])."""
        q, b = self._mk(window_ms=0.0, max_batch=max_batch)
        for i in range(n):
            q.enqueue(QueuedRequest(id=f"r{i}", data=i))
        seen = 0
        t = 0.0
        while True:
            batch = b.poll(t)
            t += 1.0
            if batch is None:
                break
            assert 1 <= len(batch) <= max_batch
            seen += len(batch)
        assert seen == n

    def test_flush_drains_pending(self):
        q, b = self._mk(window_ms=1e9, max_batch=32)
        q.enqueue(QueuedRequest(id="r0", data=0))
        assert b.poll(10.0) is None
        batch = b.flush(11.0)
        assert batch is not None and len(batch) == 1
        assert b.flush(12.0) is None


# ---------------------------------------------------------------------------
# Scheduler strategy core — Properties 16-20 (design.md:776-804 [spec])
# ---------------------------------------------------------------------------


def _status(eid, healthy=True, active=0, waiting=0, used=0, total=100):
    return EngineStatus(
        engine_id=eid, healthy=healthy, active_requests=active,
        waiting_requests=waiting, total_processed=0,
        memory_used_pages=used, memory_total_pages=total,
    )


_status_strategy = st.builds(
    _status,
    eid=st.sampled_from(["e0", "e1", "e2", "e3"]),
    healthy=st.booleans(),
    active=st.integers(0, 50),
    waiting=st.integers(0, 50),
    used=st.integers(0, 100),
)


class TestChooseEngine:
    @settings(max_examples=100, deadline=None)
    @given(
        statuses=st.lists(
            _status_strategy, max_size=6, unique_by=lambda s: s.engine_id
        ),
        strategy=st.sampled_from(list(SchedulingStrategy)),
        rr=st.integers(0, 1000),
    )
    def test_property16_only_healthy_selected(self, statuses, strategy, rr):
        """Property 16 precondition shared by every strategy: routing only
        ever selects healthy engines (design.md:776-780 [spec])."""
        chosen = choose_engine(strategy, statuses, rr)
        if chosen is None:
            assert not any(s.healthy for s in statuses)
        else:
            assert any(s.engine_id == chosen and s.healthy for s in statuses)

    @settings(max_examples=100, deadline=None)
    @given(
        statuses=st.lists(
            _status_strategy, min_size=1, max_size=6,
            unique_by=lambda s: s.engine_id,
        ),
        rr=st.integers(0, 1000),
    )
    def test_property17_least_loaded_minimal(self, statuses, rr):
        """Property 16 (least-loaded routes to min active batches) with
        Property 17's memory-aware variant covered below
        (design.md:776-786 [spec])."""
        chosen = choose_engine(SchedulingStrategy.LEAST_LOADED, statuses, rr)
        healthy = [s for s in statuses if s.healthy]
        if healthy:
            min_load = min(s.active_requests + s.waiting_requests for s in healthy)
            load = {
                s.engine_id: s.active_requests + s.waiting_requests
                for s in healthy
            }
            assert load[chosen] == min_load

    def test_round_robin_rotates(self):
        statuses = [_status("e0"), _status("e1"), _status("e2")]
        picks = [
            choose_engine(SchedulingStrategy.ROUND_ROBIN, statuses, i)
            for i in range(6)
        ]
        assert picks == ["e0", "e1", "e2", "e0", "e1", "e2"]

    def test_memory_aware_prefers_free_pages(self):
        """Property 17: memory-aware routing picks the engine with the
        most available KV pages (design.md:782-786 [spec])."""
        statuses = [
            _status("full", used=90, total=100),
            _status("empty", used=10, total=100),
        ]
        assert (
            choose_engine(SchedulingStrategy.MEMORY_AWARE, statuses, 0) == "empty"
        )

    def test_property20_no_healthy_none(self):
        """Property 20's graceful-failure edge: with zero healthy engines
        every strategy returns None instead of crashing (the spawn-N side
        of Property 20 is covered by the server scale tests,
        design.md:800-804 [spec])."""
        statuses = [_status("e0", healthy=False), _status("e1", healthy=False)]
        for strat in SchedulingStrategy:
            assert choose_engine(strat, statuses, 0) is None


class _FakeRunner:
    """Minimal EngineRunner stand-in for routing/health-loop tests."""

    def __init__(self, eid: str):
        self.engine_id = eid
        self.healthy = True
        self.restarts = 0

    def status(self):
        return _status(self.engine_id, healthy=self.healthy)

    def is_healthy(self):
        return self.healthy

    def restart(self, wait_ready=True):
        self.restarts += 1
        self.healthy = True


class TestAdaptiveScheduler:
    def test_property18_unhealthy_removed_from_routing(self):
        """Property 18: an engine that fails its health check leaves the
        routing pool — no new batch is ever routed to it
        (design.md:788-792 [spec])."""
        s = AdaptiveScheduler(SchedulingStrategy.ROUND_ROBIN)
        good, bad = _FakeRunner("good"), _FakeRunner("bad")
        s.register(good)
        s.register(bad)
        bad.healthy = False
        picks = {s.schedule().engine_id for _ in range(8)}
        assert picks == {"good"}

    def test_property19_recovered_engine_reinstated(self):
        """Property 19: a previously unhealthy engine that passes its
        health check again is eligible for routing (design.md:794-798
        [spec]). The health loop's auto-restart is what brings it back."""
        s = AdaptiveScheduler(
            SchedulingStrategy.ROUND_ROBIN,
            health_check_interval_s=0.01,
            auto_restart=True,
        )
        r = _FakeRunner("solo")
        s.register(r)
        r.healthy = False
        assert s.schedule() is None  # removed while unhealthy
        s.start_health_loop()
        try:
            deadline = time.monotonic() + 5.0
            while r.restarts == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            s.stop_health_loop()
        assert r.restarts >= 1
        picked = s.schedule()
        assert picked is not None and picked.engine_id == "solo"

    def test_runtime_strategy_switch(self):
        s = AdaptiveScheduler(SchedulingStrategy.ROUND_ROBIN)
        assert s.strategy() is SchedulingStrategy.ROUND_ROBIN
        s.set_strategy(SchedulingStrategy.MEMORY_AWARE)
        assert s.strategy() is SchedulingStrategy.MEMORY_AWARE

    def test_schedule_empty_returns_none(self):
        assert AdaptiveScheduler().schedule() is None


# ---------------------------------------------------------------------------
# Dispatcher — backpressure (503) and timeout sweep (408)
# ---------------------------------------------------------------------------


class TestDispatcher:
    def test_backpressure_raises_queue_full(self):
        d = Dispatcher(
            AdaptiveScheduler(),
            queue_config=QueueConfig(high_watermark=2, low_watermark=1,
                                     max_queue_size=10),
        )
        d._accepting = True
        d.submit(_req("a"))
        d.submit(_req("b"))
        d.submit(_req("c"))  # total 3 > high watermark → backpressure on
        try:
            d.submit(_req("d"))
            assert False, "expected QueueFull"
        except QueueFull:
            pass

    def test_not_accepting_raises_queue_full(self):
        d = Dispatcher(AdaptiveScheduler())
        try:
            d.submit(_req())
            assert False, "expected QueueFull"
        except QueueFull:
            pass

    def test_sweep_expires_to_queue_timeout(self):
        """Expired queued requests resolve with the DISTINCT
        queue_timeout code (not a generic failure) and count into
        requests_expired_total (ISSUE 6 satellite)."""
        m = MetricsCollector()
        d = Dispatcher(
            AdaptiveScheduler(),
            queue_config=QueueConfig(request_timeout_s=5.0),
            metrics=m,
        )
        d._accepting = True
        r = _req("victim")
        d.submit(r)
        d._sweep(time.monotonic() + 10.0)
        assert len(r.sink.errors) == 1
        assert r.sink.errors[0][1] == "queue_timeout"
        assert d.queue.is_empty()
        snap = m.snapshot().to_dict()
        assert snap["resilience"]["requests_expired"] == 1
        assert b"requests_expired_total 1.0" in m.prometheus_text()

    def test_sweep_not_expired_no_error(self):
        d = Dispatcher(
            AdaptiveScheduler(),
            queue_config=QueueConfig(request_timeout_s=5.0),
            metrics=MetricsCollector(),
        )
        d._accepting = True
        r = _req("fresh")
        d.submit(r)
        d._sweep(time.monotonic())
        assert r.sink.errors == []
        assert not d.queue.is_empty()

    def test_dispatch_without_engines_fails_batch(self):
        d = Dispatcher(AdaptiveScheduler(), metrics=MetricsCollector())
        r = _req()
        d._dispatch([QueuedRequest(id=r.request_id, data=r)])
        assert r.sink.errors and r.sink.errors[0][1] == "no_workers"

    def test_abort_cancels_queued(self):
        d = Dispatcher(AdaptiveScheduler())
        d._accepting = True
        r = _req("gone")
        d.submit(r)
        d.abort("gone")
        assert d.queue.is_empty()

    def test_abort_cancels_batcher_pending(self):
        """A request already pulled into the batching window is still
        abortable (Req 5.4 between dequeue and dispatch)."""
        d = Dispatcher(
            AdaptiveScheduler(),
            batcher_config=BatcherConfig(window_ms=1e9, max_batch_size=32),
        )
        d._accepting = True
        r = _req("windowed")
        d.submit(r)
        assert d.batcher.poll(time.monotonic()) is None  # pulled, window open
        assert d.batcher.pending_count() == 1
        d.abort("windowed")
        assert d.batcher.pending_count() == 0
        assert d.batcher.flush() is None


# ---------------------------------------------------------------------------
# SSE wire format — Properties 13-15 (design.md:758-774 [spec])
# ---------------------------------------------------------------------------


class TestSse:
    def test_token_frame(self):
        frame = sse_encode(TokenEvent.token_event("hi", 3))
        assert frame == b'data: {"type": "token", "token": "hi", "index": 3}\n\n'

    def test_roundtrip_done(self):
        import json

        ev = TokenEvent.done_event(FinishReason.LENGTH, Usage.of(5, 7))
        payload = sse_encode(ev).decode()
        assert payload.startswith("data: ") and payload.endswith("\n\n")
        parsed = TokenEvent.from_dict(json.loads(payload[6:-2]))
        assert parsed == ev


# ---------------------------------------------------------------------------
# Metrics snapshot
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_snapshot_basic(self):
        m = MetricsCollector()
        m.record_request("/generate", 200, 0.1)
        m.record_request("/generate", 400, 0.3)
        m.record_batch(4, 0.1)
        m.record_tokens(100)
        m.record_ttft(0.05)
        m.record_cache(hits=3, misses=1)
        m.set_queue_depth(1, 2, 3)
        snap = m.snapshot()
        assert snap.total_requests == 2
        assert snap.queue_depth == 6
        assert abs(snap.average_latency_ms - 200.0) < 1e-6
        assert abs(snap.cache_hit_rate - 0.75) < 1e-9
        assert snap.average_batch_size == 4.0
        assert snap.tokens_per_second > 0
        d = snap.to_dict()
        assert d["total_requests"] == 2

    def test_prometheus_render(self):
        m = MetricsCollector()
        m.record_tokens(5)
        text = m.prometheus_text().decode()
        assert "tokens_generated_total 5.0" in text

    def test_active_requests_floor(self):
        m = MetricsCollector()
        m.request_finished()
        assert m.snapshot().active_requests == 0


class TestEmbedInterleaving:
    """Embeddings run as incremental jobs between decode steps (VERDICT
    r1 weak #7: a large embeddings batch stalled every in-flight
    generation on the replica)."""

    def _runner(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.engine.engine import (
            EngineConfig,
            LLMEngine,
        )
        from distributed_inference_server_tpu.engine.kv_cache import (
            PagedCacheConfig,
        )
        from distributed_inference_server_tpu.models import llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.runner import (
            EngineRunner,
        )

        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)

        def factory():
            return LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(
                    max_batch=2, prefill_buckets=(16,),
                    paged=PagedCacheConfig(num_pages=64, page_size=8,
                                           max_pages_per_seq=8),
                ),
                dtype=jnp.float32,
            )

        return EngineRunner("e0", factory), factory

    def test_embed_matches_one_shot_and_interleaves(self):
        import threading

        import numpy as np

        from distributed_inference_server_tpu.engine.engine import (
            SamplingParams,
        )
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.runner import (
            ServerRequest,
        )

        runner, factory = self._runner()
        runner.start()
        try:
            tok = ByteTokenizer()
            rows = [tok.encode(f"embedding input number {i}")
                    for i in range(6)]

            class Sink:
                def __init__(self):
                    self.tokens = []
                    self.done = threading.Event()

                def on_token(self, token_id, text, token_index, logprob=None):
                    self.tokens.append(token_id)

                def on_done(self, finish_reason, usage):
                    self.done.set()

                def on_error(self, message, code):
                    self.done.set()

            sink = Sink()
            req = ServerRequest(
                request_id="g1", prompt_ids=tok.encode("generate this"),
                params=SamplingParams(max_tokens=16, temperature=0.0),
                sink=sink,
            )
            got = {}
            ev = threading.Event()

            def on_result(arr, err):
                got["arr"], got["err"] = arr, err
                ev.set()

            # submit generation AND embeddings together: both must finish
            runner.submit([req])
            runner.submit_embed(rows, on_result)
            assert ev.wait(120), "embeddings never completed"
            assert sink.done.wait(120), "generation never completed"
            assert got["err"] is None
            # final token arrives as id event + held-back-text flush
            assert len(sink.tokens) >= 16
            # same numerics as the one-shot engine API
            want = factory().embed_ids(rows)
            np.testing.assert_allclose(got["arr"], want, rtol=1e-5,
                                       atol=1e-5)
        finally:
            runner.shutdown()


class TestStreamingSinkCoalescing:
    """Cross-thread wakeup coalescing: a burst of tokens pushed from the
    runner thread drains to the loop in order with one scheduled flush."""

    def test_burst_order_and_termination(self):
        import asyncio
        import threading

        from distributed_inference_server_tpu.core.models import (
            FinishReason,
            Usage,
        )
        from distributed_inference_server_tpu.serving.streamer import (
            StreamingSink,
        )

        async def main():
            loop = asyncio.get_running_loop()
            sink = StreamingSink(loop)
            flushes = []
            orig = sink._flush

            def counted_flush():
                flushes.append(1)
                orig()

            sink._flush = counted_flush

            def producer():
                for i in range(6):
                    sink.on_token(i, f"t{i}", i)
                sink.on_done(FinishReason.LENGTH, Usage.of(3, 6))

            t = threading.Thread(target=producer)
            t.start()
            t.join()  # whole burst lands before the loop runs once
            events = [e async for e in sink.events()]
            assert [e.token for e in events[:6]] == [
                f"t{i}" for i in range(6)]
            assert events[-1].type == "done"
            # 8 items (6 tokens + done + None) in far fewer flushes
            assert 1 <= len(flushes) <= 2
            assert sink.finish_reason == FinishReason.LENGTH

        asyncio.run(main())


class TestEmbedStartFailure:
    """Regression (r2 review): a failure in embed_start on the engine
    thread must still resolve the callback exactly once with the error —
    not strand the /embeddings future forever."""

    def test_embed_start_error_reaches_callback(self):
        import threading

        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.engine.engine import (
            EngineConfig,
            LLMEngine,
        )
        from distributed_inference_server_tpu.engine.kv_cache import (
            PagedCacheConfig,
        )
        from distributed_inference_server_tpu.models import llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.runner import (
            EngineRunner,
        )

        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)

        def factory():
            eng = LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(max_batch=2, prefill_buckets=(16,),
                             paged=PagedCacheConfig(
                                 num_pages=32, page_size=8,
                                 max_pages_per_seq=4)),
                dtype=jnp.float32,
            )

            def boom(ids_list):
                raise RuntimeError("embed_start exploded")

            eng.embed_start = boom
            return eng

        runner = EngineRunner("e0", factory)
        runner.start()
        try:
            got = {}
            ev = threading.Event()

            def cb(arr, err):
                got["arr"], got["err"] = arr, err
                ev.set()

            runner.submit_embed([[1, 2, 3]], cb)
            assert ev.wait(30), "callback never fired"
            assert got["arr"] is None
            assert "embed_start exploded" in got["err"]
        finally:
            runner.shutdown()
