"""Conformance tests for the wire models.

Ports the reference's property suite (``crates/core/src/models.rs:328-476``):
serde round-trip properties for all response types at 100 cases each
(**Property 25**, design.md:830-834), plus the SSE TokenEvent wire format
(**Properties 13-15**, design.md:758-774), serde defaults
(models.rs:294-304), and error-body shape (**Property 24**).
"""

import json

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core import (
    ChatChoice,
    ChatMessage,
    ChatRequest,
    ChatResponse,
    EmbeddingData,
    EmbeddingsRequest,
    EmbeddingsResponse,
    ErrorResponse,
    FinishReason,
    GenerateChoice,
    GenerateRequest,
    GenerateResponse,
    InvalidJson,
    Priority,
    Role,
    TokenEvent,
    Usage,
    dumps,
    loads,
)

CASES = settings(max_examples=100, deadline=None)

# -- generator strategies (mirroring models.rs:334-381) ----------------------

arb_usage = st.builds(
    Usage.of,
    st.integers(min_value=0, max_value=100_000),
    st.integers(min_value=0, max_value=100_000),
)
arb_finish = st.sampled_from(list(FinishReason))
arb_role = st.sampled_from(list(Role))
arb_text = st.text(max_size=200)
arb_chat_message = st.builds(ChatMessage, role=arb_role, content=arb_text)

arb_generate_response = st.builds(
    GenerateResponse,
    id=st.uuids().map(str),
    object=st.just("text_completion"),
    created=st.integers(min_value=0, max_value=2**40),
    model=st.text(min_size=1, max_size=50),
    choices=st.lists(
        st.builds(
            GenerateChoice,
            text=arb_text,
            index=st.integers(min_value=0, max_value=64),
            finish_reason=arb_finish,
        ),
        max_size=4,
    ).map(tuple),
    usage=arb_usage,
)

arb_chat_response = st.builds(
    ChatResponse,
    id=st.uuids().map(str),
    object=st.just("chat.completion"),
    created=st.integers(min_value=0, max_value=2**40),
    model=st.text(min_size=1, max_size=50),
    choices=st.lists(
        st.builds(
            ChatChoice,
            index=st.integers(min_value=0, max_value=64),
            message=arb_chat_message,
            finish_reason=arb_finish,
        ),
        max_size=4,
    ).map(tuple),
    usage=arb_usage,
)

arb_embeddings_response = st.builds(
    EmbeddingsResponse,
    object=st.just("list"),
    data=st.lists(
        st.builds(
            EmbeddingData,
            object=st.just("embedding"),
            embedding=st.lists(
                st.floats(
                    allow_nan=False, allow_infinity=False, width=32, min_value=-10, max_value=10
                ),
                max_size=16,
            ).map(tuple),
            index=st.integers(min_value=0, max_value=64),
        ),
        max_size=4,
    ).map(tuple),
    model=st.text(min_size=1, max_size=50),
    usage=arb_usage,
)

arb_error_response = st.builds(
    ErrorResponse.of,
    st.text(max_size=200),
    st.sampled_from(
        ["invalid_request_error", "rate_limit_error", "timeout_error", "server_error"]
    ),
    st.text(min_size=1, max_size=40),
)


# -- Property 25: response serialization round-trips -------------------------


@CASES
@given(arb_generate_response)
def test_generate_response_roundtrip(resp):
    assert loads(GenerateResponse, dumps(resp)) == resp


@CASES
@given(arb_chat_response)
def test_chat_response_roundtrip(resp):
    assert loads(ChatResponse, dumps(resp)) == resp


@CASES
@given(arb_embeddings_response)
def test_embeddings_response_roundtrip(resp):
    assert loads(EmbeddingsResponse, dumps(resp)) == resp


@CASES
@given(arb_error_response)
def test_error_response_roundtrip(resp):
    assert loads(ErrorResponse, dumps(resp)) == resp


# -- Property 23/24: response shapes ----------------------------------------


@CASES
@given(arb_generate_response)
def test_generate_response_shape(resp):
    obj = json.loads(dumps(resp))
    for key in ("id", "object", "created", "model", "choices", "usage"):
        assert key in obj
    assert isinstance(obj["created"], int)
    for key in ("prompt_tokens", "completion_tokens", "total_tokens"):
        assert key in obj["usage"]


@CASES
@given(arb_error_response)
def test_error_response_shape(resp):
    obj = json.loads(dumps(resp))
    assert set(obj) == {"error"}
    for key in ("message", "error_type", "code"):
        assert key in obj["error"]


# -- request parsing defaults (models.rs:294-304) ---------------------------


def test_generate_request_defaults():
    req = loads(GenerateRequest, '{"prompt": "hello"}')
    assert req.max_tokens == 256
    assert req.temperature == 1.0
    assert req.top_p == 1.0
    assert req.stop_sequences == []
    assert req.stream is False
    assert req.priority is None


def test_generate_request_priority_parsing():
    for wire in ("High", "high", "HIGH"):
        req = loads(GenerateRequest, json.dumps({"prompt": "x", "priority": wire}))
        assert req.priority == Priority.HIGH
    with pytest.raises(InvalidJson):
        loads(GenerateRequest, '{"prompt": "x", "priority": "urgent"}')


def test_chat_request_parsing():
    req = loads(
        ChatRequest,
        json.dumps(
            {
                "messages": [
                    {"role": "system", "content": "be brief"},
                    {"role": "user", "content": "hi"},
                ],
                "stream": True,
            }
        ),
    )
    assert req.messages[0].role == Role.SYSTEM
    assert req.stream is True
    assert req.max_tokens == 256


def test_embeddings_untagged_input():
    single = loads(EmbeddingsRequest, '{"input": "hello"}')
    assert single.input_list() == ["hello"]
    multi = loads(EmbeddingsRequest, '{"input": ["a", "b"]}')
    assert multi.input_list() == ["a", "b"]
    with pytest.raises(InvalidJson):
        loads(EmbeddingsRequest, '{"input": 42}')


def test_malformed_json_rejected():
    with pytest.raises(InvalidJson):
        loads(GenerateRequest, "{not json")


def test_response_side_strict_numbers():
    # malformed numeric fields in responses/events raise InvalidJson, not
    # bare ValueError (the loads() contract)
    with pytest.raises(InvalidJson):
        loads(TokenEvent, '{"type":"token","token":"a","index":"oops"}')
    with pytest.raises(InvalidJson):
        loads(
            GenerateResponse,
            '{"id":"x","object":"text_completion","created":"now","model":"m",'
            '"choices":[],"usage":{"prompt_tokens":1,"completion_tokens":1,'
            '"total_tokens":2}}',
        )
    with pytest.raises(InvalidJson):
        loads(
            GenerateResponse,
            '{"id":"x","object":"o","created":1,"model":"m","choices":[],'
            '"usage":{"prompt_tokens":1.5,"completion_tokens":1,"total_tokens":2}}',
        )


def test_wrong_field_types_rejected():
    # Strict-typed fields: the reference's serde rejects these with 400
    # invalid_json; no truthiness coercion ("false" must not enable streaming).
    bad = [
        '{"prompt": "x", "max_tokens": null}',
        '{"prompt": "x", "max_tokens": "many"}',
        '{"prompt": "x", "max_tokens": true}',
        '{"prompt": "x", "temperature": "hot"}',
        '{"prompt": "x", "stream": "false"}',
        '{"prompt": "x", "stop_sequences": "END"}',
        '{"prompt": "x", "stop_sequences": [1, 2]}',
        '{"prompt": 42}',
    ]
    for payload in bad:
        with pytest.raises(InvalidJson):
            loads(GenerateRequest, payload)
    with pytest.raises(InvalidJson):
        loads(ChatRequest, '{"messages": [{"role": "user", "content": "x"}], "stream": 1}')


# -- Properties 13-15: SSE token event wire format --------------------------


@CASES
@given(
    token=arb_text,
    index=st.integers(min_value=0, max_value=10_000),
    logprob=st.one_of(
        st.none(), st.floats(allow_nan=False, allow_infinity=False, width=32)
    ),
)
def test_token_event_format(token, index, logprob):
    """Property 13: SSE token event wire format (design.md:758-762)."""
    ev = TokenEvent.token_event(token, index, logprob)
    obj = json.loads(dumps(ev))
    assert obj["type"] == "token"
    assert obj["token"] == token
    assert obj["index"] == index
    if logprob is None:
        assert "logprob" not in obj  # skip_serializing_if (models.rs:275)
    assert TokenEvent.from_dict(obj) == ev


@CASES
@given(finish=arb_finish, usage=arb_usage)
def test_done_event_format(finish, usage):
    """Property 14: stream completion event format (design.md:764-768)."""
    ev = TokenEvent.done_event(finish, usage)
    obj = json.loads(dumps(ev))
    assert obj["type"] == "done"
    assert obj["finish_reason"] in ("stop", "length", "stop_sequence")
    assert set(obj["usage"]) == {"prompt_tokens", "completion_tokens", "total_tokens"}
    assert TokenEvent.from_dict(obj) == ev


@CASES
@given(messages=arb_text, code=st.text(min_size=1, max_size=40))
def test_error_event_format(messages, code):
    """Property 15: streaming error event format (design.md:770-774)."""
    ev = TokenEvent.error_event(messages, code)
    obj = json.loads(dumps(ev))
    assert obj["type"] == "error"
    assert obj["messages"] == messages
    assert obj["code"] == code
    assert TokenEvent.from_dict(obj) == ev
