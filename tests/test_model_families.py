"""Mistral (sliding-window attention), Qwen2 (q/k/v bias), and Gemma-2
(alternating local/global attention, sandwich norms, GeGLU, logit
soft-capping) family support: masking numerics, param/loader round-trip,
HF-transformers logits parity, engine serving on both XLA and Pallas
paths, and TP/PP sharding.

The reference targeted "Llama-3 8B or compatible" GGUF checkpoints
(requirements.md:5 [spec]); these are the compatible families a
llama.cpp deployment would serve next.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import (
    MISTRAL_7B,
    QWEN2_7B,
    TINY,
    TINY_BIAS,
    TINY_SWA,
    get_config,
)
from distributed_inference_server_tpu.models.loader import (
    config_from_hf_json,
    params_from_hf_state_dict,
)
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.ops.attention import gqa_attention

PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)


def _dense_case(T=24, B=2, H=4, KV=2, D=16, seed=3):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, KV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, KV, D), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.asarray([T, T - 4], jnp.int32)
    return q, k, v, pos, valid


class TestSlidingWindowMask:
    def test_window_masks_old_tokens(self):
        q, k, v, pos, valid = _dense_case()
        W = 6
        got = gqa_attention(q, k, v, pos, valid, sliding_window=W)
        # reference: manual softmax with the window mask
        B, T, H, D = q.shape
        KV = k.shape[2]
        G = H // KV
        qg = np.asarray(q).reshape(B, T, KV, G, D)
        s = np.einsum("btkgd,bskd->bkgts", qg, np.asarray(k)) / np.sqrt(D)
        kv_pos = np.arange(T)
        m = (
            (kv_pos[None, None, :] <= np.asarray(pos)[:, :, None])
            & (kv_pos[None, None, :] > np.asarray(pos)[:, :, None] - W)
            & (kv_pos[None, None, :] < np.asarray(valid)[:, None, None])
        )[:, None, None]
        s = np.where(m, s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("bkgts,bskd->btkgd", p, np.asarray(v)).reshape(
            B, T, H, D
        )
        for b in range(B):
            n = int(valid[b])
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], want[b, :n], rtol=2e-5, atol=2e-5
            )

    def test_window_changes_output_vs_full_causal(self):
        q, k, v, pos, valid = _dense_case()
        full = gqa_attention(q, k, v, pos, valid)
        windowed = gqa_attention(q, k, v, pos, valid, sliding_window=4)
        # early tokens (inside the window) agree; late tokens differ
        np.testing.assert_allclose(
            np.asarray(full)[:, :4], np.asarray(windowed)[:, :4],
            rtol=1e-6, atol=1e-6,
        )
        assert not np.allclose(np.asarray(full)[0, -1],
                               np.asarray(windowed)[0, -1], atol=1e-4)


def _generate(cfg, impl="xla", mesh=None, prompt="sliding windows!",
              max_tokens=20):
    params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    tok = ByteTokenizer()
    eng = LLMEngine(
        params, cfg, tok,
        EngineConfig(max_batch=2, prefill_buckets=(16,), paged=PAGED,
                     attention_impl=impl),
        dtype=jnp.float32, mesh=mesh,
    )
    eng.add_request("r", tok.encode(prompt),
                    SamplingParams(max_tokens=max_tokens, temperature=0.0))
    out = []
    while eng.has_work():
        for o in eng.step():
            assert o.error is None, o.error
            if o.token_id is not None:
                out.append(o.token_id)
    return out


class TestSWAFamily:
    def test_engine_pallas_matches_xla_with_window(self):
        # TINY_SWA: window 8 < prompt+output length, so the window is live
        assert _generate(TINY_SWA, "xla") == _generate(TINY_SWA, "pallas")

    def test_window_actually_changes_logits(self):
        # same weights, windowed vs full causal: late-position logits
        # must diverge once the context exceeds the window
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        T = 24
        ids = jax.random.randint(jax.random.PRNGKey(2), (1, T), 1, 250)
        pos = jnp.arange(T)[None]
        valid = jnp.asarray([T], jnp.int32)
        cache = llama.KVCache.create(TINY, 1, T, dtype=jnp.float32)
        lf, _ = llama.forward(params, TINY, ids, pos, cache, pos, valid)
        lw, _ = llama.forward(params, TINY_SWA, ids, pos, cache, pos, valid)
        # inside the window (first 8 positions) identical
        np.testing.assert_allclose(np.asarray(lf)[:, :8],
                                   np.asarray(lw)[:, :8],
                                   rtol=1e-5, atol=1e-5)
        assert not np.allclose(np.asarray(lf)[:, -1],
                               np.asarray(lw)[:, -1], atol=1e-4)

    def test_cp_prefill_with_window(self):
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        mesh = make_mesh(MeshSpec(seq=4))
        out = _generate(TINY_SWA, "xla", mesh=mesh,
                        prompt="a rather long prompt that exceeds the "
                               "largest bucket", max_tokens=6)
        want = _generate(TINY_SWA, "xla",
                         prompt="a rather long prompt that exceeds the "
                                "largest bucket", max_tokens=6)
        assert out == want


class TestBiasFamily:
    def test_bias_params_created_and_used(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY_BIAS,
                                   jnp.float32)
        assert {"bq", "bk", "bv"} <= set(params["layers"])
        # zero-bias model == plain model logits
        zeroed = dict(params, layers=dict(
            params["layers"],
            bq=jnp.zeros_like(params["layers"]["bq"]),
            bk=jnp.zeros_like(params["layers"]["bk"]),
            bv=jnp.zeros_like(params["layers"]["bv"]),
        ))
        plain = {k: v for k, v in zeroed.items()}
        plain_layers = dict(zeroed["layers"])
        for k in ("bq", "bk", "bv"):
            plain_layers.pop(k)
        plain["layers"] = plain_layers
        ids = jnp.ones((1, 8), jnp.int32)
        pos = jnp.arange(8)[None]
        valid = jnp.asarray([8], jnp.int32)
        cache = llama.KVCache.create(TINY_BIAS, 1, 8, dtype=jnp.float32)
        lz, _ = llama.forward(zeroed, TINY_BIAS, ids, pos, cache, pos, valid)
        lp, _ = llama.forward(plain, TINY, ids, pos, cache, pos, valid)
        np.testing.assert_allclose(np.asarray(lz), np.asarray(lp),
                                   rtol=1e-6, atol=1e-6)
        # random bias changes the output
        lr, _ = llama.forward(params, TINY_BIAS, ids, pos, cache, pos, valid)
        assert not np.allclose(np.asarray(lr), np.asarray(lp), atol=1e-4)

    def test_engine_serves_bias_model_both_impls(self):
        assert _generate(TINY_BIAS, "xla") == _generate(TINY_BIAS, "pallas")

    def test_bias_model_under_tp(self):
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        plain = _generate(TINY_BIAS, "xla")
        tp = _generate(TINY_BIAS, "xla", mesh=make_mesh(MeshSpec(tensor=2)))
        assert plain == tp

    def test_bias_model_under_pp(self):
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        plain = _generate(TINY_BIAS, "xla")
        pp = _generate(TINY_BIAS, "xla", mesh=make_mesh(MeshSpec(stage=2)))
        assert plain == pp

    def test_loader_round_trip_with_bias(self):
        cfg = TINY_BIAS
        ref = llama.init_params(jax.random.PRNGKey(1), cfg, jnp.float32)
        state = {}
        L = cfg.num_layers
        lay = ref["layers"]
        for i in range(L):
            state[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
                lay["attn_norm"][i])
            state[f"model.layers.{i}.post_attention_layernorm.weight"] = (
                np.asarray(lay["mlp_norm"][i]))
            for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                             ("wv", "v_proj"), ("wo", "o_proj")):
                state[f"model.layers.{i}.self_attn.{hf}.weight"] = (
                    np.asarray(lay[ours][i]).T)
            for ours, hf in (("bq", "q_proj"), ("bk", "k_proj"),
                             ("bv", "v_proj")):
                state[f"model.layers.{i}.self_attn.{hf}.bias"] = (
                    np.asarray(lay[ours][i]))
            for ours, hf in (("w_gate", "gate_proj"), ("w_up", "up_proj"),
                             ("w_down", "down_proj")):
                state[f"model.layers.{i}.mlp.{hf}.weight"] = (
                    np.asarray(lay[ours][i]).T)
        state["model.embed_tokens.weight"] = np.asarray(ref["embed"])
        state["model.norm.weight"] = np.asarray(ref["final_norm"])
        got = params_from_hf_state_dict(state, cfg, dtype=jnp.float32)
        for key in ("bq", "bk", "bv", "wq"):
            np.testing.assert_allclose(
                np.asarray(got["layers"][key]),
                np.asarray(ref["layers"][key]), rtol=1e-6, atol=1e-6,
            )


class TestHFConfigParsing:
    def test_mistral_style_json(self):
        cfg = config_from_hf_json({
            "vocab_size": 32000, "hidden_size": 4096,
            "intermediate_size": 14336, "num_hidden_layers": 32,
            "num_attention_heads": 32, "num_key_value_heads": 8,
            "rope_theta": 10000.0, "sliding_window": 4096,
            "model_type": "mistral",
        }, name="mistral")
        assert cfg.sliding_window == 4096
        assert cfg.attention_bias is False

    def test_qwen2_style_json(self):
        cfg = config_from_hf_json({
            "vocab_size": 152064, "hidden_size": 3584,
            "intermediate_size": 18944, "num_hidden_layers": 28,
            "num_attention_heads": 28, "num_key_value_heads": 4,
            "rope_theta": 1e6, "model_type": "qwen2",
            "sliding_window": 131072, "use_sliding_window": False,
        }, name="qwen2")
        assert cfg.attention_bias is True
        assert cfg.sliding_window is None  # gated off

    def test_presets_registered(self):
        assert get_config("mistral-7b") is MISTRAL_7B
        assert get_config("qwen2-7b") is QWEN2_7B
        assert MISTRAL_7B.sliding_window == 4096
        assert QWEN2_7B.attention_bias


class TestWindowKVReclaim:
    """Sliding-window page reclamation: pages fully behind the attention
    window are freed mid-generation, so per-sequence KV is O(window) not
    O(length) — while output stays bit-identical to the dense reference."""

    def test_pages_freed_during_generation_and_output_exact(self):
        from distributed_inference_server_tpu.models.generate import (
            greedy_generate,
        )

        cfg = TINY_SWA  # window 8
        paged = PagedCacheConfig(num_pages=64, page_size=4,
                                 max_pages_per_seq=32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        tok = ByteTokenizer()
        eng = LLMEngine(
            params, cfg, tok,
            EngineConfig(max_batch=2, prefill_buckets=(16,), paged=paged,
                         decode_block_size=4),
            dtype=jnp.float32,
        )
        prompt = tok.encode("window reclaim")
        eng.add_request("r", prompt, SamplingParams(
            max_tokens=60, temperature=0.0))
        got = []
        min_live = 10**9
        max_live = 0
        sentinel = paged.num_pages
        while eng.has_work():
            for o in eng.step():
                assert o.error is None, o.error
                if o.token_id is not None:
                    got.append(o.token_id)
            seq = eng._by_id.get("r")
            if seq is not None and seq.seq_len > 30:
                live = sum(1 for p in seq.block_table if p != sentinel)
                min_live = min(min_live, live)
                max_live = max(max_live, live)
        # ~74 total positions = 19 pages unreclaimed; with window 8 the
        # live set must stay near ceil(8/4)+inflight, far below that
        assert min_live <= 8, f"reclaim never kicked in (live={min_live})"
        ref = list(greedy_generate(params, cfg, prompt, 60))
        assert got == ref[: len(got)] and len(got) == 60

    def test_pool_pressure_relieved_for_concurrent_seqs(self):
        # a pool too small to hold two FULL-length sequences serves them
        # concurrently once the window frees the tail
        cfg = TINY_SWA
        paged = PagedCacheConfig(num_pages=24, page_size=4,
                                 max_pages_per_seq=24)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        tok = ByteTokenizer()
        eng = LLMEngine(
            params, cfg, tok,
            EngineConfig(max_batch=2, prefill_buckets=(16,), paged=paged,
                         decode_block_size=4),
            dtype=jnp.float32,
        )
        for rid in ("a", "b"):
            eng.add_request(rid, tok.encode(f"request {rid}"),
                            SamplingParams(max_tokens=64, temperature=0.0))
        done = {"a": 0, "b": 0}
        while eng.has_work():
            for o in eng.step():
                assert o.error is None, o.error
                if o.token_id is not None:
                    done[o.request_id] += 1
        # 2 seqs x ~74 positions = 37 pages > 24 in the pool: only
        # window reclaim makes both finish
        assert done["a"] >= 64 and done["b"] >= 64


class TestTransformersParity:
    """Numerics parity vs HuggingFace eager implementations for the new
    families — sliding-window masking (Mistral) and q/k/v bias (Qwen2)
    verified against the upstream reference model, random weights."""

    def _parity(self, hf_cfg, hf_model_cls, T=12):
        import numpy as _np

        torch = pytest.importorskip("torch")
        torch.manual_seed(0)
        hf_model = hf_model_cls(hf_cfg).eval()
        state = {k: v.detach().numpy()
                 for k, v in hf_model.state_dict().items()}
        cfg = config_from_hf_json(hf_cfg.to_dict(), name="hf-parity")
        params = params_from_hf_state_dict(state, cfg, dtype=jnp.float32)
        rng = _np.random.default_rng(0)
        ids = rng.integers(0, hf_cfg.vocab_size, size=(2, T))
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
        B = ids.shape[0]
        cache = llama.KVCache.create(cfg, B, T, dtype=jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        lens = jnp.full((B,), T, jnp.int32)
        ours, _ = llama.forward(
            params, cfg, jnp.asarray(ids, jnp.int32), positions, cache,
            positions, lens,
        )
        np.testing.assert_allclose(
            np.asarray(ours), hf_logits, atol=3e-4, rtol=3e-3
        )
        return cfg

    def test_mistral_sliding_window_parity(self):
        from transformers import MistralConfig, MistralForCausalLM

        cfg = self._parity(MistralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, rms_norm_eps=1e-5, rope_theta=10000.0,
            sliding_window=4,  # < T: the window masking is live
            max_position_embeddings=512, attn_implementation="eager",
        ), MistralForCausalLM)
        assert cfg.sliding_window == 4

    def test_qwen2_bias_parity(self):
        from transformers import Qwen2Config, Qwen2ForCausalLM

        cfg = self._parity(Qwen2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, rms_norm_eps=1e-6, rope_theta=10000.0,
            use_sliding_window=False, max_position_embeddings=512,
            attn_implementation="eager",
        ), Qwen2ForCausalLM)
        assert cfg.attention_bias

    def test_gemma2_parity(self):
        # Gemma-2 stacks every family-specific feature at once: sandwich
        # norms with unit-offset weights, GeGLU, embedding scaling,
        # attention + final logit soft-capping, a query scale override,
        # and ALTERNATING local/global attention (layer 0 slides with
        # window 4 < T, layer 1 is full causal)
        from transformers import Gemma2Config, Gemma2ForCausalLM

        cfg = self._parity(Gemma2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16, rms_norm_eps=1e-6,
            rope_theta=10000.0, sliding_window=4,
            query_pre_attn_scalar=24.0, attn_logit_softcapping=50.0,
            final_logit_softcapping=30.0, max_position_embeddings=512,
            attn_implementation="eager",
        ), Gemma2ForCausalLM)
        assert cfg.sandwich_norms
        assert cfg.sliding_window_pattern == 2
        assert cfg.layer_windows() == (4, 0)
        assert cfg.activation == "gelu_tanh"
        assert cfg.scale_embeddings


class TestGemma2Family:
    def test_engine_pallas_matches_xla(self):
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        # long enough that layer 0's window (8) is live while layer 1
        # attends the full history — the per-layer window rides the scan
        # through BOTH attention backends
        xla = _generate(TINY_GEMMA2, "xla", prompt="gemma alternating!!")
        pal = _generate(TINY_GEMMA2, "pallas", prompt="gemma alternating!!")
        assert xla == pal

    def test_alternating_window_differs_from_uniform(self):
        """The pattern matters: all-layers-windowed vs alternating must
        produce different generations once the context exceeds the
        window (else the global layers aren't actually global)."""
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        uniform = TINY_GEMMA2.with_overrides(
            name="tiny-gemma2-uniform", sliding_window_pattern=None
        )
        prompt = "alternating windows change attention"
        assert _generate(TINY_GEMMA2, "xla", prompt=prompt) != _generate(
            uniform, "xla", prompt=prompt
        )

    def test_gemma2_under_tp(self):
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        plain = _generate(TINY_GEMMA2, "xla")
        tp = _generate(TINY_GEMMA2, "xla",
                       mesh=make_mesh(MeshSpec(tensor=2)))
        assert plain == tp

    def test_gemma2_under_pp(self):
        # the stage-axis path has its own embed/unembed: embedding
        # scaling, final soft-capping, and the per-stage window schedule
        # must match the single-device result exactly
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        plain = _generate(TINY_GEMMA2, "xla")
        pp = _generate(TINY_GEMMA2, "xla",
                       mesh=make_mesh(MeshSpec(stage=2)))
        assert plain == pp

    def test_no_page_reclaim_with_pattern(self):
        """Global layers keep the full history, so the window page
        reclaim must stay off for pattern models."""
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        from distributed_inference_server_tpu.models.configs import TINY_SWA

        def reclaim_outcome(cfg):
            params = llama.init_params(jax.random.PRNGKey(0), cfg,
                                       jnp.float32)
            eng = LLMEngine(
                params, cfg, ByteTokenizer(),
                EngineConfig(max_batch=1, prefill_buckets=(16,),
                             paged=PAGED, attention_impl="xla"),
                dtype=jnp.float32,
            )
            from distributed_inference_server_tpu.engine.engine import _Seq

            s = _Seq("x", [1] * 40, SamplingParams(max_tokens=4))
            s.block_table = list(eng.allocator.allocate(5))
            s.seq_len = 40  # window 8 -> pages 0..3 are dead if reclaimable
            before = list(s.block_table)
            eng._reclaim_window_pages(s)
            return before, s.block_table

        # uniform window (Mistral-style): early pages become sentinels
        before, after = reclaim_outcome(TINY_SWA)
        assert after != before and after[0] == PAGED.num_pages
        # alternating pattern (Gemma-2): global layers still attend the
        # full history -> nothing may be freed
        before, after = reclaim_outcome(TINY_GEMMA2)
        assert after == before
