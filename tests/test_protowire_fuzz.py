"""KvHandoff wire-format fuzz: random SequenceExports round-tripped
through the protowire codec (serving/disagg.py export_to_wire /
export_from_wire), plus schema agreement between serving/inference.proto
and the protowire tables — the runtime twin of distlint rule DL005.

Deterministic seeded random (the image ships no hypothesis): failures
reproduce exactly, and the test always runs in tier 1."""

from __future__ import annotations

import random

import pytest

from distributed_inference_server_tpu.engine.engine import (
    SamplingParams,
    SequenceExport,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    KvChunk,
    chunk_crc,
)
from distributed_inference_server_tpu.serving import protowire
from distributed_inference_server_tpu.serving.disagg import (
    HandoffError,
    export_from_wire,
    export_to_wire,
    stream_from_frames,
    stream_to_frames,
)
from tools.lint import proto as protodef
from tools.lint.rules import compare_wire_schema

# code points that exercise 1..4-byte UTF-8, U+FFFD, and ASCII controls
_CHARS = (
    "abc XYZ 0189 \t\n" "äßçñ" "中文日本語" "🙂🚀" "�" "'\"\\{}[]"
)


def _rand_text(rng: random.Random, max_len: int = 40) -> str:
    return "".join(rng.choice(_CHARS) for _ in range(rng.randrange(max_len)))


def _rand_export(rng: random.Random) -> SequenceExport:
    n_tokens = rng.randrange(0, 60)
    token_ids = [rng.randrange(0, 2 ** 32) for _ in range(n_tokens)]
    return SequenceExport(
        request_id=_rand_text(rng, 20) or "req-0",
        token_ids=token_ids,
        prompt_len=rng.randrange(0, 4096),
        seq_len=n_tokens,
        next_token=rng.randrange(0, 2 ** 31),
        params=SamplingParams(
            max_tokens=rng.randrange(1, 8192),
            # full-range doubles: bit-exactness across the handoff is the
            # whole point of the double fields (inference.proto note)
            temperature=rng.choice(
                [0.0, 1.0, rng.random() * 2, 7e-45, 0.6999999999999998]
            ),
            top_p=rng.choice([1.0, rng.random() or 0.5, 0.9]),
            stop_sequences=tuple(
                _rand_text(rng, 8) for _ in range(rng.randrange(3))
            ),
        ),
        output_text=_rand_text(rng, 120),
        emitted_upto=rng.randrange(0, 120),
        emitted_tokens=rng.randrange(0, 8192),
        pending_ids=[rng.randrange(0, 2 ** 20)
                     for _ in range(rng.randrange(4))],
        kv=rng.randbytes(rng.randrange(0, 256)),
        draft_kv=(rng.randbytes(rng.randrange(1, 64))
                  if rng.random() < 0.5 else None),
        source_engine=rng.choice(["", "engine-0", "engine-17"]),
    )


def test_kvhandoff_roundtrip_fuzz():
    rng = random.Random(0xD157)
    for i in range(300):
        exp = _rand_export(rng)
        got = export_from_wire(export_to_wire(exp))
        for attr in ("request_id", "token_ids", "prompt_len", "seq_len",
                     "next_token", "output_text", "emitted_upto",
                     "emitted_tokens", "pending_ids", "kv", "source_engine"):
            assert getattr(got, attr) == getattr(exp, attr), (i, attr)
        # draft_kv is `optional bytes`: absent stays absent (None), never
        # collapses to b""
        assert got.draft_kv == exp.draft_kv, i
        p, q = got.params, exp.params
        assert p.max_tokens == q.max_tokens, i
        # doubles must survive BIT-EXACT (sampled-token identity across
        # the handoff); repr equality catches any float32 truncation
        assert repr(p.temperature) == repr(q.temperature), i
        assert repr(p.top_p) == repr(q.top_p), i
        assert tuple(p.stop_sequences) == tuple(q.stop_sequences), i


def test_kvhandoff_decode_fills_proto3_defaults():
    """An all-defaults frame (zero bytes on the wire) reconstructs the
    full key set with proto3 zero values."""
    d = protowire.decode("KvHandoff", b"")
    assert d["token_ids"] == [] and d["pending_ids"] == []
    assert d["stop_sequences"] == []
    assert d["kv"] == b"" and "draft_kv" not in d
    assert d["temperature"] == 0.0 and d["max_tokens"] == 0
    assert d["request_id"] == "" and d["source_engine"] == ""


def test_kvhandoff_unknown_fields_skipped():
    """Forward compatibility: a frame carrying an unknown field decodes
    cleanly (future senders may extend the message)."""
    base = export_to_wire(_rand_export(random.Random(7)))
    # field 100, length-delimited, 3 payload bytes
    unknown = protowire._key(100, 2) + bytes([3, 1, 2, 3])
    d = protowire.decode("KvHandoff", unknown + base)
    assert d == protowire.decode("KvHandoff", base)


def _rand_chunk(rng: random.Random, index: int, total: int,
                page_start: int) -> KvChunk:
    payload = rng.randbytes(rng.randrange(1, 512))
    return KvChunk(
        index=index, total=total, page_start=page_start,
        page_count=rng.randrange(1, 9), payload=payload,
        crc32=chunk_crc(payload),
    )


def test_kvchunk_and_header_roundtrip_fuzz():
    """Seeded random KvChunk / KvHandoffHeader frames survive the wire
    field-for-field (crc32 is a full-range uint32 varint)."""
    rng = random.Random(0xC4C4)
    for i in range(200):
        c = _rand_chunk(rng, rng.randrange(0, 2 ** 20),
                        rng.randrange(0, 2 ** 20), rng.randrange(0, 2 ** 16))
        d = protowire.decode("KvChunk", protowire.encode("KvChunk", {
            "handoff_id": f"h{i}", "index": c.index, "total": c.total,
            "page_start": c.page_start, "page_count": c.page_count,
            "crc32": c.crc32, "payload": c.payload,
        }))
        assert (d["index"], d["total"], d["page_start"], d["page_count"],
                d["crc32"], d["payload"]) == (
            c.index, c.total, c.page_start, c.page_count, c.crc32,
            c.payload), i
        h = {"handoff_id": f"h{i}", "request_id": _rand_text(rng, 16),
             "wire_quant": rng.choice(["none", "int8"]),
             # trace context (docs/OBSERVABILITY.md): untraced headers
             # keep the fields off the wire; decode fills the defaults
             "trace_id": rng.choice(["", "aabbccdd11223344"]),
             "parent_span_id": rng.choice(["", "5566778899aabbcc"]),
             # fleet KV data plane (serving/fleet_kv.py): stream op tag
             # + geometry; "" / 0 = the legacy in-process framing
             "op": rng.choice(["", "open", "commit", "resume", "fetch"]),
             "engine_id": rng.choice(["", "engine-0"]),
             "prefix_pages": rng.randrange(0, 2 ** 16),
             "total_chunks": rng.randrange(0, 2 ** 12)}
        got = protowire.decode("KvHandoffHeader",
                               protowire.encode("KvHandoffHeader", h))
        assert got == h, i


def _streamed_export(rng: random.Random) -> SequenceExport:
    exp = _rand_export(rng)
    total = rng.randrange(1, 6)
    page_start = 0
    chunks = []
    for i in range(total):
        c = _rand_chunk(rng, i, total, page_start)
        page_start += c.page_count
        chunks.append(c)
    exp.kv_chunks = chunks
    exp.kv = b""
    exp.wire_quant = rng.choice(["none", "int8"])
    return exp


def test_streamed_frames_roundtrip_and_reorder():
    """The header/chunks/state frame sequence reassembles the export
    exactly — including when chunk frames arrive OUT OF ORDER (a real
    transport may reorder per-chunk streams)."""
    rng = random.Random(0x57EA)
    for i in range(50):
        exp = _streamed_export(rng)
        frames = list(stream_to_frames(exp))
        # shuffle the chunk frames only (header first, state anywhere after)
        chunk_frames = frames[1:-1]
        rng.shuffle(chunk_frames)
        got = stream_from_frames(
            [frames[0]] + chunk_frames + [frames[-1]])
        assert [c.index for c in got.kv_chunks] == sorted(
            c.index for c in exp.kv_chunks), i
        assert {c.index: (c.payload, c.crc32, c.page_start, c.page_count,
                          c.total) for c in got.kv_chunks} == {
            c.index: (c.payload, c.crc32, c.page_start, c.page_count,
                      c.total) for c in exp.kv_chunks}, i
        assert got.wire_quant == exp.wire_quant
        assert got.token_ids == exp.token_ids


def test_streamed_frames_truncation_rejected():
    """A stream missing its header or terminal state frame is rejected
    (never silently reassembled), and a truncated chunk frame fails to
    decode."""
    exp = _streamed_export(random.Random(3))
    frames = list(stream_to_frames(exp))
    with pytest.raises(HandoffError):
        stream_from_frames(frames[1:])  # header dropped
    with pytest.raises(HandoffError):
        stream_from_frames(frames[:-1])  # state dropped
    kind, data = frames[1]  # a KvChunk frame cut mid-payload
    with pytest.raises(ValueError):
        protowire.decode("KvChunk", data[: len(data) // 2])


def test_kvchunk_crc_corruption_detected():
    """A flipped payload byte survives protowire (payload is opaque
    bytes) but fails the crc check the import session applies."""
    c = _rand_chunk(random.Random(9), 0, 1, 0)
    wire = protowire.encode("KvChunk", {
        "handoff_id": "h", "index": c.index, "total": c.total,
        "page_start": c.page_start, "page_count": c.page_count,
        "crc32": c.crc32, "payload": c.payload[:-1]
        + bytes([c.payload[-1] ^ 0xFF]),
    })
    d = protowire.decode("KvChunk", wire)
    assert chunk_crc(d["payload"]) != d["crc32"]


def test_kvchunk_unknown_fields_skipped():
    """Forward compatibility for the chunk frame: unknown fields are
    skipped, known fields decode unchanged."""
    c = _rand_chunk(random.Random(11), 2, 4, 8)
    base = protowire.encode("KvChunk", {
        "handoff_id": "h", "index": c.index, "total": c.total,
        "page_start": c.page_start, "page_count": c.page_count,
        "crc32": c.crc32, "payload": c.payload,
    })
    unknown = protowire._key(99, 2) + bytes([4, 9, 9, 9, 9])
    assert protowire.decode("KvChunk", unknown + base) == \
        protowire.decode("KvChunk", base)


def test_kv_stream_result_roundtrip_fuzz():
    """KvStreamResult — the data-channel per-stream terminal status
    frame (serving/fleet_kv.py) — survives the wire field-for-field."""
    rng = random.Random(0xDA7A)
    for i in range(200):
        msg = {
            "stream_id": _rand_text(rng, 24) or f"s{i}",
            "op": rng.choice(["open", "commit", "resume", "fetch",
                              "abort"]),
            "ok": rng.random() < 0.5,
            "error": rng.choice(["", "torn stream", _rand_text(rng, 40)]),
            "depth": rng.randrange(0, 2 ** 20),
            "engine_id": rng.choice(["", "engine-0", "engine-17"]),
        }
        got = protowire.decode("KvStreamResult",
                               protowire.encode("KvStreamResult", msg))
        assert got == msg, i


def test_kv_stream_result_truncation_and_unknown_fields():
    """Data-channel framing hardening: a result frame cut mid-field is
    rejected (never a plausible-but-wrong decode), and unknown fields
    skip cleanly (forward compatibility for future stream ops)."""
    base = protowire.encode("KvStreamResult", {
        "stream_id": "req-77", "op": "fetch", "ok": True,
        "error": "", "depth": 9, "engine_id": "engine-1",
    })
    with pytest.raises(ValueError):
        protowire.decode("KvStreamResult", base[: len(base) - 3])
    unknown = protowire._key(90, 2) + bytes([2, 7, 7])
    assert protowire.decode("KvStreamResult", unknown + base) == \
        protowire.decode("KvStreamResult", base)


def test_kv_stream_result_decode_fills_defaults():
    d = protowire.decode("KvStreamResult", b"")
    assert d == {"stream_id": "", "op": "", "ok": False, "error": "",
                 "depth": 0, "engine_id": ""}


def test_fleet_heartbeat_data_port_roundtrip():
    """The member's KV data listener port rides every heartbeat
    (serving/fleet_kv.py); 0 (no data plane) stays off the wire and
    decodes back as the proto3 default."""
    on = protowire.decode("FleetHeartbeat", protowire.encode(
        "FleetHeartbeat",
        {"member_id": "w1", "seq": 3, "engines": [], "data_port": 40123},
    ))
    assert on["data_port"] == 40123
    off = protowire.decode("FleetHeartbeat", protowire.encode(
        "FleetHeartbeat", {"member_id": "w1", "seq": 4, "engines": []},
    ))
    assert off["data_port"] == 0


def test_kv_prefix_fetch_engine_id_roundtrip():
    """The data-plane fetch request targets a member-local engine;
    legacy (in-process) requests leave the field off the wire."""
    d = protowire.decode("KvPrefixFetch", protowire.encode(
        "KvPrefixFetch",
        {"request_id": "r1", "hashes": [1, 2 ** 63 + 1], "chunk_pages": 8,
         "wire_quant": "int8", "engine_id": "engine-2"},
    ))
    assert d["engine_id"] == "engine-2"
    assert d["hashes"] == [1, 2 ** 63 + 1]


def test_total_processed_uint64_roundtrip():
    """EngineStatus.total_processed is uint64 in inference.proto; counts
    past 2^63 must not decode negative (distlint DL005 fix)."""
    big = 2 ** 63 + 5
    data = protowire.encode("EngineStatus", {
        "engine_id": "e", "healthy": True, "total_processed": big,
    })
    assert protowire.decode("EngineStatus", data)["total_processed"] == big


def test_wire_schema_field_numbers_agree_with_proto():
    """Field-number/type/cardinality agreement between inference.proto
    and the live protowire tables — the runtime half of DL005, pinned
    here so a drift fails even if someone disables the linter."""
    import distributed_inference_server_tpu as pkg
    from pathlib import Path

    proto_path = (Path(pkg.__file__).parent / "serving" / "inference.proto")
    schema = protodef.parse_file(proto_path)
    diffs = compare_wire_schema(schema, protowire.MESSAGES, protowire.ENUMS)
    assert diffs == [], diffs
    # and KvHandoff specifically covers every SequenceExport field
    kv = schema.messages["KvHandoff"]
    names = {f.name for f in kv.fields.values()}
    assert {"request_id", "token_ids", "kv", "draft_kv", "temperature",
            "top_p", "stop_sequences", "source_engine"} <= names


def _rand_telemetry(rng: random.Random) -> dict:
    """A random FleetTelemetry frame in the canonical wire-dict form
    (serving/teledigest.py: sorted epochs, sorted parallel arrays)."""
    digests = []
    for d in range(rng.randrange(0, 5)):
        epochs = []
        base_epoch = rng.randrange(0, 2 ** 40)
        for k in sorted(rng.sample(range(16), rng.randrange(0, 5))):
            buckets = sorted(rng.sample(range(300), rng.randrange(0, 6)))
            counts = [rng.randrange(1, 2 ** 50) for _ in buckets]
            epochs.append({
                "index": base_epoch + k,
                "buckets": buckets,
                "counts": counts,
                "n": sum(counts) + rng.randrange(0, 10),
                "sum_us": rng.randrange(0, 2 ** 60),
            })
        digests.append({
            "name": rng.choice(["ttft_ms", "tbt_ms", "step_ms.mixed",
                                f"series_{d}"]),
            "epoch_s": rng.choice([1.0, 5.0, 30.0]),
            "epochs": epochs,
        })
    counters = [
        {"name": f"step.engine-{i}.prefill.tokens",
         "value": rng.random() * 1e12}
        for i in range(rng.randrange(0, 4))
    ]
    return {"member_id": _rand_text(rng, 16) or "m0",
            "digests": digests, "counters": counters}


def test_fleet_telemetry_roundtrip_fuzz():
    """FleetTelemetry — the heartbeat-piggybacked perf-digest frame
    (fleet-wire kind 5, serving/teledigest.py) — survives the wire
    field-for-field: epoch indices, bucket/count arrays, exact sums."""
    rng = random.Random(0x7E1E)
    for i in range(120):
        msg = _rand_telemetry(rng)
        got = protowire.decode("FleetTelemetry",
                               protowire.encode("FleetTelemetry", msg))
        assert got == msg, i


def test_fleet_telemetry_truncation_and_unknown_fields():
    """A telemetry frame cut mid-field is rejected (never a
    plausible-but-wrong digest), and unknown fields skip cleanly."""
    rng = random.Random(0x7E1F)
    msg = _rand_telemetry(rng)
    while not msg["digests"]:
        msg = _rand_telemetry(rng)
    base = protowire.encode("FleetTelemetry", msg)
    with pytest.raises(ValueError):
        protowire.decode("FleetTelemetry", base[: len(base) - 2])
    unknown = protowire._key(88, 2) + bytes([3, 1, 2, 3])
    assert protowire.decode("FleetTelemetry", unknown + base) == \
        protowire.decode("FleetTelemetry", base)


def test_tele_digest_wire_matches_live_digest():
    """A live WindowedDigest's to_wire() dict IS the TeleDigest wire
    message: encode/decode returns it unchanged (canonical sorted
    arrays survive), so merge identity holds across the wire."""
    from distributed_inference_server_tpu.serving.teledigest import (
        WindowedDigest,
        merge_digests,
    )

    rng = random.Random(0x7E20)
    dig = WindowedDigest(epoch_s=5.0, window_s=60.0)
    for _ in range(300):
        dig.observe(rng.random() * 1000.0,
                    now=1_000_000.0 + rng.random() * 40.0)
    wire = dig.to_wire("ttft_ms")
    got = protowire.decode("TeleDigest",
                           protowire.encode("TeleDigest", wire))
    assert got == wire
    # and a wire round-trip is transparent to the merge algebra
    assert merge_digests([got, got]) == merge_digests([wire, wire])


# ---------------------------------------------------------------------------
# KvIntro — the mesh introduction frame (fleet-wire kind 6)
# ---------------------------------------------------------------------------


def _rand_intro(rng: random.Random) -> dict:
    return {
        "member_id": _rand_text(rng, 24) or "m0",
        "host": rng.choice(["127.0.0.1", "10.1.2.3", "fe80::1%eth0",
                            _rand_text(rng, 16)]),
        "data_port": rng.randrange(0, 65536),
        "max_streams": rng.randrange(0, 64),
        "gone": rng.random() < 0.3,
        # registry HA: the broker stamps its fencing epoch on re-brokered
        # intros (serving/fleet_ha.py)
        "epoch": rng.randrange(0, 1 << 31),
    }


def test_kv_intro_roundtrip_fuzz():
    """KvIntro — the registry's mesh introduction broker frame
    (fleet-wire kind 6, serving/fleet_mesh.py) — survives the wire
    field-for-field, including zero ports and gone retractions."""
    rng = random.Random(0x7E21)
    for i in range(200):
        msg = _rand_intro(rng)
        got = protowire.decode("KvIntro",
                               protowire.encode("KvIntro", msg))
        assert got == msg, i


def test_kv_intro_truncation_and_unknown_fields():
    """An intro cut mid-field is rejected — a member must never dial a
    half-parsed endpoint — and unknown fields skip cleanly (newer
    registries can extend the introduction without breaking members)."""
    rng = random.Random(0x7E22)
    msg = _rand_intro(rng)
    msg["gone"] = True  # a trailing one-byte field to cut the value off
    base = protowire.encode("KvIntro", msg)
    with pytest.raises(ValueError):
        protowire.decode("KvIntro", base[: len(base) - 1])
    unknown = protowire._key(77, 2) + bytes([4, 9, 9, 9, 9])
    assert protowire.decode("KvIntro", unknown + base) == \
        protowire.decode("KvIntro", base)


def test_kv_intro_decode_fills_proto3_defaults():
    """A minimal intro (member_id only) decodes with every other field
    at its proto3 default — absent gone reads False, absent port 0, so
    MeshClient's gone-or-invalid-endpoint check is well-defined."""
    got = protowire.decode(
        "KvIntro", protowire.encode("KvIntro", {"member_id": "m1"}))
    assert got == {"member_id": "m1", "host": "", "data_port": 0,
                   "max_streams": 0, "gone": False, "epoch": 0}


def test_latent_kind3_chunk_wire_fuzz():
    """Kind-3 (latent) payloads ride the SAME self-describing KvChunk
    frame (ISSUE 20 — no proto schema change, DL005 untouched): real
    latent chunks round-trip protowire field-for-field in any order, a
    truncated frame fails to decode, and a payload truncated *with a
    recomputed crc* still rejects at the import session (the kind-3
    buffer-length check), releasing every reserved page."""
    import dataclasses
    import zlib

    import jax.numpy as jnp
    import numpy as np

    from distributed_inference_server_tpu.core.errors import (
        CacheDeserializationError,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        KvImportSession,
        LatentCodec,
        PageAllocator,
        PagedCacheConfig,
        PagedKVState,
        serialize_kv_chunks,
    )
    from distributed_inference_server_tpu.models.configs import TINY

    cfg = PagedCacheConfig(num_pages=16, page_size=4, max_pages_per_seq=8)
    state = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
    nprng = np.random.default_rng(0x7A13)
    k = nprng.standard_normal(state.k.shape).astype(np.float32)
    v = nprng.standard_normal(state.v.shape).astype(np.float32)
    state.k, state.v = jnp.asarray(k), jnp.asarray(v)
    codec = LatentCodec.calibrate(k, v, rank=4)

    rng = random.Random(0x7A14)
    for wire_quant in ("latent", "latent_int8"):
        pages = rng.sample(range(16), 4)
        chunks = list(serialize_kv_chunks(state, pages, cfg.page_size,
                                          chunk_pages=1,
                                          wire_quant=wire_quant,
                                          codec=codec))
        chunks = [dataclasses.replace(c, total=len(chunks))
                  for c in chunks]
        # protowire round-trip, arbitrary arrival order
        wired = []
        for c in chunks:
            d = protowire.decode("KvChunk", protowire.encode("KvChunk", {
                "handoff_id": "h", "index": c.index, "total": c.total,
                "page_start": c.page_start, "page_count": c.page_count,
                "crc32": c.crc32, "payload": c.payload,
            }))
            assert chunk_crc(d["payload"]) == d["crc32"]
            wired.append(KvChunk(index=d["index"], total=d["total"],
                                 page_start=d["page_start"],
                                 page_count=d["page_count"],
                                 payload=d["payload"], crc32=d["crc32"]))
        rng.shuffle(wired)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        alloc = PageAllocator(cfg)
        sess = KvImportSession(fresh, alloc, cfg.page_size, codec=codec)
        sess.reserve(len(pages))
        for c in wired:
            sess.add_chunk(c)
        restored, _ = sess.finish(fresh, list(range(len(pages) * 4)))

        # a frame cut mid-payload never decodes
        frame = protowire.encode("KvChunk", {
            "handoff_id": "h", "index": 0, "total": len(chunks),
            "page_start": 0, "page_count": 1,
            "crc32": chunks[0].crc32, "payload": chunks[0].payload,
        })
        with pytest.raises(ValueError):
            protowire.decode("KvChunk", frame[: len(frame) // 2])

        # truncated payload with a RECOMPUTED crc: survives the wire,
        # rejects at the kind-3 decode, zero pages leaked
        cut = chunks[0].payload[: len(chunks[0].payload) - 8]
        bad = dataclasses.replace(chunks[0], payload=cut,
                                  crc32=zlib.crc32(cut) & 0xFFFFFFFF)
        alloc2 = PageAllocator(cfg)
        free0 = alloc2.num_free()
        sess2 = KvImportSession(PagedKVState.create(TINY, cfg,
                                                    dtype=jnp.float32),
                                alloc2, cfg.page_size, codec=codec)
        sess2.reserve(len(pages))
        with pytest.raises(CacheDeserializationError):
            sess2.add_chunk(bad)
        sess2.abort()
        assert alloc2.num_free() == free0
