"""KvHandoff wire-format fuzz: random SequenceExports round-tripped
through the protowire codec (serving/disagg.py export_to_wire /
export_from_wire), plus schema agreement between serving/inference.proto
and the protowire tables — the runtime twin of distlint rule DL005.

Deterministic seeded random (the image ships no hypothesis): failures
reproduce exactly, and the test always runs in tier 1."""

from __future__ import annotations

import random

from distributed_inference_server_tpu.engine.engine import (
    SamplingParams,
    SequenceExport,
)
from distributed_inference_server_tpu.serving import protowire
from distributed_inference_server_tpu.serving.disagg import (
    export_from_wire,
    export_to_wire,
)
from tools.lint import proto as protodef
from tools.lint.rules import compare_wire_schema

# code points that exercise 1..4-byte UTF-8, U+FFFD, and ASCII controls
_CHARS = (
    "abc XYZ 0189 \t\n" "äßçñ" "中文日本語" "🙂🚀" "�" "'\"\\{}[]"
)


def _rand_text(rng: random.Random, max_len: int = 40) -> str:
    return "".join(rng.choice(_CHARS) for _ in range(rng.randrange(max_len)))


def _rand_export(rng: random.Random) -> SequenceExport:
    n_tokens = rng.randrange(0, 60)
    token_ids = [rng.randrange(0, 2 ** 32) for _ in range(n_tokens)]
    return SequenceExport(
        request_id=_rand_text(rng, 20) or "req-0",
        token_ids=token_ids,
        prompt_len=rng.randrange(0, 4096),
        seq_len=n_tokens,
        next_token=rng.randrange(0, 2 ** 31),
        params=SamplingParams(
            max_tokens=rng.randrange(1, 8192),
            # full-range doubles: bit-exactness across the handoff is the
            # whole point of the double fields (inference.proto note)
            temperature=rng.choice(
                [0.0, 1.0, rng.random() * 2, 7e-45, 0.6999999999999998]
            ),
            top_p=rng.choice([1.0, rng.random() or 0.5, 0.9]),
            stop_sequences=tuple(
                _rand_text(rng, 8) for _ in range(rng.randrange(3))
            ),
        ),
        output_text=_rand_text(rng, 120),
        emitted_upto=rng.randrange(0, 120),
        emitted_tokens=rng.randrange(0, 8192),
        pending_ids=[rng.randrange(0, 2 ** 20)
                     for _ in range(rng.randrange(4))],
        kv=rng.randbytes(rng.randrange(0, 256)),
        draft_kv=(rng.randbytes(rng.randrange(1, 64))
                  if rng.random() < 0.5 else None),
        source_engine=rng.choice(["", "engine-0", "engine-17"]),
    )


def test_kvhandoff_roundtrip_fuzz():
    rng = random.Random(0xD157)
    for i in range(300):
        exp = _rand_export(rng)
        got = export_from_wire(export_to_wire(exp))
        for attr in ("request_id", "token_ids", "prompt_len", "seq_len",
                     "next_token", "output_text", "emitted_upto",
                     "emitted_tokens", "pending_ids", "kv", "source_engine"):
            assert getattr(got, attr) == getattr(exp, attr), (i, attr)
        # draft_kv is `optional bytes`: absent stays absent (None), never
        # collapses to b""
        assert got.draft_kv == exp.draft_kv, i
        p, q = got.params, exp.params
        assert p.max_tokens == q.max_tokens, i
        # doubles must survive BIT-EXACT (sampled-token identity across
        # the handoff); repr equality catches any float32 truncation
        assert repr(p.temperature) == repr(q.temperature), i
        assert repr(p.top_p) == repr(q.top_p), i
        assert tuple(p.stop_sequences) == tuple(q.stop_sequences), i


def test_kvhandoff_decode_fills_proto3_defaults():
    """An all-defaults frame (zero bytes on the wire) reconstructs the
    full key set with proto3 zero values."""
    d = protowire.decode("KvHandoff", b"")
    assert d["token_ids"] == [] and d["pending_ids"] == []
    assert d["stop_sequences"] == []
    assert d["kv"] == b"" and "draft_kv" not in d
    assert d["temperature"] == 0.0 and d["max_tokens"] == 0
    assert d["request_id"] == "" and d["source_engine"] == ""


def test_kvhandoff_unknown_fields_skipped():
    """Forward compatibility: a frame carrying an unknown field decodes
    cleanly (future senders may extend the message)."""
    base = export_to_wire(_rand_export(random.Random(7)))
    # field 100, length-delimited, 3 payload bytes
    unknown = protowire._key(100, 2) + bytes([3, 1, 2, 3])
    d = protowire.decode("KvHandoff", unknown + base)
    assert d == protowire.decode("KvHandoff", base)


def test_total_processed_uint64_roundtrip():
    """EngineStatus.total_processed is uint64 in inference.proto; counts
    past 2^63 must not decode negative (distlint DL005 fix)."""
    big = 2 ** 63 + 5
    data = protowire.encode("EngineStatus", {
        "engine_id": "e", "healthy": True, "total_processed": big,
    })
    assert protowire.decode("EngineStatus", data)["total_processed"] == big


def test_wire_schema_field_numbers_agree_with_proto():
    """Field-number/type/cardinality agreement between inference.proto
    and the live protowire tables — the runtime half of DL005, pinned
    here so a drift fails even if someone disables the linter."""
    import distributed_inference_server_tpu as pkg
    from pathlib import Path

    proto_path = (Path(pkg.__file__).parent / "serving" / "inference.proto")
    schema = protodef.parse_file(proto_path)
    diffs = compare_wire_schema(schema, protowire.MESSAGES, protowire.ENUMS)
    assert diffs == [], diffs
    # and KvHandoff specifically covers every SequenceExport field
    kv = schema.messages["KvHandoff"]
    names = {f.name for f in kv.fields.values()}
    assert {"request_id", "token_ids", "kv", "draft_kv", "temperature",
            "top_p", "stop_sequences", "source_engine"} <= names
