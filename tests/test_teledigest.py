"""Fleet-federated performance telemetry (serving/teledigest.py;
docs/OBSERVABILITY.md "Performance telemetry"): log-bucket layout
determinism, the merge-identity acceptance (merging member digests is
bit-equal to any re-grouping — fuzzed over epochs/buckets), windowed
stats, SLO verdict derivation, the PerfTelemetry store, the
/server/perf payload's enforced field catalog, and the metrics-layer
integration (sliding p99, step clock, slo counters).

Deterministic seeded random (no hypothesis in the image)."""

from __future__ import annotations

import random
import time

from distributed_inference_server_tpu.serving.metrics import (
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.teledigest import (
    DIGEST_NAMES,
    MAX_BUCKET,
    PERF_FIELDS,
    PerfTelemetry,
    SloSettings,
    TELEMETRY_METRICS,
    WindowedDigest,
    bucket_of,
    bucket_value_ms,
    build_perf_payload,
    merge_digests,
    slo_verdict,
    window_stats,
    windowed_count,
)

NOW = 1_700_000_000.0  # fixed wall-clock anchor for determinism


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------


class TestBuckets:
    def test_monotonic_and_bounded(self):
        prev = -1
        for v in [0.0, 1e-6, 1e-3, 0.01, 0.5, 1.0, 7.3, 99.0, 1e4, 1e7,
                  1e12]:
            b = bucket_of(v)
            assert 0 <= b <= MAX_BUCKET
            assert b >= prev, v
            prev = b

    def test_zero_and_negative_land_in_bucket_zero(self):
        assert bucket_of(0.0) == 0
        assert bucket_of(-5.0) == 0
        assert bucket_value_ms(0) == 0.0

    def test_midpoint_within_relative_error(self):
        # 8 buckets/octave: the geometric midpoint is within ~4.4% of
        # any value that mapped into the bucket
        rng = random.Random(7)
        for _ in range(500):
            v = 10 ** rng.uniform(-2.5, 6.5)
            mid = bucket_value_ms(bucket_of(v))
            assert abs(mid - v) / v < 0.05, v


# ---------------------------------------------------------------------------
# merge identity (the tentpole property)
# ---------------------------------------------------------------------------


def _rand_digest(rng: random.Random, epoch_s: float = 5.0,
                 window_s: float = 60.0) -> WindowedDigest:
    d = WindowedDigest(epoch_s=epoch_s, window_s=window_s)
    for _ in range(rng.randrange(0, 200)):
        # spread observations over ~6 epochs
        d.observe(10 ** rng.uniform(-1, 4),
                  now=NOW + rng.random() * 30.0)
    for _ in range(rng.randrange(0, 20)):
        d.count(rng.randrange(1, 4), now=NOW + rng.random() * 30.0)
    return d


class TestMergeIdentity:
    def test_merge_is_grouping_invariant_fuzz(self):
        """THE acceptance property: merge(all members) is bit-equal to
        merge(merge(any partition)) — so the registry's fleet view and
        an operator's offline re-merge of per-member digests can never
        disagree, fuzzed over member counts, epochs, and buckets."""
        rng = random.Random(0x5EED)
        for trial in range(30):
            members = [_rand_digest(rng).to_wire("ttft_ms")
                       for _ in range(rng.randrange(1, 6))]
            flat = merge_digests(members)
            cut = rng.randrange(0, len(members) + 1)
            grouped = merge_digests([
                merge_digests(members[:cut]),
                merge_digests(members[cut:]),
            ])
            assert grouped == flat, trial
            # order invariance
            shuffled = list(members)
            rng.shuffle(shuffled)
            assert merge_digests(shuffled) == flat, trial
            # and the windowed percentiles are therefore identical
            as_of = int((NOW + 30.0) // 5.0)
            assert window_stats(grouped, 60.0, as_of) == \
                window_stats(flat, 60.0, as_of), trial

    def test_merge_counts_are_sums(self):
        a = WindowedDigest(5.0, 60.0)
        b = WindowedDigest(5.0, 60.0)
        for _ in range(10):
            a.observe(12.0, now=NOW)
            b.observe(12.0, now=NOW)
        merged = merge_digests([a.to_wire("x"), b.to_wire("x")])
        as_of = int(NOW // 5.0)
        assert window_stats(merged, 60.0, as_of)["count"] == 20

    def test_wire_form_is_canonical(self):
        """Equal contents produce equal dicts regardless of insertion
        order (sorted epochs + sorted parallel arrays)."""
        rng = random.Random(3)
        values = [(10 ** rng.uniform(-1, 3), NOW + rng.random() * 20)
                  for _ in range(100)]
        d1 = WindowedDigest(5.0, 60.0)
        for v, t in values:
            d1.observe(v, now=t)
        d2 = WindowedDigest(5.0, 60.0)
        for v, t in reversed(values):
            d2.observe(v, now=t)
        assert d1.to_wire("s") == d2.to_wire("s")


class TestWindowing:
    def test_old_epochs_fall_out_of_the_window(self):
        d = WindowedDigest(epoch_s=5.0, window_s=10.0)
        d.observe(100.0, now=NOW)
        d.observe(100.0, now=NOW + 100.0)  # much later epoch
        late = int((NOW + 100.0) // 5.0)
        assert window_stats(d.to_wire("x"), 10.0, late)["count"] == 1

    def test_ring_is_bounded(self):
        d = WindowedDigest(epoch_s=1.0, window_s=10.0)
        for k in range(500):
            d.observe(1.0, now=NOW + k)
        assert len(d._epochs) <= d.ring_epochs

    def test_quantiles_ordered_and_plausible(self):
        d = WindowedDigest(5.0, 60.0)
        for v in range(1, 101):
            d.observe(float(v), now=NOW)
        s = window_stats(d.to_wire("x"), 60.0, int(NOW // 5.0))
        assert s["count"] == 100
        assert s["p50"] <= s["p90"] <= s["p99"]
        assert abs(s["p50"] - 50.0) / 50.0 < 0.10
        assert abs(s["p99"] - 99.0) / 99.0 < 0.10
        assert abs(s["mean"] - 50.5) < 0.01  # exact sums, not buckets

    def test_windowed_count_only_series(self):
        d = WindowedDigest(5.0, 60.0)
        d.count(3, now=NOW)
        d.count(2, now=NOW + 1.0)
        assert windowed_count(d.to_wire("slo.ok"), 60.0,
                              int(NOW // 5.0)) == 5
        assert "p99" not in window_stats(d.to_wire("slo.ok"), 60.0,
                                         int(NOW // 5.0))


# ---------------------------------------------------------------------------
# SLO verdicts
# ---------------------------------------------------------------------------


class TestSloVerdict:
    SLO = SloSettings(ttft_ms=500.0, tbt_p99_ms=50.0,
                      tenant_ttft_ms={"gold": 200.0})

    def test_ok_within_objectives(self):
        v = slo_verdict(self.SLO, "default", 0.3, 0.02, "ok")
        assert v["verdict"] == "ok"
        assert v["ttft_violated"] is False
        assert v["tbt_violated"] is False

    def test_ttft_violation(self):
        v = slo_verdict(self.SLO, "default", 0.9, 0.02, "ok")
        assert v["verdict"] == "violated" and v["ttft_violated"]

    def test_tbt_violation(self):
        v = slo_verdict(self.SLO, "default", 0.1, 0.2, "ok")
        assert v["verdict"] == "violated" and v["tbt_violated"]

    def test_tenant_override_wins(self):
        # 300ms TTFT: fine globally (500), violates gold's 200
        assert slo_verdict(self.SLO, "default", 0.3, None,
                           "ok")["verdict"] == "ok"
        assert slo_verdict(self.SLO, "gold", 0.3, None,
                           "ok")["verdict"] == "violated"

    def test_error_with_applicable_slo_is_violation(self):
        v = slo_verdict(self.SLO, "default", 0.1, 0.01, "error")
        assert v["verdict"] == "violated" and v["errored"]

    def test_no_applicable_objective_no_verdict(self):
        assert slo_verdict(SloSettings(), "default", 0.1, 0.01,
                           "ok") is None

    def test_no_first_token_violates_ttft(self):
        v = slo_verdict(self.SLO, "default", None, None, "error")
        assert v["verdict"] == "violated" and v["ttft_violated"]

    def test_enabled(self):
        assert self.SLO.enabled()
        assert not SloSettings().enabled()
        assert SloSettings(tenant_tbt_ms={"a": 1.0}).enabled()


# ---------------------------------------------------------------------------
# PerfTelemetry store + /server/perf payload
# ---------------------------------------------------------------------------


class TestPerfTelemetry:
    def test_observe_counter_wire_stats(self):
        p = PerfTelemetry(epoch_s=5.0, window_s=60.0)
        p.observe("ttft_ms", 120.0)
        p.count("slo.ok")
        p.add_counter("step.engine-0.prefill.tokens", 64)
        p.add_counter("step.engine-0.prefill.tokens", 36)
        wire = p.wire()
        assert {d["name"] for d in wire["digests"]} == {"ttft_ms",
                                                        "slo.ok"}
        assert wire["counters"] == [
            {"name": "step.engine-0.prefill.tokens", "value": 100.0}
        ]
        assert p.stats()["ttft_ms"]["count"] == 1

    def test_payload_fields_are_cataloged(self):
        """Every top-level /server/perf key is a PERF_FIELDS entry —
        the runtime half of distlint DL014."""
        p = PerfTelemetry()
        p.observe("ttft_ms", 50.0)
        p.count("slo.violated")
        p.add_counter("step.engine-0.decode_block.wall_s", 1.5)
        p.add_counter("events.engine-0.preempt", 2)
        payload = build_perf_payload(
            p, SloSettings(ttft_ms=100.0),
            slo_counts={"default": {"ok": 3, "violated": 1}},
            goodput={"default": 120},
            fleet_members={"w1": {"digests": {}, "counters": {},
                                  "age_s": 0.2}},
        )
        assert set(payload) <= set(PERF_FIELDS), payload.keys()
        assert payload["engines"]["engine-0"]["events"]["preempt"] == 2
        assert payload["engines"]["engine-0"]["kinds"]["decode_block"][
            "wall_s"] == 1.5
        assert payload["slo"]["requests"]["default"]["violated"] == 1
        assert payload["slo"]["goodput_tokens"]["default"] == 120
        assert "w1" in payload["fleet"]["members"]
        # burn rate counts only the windowed slo digests
        assert payload["slo"]["window_requests"]["violated"] == 1
        assert payload["slo"]["burn_rate"] == 1.0

    def test_fleet_merge_in_payload_equals_offline_remerge(self):
        """The two-process acceptance, in miniature: the payload's
        fleet-merged p99 equals re-merging the payload's own member
        digests with the local ones at the payload's as_of_epoch."""
        host = PerfTelemetry(epoch_s=5.0, window_s=60.0)
        member = PerfTelemetry(epoch_s=5.0, window_s=60.0)
        rng = random.Random(11)
        for _ in range(150):
            host.observe("ttft_ms", 10 ** rng.uniform(0, 3))
            member.observe("ttft_ms", 10 ** rng.uniform(0, 3))
        member_wire = member.wire_digests()
        payload = build_perf_payload(
            host, None,
            fleet_members={"w1": {"digests": member_wire,
                                  "counters": {}, "age_s": 0.1}},
        )
        remerged = merge_digests(
            [payload["digests"]["ttft_ms"],
             payload["fleet"]["members"]["w1"]["digests"]["ttft_ms"]])
        expect = window_stats(remerged, payload["window_s"],
                              payload["as_of_epoch"])
        assert payload["fleet"]["merged"]["ttft_ms"] == expect
        assert expect["count"] == 300

    def test_configure_reshapes_rings(self):
        p = PerfTelemetry()
        p.observe("ttft_ms", 1.0)
        p.configure(epoch_s=1.0, window_s=10.0)
        assert p.wire_digests() == {}
        assert p.epoch_s == 1.0 and p.window_s == 10.0


# ---------------------------------------------------------------------------
# metrics-layer integration
# ---------------------------------------------------------------------------


class TestMetricsIntegration:
    def test_telemetry_metric_names_all_registered(self):
        """TELEMETRY_METRICS (the DL014 catalog constant) matches what
        a fresh collector actually registers."""
        m = MetricsCollector()
        registered = {metric.name for metric in
                      m.registry.collect()}
        for name in TELEMETRY_METRICS:
            # prometheus_client strips the _total suffix on counters
            base = (name[:-6] if name.endswith("_total") else name)
            assert base in registered, name

    def test_digest_names_fed_by_collector(self):
        """Every DIGEST_NAMES series has a live feeding path through
        the collector (+ flightrec for the slo counters)."""
        m = MetricsCollector()
        m.record_request("/generate", 200, 0.25)
        m.record_ttft(0.05)
        m.record_request_phases(
            {"queue_wait": 0.01, "prefill": 0.02, "peer_fetch": 0.0,
             "handoff_stall": 0.0, "decode": 0.1, "detok": 0.001},
            tbt_s=0.02,
        )
        for kind in ("prefill", "decode_block", "mixed", "loop"):
            m.observe_step(kind, 0.003)
        m.record_slo("default", "ok", tokens=10)
        m.record_slo("default", "violated")
        assert set(m.perf.wire_digests()) == set(DIGEST_NAMES)

    def test_sliding_p99_replaces_lifetime_sort(self):
        """/server/stats p99 now reads the windowed digest: lifetime
        history outside the window no longer shapes it."""
        m = MetricsCollector()
        for _ in range(50):
            m.record_request("/generate", 200, 0.1)
        snap = m.snapshot()
        assert abs(snap.average_latency_ms - 100.0) < 1e-6
        assert abs(snap.p99_latency_ms - 100.0) / 100.0 < 0.05
        assert not hasattr(m, "_latencies_ms")

    def test_step_clock_recording(self):
        m = MetricsCollector()
        m.record_step_clock("engine-0", "prefill", dispatches=2,
                            wall_s=0.01, tokens=128, rows=3)
        m.record_step_events("engine-0", {"cache_full": 1, "preempt": 0})
        counters = m.perf.counters()
        assert counters["step.engine-0.prefill.tokens"] == 128
        assert counters["events.engine-0.cache_full"] == 1
        assert "events.engine-0.preempt" not in counters
        text = m.prometheus_text().decode()
        assert ('engine_step_tokens_total{engine_id="engine-0",'
                'kind="prefill"} 128.0') in text
        assert ('engine_step_events_total{engine_id="engine-0",'
                'event="cache_full"} 1.0') in text

    def test_slo_tenant_label_set_is_bounded(self):
        m = MetricsCollector()
        for i in range(100):
            m.record_slo(f"tenant-{i}", "ok", tokens=1)
        counts, _ = m.slo_counts()
        assert len(counts) <= 33  # cap + "other"
        assert "other" in counts

    def test_member_telemetry_gauges(self):
        m = MetricsCollector()
        m.record_telemetry_frame("ingested")
        m.set_member_telemetry("w1", {"prefill": 512.0}, 42.0)
        text = m.prometheus_text().decode()
        assert ('fleet_member_step_tokens{kind="prefill",'
                'member="w1"} 512.0') in text
        assert 'fleet_member_ttft_p99_ms{member="w1"} 42.0' in text
        assert ('fleet_telemetry_frames_total{outcome="ingested"} 1.0'
                in text)


# ---------------------------------------------------------------------------
# fleet ingest (host side)
# ---------------------------------------------------------------------------


class TestFleetIngest:
    def _server(self):
        from distributed_inference_server_tpu.serving.fleet import (
            FleetRegistry,
            FleetServer,
            FleetSettings,
        )

        m = MetricsCollector()
        settings = FleetSettings()
        registry = FleetRegistry(settings, metrics=m)
        return FleetServer(registry, scheduler=None, settings=settings,
                           metrics=m), m

    def test_ingest_stores_and_publishes_member_series(self):
        srv, m = self._server()
        dig = WindowedDigest(5.0, 60.0)
        for _ in range(20):
            dig.observe(30.0, now=time.time())
        srv.ingest_telemetry({
            "member_id": "w1",
            "digests": [dig.to_wire("ttft_ms")],
            "counters": [
                {"name": "step.engine-0.prefill.tokens", "value": 64.0},
                {"name": "step.engine-1.prefill.tokens", "value": 36.0},
            ],
        }, "w1")
        snap = srv.telemetry_snapshot()
        assert set(snap) == {"w1"}
        assert "ttft_ms" in snap["w1"]["digests"]
        text = m.prometheus_text().decode()
        # per-engine counters of one kind sum into the member series
        assert ('fleet_member_step_tokens{kind="prefill",'
                'member="w1"} 100.0') in text
        assert 'fleet_member_ttft_p99_ms{member="w1"}' in text

    def test_last_frame_wins(self):
        srv, _ = self._server()
        srv.ingest_telemetry({"digests": [], "counters": [
            {"name": "step.e.prefill.tokens", "value": 1.0}]}, "w1")
        srv.ingest_telemetry({"digests": [], "counters": [
            {"name": "step.e.prefill.tokens", "value": 5.0}]}, "w1")
        snap = srv.telemetry_snapshot()
        assert snap["w1"]["counters"]["step.e.prefill.tokens"] == 5.0

    def test_anonymous_frame_dropped(self):
        srv, _ = self._server()
        srv.ingest_telemetry({"digests": [], "counters": []}, "")
        assert srv.telemetry_snapshot() == {}


class TestReviewFixes:
    """Regressions for the review pass: foreign epoch geometry never
    mis-merges, and pruned members' gauge series are removed."""

    def test_merge_excludes_foreign_epoch_s(self):
        a = WindowedDigest(epoch_s=5.0, window_s=60.0)
        b = WindowedDigest(epoch_s=10.0, window_s=60.0)
        for _ in range(4):
            a.observe(10.0, now=NOW)
            b.observe(10.0, now=NOW)
        merged = merge_digests([a.to_wire("x"), b.to_wire("x")])
        assert merged["epoch_s"] == 5.0
        # the foreign-unit digest contributed nothing
        assert window_stats(merged, 60.0,
                            int(NOW // 5.0))["count"] == 4

    def test_ingest_drops_foreign_epoch_digests(self):
        srv, m = TestFleetIngest()._server()  # host perf epoch_s = 5.0
        foreign = WindowedDigest(epoch_s=10.0, window_s=60.0)
        native = WindowedDigest(epoch_s=5.0, window_s=60.0)
        foreign.observe(5.0, now=time.time())
        native.observe(5.0, now=time.time())
        srv.ingest_telemetry({
            "digests": [foreign.to_wire("ttft_ms"),
                        native.to_wire("tbt_ms")],
            "counters": [],
        }, "w1")
        snap = srv.telemetry_snapshot()
        assert set(snap["w1"]["digests"]) == {"tbt_ms"}
        text = m.prometheus_text().decode()
        assert ('fleet_telemetry_frames_total{outcome="epoch_mismatch"}'
                ' 1.0') in text

    def test_pruned_member_gauge_series_removed(self):
        srv, m = TestFleetIngest()._server()
        srv.ingest_telemetry({"digests": [], "counters": [
            {"name": "step.e.prefill.tokens", "value": 7.0}]}, "old")
        assert 'member="old"' in m.prometheus_text().decode()
        # age the frame past dead_after_s + dead_retention_s
        with srv._lock:
            srv._telemetry["old"]["at"] -= (
                srv.settings.dead_after_s
                + srv.settings.dead_retention_s + 1.0)
        assert srv.telemetry_snapshot() == {}
        text = m.prometheus_text().decode()
        assert 'fleet_member_step_tokens{kind="prefill",member="old"' \
            not in text
        assert 'fleet_member_ttft_p99_ms{member="old"' not in text

    def test_ingest_prunes_even_without_snapshot_polls(self):
        srv, _ = TestFleetIngest()._server()
        srv.ingest_telemetry({"digests": [], "counters": []}, "old")
        with srv._lock:
            srv._telemetry["old"]["at"] -= (
                srv.settings.dead_after_s
                + srv.settings.dead_retention_s + 1.0)
        # a DIFFERENT member's ingest sweeps the stale entry
        srv.ingest_telemetry({"digests": [], "counters": []}, "new")
        with srv._lock:
            assert set(srv._telemetry) == {"new"}

    def test_frame_counts_exactly_one_outcome(self):
        srv, m = TestFleetIngest()._server()
        foreign = WindowedDigest(epoch_s=10.0, window_s=60.0)
        foreign.observe(5.0, now=time.time())
        srv.ingest_telemetry({"digests": [foreign.to_wire("ttft_ms")],
                              "counters": []}, "w1")
        srv.ingest_telemetry({"digests": [], "counters": []}, "w2")
        text = m.prometheus_text().decode()
        assert ('fleet_telemetry_frames_total{outcome="epoch_mismatch"}'
                ' 1.0') in text
        assert ('fleet_telemetry_frames_total{outcome="ingested"} 1.0'
                in text)

    def test_slo_tenant_zero_override_exempts(self):
        """A tenant=0 override is the opt-out from a global objective
        (parse accepts it; limits_for yields no applicable limit)."""
        from distributed_inference_server_tpu.serving.config import (
            ServerConfig,
            parse_tenant_weights,
        )

        assert parse_tenant_weights("batch=0", key="slo.tenant_ttft_ms",
                                    allow_zero=True) == {"batch": 0.0}
        cfg = ServerConfig.load(cli_args=[
            "--slo-ttft-ms", "500", "--slo-tenant-ttft-ms", "batch=0"])
        slo = cfg.slo_settings()
        assert slo.limits_for("batch") == (0.0, 0.0)
        assert slo_verdict(slo, "batch", 99.0, None, "ok") is None
        assert slo_verdict(slo, "default", 99.0, None,
                           "ok")["verdict"] == "violated"
        # the DRR weight grammar still rejects 0 (a zero weight starves)
        import pytest
        from distributed_inference_server_tpu.core.errors import (
            ConfigError,
        )

        with pytest.raises(ConfigError):
            parse_tenant_weights("a=0")

    def test_warmup_compiles_do_not_count_as_retrace(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.engine.engine import (
            EngineConfig,
            LLMEngine,
            SamplingParams,
        )
        from distributed_inference_server_tpu.engine.kv_cache import (
            PagedCacheConfig,
        )
        from distributed_inference_server_tpu.models import llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )

        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)
        eng = LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=2, prefill_buckets=(16,),
                         paged=PagedCacheConfig(num_pages=64, page_size=8,
                                                max_pages_per_seq=8),
                         warmup_compile=False),
            dtype=jnp.float32,
        )
        eng.warmup()
        assert eng.step_clock_stats()["events"]["retrace"] == 0
        # a post-warmup request hitting a NEW bucket does count
        eng.add_request("r1", [3] * 30,
                        SamplingParams(max_tokens=4, temperature=0.0))
        while eng.has_work():
            eng.step()
        # (same bucket as warmup -> 0 is fine; the invariant under test
        # is only that warmup itself contributed nothing)
        stats = eng.step_clock_stats()
        assert stats["kinds"]["prefill"]["dispatches"] >= 1
