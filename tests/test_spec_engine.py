"""Speculative decoding integrated in the continuous-batching engine
(Req 12, requirements.md:164-170): greedy bit-exactness vs the plain
decode path, acceptance tracking, auto-disable fallback, and
nucleus-aware verification for top-p rows (draft samples from its
filtered q̃, verifier filters both sides — full multi-token acceptance,
VERDICT r2 weak #4)."""

import jax
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.core.models import FinishReason
from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.engine.speculative import SpecConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.generate import greedy_generate
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

TOK = ByteTokenizer()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


@pytest.fixture(scope="module")
def draft_params():
    # a *different* tiny model as the draft: realistic partial acceptance
    return llama.init_params(jax.random.PRNGKey(7), TINY, dtype=jnp.float32)


def make_engine(tiny_params, draft=None, spec=None, max_batch=2, rounds=3):
    return LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=max_batch,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(num_pages=64, page_size=4,
                                   max_pages_per_seq=16),
            decode_block_size=rounds,
        ),
        dtype=jnp.float32,
        draft_params=draft,
        draft_cfg=TINY if draft is not None else None,
        spec=spec,
    )


def run(engine, max_steps=500):
    results = {}
    for _ in range(max_steps):
        if not engine.has_work():
            break
        for out in engine.step():
            r = results.setdefault(
                out.request_id,
                {"text": "", "tokens": [], "finish": None, "error": None},
            )
            r["text"] += out.text
            if out.token_id is not None:
                r["tokens"].append(out.token_id)
            if out.finished:
                r["finish"] = out.finish_reason
                r["error"] = out.error
    assert not engine.has_work(), "engine did not drain"
    return results


GREEDY = SamplingParams(max_tokens=12, temperature=0.0)


def test_spec_greedy_bit_exact_same_draft(tiny_params):
    """Draft == target: every proposal accepted, output still must be the
    plain greedy sequence."""
    engine = make_engine(tiny_params, draft=tiny_params,
                         spec=SpecConfig(num_draft_tokens=3))
    prompt = TOK.encode("hello spec")
    engine.add_request("r", prompt, GREEDY)
    out = run(engine)["r"]
    expected = greedy_generate(
        tiny_params, TINY, prompt, max_new_tokens=12, max_seq=64,
        eos_ids=TOK.eos_ids,
    )
    assert out["tokens"] == expected
    assert out["finish"] == FinishReason.LENGTH
    stats = engine.spec_stats()
    assert stats is not None and stats["enabled"]
    assert stats["acceptance_rate"] == 1.0  # greedy, identical models


def test_spec_greedy_bit_exact_different_draft(tiny_params, draft_params):
    """Speculative decoding is exact regardless of the draft: greedy
    output matches the plain engine token-for-token."""
    spec = make_engine(tiny_params, draft=draft_params,
                       spec=SpecConfig(num_draft_tokens=4))
    plain = make_engine(tiny_params)
    prompts = {f"r{i}": TOK.encode(f"prompt {i} xyz") for i in range(3)}
    for rid, ids in prompts.items():
        spec.add_request(rid, ids, GREEDY)
        plain.add_request(rid, ids, GREEDY)
    spec_out = run(spec)
    plain_out = run(plain)
    for rid in prompts:
        assert spec_out[rid]["tokens"] == plain_out[rid]["tokens"], rid
    stats = spec.spec_stats()
    assert stats is not None
    assert 0.0 <= stats["acceptance_rate"] <= 1.0
    assert stats["estimated_speedup"] >= 1.0


def test_spec_auto_disable_falls_back(tiny_params, draft_params):
    """A disabled pattern must fall back to plain decode blocks and still
    produce the exact greedy output (Req 12.5)."""
    from distributed_inference_server_tpu.engine.speculative import (
        spec_signature,
    )

    engine = make_engine(tiny_params, draft=draft_params,
                         spec=SpecConfig(num_draft_tokens=3))
    engine.spec_trackers.disable(spec_signature(GREEDY))
    prompt = TOK.encode("fallback")
    engine.add_request("r", prompt, GREEDY)
    out = run(engine)["r"]
    expected = greedy_generate(
        tiny_params, TINY, prompt, max_new_tokens=12, max_seq=64,
        eos_ids=TOK.eos_ids,
    )
    assert out["tokens"] == expected
    assert engine.spec_stats()["enabled"] is False


def test_spec_pattern_keyed_disable(tiny_params, draft_params):
    """Req 12.5 'per request pattern': with the greedy pattern disabled,
    an interleaved top-p request KEEPS speculating (its pattern tracker
    accrues proposals) while the greedy rows ride the same launches
    masked out (no proposals attributed to the greedy pattern) — and
    greedy output stays bit-exact."""
    from distributed_inference_server_tpu.engine.speculative import (
        spec_signature,
    )

    # probation must NOT fire mid-test: under a contended full-suite run
    # the compile time alone can exceed the 30 s default, re-enabling the
    # deliberately-disabled greedy pattern and flaking the final assert
    engine = make_engine(
        tiny_params, draft=tiny_params,
        spec=SpecConfig(num_draft_tokens=3, reenable_after_s=1e9),
    )
    topp = SamplingParams(max_tokens=12, temperature=0.8, top_p=0.9)
    greedy_sig = spec_signature(GREEDY)
    topp_sig = spec_signature(topp)
    assert greedy_sig != topp_sig
    engine.spec_trackers.disable(greedy_sig)

    prompt = TOK.encode("mixed batch")
    engine.add_request("g", prompt, GREEDY)
    engine.add_request("t", TOK.encode("sampled"), topp)
    out = run(engine)
    assert out["g"]["error"] is None and out["t"]["error"] is None

    # greedy correctness unaffected by riding spec launches masked out
    expected = greedy_generate(
        tiny_params, TINY, prompt, max_new_tokens=12, max_seq=64,
        eos_ids=TOK.eos_ids,
    )
    assert out["g"]["tokens"] == expected

    stats = engine.spec_stats()["patterns"]
    g_key = f"temp_band={greedy_sig[0]},top_p_band={greedy_sig[1]}"
    t_key = f"temp_band={topp_sig[0]},top_p_band={topp_sig[1]}"
    # the top-p pattern actually speculated (draft == target: perfect
    # acceptance) while the greedy pattern logged nothing
    assert t_key in stats
    assert stats[t_key]["acceptance_rate"] > 0.99
    assert stats[t_key]["estimated_speedup"] > 1.5
    assert g_key not in stats or stats[g_key]["estimated_speedup"] == 1.0
    assert engine.spec_stats()["enabled"] is False  # greedy on cooldown


def test_spec_topp_rows_ride_along(tiny_params, draft_params):
    """top-p rows speculate nucleus-aware alongside greedy batch-mates —
    both must finish correctly and greedy stays bit-exact."""
    engine = make_engine(tiny_params, draft=draft_params,
                         spec=SpecConfig(num_draft_tokens=3))
    engine.add_request("greedy", TOK.encode("aaa"), GREEDY)
    engine.add_request(
        "topp", TOK.encode("bbb"),
        SamplingParams(max_tokens=6, temperature=0.8, top_p=0.9),
    )
    out = run(engine)
    expected = greedy_generate(
        tiny_params, TINY, TOK.encode("aaa"), max_new_tokens=12, max_seq=64,
        eos_ids=TOK.eos_ids,
    )
    assert out["greedy"]["tokens"] == expected
    assert out["topp"]["error"] is None
    assert len(out["topp"]["tokens"]) <= 6
    assert out["topp"]["finish"] is not None


def test_spec_stop_sequence_and_page_accounting(tiny_params, draft_params):
    """Stop sequences (host-side) truncate speculative bursts; no page
    leaks afterwards."""
    engine = make_engine(tiny_params, draft=draft_params,
                         spec=SpecConfig(num_draft_tokens=3))
    prompt = TOK.encode("hello")
    engine.add_request("probe", prompt, GREEDY)
    text = run(engine)["probe"]["text"]
    assert len(text) >= 3
    stop = text[1:3]
    engine.add_request(
        "s", prompt,
        SamplingParams(max_tokens=12, temperature=0.0,
                       stop_sequences=(stop,)),
    )
    r = run(engine)["s"]
    assert r["finish"] == FinishReason.STOP_SEQUENCE
    assert stop not in r["text"]
    s = engine.allocator.stats()
    assert s.pages_free + s.pages_cached == s.pages_total


class TestSpecPageCoverage:
    """Regression: with blocks in flight, the projected dev_steps_left is
    only a LOWER bound on the device row's remaining steps (speculative
    rounds emit fewer tokens than assumed when acceptance < 100%), so page
    pre-allocation must keep covering the conserved end dev_pos +
    dev_steps_left + gamma — a projection <= 0 must NOT zero the coverage
    while a block is pending, or the device writes K/V through stale
    block-table entries into other sequences' pages."""

    def test_assumed_adv_covers_conserved_end_with_pending(
        self, tiny_params, draft_params
    ):
        eng = make_engine(tiny_params, draft=draft_params,
                          spec=SpecConfig(num_draft_tokens=3), rounds=2)
        gamma = eng.spec.num_draft_tokens

        class FakeSeq:
            dev_pos = 40
            dev_steps_left = -2  # projection after an assumed-8 launch

        eng._pending.append(object())  # a block is in flight
        # conserved end = dev_pos + dsl + gamma - 1 = 41: one more slot
        assert eng._assumed_adv(FakeSeq(), True) == 1
        eng._pending.clear()
        # host view exact: the row is genuinely frozen
        assert eng._assumed_adv(FakeSeq(), True) == 0

    def test_partial_acceptance_under_pipelining_is_correct(
        self, tiny_params, draft_params
    ):
        # draft != target => partial acceptance; pipeline_depth=1 keeps a
        # block in flight at every launch. Output must still be greedy-
        # bit-exact (corrupted KV would flip tokens).
        eng = make_engine(tiny_params, draft=draft_params,
                          spec=SpecConfig(num_draft_tokens=3), rounds=2)
        ids = TOK.encode("speculate under pipelining")
        eng.add_request("r", ids, SamplingParams(
            max_tokens=24, temperature=0.0))
        got = []
        while eng.has_work():
            for out in eng.step():
                assert out.error is None, out.error
                if out.token_id is not None:
                    got.append(out.token_id)
        ref = list(greedy_generate(tiny_params, TINY, ids, 24))
        assert got == ref[: len(got)] and len(got) == 24


def test_spec_topp_full_acceptance_same_draft(tiny_params):
    """Nucleus-aware verification: with draft == target, a top-p row's
    proposals come from the SAME filtered q̃ the verifier scores with, so
    acceptance is (near-)total — >1 expected token per round, where the
    old forced-rejection path pinned top-p rows to exactly one
    (VERDICT r2 weak #4)."""
    engine = make_engine(tiny_params, draft=tiny_params,
                         spec=SpecConfig(num_draft_tokens=3))
    engine.add_request(
        "topp", TOK.encode("abcabc"),
        SamplingParams(max_tokens=24, temperature=0.8, top_p=0.9),
    )
    out = run(engine)
    assert out["topp"]["error"] is None
    assert len(out["topp"]["tokens"]) == 24
    t = engine.spec_trackers
    # p̃ == q̃ -> accept prob min(1, 1) = 1 at every position
    assert t.rate() > 0.99, t.rate()
    # speedup: tokens per row per target forward must beat 1/round
    assert t.speedup() > 2.0, t.speedup()
