"""Weight quantization (ops/quant.py): round-trip accuracy, exactness on
the integer grid, model-forward fidelity, engine e2e, and TP sharding of
quantized trees (reference quantization levels design.md:324-332 [spec])."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY, TINY_MOE
from distributed_inference_server_tpu.ops.quant import (
    Q4Tensor,
    Q8Tensor,
    dequantize,
    quantize_int4,
    quantize_int8,
    quantize_params,
)


def test_int8_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
    deq = dequantize(quantize_int8(w, 32), jnp.float32)
    err = np.abs(np.asarray(deq - w))
    scale = 0.1  # |w| ~ N(0, 0.1): per-group absmax ~ 0.3
    assert err.max() < scale * 4.5 / 127  # half-step of the grid, padded


def test_int4_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 32)) * 0.1
    deq = dequantize(quantize_int4(w, 32), jnp.float32)
    err = np.abs(np.asarray(deq - w))
    assert err.max() < 0.1 * 4.5 / 7


def test_int4_grid_exact():
    """Values already on the int4 grid survive pack/unpack exactly,
    including negatives (sign extension)."""
    s = 0.5
    grid = jnp.asarray(np.arange(-7, 8, dtype=np.float32) * s)
    w = jnp.tile(grid[:, None], (2, 4))[:28]  # [28, 4], even in-dim
    deq = dequantize(quantize_int4(w, 28), jnp.float32)
    np.testing.assert_allclose(np.asarray(deq), np.asarray(w), atol=1e-6)


def test_stacked_and_moe_shapes():
    p = llama.init_params(jax.random.PRNGKey(0), TINY_MOE, dtype=jnp.float32)
    qp = quantize_params(p, "int8", group_size=32)
    assert isinstance(qp["layers"]["wq"], Q8Tensor)
    assert isinstance(qp["layers"]["w_gate"], Q8Tensor)  # [L, E, in, out]
    assert qp["layers"]["w_gate"].q.shape == p["layers"]["w_gate"].shape
    qp4 = quantize_params(p, "int4", group_size=32)
    assert isinstance(qp4["layers"]["wo"], Q4Tensor)
    assert qp4["layers"]["wo"].q.shape[-2] == p["layers"]["wo"].shape[-2] // 2


@pytest.mark.parametrize("cfg,mode", [(TINY, "int8"), (TINY, "int4"),
                                      (TINY_MOE, "int8")])
def test_forward_close_to_fp32(cfg, mode):
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    qparams = quantize_params(params, mode, group_size=32)
    B, T = 2, 8
    ids = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)

    def run(p):
        cache = llama.KVCache.create(cfg, B, T, dtype=jnp.float32)
        logits, _ = llama.forward(p, cfg, ids, positions, cache, positions,
                                  valid)
        return np.asarray(logits)

    full, quant = run(params), run(qparams)
    # random-weight logits are O(1); weight-only quant keeps them close
    tol = 0.05 if mode == "int8" else 0.4
    assert np.abs(full - quant).max() < tol
    # greedy argmax should rarely flip at int8
    if mode == "int8":
        agree = (full.argmax(-1) == quant.argmax(-1)).mean()
        assert agree > 0.9


def test_engine_serves_quantized_model():
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    qparams = quantize_params(params, "int8", group_size=32)
    eng = LLMEngine(
        qparams, TINY, tok,
        EngineConfig(max_batch=2, prefill_buckets=(8, 32),
                     paged=PagedCacheConfig(num_pages=32, page_size=4,
                                            max_pages_per_seq=8)),
        dtype=jnp.float32,
    )
    eng.add_request("r", tok.encode("quant"),
                    SamplingParams(max_tokens=8, temperature=0.0))
    toks = []
    while eng.has_work():
        for o in eng.step():
            assert o.error is None
            if o.token_id is not None:
                toks.append(o.token_id)
    assert len(toks) == 8


def test_quantized_params_shard_over_tp_mesh():
    from distributed_inference_server_tpu.parallel import (
        MeshSpec,
        make_mesh,
        shard_params,
    )

    mesh = make_mesh(MeshSpec(tensor=2))
    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    qparams = quantize_params(params, "int8", group_size=32)
    sharded = shard_params(qparams, mesh, TINY)
    wq = sharded["layers"]["wq"]
    assert isinstance(wq, Q8Tensor)
    # column-parallel: out axis split over tensor
    assert "tensor" in str(wq.q.sharding.spec)
    # forward still matches the unsharded quantized forward
    B, T = 1, 4
    ids = jnp.ones((B, T), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)

    def run(p):
        cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
        return np.asarray(
            llama.forward(p, TINY, ids, positions, cache, positions, valid)[0]
        )

    with mesh:
        np.testing.assert_allclose(run(sharded), run(qparams), rtol=1e-4,
                                   atol=1e-4)


def test_quantized_default_group_shards_with_tp():
    """Regression: default group_size (128 > TINY dims -> one group) used
    to crash shard_params on row-parallel scales; scales now replicate
    their group axis."""
    from distributed_inference_server_tpu.parallel import (
        MeshSpec,
        make_mesh,
        shard_params,
    )

    mesh = make_mesh(MeshSpec(tensor=2))
    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    qparams = quantize_params(params, "int8")  # default group_size
    sharded = shard_params(qparams, mesh, TINY)  # must not raise
    assert isinstance(sharded["layers"]["wo"], Q8Tensor)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_init_random_quantized_matches_quantize_params_structure(mode):
    """init_random_quantized builds the SAME pytree structure as
    quantize_params(init_params(...)) — same treedef, same leaf shapes
    and dtypes — without materializing the dense tree (the 8B-int8
    single-chip bench path, bench.py BENCH_QUANT)."""
    from distributed_inference_server_tpu.ops.quant import (
        init_random_quantized,
    )

    key = jax.random.PRNGKey(1)
    want = quantize_params(
        llama.init_params(key, TINY, dtype=jnp.float32), mode
    )
    got = init_random_quantized(key, TINY, mode, dtype=jnp.float32)
    wl, wd = jax.tree_util.tree_flatten(want)
    gl, gd = jax.tree_util.tree_flatten(got)
    assert wd == gd
    for w, g in zip(wl, gl):
        assert w.shape == g.shape and w.dtype == g.dtype


def test_init_random_quantized_generates():
    """A model built from init_random_quantized decodes finite logits
    end-to-end (dequant fuses into the matmuls; content is random but
    numerics must stay finite)."""
    from distributed_inference_server_tpu.ops.quant import (
        init_random_quantized,
    )

    params = init_random_quantized(
        jax.random.PRNGKey(2), TINY, "int8", dtype=jnp.float32
    )
    B, T = 2, 8
    ids = jnp.ones((B, T), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    logits = llama.forward(
        params, TINY, ids, positions, cache, positions, valid
    )[0]
    assert bool(jnp.isfinite(logits).all())


def test_init_random_quantized_8b_shapes_fit_one_chip():
    """The 8B-int8 bench path (BENCH_MODEL=llama-3-8b BENCH_QUANT=int8)
    must not hit a shape/divisibility bug in its first real run on the
    chip: eval_shape builds the full quantized tree abstractly (zero
    allocation) and its byte count must fit v5e HBM (~16 GB) with room
    for the KV pool."""
    from distributed_inference_server_tpu.models.configs import LLAMA_3_8B
    from distributed_inference_server_tpu.ops.quant import (
        init_random_quantized,
    )

    shapes = jax.eval_shape(
        lambda k: init_random_quantized(k, LLAMA_3_8B, "int8"),
        jax.random.PRNGKey(0),
    )
    total = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(shapes)
    )
    # ~8 GB int8 linears + ~2 GB bf16 embed/unembed + scales
    assert 8e9 < total < 13e9, total
    # int4 halves the linear bytes again
    shapes4 = jax.eval_shape(
        lambda k: init_random_quantized(k, LLAMA_3_8B, "int4"),
        jax.random.PRNGKey(0),
    )
    total4 = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree_util.tree_leaves(shapes4)
    )
    assert total4 < total - 2e9, (total4, total)
