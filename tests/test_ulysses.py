"""Ulysses all-to-all sequence parallelism (SURVEY §2.3 named ring AND
Ulysses; VERDICT r1 flagged Ulysses absent): op-level numerics vs the
dense reference, cp_prefill flavor equivalence, and the engine's
long-prompt path with sp_impl='ulysses'."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.ops.attention import gqa_attention
from distributed_inference_server_tpu.ops.ulysses import (
    ulysses_attention_sharded,
)
from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh
from distributed_inference_server_tpu.parallel.cp import cp_prefill


class TestUlyssesOp:
    def _case(self, B=2, T=32, H=4, KV=2, D=16, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, T, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, T, KV, D), jnp.float32)
        valid = jnp.asarray([T, T - 5], jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        pos = jnp.where(pos < valid[:, None], pos, -1)
        return q, k, v, pos, valid

    def test_matches_dense_reference(self):
        q, k, v, pos, valid = self._case()
        mesh = make_mesh(MeshSpec(seq=2))
        got = ulysses_attention_sharded(mesh, q, k, v, pos, valid)
        want = gqa_attention(
            q, k, v, jnp.broadcast_to(jnp.arange(q.shape[1])[None],
                                      pos.shape), valid
        )
        # compare only valid rows/positions (padding outputs are garbage
        # by contract)
        for b in range(q.shape[0]):
            n = int(valid[b])
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(want)[b, :n],
                rtol=2e-5, atol=2e-5,
            )

    def test_composes_with_tp(self):
        # tensor=2 halves the local head counts; seq=2 must divide the
        # per-shard 4 q / 2 kv heads
        q, k, v, pos, valid = self._case(H=8, KV=4)
        mesh = make_mesh(MeshSpec(seq=2, tensor=2))
        got = ulysses_attention_sharded(mesh, q, k, v, pos, valid)
        want = gqa_attention(
            q, k, v, jnp.broadcast_to(jnp.arange(q.shape[1])[None],
                                      pos.shape), valid
        )
        n = int(valid[1])
        np.testing.assert_allclose(
            np.asarray(got)[1, :n], np.asarray(want)[1, :n],
            rtol=2e-5, atol=2e-5,
        )

    def test_indivisible_heads_rejected(self):
        q, k, v, pos, valid = self._case()  # KV=2 heads
        mesh = make_mesh(MeshSpec(seq=4))  # 4 does not divide KV=2
        with pytest.raises(ValueError, match="Ulysses"):
            ulysses_attention_sharded(mesh, q, k, v, pos, valid)


class TestUlyssesPrefill:
    def test_cp_prefill_flavors_agree(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        mesh = make_mesh(MeshSpec(seq=2))
        B, T = 2, 32
        ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 1, 250)
        valid = jnp.asarray([T, T - 7], jnp.int32)
        with mesh:
            lr, kr, vr = cp_prefill(params, TINY, mesh, ids, valid,
                                    sp_impl="ring")
            lu, ku, vu = cp_prefill(params, TINY, mesh, ids, valid,
                                    sp_impl="ulysses")
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lu),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(kr), np.asarray(ku),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(vr), np.asarray(vu),
                                   rtol=2e-4, atol=2e-4)

    def test_bad_impl_rejected(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        mesh = make_mesh(MeshSpec(seq=2))
        ids = jnp.ones((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="sp_impl"):
            cp_prefill(params, TINY, mesh, ids,
                       jnp.asarray([16], jnp.int32), sp_impl="nope")


class TestUlyssesEngine:
    PROMPT = "ulysses scatters heads across the interconnect!"  # 48 toks

    def _generate(self, mesh=None, **kw):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        tok = ByteTokenizer()
        eng = LLMEngine(
            params, TINY, tok,
            EngineConfig(
                max_batch=2, prefill_buckets=(16,),
                paged=PagedCacheConfig(num_pages=64, page_size=8,
                                       max_pages_per_seq=8),
                **kw,
            ),
            dtype=jnp.float32, mesh=mesh,
        )
        eng.add_request("r", tok.encode(self.PROMPT),
                        SamplingParams(max_tokens=8, temperature=0.0))
        text = []
        while eng.has_work():
            for out in eng.step():
                assert out.error is None, out.error
                text.append(out.text)
        return "".join(text)

    def test_engine_ulysses_matches_plain(self):
        plain = self._generate()
        uly = self._generate(mesh=make_mesh(MeshSpec(seq=2)),
                             sp_impl="ulysses")
        assert plain == uly

    def test_engine_rejects_indivisible_ulysses(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        with pytest.raises(ValueError, match="Ulysses"):
            LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(sp_impl="ulysses"),
                dtype=jnp.float32, mesh=make_mesh(MeshSpec(seq=4)),
            )
