"""Pipeline parallelism (parallel/pp.py) vs the single-device forward.

GPipe fill-drain over the stage axis must reproduce dense-path logits for
prefill and decode, compose with TP, and emit collective-permute."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.generate import greedy_generate
from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh, shard_params
from distributed_inference_server_tpu.parallel.pp import (
    pp_forward,
    pp_greedy_generate,
    validate_pp,
)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def _dense(params, ids, valid_len, max_seq):
    B, T = ids.shape
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    cache = llama.KVCache.create(TINY, B, max_seq, dtype=jnp.float32)
    return llama.forward(
        params, TINY, ids, positions, cache, positions, valid_len
    )


@pytest.mark.parametrize("stages,mb", [(2, 1), (2, 2), (2, 4)])
def test_pp_prefill_matches_dense(params, stages, mb):
    mesh = make_mesh(MeshSpec(stage=stages))
    B, T = 4, 8
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, TINY.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    want, want_cache = _dense(params, ids, valid, T)

    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    with mesh:
        got, ck, cv = pp_forward(
            mesh, params, TINY, ids, positions, cache.k, cache.v,
            positions, valid, num_microbatches=mb,
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(ck), np.asarray(want_cache.k), rtol=2e-4, atol=2e-4
    )


def test_pp_generation_matches_single_device(params):
    from distributed_inference_server_tpu.models.generate import generate

    mesh = make_mesh(MeshSpec(stage=2))
    B, T0 = 2, 4
    prompt = jax.random.randint(jax.random.PRNGKey(2), (B, T0), 0,
                                TINY.vocab_size)
    want = generate(
        params, TINY, prompt, jnp.full((B,), T0, jnp.int32),
        jax.random.PRNGKey(0), jnp.zeros((B,)), jnp.ones((B,)),
        max_new_tokens=6, max_seq=16,
    ).tokens  # greedy: temperature 0
    got = pp_greedy_generate(mesh, params, TINY, prompt, 6, 16,
                             num_microbatches=2)
    assert np.asarray(got).tolist() == np.asarray(want).tolist()


def test_pp_composes_with_tp(params):
    mesh = make_mesh(MeshSpec(tensor=2, stage=2))
    B, T = 2, 8
    ids = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, TINY.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    want, _ = _dense(params, ids, valid, T)

    sharded = shard_params(params, mesh, TINY)
    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    with mesh:
        got, _, _ = pp_forward(
            mesh, sharded, TINY, ids, positions, cache.k, cache.v,
            positions, valid, num_microbatches=2,
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_pp_emits_collective_permute(params):
    mesh = make_mesh(MeshSpec(stage=2))
    B, T = 4, 4
    ids = jnp.zeros((B, T), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    valid = jnp.full((B,), T, jnp.int32)
    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    with mesh:
        hlo = (
            jax.jit(
                lambda i, p, ck, cv: pp_forward(
                    mesh, params, TINY, i, p, ck, cv, p, valid,
                    num_microbatches=2,
                )[0]
            )
            .lower(ids, positions, cache.k, cache.v)
            .compile()
            .as_text()
        )
    assert "collective-permute" in hlo


def test_validate_pp():
    with pytest.raises(ValueError, match="stages"):
        validate_pp(TINY, 3, 4, 2)  # 2 layers, 3 stages
    with pytest.raises(ValueError, match="microbatches"):
        validate_pp(TINY, 2, 4, 3)
