"""Gray-failure defense (ISSUE 15, serving/health.py;
docs/RESILIENCE.md "Gray failures and overload"): the circuit breaker
state machine, the shared retry budget, deadline-aware admission
shedding (503 + Retry-After + the distinct ``admission_shed`` code,
brownout ordering on DRR weights), the latency-scored HealthScorer's
two-sided hysteresis (wedge / latency / wire evidence), the routing
tiering that consumes its verdicts without ever stranding a request
(Property 20), and the SLO burn rate escalating the degradation
ladder."""

from __future__ import annotations

import time

import pytest

from distributed_inference_server_tpu.core.errors import (
    AdmissionShedApiError,
    ConfigError,
)
from distributed_inference_server_tpu.serving.config import ServerConfig
from distributed_inference_server_tpu.serving.health import (
    AdmissionControl,
    AdmissionSettings,
    AdmissionShed,
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    HEALTH_DEGRADED,
    HEALTH_EJECTED,
    HEALTH_HEALTHY,
    HealthScorer,
    HealthSettings,
    RetryBudget,
    health_rank,
)
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.scheduler import (
    SchedulingStrategy,
    choose_engine,
    health_tier,
    plan_route,
)
from distributed_inference_server_tpu.serving.teledigest import (
    SloSettings,
    WindowedDigest,
)


def _status(eid, health="healthy", healthy=True, load=0, role="unified",
            **kw):
    return EngineStatus(
        engine_id=eid, healthy=healthy, active_requests=load,
        waiting_requests=0, total_processed=0, role=role, health=health,
        **kw,
    )


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_threshold_consecutive_failures(self):
        b = CircuitBreaker(threshold=3, open_s=10.0)
        b.record_failure(now=0.0)
        b.record_failure(now=0.1)
        assert b.state(now=0.2) == BREAKER_CLOSED
        b.record_failure(now=0.2)
        assert b.state(now=0.3) == BREAKER_OPEN
        assert not b.available(now=0.3)
        assert not b.try_acquire(now=0.3)

    def test_success_resets_the_failure_streak(self):
        b = CircuitBreaker(threshold=2, open_s=10.0)
        b.record_failure(now=0.0)
        b.record_success()
        b.record_failure(now=0.1)
        assert b.state(now=0.2) == BREAKER_CLOSED

    def test_half_open_probe_after_cooldown_then_close(self):
        b = CircuitBreaker(threshold=1, open_s=1.0)
        b.record_failure(now=0.0)
        assert not b.try_acquire(now=0.5)  # inside the cooldown
        assert b.state(now=1.1) == BREAKER_HALF_OPEN
        assert b.available(now=1.1)  # election may consider it again
        assert b.try_acquire(now=1.1)  # THE probe
        assert not b.try_acquire(now=1.2)  # only one probe at a time
        b.record_success()
        assert b.state(now=1.3) == BREAKER_CLOSED

    def test_failed_probe_reopens(self):
        b = CircuitBreaker(threshold=1, open_s=1.0)
        b.record_failure(now=0.0)
        assert b.try_acquire(now=1.1)
        b.record_failure(now=1.2)
        assert b.state(now=1.3) == BREAKER_OPEN
        # a fresh cooldown starts at the re-open
        assert not b.try_acquire(now=1.9)
        assert b.try_acquire(now=2.3)

    def test_release_unwedges_an_unused_probe(self):
        b = CircuitBreaker(threshold=1, open_s=1.0)
        b.record_failure(now=0.0)
        assert b.try_acquire(now=1.1)
        b.release()  # the attempt never ran (e.g. window full)
        assert b.try_acquire(now=1.2)  # probe available again

    def test_unanswered_probe_times_out_back_to_open(self):
        """Review regression: a probe whose stream is sent but NEVER
        answered (the wedged-member gray failure) must not pin the
        breaker half-open with the probe consumed — after another
        cooldown the unanswered probe counts as a failure and the
        breaker re-opens (election drops the member again)."""
        b = CircuitBreaker(threshold=1, open_s=1.0)
        b.record_failure(now=0.0)
        assert b.try_acquire(now=1.1)  # the probe goes out... silence
        assert b.state(now=1.5) == BREAKER_HALF_OPEN
        assert b.state(now=2.2) == BREAKER_OPEN  # probe timed out
        assert not b.available(now=2.2)
        # and the cycle continues: a LATER probe can still close it
        assert b.try_acquire(now=3.3)
        b.record_success()
        assert b.state(now=3.4) == BREAKER_CLOSED

    def test_history_and_transition_callback(self):
        seen = []
        b = CircuitBreaker(threshold=1, open_s=1.0,
                           on_transition=seen.append)
        b.record_failure(now=0.0)
        b.state(now=1.1)  # open -> half_open
        b.record_success()
        assert seen == [BREAKER_OPEN, BREAKER_HALF_OPEN, BREAKER_CLOSED]
        assert [s for _, s in b.history()] == seen
        assert b.stats()["transitions"] == 3


# ---------------------------------------------------------------------------
# RetryBudget
# ---------------------------------------------------------------------------


class TestRetryBudget:
    def test_floor_allows_min_retries_with_no_admits(self):
        rb = RetryBudget(ratio=0.1, min_retries=2, window_s=10.0)
        assert rb.acquire("redispatch", now=0.0)
        assert rb.acquire("redispatch", now=0.1)
        assert not rb.acquire("redispatch", now=0.2)

    def test_ratio_scales_with_windowed_admits(self):
        rb = RetryBudget(ratio=0.5, min_retries=1, window_s=10.0)
        for i in range(10):
            rb.note_admit(now=float(i) * 0.1)
        grants = sum(rb.acquire("x", now=2.0) for _ in range(10))
        assert grants == 5  # floor(0.5 * 10)

    def test_window_decay_replenishes(self):
        rb = RetryBudget(ratio=0.0, min_retries=1, window_s=1.0)
        assert rb.acquire("x", now=0.0)
        assert not rb.acquire("x", now=0.5)
        assert rb.acquire("x", now=1.6)  # the old retry fell out

    def test_denials_count_into_metrics(self):
        mc = MetricsCollector()
        rb = RetryBudget(ratio=0.0, min_retries=1, window_s=10.0,
                         metrics=mc)
        rb.acquire("redispatch", now=0.0)
        rb.acquire("redispatch", now=0.1)
        snap = mc.snapshot().to_dict()
        assert snap["resilience"]["retry_denied"] == {"redispatch": 1}
        assert rb.stats()["denied_total"] == 1


# ---------------------------------------------------------------------------
# Deadline-aware admission
# ---------------------------------------------------------------------------


def _overloaded_metrics(wait_ms=2000.0, n=12, window=60.0):
    mc = MetricsCollector()
    mc.configure_perf(5.0, window)
    for _ in range(n):
        mc.perf.observe("queue_wait_ms", wait_ms)
    return mc


class TestAdmission:
    def test_deadline_from_slo_with_tenant_override(self):
        slo = SloSettings(ttft_ms=500.0, tenant_ttft_ms={"vip": 2000.0})
        ac = AdmissionControl(AdmissionSettings(deadline_factor=2.0),
                              slo=slo)
        assert ac.deadline_ms("default") == 1000.0
        assert ac.deadline_ms("vip") == 4000.0

    def test_explicit_deadline_wins(self):
        ac = AdmissionControl(AdmissionSettings(deadline_ms=750.0),
                              slo=SloSettings(ttft_ms=500.0))
        assert ac.deadline_ms("default") == 750.0

    def test_no_deadline_never_sheds(self):
        ac = AdmissionControl(AdmissionSettings(),
                              metrics=_overloaded_metrics())
        assert ac.check("default") is None

    def test_cold_estimator_never_sheds(self):
        mc = _overloaded_metrics(n=3)
        ac = AdmissionControl(AdmissionSettings(min_window_requests=8),
                              slo=SloSettings(ttft_ms=100.0), metrics=mc)
        assert ac.check("default") is None

    def test_sheds_when_estimate_blows_deadline(self):
        ac = AdmissionControl(AdmissionSettings(),
                              slo=SloSettings(ttft_ms=500.0),
                              metrics=_overloaded_metrics(wait_ms=2000.0))
        shed = ac.check("default")
        assert isinstance(shed, AdmissionShed)
        assert shed.reason == "deadline"
        assert shed.retry_after_s >= 1.0
        assert shed.estimate_ms > shed.deadline_ms

    def test_admits_under_the_deadline(self):
        ac = AdmissionControl(AdmissionSettings(),
                              slo=SloSettings(ttft_ms=5000.0),
                              metrics=_overloaded_metrics(wait_ms=100.0))
        assert ac.check("default") is None

    def test_brownout_sheds_low_weight_tenant_first(self):
        """At an intermediate backlog, the low-weight tenant sheds
        (reason "brownout") while the heavy tenant still admits — the
        DRR weights order the brownout."""
        mc = _overloaded_metrics(wait_ms=600.0)
        ac = AdmissionControl(
            AdmissionSettings(),
            slo=SloSettings(ttft_ms=1000.0),
            metrics=mc,
            tenant_weights={"gold": 4.0, "bronze": 1.0},
        )
        # estimate ~600ms: gold's threshold is 1000, bronze's is 250
        assert ac.check("gold") is None
        shed = ac.check("bronze")
        assert shed is not None and shed.reason == "brownout"

    def test_brownout_off_treats_tenants_equally(self):
        mc = _overloaded_metrics(wait_ms=600.0)
        ac = AdmissionControl(
            AdmissionSettings(brownout=False),
            slo=SloSettings(ttft_ms=1000.0),
            metrics=mc,
            tenant_weights={"gold": 4.0, "bronze": 1.0},
        )
        assert ac.check("bronze") is None

    def test_retry_after_capped(self):
        ac = AdmissionControl(
            AdmissionSettings(retry_after_cap_s=5.0),
            slo=SloSettings(ttft_ms=100.0),
            metrics=_overloaded_metrics(wait_ms=60000.0),
        )
        shed = ac.check("default")
        assert shed is not None and shed.retry_after_s == 5.0

    def test_shed_is_a_queue_full_subclass(self):
        # every existing backpressure handler keeps working
        from distributed_inference_server_tpu.core.errors import QueueFull

        assert issubclass(AdmissionShed, QueueFull)

    def test_api_error_maps_503_with_retry_after_header(self):
        from distributed_inference_server_tpu.serving.app import (
            _error_response,
        )

        err = AdmissionShedApiError(retry_after_s=7.0)
        assert err.status_code() == 503
        assert err.code() == "admission_shed"
        resp = _error_response(err)
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "7"


class TestDispatcherShed:
    def _dispatcher(self, ac):
        from distributed_inference_server_tpu.serving.dispatcher import (
            Dispatcher,
        )
        from distributed_inference_server_tpu.serving.flightrec import (
            FlightRecorder,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        mc = ac.metrics
        rec = FlightRecorder(metrics=mc)
        d = Dispatcher(AdaptiveScheduler(), metrics=mc, recorder=rec,
                       admission=ac, retry_budget=RetryBudget(metrics=mc))
        d._accepting = True  # no dispatch thread needed for submit
        return d, rec

    def _request(self, rid="shed-1", tenant="default"):
        from distributed_inference_server_tpu.engine.engine import (
            SamplingParams,
        )
        from distributed_inference_server_tpu.serving.runner import (
            ServerRequest,
        )

        class _Sink:
            def on_token(self, *a, **k): ...
            def on_done(self, *a, **k): ...
            def on_error(self, *a, **k): ...

        return ServerRequest(rid, [1, 2, 3], SamplingParams(max_tokens=4),
                             _Sink(), tenant=tenant)

    def test_submit_sheds_with_terminal_and_metric(self):
        ac = AdmissionControl(AdmissionSettings(),
                              slo=SloSettings(ttft_ms=100.0),
                              metrics=_overloaded_metrics())
        d, rec = self._dispatcher(ac)
        with pytest.raises(AdmissionShed) as ei:
            d.submit(self._request())
        assert ei.value.reason == "deadline"
        tl = rec.timeline("shed-1")
        assert tl["code"] == "admission_shed"
        assert tl["status"] == "error"
        assert any(e["name"] == "admission_shed" for e in tl["events"])
        snap = ac.metrics.snapshot().to_dict()
        assert snap["resilience"]["requests_shed"] == {
            "default": {"deadline": 1}
        }
        assert d.queue.is_empty()  # shed never touches the queue

    def test_admitted_requests_feed_the_retry_budget_window(self):
        ac = AdmissionControl(AdmissionSettings(), metrics=MetricsCollector())
        d, _rec = self._dispatcher(ac)
        d.submit(self._request("ok-1"))
        assert d.retry_budget.stats()["window_admits"] == 1

    def test_shed_does_not_poison_estimator_or_slo(self):
        """Review regression: a shed request's flightrec terminal must
        NOT export its ~0s queue_wait into the very digest the
        estimator reads (admission would oscillate open under a
        standing backlog), and must NOT count an SLO verdict (the burn
        rate tracks admitted traffic only)."""
        from distributed_inference_server_tpu.serving.flightrec import (
            FlightRecorder,
        )
        from distributed_inference_server_tpu.serving.teledigest import (
            window_stats,
        )

        mc = _overloaded_metrics(wait_ms=2000.0, n=12)
        rec = FlightRecorder(metrics=mc, slo=SloSettings(ttft_ms=100.0))
        before = window_stats(mc.perf_store().wire_digest("queue_wait_ms"),
                              mc.perf_store().window_s)
        rec.note("shed-p", "admission_shed", tenant="t", reason="deadline")
        rec.finish("shed-p", "error", code="admission_shed")
        after = window_stats(mc.perf_store().wire_digest("queue_wait_ms"),
                             mc.perf_store().window_s)
        assert after == before  # no 0ms sample landed
        counts, _goodput = mc.slo_counts()
        assert counts == {}  # no verdict for a never-admitted request
        # the timeline itself still carries the full story
        tl = rec.timeline("shed-p")
        assert tl["code"] == "admission_shed" and "slo" not in tl


# ---------------------------------------------------------------------------
# HealthScorer
# ---------------------------------------------------------------------------


class _FakeRunner:
    is_remote = False

    def __init__(self, eid, active=0, waiting=0, remote=False,
                 wire_failures=0, kv_channel=None):
        self.engine_id = eid
        self.is_remote = remote
        self.consecutive_wire_failures = wire_failures
        self.kv_channel = kv_channel
        self._active = active
        self._waiting = waiting

    def status(self):
        return EngineStatus(
            engine_id=self.engine_id, healthy=True,
            active_requests=self._active, waiting_requests=self._waiting,
            total_processed=0, remote=self.is_remote,
        )


class _FakeScheduler:
    def __init__(self, runners):
        self._runners = runners

    def engines(self):
        return list(self._runners)


def _ttft_wire(values_ms, epoch_s=5.0, window_s=60.0):
    d = WindowedDigest(epoch_s, window_s)
    for v in values_ms:
        d.observe(v)
    return d.to_wire("ttft_ms")


class TestHealthScorer:
    def _scorer(self, runners, telemetry=None, metrics=None, **kw):
        kw.setdefault("stall_s", 1.0)
        settings = HealthSettings(
            demote_after=2, recover_after=2, min_window_requests=3,
            latency_ratio=3.0, recover_ratio=1.5,
            wire_failures=2, **kw,
        )
        return HealthScorer(settings, _FakeScheduler(runners),
                            metrics=metrics,
                            telemetry_fn=(lambda: telemetry)
                            if telemetry is not None else None)

    def test_latency_demotes_member_after_demote_after_evals(self):
        mc = MetricsCollector()
        for _ in range(5):
            mc.perf.observe("ttft_ms", 100.0)
        runner = _FakeRunner("m1:engine-0", remote=True)
        telemetry = {"m1": {"digests": {
            "ttft_ms": _ttft_wire([1000.0] * 5)}}}
        s = self._scorer([runner], telemetry=telemetry, metrics=mc)
        assert s.evaluate() == []  # streak 1 of 2
        assert s.state("m1:engine-0") == HEALTH_HEALTHY
        assert s.evaluate() == [("m1:engine-0", HEALTH_HEALTHY,
                                 HEALTH_DEGRADED)]
        assert s.state("m1:engine-0") == HEALTH_DEGRADED
        assert s.stats()["engines"]["m1:engine-0"]["reasons"] == [
            "latency"]

    def test_latency_band_holds_neither_streak(self):
        """Between recover_ratio and latency_ratio x the baseline, a
        demoted source neither recovers nor demotes further — the
        two-sided hysteresis band."""
        mc = MetricsCollector()
        for _ in range(5):
            mc.perf.observe("ttft_ms", 100.0)
        runner = _FakeRunner("m1:engine-0", remote=True)
        bad = {"m1": {"digests": {"ttft_ms": _ttft_wire([1000.0] * 5)}}}
        band = {"m1": {"digests": {"ttft_ms": _ttft_wire([200.0] * 5)}}}
        state = {"t": bad}
        s = HealthScorer(
            HealthSettings(demote_after=2, recover_after=2,
                           min_window_requests=3),
            _FakeScheduler([runner]), metrics=mc,
            telemetry_fn=lambda: state["t"],
        )
        s.evaluate()
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED
        state["t"] = band  # 2x the baseline: inside the band
        for _ in range(5):
            s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED  # held
        state["t"] = {"m1": {"digests": {
            "ttft_ms": _ttft_wire([100.0] * 5)}}}
        s.evaluate()
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_HEALTHY  # recovered

    def test_single_source_never_compares(self):
        mc = MetricsCollector()
        for _ in range(5):
            mc.perf.observe("ttft_ms", 5000.0)
        runner = _FakeRunner("engine-0")
        s = self._scorer([runner], metrics=mc)
        s.evaluate()
        s.evaluate()
        assert s.state("engine-0") == HEALTH_HEALTHY

    def test_wire_failures_eject(self):
        runner = _FakeRunner("m1:engine-0", remote=True, wire_failures=2)
        s = self._scorer([runner])
        s.evaluate()
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED
        s.evaluate()  # eject-class evidence keeps its streak alive
        assert s.state("m1:engine-0") == HEALTH_EJECTED
        runner.consecutive_wire_failures = 0
        s.evaluate()
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED  # one level up

    def test_kv_breaker_open_degrades(self):
        class _Ch:
            def __init__(self):
                # long cooldown: stays OPEN for the whole test
                self.breaker = CircuitBreaker(threshold=1, open_s=600.0)

        ch = _Ch()
        ch.breaker.record_failure()
        runner = _FakeRunner("m1:engine-0", remote=True, kv_channel=ch)
        s = self._scorer([runner])
        s.evaluate()
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED
        assert "kv_breaker_open" in (
            s.stats()["engines"]["m1:engine-0"]["reasons"])

    def test_wedge_ejects_only_after_progress_then_stall(self):
        mc = MetricsCollector()
        runner = _FakeRunner("engine-0", active=2)
        s = self._scorer([runner], metrics=mc, stall_s=0.05)
        # never dispatched: queued work alone is NOT a wedge (a cold
        # replica mid-compile must not read as wedged)
        time.sleep(0.08)
        s.evaluate()
        s.evaluate()
        assert s.state("engine-0") == HEALTH_HEALTHY
        # progress, then a stall past stall_s with work queued
        mc.perf.add_counter("step.engine-0.decode_block.dispatches", 5)
        s.evaluate()
        time.sleep(0.08)
        s.evaluate()
        s.evaluate()
        s.evaluate()
        assert s.state("engine-0") == HEALTH_EJECTED
        reasons = s.stats()["engines"]["engine-0"]["reasons"]
        assert "eject:stalled" in reasons
        # progress resumes -> recovery walks back up
        mc.perf.add_counter("step.engine-0.decode_block.dispatches", 1)
        for _ in range(4):
            s.evaluate()
        assert s.state("engine-0") == HEALTH_HEALTHY

    def test_wedge_clock_restarts_when_work_arrives_after_idle(self):
        """Review regression: idle time is not stall time — an engine
        that sat idle past stall_s must get the FULL stall window after
        work arrives before it can read as wedged."""
        mc = MetricsCollector()
        runner = _FakeRunner("engine-0", active=0)
        s = self._scorer([runner], metrics=mc, stall_s=0.2)
        mc.perf.add_counter("step.engine-0.decode_block.dispatches", 3)
        s.evaluate()  # progress observed, then a long idle stretch
        time.sleep(0.3)
        s.evaluate()  # still idle: clock keeps aging, but no work
        runner._active = 2  # work arrives NOW
        s.evaluate()
        s.evaluate()
        s.evaluate()
        # evaluations are back-to-back: nowhere near stall_s since the
        # work arrived, so no wedge despite the long idle gap
        assert s.state("engine-0") == HEALTH_HEALTHY

    def test_stamp_overlays_and_transitions_counted(self):
        mc = MetricsCollector()
        runner = _FakeRunner("m1:engine-0", remote=True, wire_failures=5)
        s = self._scorer([runner], metrics=mc)
        s.evaluate()
        s.evaluate()
        stamped = s.stamp([_status("m1:engine-0"), _status("other")])
        assert stamped[0].health == HEALTH_DEGRADED
        assert stamped[1].health == HEALTH_HEALTHY
        snap = mc.snapshot().to_dict()
        # transition counted (the gauge rides /metrics, not the snapshot)
        assert "health" not in snap  # health block is served by the app
        assert s.stats()["engines"]["m1:engine-0"]["state"] == (
            HEALTH_DEGRADED)

    def test_unregistered_engines_pruned(self):
        runner = _FakeRunner("m1:engine-0", remote=True, wire_failures=5)
        sched = _FakeScheduler([runner])
        s = HealthScorer(HealthSettings(demote_after=1), sched)
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_DEGRADED
        sched._runners = []
        s.evaluate()
        assert s.state("m1:engine-0") == HEALTH_HEALTHY  # gone = default


# ---------------------------------------------------------------------------
# Routing tiering (Property 20 preserved)
# ---------------------------------------------------------------------------


class TestHealthTiering:
    def test_rank_order(self):
        assert (health_rank(HEALTH_HEALTHY) < health_rank(HEALTH_DEGRADED)
                < health_rank(HEALTH_EJECTED))

    def test_tier_prefers_healthy(self):
        pool = [_status("a", "degraded"), _status("b"), _status("c")]
        assert {s.engine_id for s in health_tier(pool)} == {"b", "c"}

    def test_tier_falls_back_to_degraded_then_ejected(self):
        pool = [_status("a", "degraded"), _status("b", "ejected")]
        assert [s.engine_id for s in health_tier(pool)] == ["a"]
        pool = [_status("b", "ejected")]
        assert [s.engine_id for s in health_tier(pool)] == ["b"]

    def test_choose_engine_avoids_degraded(self):
        statuses = [_status("a", "degraded", load=0), _status("b", load=9)]
        got = choose_engine(SchedulingStrategy.LEAST_LOADED, statuses, 0)
        assert got == "b"  # degraded loses even at much lower load

    def test_choose_engine_never_strands(self):
        statuses = [_status("a", "ejected"), _status("b", "ejected")]
        got = choose_engine(SchedulingStrategy.LEAST_LOADED, statuses, 0)
        assert got == "a"  # Property 20: ejected beats a 503

    def test_plan_route_excludes_ejected_peer_as_fetch_source(self):
        digest = frozenset([11, 22, 33])
        warm_ejected = _status("warm", "ejected", load=0,
                               prefix_digest=digest, page_size=4)
        cold = _status("cold", load=0, page_size=4)
        plan = plan_route([warm_ejected, cold], [11, 22, 33])
        assert plan is not None
        # the ejected replica neither takes the request nor sources a
        # fetch: the cold replica recomputes
        assert plan.engine_id == "cold"
        assert plan.decision == "recompute"


# ---------------------------------------------------------------------------
# SLO burn rate -> degradation ladder
# ---------------------------------------------------------------------------


class TestBurnEscalation:
    def _controller(self, mc, burn_min=5):
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationController,
        )
        from distributed_inference_server_tpu.serving.dispatcher import (
            Dispatcher,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        d = Dispatcher(AdaptiveScheduler(), metrics=mc)
        return DegradationController(
            d, d.scheduler, metrics=mc, burn_high=0.5,
            burn_min_requests=burn_min,
        )

    def test_burn_escalates_and_rung_lifts_on_decay(self):
        """THE regression pin: a violated-heavy window floors the ladder
        at REJECT_LOW_PRIORITY with memory pressure at zero; the rung
        lifts once the short window decays."""
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationLevel,
        )

        mc = MetricsCollector()
        mc.configure_perf(0.05, 0.1)  # tiny window so decay is testable
        ctl = self._controller(mc)
        for _ in range(6):
            mc.record_slo("default", "violated")
        assert ctl.slo_burn_rate() == 1.0
        assert ctl.evaluate(pressure=0.1) == (
            DegradationLevel.REJECT_LOW_PRIORITY)
        assert ctl.dispatcher.reject_low_priority
        time.sleep(0.3)  # the window forgets the violations
        assert ctl.slo_burn_rate() is None
        assert ctl.evaluate(pressure=0.1) == DegradationLevel.NORMAL
        assert not ctl.dispatcher.reject_low_priority

    def test_half_burn_reduces_batch_size(self):
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationLevel,
        )

        mc = MetricsCollector()
        mc.configure_perf(5.0, 60.0)
        ctl = self._controller(mc)
        for _ in range(3):
            mc.record_slo("default", "violated")
        for _ in range(7):
            mc.record_slo("default", "ok")
        assert ctl.slo_burn_rate() == pytest.approx(0.3)
        assert ctl.evaluate(pressure=0.1) == (
            DegradationLevel.REDUCED_BATCH_SIZE)

    def test_below_min_requests_never_escalates(self):
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationLevel,
        )

        mc = MetricsCollector()
        mc.configure_perf(5.0, 60.0)
        ctl = self._controller(mc, burn_min=20)
        for _ in range(6):
            mc.record_slo("default", "violated")
        assert ctl.slo_burn_rate() is None
        assert ctl.evaluate(pressure=0.1) == DegradationLevel.NORMAL

    def test_memory_still_wins_when_worse(self):
        from distributed_inference_server_tpu.serving.degradation import (
            DegradationLevel,
        )

        mc = MetricsCollector()
        mc.configure_perf(5.0, 60.0)
        ctl = self._controller(mc)
        for _ in range(6):
            mc.record_slo("default", "violated")
        assert ctl.evaluate(pressure=0.97) == DegradationLevel.EMERGENCY


# ---------------------------------------------------------------------------
# Redispatch draws from the shared budget
# ---------------------------------------------------------------------------


class TestRedispatchBudget:
    def test_dry_budget_declines_redispatch(self):
        from distributed_inference_server_tpu.serving.dispatcher import (
            Dispatcher,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        mc = MetricsCollector()
        rb = RetryBudget(ratio=0.0, min_retries=0, window_s=10.0,
                         metrics=mc)
        d = Dispatcher(AdaptiveScheduler(), metrics=mc, retry_budget=rb)
        d._accepting = True
        req = TestDispatcherShed()._request("rb-1")
        assert d.redispatch(req, "engine-0", "crash") is False
        snap = mc.snapshot().to_dict()
        assert snap["resilience"]["redispatched"] == {"exhausted": 1}
        assert snap["resilience"]["retry_denied"] == {"redispatch": 1}


# ---------------------------------------------------------------------------
# Config mapping + validation
# ---------------------------------------------------------------------------


class TestHealthConfig:
    def test_settings_mapping(self):
        cfg = ServerConfig.load(environ={
            "DIS_TPU_HEALTH__STALL_S": "9.0",
            "DIS_TPU_HEALTH__WIRE_FAILURES": "5",
            "DIS_TPU_ADMISSION__DEADLINE_MS": "1234",
            "DIS_TPU_ADMISSION__BROWNOUT": "false",
        })
        h = cfg.health_settings()
        assert h.stall_s == 9.0 and h.wire_failures == 5
        a = cfg.admission_settings()
        assert a.deadline_ms == 1234.0 and a.brownout is False

    @pytest.mark.parametrize("env,frag", [
        ({"DIS_TPU_HEALTH__RECOVER_RATIO": "0.9"}, "recover_ratio"),
        ({"DIS_TPU_HEALTH__LATENCY_RATIO": "1.2"}, "latency_ratio"),
        ({"DIS_TPU_HEALTH__RETRY_BUDGET_RATIO": "1.5"},
         "retry_budget_ratio"),
        ({"DIS_TPU_HEALTH__DEMOTE_AFTER": "0"}, "demote_after"),
        ({"DIS_TPU_ADMISSION__DEADLINE_FACTOR": "0"}, "deadline_factor"),
        ({"DIS_TPU_ADMISSION__DEADLINE_MS": "-1"}, "deadline_ms"),
    ])
    def test_validation_rejects(self, env, frag):
        with pytest.raises(ConfigError, match=frag):
            ServerConfig.load(environ=env)
