"""Model correctness anchors (SURVEY.md §7.2 M1).

1. Numeric parity of the JAX Llama against a randomly-initialized HF
   ``LlamaForCausalLM`` on CPU (the ground-truth implementation of the
   architecture the reference planned to serve via llama.cpp).
2. Prefill/decode consistency: incremental decode through the KV cache must
   reproduce full-sequence forward logits.
3. Ragged batching: a request's logits must not depend on its batch-mates.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY, TINY_MOE
from distributed_inference_server_tpu.models.generate import generate, greedy_generate
from distributed_inference_server_tpu.models.loader import (
    config_from_hf_json,
    params_from_hf_state_dict,
)


def _forward_full(params, cfg, ids_batch, lens, dtype=jnp.float32):
    """Single prefill pass over right-padded [B, T] prompts."""
    B, T = ids_batch.shape
    cache = llama.KVCache.create(cfg, B, T, dtype=dtype)
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    write_pos = jnp.where(positions < lens[:, None], positions, T)
    logits, cache = llama.forward(
        params, cfg, ids_batch, positions, cache, write_pos, lens
    )
    return logits, cache


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# 1. Parity vs transformers
# ---------------------------------------------------------------------------


def test_parity_with_transformers(tiny_params):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=True,
        max_position_embeddings=512,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()

    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    cfg = config_from_hf_json(hf_cfg.to_dict(), name="tiny-hf")
    params = params_from_hf_state_dict(state, cfg, dtype=jnp.float32)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()

    lens = jnp.asarray([12, 12], jnp.int32)
    ours, _ = _forward_full(params, cfg, jnp.asarray(ids, jnp.int32), lens)
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4, rtol=2e-3)


# ---------------------------------------------------------------------------
# 2. Prefill/decode consistency through the KV cache
# ---------------------------------------------------------------------------


def test_prefill_then_decode_matches_full_forward(tiny_params):
    cfg = TINY
    params = tiny_params
    rng = np.random.default_rng(1)
    total = 10
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(1, total)), jnp.int32)

    full_logits, _ = _forward_full(params, cfg, ids, jnp.asarray([total]))

    # prefill the first 4 tokens, then decode the rest one at a time
    max_seq = 16
    cache = llama.KVCache.create(cfg, 1, max_seq, dtype=jnp.float32)
    prefill_len = 4
    positions = jnp.arange(prefill_len)[None, :]
    logits, cache = llama.forward(
        params, cfg, ids[:, :prefill_len], positions, cache, positions,
        jnp.asarray([prefill_len]),
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(full_logits[0, :prefill_len]),
        atol=1e-4, rtol=1e-3,
    )

    for t in range(prefill_len, total):
        pos = jnp.asarray([[t]], jnp.int32)
        step_logits, cache = llama.forward(
            params, cfg, ids[:, t : t + 1], pos, cache, pos, jnp.asarray([t + 1])
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[0, 0]), np.asarray(full_logits[0, t]),
            atol=1e-4, rtol=1e-3,
        )


# ---------------------------------------------------------------------------
# 3. Ragged batch isolation
# ---------------------------------------------------------------------------


def test_ragged_batch_matches_single(tiny_params):
    cfg = TINY
    params = tiny_params
    rng = np.random.default_rng(2)
    a = rng.integers(0, cfg.vocab_size, size=9)
    b = rng.integers(0, cfg.vocab_size, size=5)

    T = 9
    batch = np.zeros((2, T), np.int32)
    batch[0, : len(a)] = a
    batch[1, : len(b)] = b
    lens = jnp.asarray([len(a), len(b)], jnp.int32)
    batched, _ = _forward_full(params, cfg, jnp.asarray(batch), lens)

    solo_a, _ = _forward_full(
        params, cfg, jnp.asarray(a[None, :], jnp.int32), jnp.asarray([len(a)])
    )
    solo_b, _ = _forward_full(
        params, cfg, jnp.asarray(b[None, :], jnp.int32), jnp.asarray([len(b)])
    )
    np.testing.assert_allclose(
        np.asarray(batched[0, : len(a)]), np.asarray(solo_a[0]), atol=1e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(batched[1, : len(b)]), np.asarray(solo_b[0]), atol=1e-4, rtol=1e-3
    )


# ---------------------------------------------------------------------------
# 4. Generation loop
# ---------------------------------------------------------------------------


def test_greedy_generate_deterministic(tiny_params):
    prompt = [1, 2, 3, 4]
    out1 = greedy_generate(tiny_params, TINY, prompt, max_new_tokens=8, max_seq=32)
    out2 = greedy_generate(tiny_params, TINY, prompt, max_new_tokens=8, max_seq=32)
    assert out1 == out2
    assert len(out1) == 8


def test_generate_respects_eos(tiny_params):
    # Use the greedy first token as a forced EOS: generation must stop at 0.
    prompt = [1, 2, 3, 4]
    first = greedy_generate(tiny_params, TINY, prompt, max_new_tokens=1, max_seq=32)[0]
    out = greedy_generate(
        tiny_params, TINY, prompt, max_new_tokens=8, max_seq=32, eos_ids=(first,)
    )
    assert out == []


def test_length_stop_not_reported_as_eos(tiny_params):
    # cache-full stop (no EOS configured) must NOT set finished_eos
    cfg = TINY
    ids = jnp.asarray([[1, 2, 3, 4, 5, 6]], jnp.int32)
    res = generate(
        tiny_params, cfg, ids, jnp.asarray([6]), jax.random.PRNGKey(0),
        jnp.zeros((1,)), jnp.ones((1,)), 8, 8, (),
    )
    assert int(res.lengths[0]) == 2  # 8-slot cache, 6-token prompt
    assert not bool(res.finished_eos[0])


def test_generate_batch_ragged(tiny_params):
    cfg = TINY
    ids = jnp.asarray([[1, 2, 3, 4], [5, 6, 0, 0]], jnp.int32)
    lens = jnp.asarray([4, 2], jnp.int32)
    res = generate(
        tiny_params, cfg, ids, lens, jax.random.PRNGKey(0),
        jnp.zeros((2,)), jnp.ones((2,)), 6, 32, (),
    )
    assert res.tokens.shape == (2, 6)
    assert int(res.lengths[0]) == 6 and int(res.lengths[1]) == 6
    # row 1's output must equal generating it alone (batch isolation)
    solo = greedy_generate(tiny_params, cfg, [5, 6], max_new_tokens=6, max_seq=32)
    assert np.asarray(res.tokens[1]).tolist() == solo


# ---------------------------------------------------------------------------
# 5. MoE forward
# ---------------------------------------------------------------------------


def test_moe_forward_runs_and_is_deterministic():
    cfg = TINY_MOE
    params = llama.init_params(jax.random.PRNGKey(3), cfg, dtype=jnp.float32)
    ids = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
    lens = jnp.asarray([5], jnp.int32)
    l1, _ = _forward_full(params, cfg, ids, lens)
    l2, _ = _forward_full(params, cfg, ids, lens)
    assert l1.shape == (1, 5, cfg.vocab_size)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert np.all(np.isfinite(np.asarray(l1)))


class TestCheckpointSaveRoundTrip:
    """save_checkpoint -> load_checkpoint restores identical params and
    an equivalent config for every model family (the persistence half of
    checkpoint/resume; load-only before this)."""

    @pytest.mark.parametrize("preset", [
        "tiny", "tiny-moe", "tiny-bias", "tiny-gemma2",
    ])
    def test_round_trip(self, tmp_path, preset):
        from distributed_inference_server_tpu.models.configs import get_config
        from distributed_inference_server_tpu.models.loader import (
            load_checkpoint,
            save_checkpoint,
        )

        cfg = get_config(preset)
        params = llama.init_params(jax.random.PRNGKey(3), cfg, jnp.float32)
        save_checkpoint(params, cfg, str(tmp_path / preset))
        restored, rcfg = load_checkpoint(str(tmp_path / preset),
                                         dtype=jnp.float32)
        for field in ("vocab_size", "hidden_size", "num_layers",
                      "num_heads", "num_kv_heads", "head_dim",
                      "sliding_window", "sliding_window_pattern",
                      "attention_bias", "num_experts", "activation",
                      "sandwich_norms", "final_logit_softcap",
                      "attn_logit_softcap", "query_pre_attn_scalar",
                      "scale_embeddings", "tie_word_embeddings"):
            assert getattr(rcfg, field) == getattr(cfg, field), field

        flat_a = jax.tree_util.tree_leaves_with_path(params)
        flat_b = {jax.tree_util.keystr(p): v
                  for p, v in jax.tree_util.tree_leaves_with_path(restored)}
        for path, leaf in flat_a:
            key = jax.tree_util.keystr(path)
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(flat_b[key]),
                rtol=1e-6, atol=1e-6, err_msg=key,
            )

    def test_untied_head_round_trip(self, tmp_path):
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.loader import (
            load_checkpoint,
            save_checkpoint,
        )

        cfg = TINY.with_overrides(name="tiny-untied",
                                  tie_word_embeddings=False)
        params = llama.init_params(jax.random.PRNGKey(5), cfg, jnp.float32)
        save_checkpoint(params, cfg, str(tmp_path / "untied"))
        restored, rcfg = load_checkpoint(str(tmp_path / "untied"),
                                         dtype=jnp.float32)
        assert not rcfg.tie_word_embeddings
        np.testing.assert_allclose(
            np.asarray(params["lm_head"]), np.asarray(restored["lm_head"]),
            rtol=1e-6, atol=1e-6,
        )

    def test_saved_checkpoint_loads_in_transformers(self, tmp_path):
        """The written checkpoint is genuinely HF-format: transformers'
        AutoModelForCausalLM restores it and produces matching logits."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        AutoModelForCausalLM = transformers.AutoModelForCausalLM

        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.loader import (
            save_checkpoint,
        )

        params = llama.init_params(jax.random.PRNGKey(3), TINY, jnp.float32)
        save_checkpoint(params, TINY, str(tmp_path / "ckpt"))
        hf = AutoModelForCausalLM.from_pretrained(
            str(tmp_path / "ckpt"), dtype=torch.float32,
            attn_implementation="eager",
        ).eval()
        ids = np.arange(1, 9)[None]
        with torch.no_grad():
            hf_logits = hf(torch.tensor(ids)).logits.numpy()
        T = ids.shape[1]
        cache = llama.KVCache.create(TINY, 1, T, dtype=jnp.float32)
        pos = jnp.arange(T)[None]
        ours, _ = llama.forward(
            params, TINY, jnp.asarray(ids, jnp.int32), pos, cache, pos,
            jnp.full((1,), T, jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(ours), hf_logits, rtol=1e-5, atol=1e-5
        )
