"""Pallas ragged paged-attention decode kernel vs. the pure-XLA reference.

The XLA path (gather pages → dense gqa_attention) is the numerics ground
truth (ops/attention.py docstring); the kernel must match it bitwise-close
on ragged batches with shared/unordered page tables. Runs in Pallas
interpret mode on the CPU backend (conftest pins JAX_PLATFORMS=cpu).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY, ModelConfig
from distributed_inference_server_tpu.ops.attention import gqa_attention
from distributed_inference_server_tpu.ops.pallas import paged_attention_decode

PAGE = 8


def _make_case(rng, B, H, KV, D, num_pages, P, ragged=True):
    """Random pool + per-row block tables with distinct pages and ragged
    valid lengths (>=1: decode rows always contain the just-written token)."""
    ks = list(jax.random.split(rng, 4))
    pool_k = jax.random.normal(ks[0], (num_pages * PAGE, KV, D), jnp.float32)
    pool_v = jax.random.normal(ks[1], (num_pages * PAGE, KV, D), jnp.float32)
    q = jax.random.normal(ks[2], (B, H, D), jnp.float32)
    perm = np.asarray(
        jax.random.permutation(ks[3], num_pages)[: B * P]
    ).reshape(B, P)
    if ragged:
        valid = np.asarray(
            jax.random.randint(ks[3], (B,), 1, P * PAGE + 1)
        )
    else:
        valid = np.full((B,), P * PAGE)
    return q, pool_k, pool_v, jnp.asarray(perm), jnp.asarray(valid)


def _reference(q, pool_k, pool_v, tables, valid):
    B, P = tables.shape
    slots = (tables[:, :, None] * PAGE + jnp.arange(PAGE)[None, None, :]).reshape(
        B, P * PAGE
    )
    k_seq = pool_k[slots]
    v_seq = pool_v[slots]
    positions = (valid - 1)[:, None]  # decode: query is the last valid token
    return gqa_attention(q[:, None], k_seq, v_seq, positions, valid)[:, 0]


@pytest.mark.parametrize(
    "B,H,KV,D,P",
    [
        (4, 8, 4, 16, 4),  # GQA, ragged
        (2, 4, 4, 32, 3),  # MHA (G=1)
        (1, 16, 2, 64, 2),  # heavy grouping
    ],
)
def test_kernel_matches_xla_reference(B, H, KV, D, P):
    rng = jax.random.PRNGKey(B * 1000 + H)
    q, pk, pv, tables, valid = _make_case(rng, B, H, KV, D, num_pages=16, P=P)
    got = paged_attention_decode(
        q, pk, pv, tables, valid, page_size=PAGE, interpret=True
    )
    want = _reference(q, pk, pv, tables, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_full_pages_no_mask_edge():
    rng = jax.random.PRNGKey(7)
    q, pk, pv, tables, valid = _make_case(
        rng, 3, 8, 4, 16, num_pages=16, P=4, ragged=False
    )
    got = paged_attention_decode(
        q, pk, pv, tables, valid, page_size=PAGE, interpret=True
    )
    want = _reference(q, pk, pv, tables, valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_kernel_bf16_io():
    rng = jax.random.PRNGKey(11)
    q, pk, pv, tables, valid = _make_case(rng, 2, 8, 4, 16, num_pages=8, P=2)
    got = paged_attention_decode(
        q.astype(jnp.bfloat16),
        pk.astype(jnp.bfloat16),
        pv.astype(jnp.bfloat16),
        tables,
        valid,
        page_size=PAGE,
        interpret=True,
    )
    want = _reference(q, pk, pv, tables, valid)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), rtol=5e-2, atol=5e-2
    )


def test_paged_forward_pallas_matches_xla():
    """Full paged decode step through the model with both attention impls."""
    cfg = ModelConfig(
        name="t",
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=8,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    B, P, num_pages = 2, 2, 8
    smax = P * PAGE
    pool_shape = (cfg.num_layers, num_pages * PAGE, cfg.num_kv_heads, cfg.head_dim)
    pool_k = jax.random.normal(jax.random.PRNGKey(1), pool_shape, jnp.float32)
    pool_v = jax.random.normal(jax.random.PRNGKey(2), pool_shape, jnp.float32)
    tables = np.array([[3, 5], [0, 7]])
    seq_len = 5  # tokens already resident; decoding token 6
    tokens = jnp.array([[7], [9]], jnp.int32)
    positions = jnp.full((B, 1), seq_len, jnp.int32)
    write_slots = jnp.asarray(
        tables[:, seq_len // PAGE] * PAGE + seq_len % PAGE
    )[:, None]
    gather = jnp.asarray(
        (tables[:, :, None] * PAGE + np.arange(PAGE)[None, None, :])
        .reshape(B, smax)
        .astype(np.int32)
    )
    valid = jnp.full((B,), seq_len + 1, jnp.int32)

    logits_x, kx, vx = llama.paged_forward(
        params, cfg, tokens, positions, pool_k, pool_v, write_slots, gather,
        valid, attention_impl="xla",
    )
    logits_p, kp, vp = llama.paged_forward(
        params, cfg, tokens, positions, pool_k, pool_v, write_slots, gather,
        valid, attention_impl="pallas", page_size=PAGE,
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_x), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(np.asarray(kp), np.asarray(kx), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vp), np.asarray(vx), rtol=1e-6, atol=1e-6)


def test_engine_pallas_with_tp_mesh():
    """Pallas decode attention under a tensor=2 mesh (shard_map over KV
    heads) matches the meshless XLA path end-to-end through the engine."""
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
        SamplingParams,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    tok = ByteTokenizer()
    prompt = tok.encode("tp+pallas")
    results = {}
    for name, mesh, impl in (
        ("xla", None, "xla"),
        ("pallas_tp", make_mesh(MeshSpec(tensor=2)), "pallas"),
    ):
        eng = LLMEngine(
            params, TINY, tok,
            EngineConfig(
                max_batch=2, prefill_buckets=(16, 32),
                paged=PagedCacheConfig(num_pages=32, page_size=4,
                                       max_pages_per_seq=8),
                attention_impl=impl,
            ),
            dtype=jnp.float32, mesh=mesh,
        )
        eng.add_request("r", prompt, SamplingParams(max_tokens=8, temperature=0.0))
        toks = []
        while eng.has_work():
            for o in eng.step():
                if o.token_id is not None:
                    toks.append(o.token_id)
        results[name] = toks
    assert len(results["xla"]) == 8
    assert results["pallas_tp"] == results["xla"]


# ---------------------------------------------------------------------------
# chunked-prefill kernel (VERDICT r1: "no prefill/chunked-prefill kernel")
# ---------------------------------------------------------------------------


class TestPrefillKernel:
    def _pool_case(self, B=2, T=32, H=4, KV=2, D=16, ps=4, pages_per_row=10,
                   seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        num_pages = B * pages_per_row + 2
        q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
        pool_k = jax.random.normal(ks[1], (num_pages * ps, KV, D), jnp.float32)
        pool_v = jax.random.normal(ks[2], (num_pages * ps, KV, D), jnp.float32)
        tables = np.stack([
            2 + b * pages_per_row + np.arange(pages_per_row)
            for b in range(B)
        ]).astype(np.int32)
        return q, pool_k, pool_v, tables, ps

    def _reference(self, q, pool_k, pool_v, tables, ps, q_start, valid):
        B = q.shape[0]
        S = tables.shape[1] * ps
        gather = (tables[:, :, None] * ps
                  + np.arange(ps)[None, None, :]).reshape(B, S)
        k_seq, v_seq = pool_k[gather], pool_v[gather]
        positions = q_start[:, None] + np.arange(q.shape[1])[None]
        return gqa_attention(q, k_seq, v_seq, jnp.asarray(positions),
                             jnp.asarray(valid))

    def _check(self, q_start, valid, q_block=16, **case_kw):
        from distributed_inference_server_tpu.ops.pallas import (
            paged_attention_prefill,
        )

        q, pool_k, pool_v, tables, ps = self._pool_case(**case_kw)
        got = paged_attention_prefill(
            q, pool_k, pool_v, jnp.asarray(tables),
            jnp.asarray(q_start), jnp.asarray(valid),
            page_size=ps, q_block=q_block, pages_per_block=2,
            interpret=True,
        )
        want = self._reference(q, pool_k, pool_v, tables, ps,
                               q_start, valid)
        for b in range(q.shape[0]):
            n = min(q.shape[1], int(valid[b]) - int(q_start[b]))
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(want)[b, :n],
                rtol=2e-5, atol=2e-5,
                err_msg=f"row {b} ({n} real queries)",
            )

    def test_fresh_prefill_matches_reference(self):
        # chunk starts at position 0, full length
        self._check(np.array([0, 0], np.int32),
                    np.array([32, 32], np.int32))

    def test_chunked_prefill_with_cached_prefix(self):
        # row 0: 8 cached tokens before the chunk; row 1: fresh
        self._check(np.array([8, 0], np.int32),
                    np.array([40, 20], np.int32))

    def test_ragged_rows_and_bucket_padding(self):
        # row 1's chunk is shorter than the bucket (12 real of 32)
        self._check(np.array([0, 4], np.int32),
                    np.array([32, 16], np.int32))

    def test_q_block_smaller_than_chunk(self):
        self._check(np.array([0, 0], np.int32),
                    np.array([32, 32], np.int32), q_block=8)

    def test_single_query_degenerate(self):
        self._check(np.array([7, 3], np.int32),
                    np.array([8, 4], np.int32), T=1, q_block=1)

    @pytest.mark.parametrize(
        "H,KV,D",
        [
            (8, 4, 64),  # C=2 heads/chunk -> KVc=2: paired-head lanes
            (8, 4, 128),  # C=1 -> KVc=4: one head per 128-lane chunk
            (6, 3, 64),  # odd KV: C falls back to 1, KVc=3
        ],
    )
    def test_multi_head_chunk_grid(self, H, KV, D):
        """KVc > 1 exercises the (B, KVc, T/TQ) grid's chunk dimension —
        the 128-aligned dynamic lane-window DMA and per-chunk qbd
        expansion/extraction — which the default KV=2/D=16 cases (C=KV,
        KVc=1, lane_lo always 0) never touch. This IS the production
        geometry: head_dim-128 models run one head per chunk."""
        self._check(np.array([0, 4], np.int32),
                    np.array([32, 20], np.int32), H=H, KV=KV, D=D)

    def test_multi_head_chunk_multi_tile(self):
        # chunk grid x q-tile grid together (KVc=2, T/TQ=4)
        self._check(np.array([8, 0], np.int32),
                    np.array([40, 24], np.int32),
                    q_block=8, H=8, KV=4, D=64)

    def test_paged_forward_prefill_pallas_matches_xla(self):
        # through the model layer: full prefill forward, both impls
        cfg = TINY
        params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
        B, T, ps, pages = 2, 16, 4, 8
        num_slots = 64 * ps
        pool = jnp.zeros((cfg.num_layers, num_slots, cfg.num_kv_heads,
                          cfg.head_dim), jnp.float32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 1, 250)
        positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        tables = np.stack([np.arange(pages), 10 + np.arange(pages)])
        write_slots = jnp.asarray(
            tables[:, :T // ps].reshape(B, -1, 1) * ps
            + np.arange(ps)[None, None, :]
        ).reshape(B, T)
        gather = jnp.asarray(
            (tables[:, :, None] * ps + np.arange(ps)[None, None, :])
            .reshape(B, pages * ps).astype(np.int32)
        )
        valid = jnp.full((B,), T, jnp.int32)
        outs = {}
        for impl in ("xla", "pallas"):
            logits, k, v = llama.paged_forward(
                params, cfg, ids, positions, pool, pool, write_slots,
                gather, valid, attention_impl=impl, page_size=ps,
            )
            outs[impl] = (logits, k, v)
        np.testing.assert_allclose(
            np.asarray(outs["xla"][0]), np.asarray(outs["pallas"][0]),
            rtol=2e-4, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(outs["xla"][1]), np.asarray(outs["pallas"][1]),
            rtol=1e-6, atol=1e-6,
        )


@pytest.mark.parametrize(
    "B,H,KV,D,P",
    [
        (4, 8, 4, 16, 4),   # GQA, ragged
        (1, 16, 2, 64, 2),  # heavy grouping
    ],
)
def test_kernel_int8_pool_matches_dequantized_reference(B, H, KV, D, P):
    """QuantPool decode (int8 codes + scale pages, scales folded into the
    score/probability matrices in-kernel) must match the XLA reference
    attention run over the DEQUANTIZED pool exactly — the quantization
    error itself cancels out of the comparison."""
    from distributed_inference_server_tpu.ops.quant import (
        QuantPool,
        dequantize_kv,
        quantize_kv,
    )

    rng = jax.random.PRNGKey(B * 77 + H)
    q, pk, pv, tables, valid = _make_case(rng, B, H, KV, D, num_pages=16, P=P)
    kq, ks = quantize_kv(pk)
    vq, vs = quantize_kv(pv)
    qpool_k = QuantPool(kq, ks)
    qpool_v = QuantPool(vq, vs)
    got = paged_attention_decode(
        q, qpool_k, qpool_v, tables, valid, page_size=PAGE, interpret=True
    )
    want = _reference(
        q,
        dequantize_kv(kq, ks, jnp.float32),
        dequantize_kv(vq, vs, jnp.float32),
        tables, valid,
    )
    # kernel casts codes to bf16 and folds scales in f32; the reference
    # dequantizes to f32 directly — tolerance covers the bf16 cast only
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2
    )


def test_sharded_int8_kernel_matches_dequantized_reference():
    """The QuantPool decode kernel under shard_map (tensor=2 splitting KV
    heads, per-leaf QuantPool specs) matches the dequantized XLA
    reference — the TP wiring the DIS_TPU_KV_QUANT_PALLAS serving path
    launches."""
    from distributed_inference_server_tpu.models.llama import (
        make_pallas_attend,
        shard_pallas_attend,
    )
    from distributed_inference_server_tpu.ops.quant import (
        QuantPool,
        dequantize_kv,
        quantize_kv,
    )
    from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh

    B, H, KV, D, P = 4, 8, 4, 16, 4
    rng = jax.random.PRNGKey(5)
    q, pk, pv, tables, valid = _make_case(rng, B, H, KV, D, num_pages=16, P=P)
    kq, ks = quantize_kv(pk)
    vq, vs = quantize_kv(pv)
    mesh = make_mesh(MeshSpec(tensor=2))
    fn = shard_pallas_attend(
        make_pallas_attend(PAGE, 0.0, True, interpret=True),
        mesh, True, kv_quantized=True,
    )
    with mesh:
        got = fn(q, QuantPool(kq, ks), QuantPool(vq, vs), tables, valid,
                 jnp.int32(0))
    want = _reference(
        q, dequantize_kv(kq, ks, jnp.float32),
        dequantize_kv(vq, vs, jnp.float32), tables, valid,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2
    )
