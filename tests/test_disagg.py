"""Disaggregated prefill/decode serving (serving/disagg.py; ISSUE 1).

Covers the full handoff stack bottom-up:

- KV serialize/deserialize round-trips across pool dtypes (float32,
  bfloat16, int8 quantized pages) and the deserialize-into-allocator
  prefix registration — the handoff path's foundation;
- the KvHandoff protowire framing and both channel backends;
- engine-level export/import token identity;
- role parsing/config validation (nonsensical topologies rejected);
- role-aware scheduling (admission never lands on decode engines);
- serving-level acceptance: a request on 1 prefill + 1 decode engine is
  token-identical to the same request on a single unified engine
  (greedy), and an injected channel failure falls back to in-place
  decode without dropping the request, visibly in metrics.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.core.errors import (
    CacheDeserializationError,
    CacheFull,
    ConfigError,
)
from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
    SequenceExport,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    KvImportSession,
    PageAllocator,
    PagedCacheConfig,
    PagedKVState,
    deserialize_into_allocator,
    deserialize_kv,
    serialize_kv,
    serialize_kv_chunks,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.disagg import (
    DisaggSettings,
    InProcessChannel,
    KVTransferChannel,
    ProtowireChannel,
    export_from_wire,
    export_to_wire,
    make_channel,
    parse_roles,
)
from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.scheduler import (
    SchedulingStrategy,
    choose_engine,
)
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=192, page_size=8, max_pages_per_seq=32)
_PROMPT = "hello disaggregation world"


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def _engine(params, **over):
    return LLMEngine(
        params,
        TINY,
        ByteTokenizer(),
        EngineConfig(max_batch=4, prefill_buckets=(16, 64), paged=_PAGED,
                     **over),
        dtype=jnp.float32,
    )


def _drain(engine, sink_tokens, sink_text):
    while engine.has_work() and not engine.handoff_ready_ids():
        for o in engine.step():
            assert o.error is None, o.error
            if o.token_id is not None:
                sink_tokens.append(o.token_id)
            sink_text.append(o.text)


# ---------------------------------------------------------------------------
# KV serialize/deserialize round-trips (the handoff foundation)
# ---------------------------------------------------------------------------


class TestKvRoundTrip:
    def _state(self, dtype=jnp.float32, kv_quant="none", seed=0):
        cfg = PagedCacheConfig(num_pages=16, page_size=4, max_pages_per_seq=8)
        state = PagedKVState.create(TINY, cfg, dtype=dtype, kv_quant=kv_quant)
        rng = np.random.default_rng(seed)
        if kv_quant == "int8":
            from distributed_inference_server_tpu.ops.quant import QuantPool

            shape = state.k.data.shape
            state.k = QuantPool(
                jnp.asarray(rng.integers(-127, 127, shape, np.int8)),
                jnp.asarray(rng.random(shape[:-1], np.float32)),
            )
            state.v = QuantPool(
                jnp.asarray(rng.integers(-127, 127, shape, np.int8)),
                jnp.asarray(rng.random(shape[:-1], np.float32)),
            )
        else:
            shape = state.k.shape
            state.k = jnp.asarray(
                rng.standard_normal(shape, np.float32), dtype
            )
            state.v = jnp.asarray(
                rng.standard_normal(shape, np.float32), dtype
            )
        return cfg, state

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_roundtrip_exact(self, dtype):
        cfg, state = self._state(dtype)
        pages = [3, 7, 1]
        blob = serialize_kv(state, pages, cfg.page_size, token_count=10)
        fresh = PagedKVState.create(TINY, cfg, dtype=dtype)
        restored, n = deserialize_kv(fresh, blob, pages, cfg.page_size)
        assert n == 10
        slots = np.concatenate(
            [np.arange(p * 4, (p + 1) * 4) for p in pages]
        )
        np.testing.assert_array_equal(
            np.asarray(restored.k[:, slots]), np.asarray(state.k[:, slots])
        )
        np.testing.assert_array_equal(
            np.asarray(restored.v[:, slots]), np.asarray(state.v[:, slots])
        )

    def test_roundtrip_int8_quantized(self):
        cfg, state = self._state(kv_quant="int8")
        pages = [2, 5]
        blob = serialize_kv(state, pages, cfg.page_size, token_count=8)
        fresh = PagedKVState.create(TINY, cfg, kv_quant="int8")
        restored, n = deserialize_kv(fresh, blob, pages, cfg.page_size)
        assert n == 8
        slots = np.concatenate([np.arange(p * 4, (p + 1) * 4) for p in pages])
        np.testing.assert_array_equal(
            np.asarray(restored.k.data[:, slots]),
            np.asarray(state.k.data[:, slots]),
        )
        np.testing.assert_array_equal(
            np.asarray(restored.k.scale[:, slots]),
            np.asarray(state.k.scale[:, slots]),
        )

    def test_quantized_payload_into_float_pool_rejected(self):
        cfg, state = self._state(kv_quant="int8")
        blob = serialize_kv(state, [0], cfg.page_size, token_count=4)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        with pytest.raises(CacheDeserializationError):
            deserialize_kv(fresh, blob, [0], cfg.page_size)

    def test_deserialize_into_allocator_registers_prefix(self):
        cfg, state = self._state()
        alloc = PageAllocator(cfg)
        tokens = list(range(1, 9))  # 8 tokens = 2 full pages
        src_pages = alloc.allocate(2)
        alloc.publish(tokens, src_pages)
        blob = serialize_kv(state, src_pages, cfg.page_size, token_count=8)
        # import into a FRESH allocator (the decode engine's)
        alloc2 = PageAllocator(cfg)
        state2, pages = deserialize_into_allocator(
            state, alloc2, blob, tokens, cfg.page_size
        )
        assert len(pages) == 2
        # prefix registration: a later prompt sharing the tokens hits
        shared, matched = alloc2.match_prefix(tokens + [99])
        assert matched == 8 and shared == list(pages)
        alloc2.release(shared)

    def test_deserialize_into_allocator_no_leak_on_failure(self):
        cfg, state = self._state()
        alloc = PageAllocator(cfg)
        blob = serialize_kv(state, [0, 1], cfg.page_size, token_count=8)
        free_before = alloc.num_free()
        with pytest.raises(CacheDeserializationError):
            # 12 tokens claimed but the payload carries 8
            deserialize_into_allocator(
                state, alloc, blob, list(range(12)), cfg.page_size
            )
        assert alloc.num_free() == free_before

    def test_deserialize_into_allocator_cache_full(self):
        cfg, state = self._state()
        alloc = PageAllocator(cfg)
        held = alloc.allocate(cfg.num_pages)  # exhaust the pool
        blob = serialize_kv(state, [0], cfg.page_size, token_count=4)
        with pytest.raises(CacheFull):
            deserialize_into_allocator(
                state, alloc, blob, [1, 2, 3, 4], cfg.page_size
            )
        alloc.release(held)


# ---------------------------------------------------------------------------
# Streamed serialize: chunked round-trips + the incremental import session
# ---------------------------------------------------------------------------


def _chunks_with_totals(state, pages, page_size, **kw):
    import dataclasses

    chunks = list(serialize_kv_chunks(state, pages, page_size, **kw))
    return [dataclasses.replace(c, total=len(chunks)) for c in chunks]


class TestStreamedKv:
    _state = TestKvRoundTrip._state

    def test_serialize_roundtrip_byte_identical(self):
        """ISSUE 4 satellite: the low-copy packing round-trips to the
        BYTE — serialize(deserialize(blob)) == blob."""
        cfg, state = self._state()
        pages = [3, 7, 1]
        blob = serialize_kv(state, pages, cfg.page_size, token_count=10)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        restored, _ = deserialize_kv(fresh, blob, pages, cfg.page_size)
        assert serialize_kv(restored, pages, cfg.page_size, 10) == blob

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_chunked_roundtrip_any_order(self, dtype):
        cfg, state = self._state(dtype)
        pages = [3, 7, 1, 4, 9]
        chunks = _chunks_with_totals(state, pages, cfg.page_size,
                                     chunk_pages=2)
        assert [c.page_start for c in chunks] == [0, 2, 4]
        alloc = PageAllocator(cfg)
        fresh = PagedKVState.create(TINY, cfg, dtype=dtype)
        sess = KvImportSession(fresh, alloc, cfg.page_size)
        sess.reserve(len(pages))
        for c in reversed(chunks):  # arbitrary arrival order
            sess.add_chunk(c)
        tokens = list(range(1, len(pages) * cfg.page_size + 1))
        restored, got = sess.finish(fresh, tokens)
        src = np.concatenate(
            [np.arange(p * cfg.page_size, (p + 1) * cfg.page_size)
             for p in pages])
        dst = np.concatenate(
            [np.arange(p * cfg.page_size, (p + 1) * cfg.page_size)
             for p in got])
        np.testing.assert_array_equal(
            np.asarray(restored.k[:, dst]), np.asarray(state.k[:, src]))
        np.testing.assert_array_equal(
            np.asarray(restored.v[:, dst]), np.asarray(state.v[:, src]))
        # validated final chunk published the prefix
        shared, matched = alloc.match_prefix(tokens + [999])
        assert matched == len(tokens) and shared == got

    def test_wire_quant_int8_halves_bytes_and_bounds_error(self):
        cfg, state = self._state()
        pages = [0, 1, 2, 3]
        raw = serialize_kv(state, pages, cfg.page_size, 16)
        quant = serialize_kv(state, pages, cfg.page_size, 16,
                             wire_quant="int8")
        assert len(raw) >= 2 * len(quant)  # >= 2x on f32 pools
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        restored, _ = deserialize_kv(fresh, quant, pages, cfg.page_size)
        slots = np.concatenate(
            [np.arange(p * 4, (p + 1) * 4) for p in pages])
        orig = np.asarray(state.k[:, slots])
        got = np.asarray(restored.k[:, slots])
        # per-vector absmax int8: error bounded by scale/2 per element
        bound = np.abs(orig).max(-1, keepdims=True) / 127.0 * 0.51 + 1e-7
        assert (np.abs(got - orig) <= bound).all()

    def test_import_session_crc_corruption_rejected(self):
        cfg, state = self._state()
        chunks = _chunks_with_totals(state, [0, 1], cfg.page_size,
                                     chunk_pages=1)
        import dataclasses

        bad = dataclasses.replace(
            chunks[0],
            payload=chunks[0].payload[:-1]
            + bytes([chunks[0].payload[-1] ^ 0x55]),
        )
        alloc = PageAllocator(cfg)
        sess = KvImportSession(state, alloc, cfg.page_size)
        sess.reserve(2)
        free_before = alloc.num_free()
        with pytest.raises(CacheDeserializationError, match="crc"):
            sess.add_chunk(bad)
        sess.abort()
        assert alloc.num_free() == free_before + 2

    def test_import_session_missing_chunk_releases_everything(self):
        cfg, state = self._state()
        chunks = _chunks_with_totals(state, [0, 1, 2], cfg.page_size,
                                     chunk_pages=1)
        alloc = PageAllocator(cfg)
        total_free = alloc.num_free()
        sess = KvImportSession(state, alloc, cfg.page_size)
        sess.reserve(3)
        sess.add_chunk(chunks[0])
        sess.add_chunk(chunks[2])  # chunk 1 never arrives
        with pytest.raises(CacheDeserializationError, match="incomplete"):
            sess.finish(state, list(range(12)))
        sess.abort()
        assert alloc.num_free() == total_free

    def test_import_session_duplicate_and_overlap_rejected(self):
        cfg, state = self._state()
        chunks = _chunks_with_totals(state, [0, 1], cfg.page_size,
                                     chunk_pages=1)
        alloc = PageAllocator(cfg)
        sess = KvImportSession(state, alloc, cfg.page_size)
        sess.reserve(2)
        sess.add_chunk(chunks[0])
        with pytest.raises(CacheDeserializationError, match="duplicate"):
            sess.add_chunk(chunks[0])
        sess.abort()
        # overlapping page ranges fail the finish-time tiling check
        import dataclasses

        sess2 = KvImportSession(state, PageAllocator(cfg), cfg.page_size)
        sess2.reserve(2)
        sess2.add_chunk(chunks[0])
        sess2.add_chunk(dataclasses.replace(chunks[1], page_start=0,
                                            index=1))
        with pytest.raises(CacheDeserializationError, match="tile"):
            sess2.finish(state, list(range(8)))
        sess2.abort()

    def test_one_shot_chunked_import_sequence(self, tiny_params):
        """SequenceExport.kv_chunks through import_sequence (the in-place
        fallback path for a streamed export)."""
        tok = ByteTokenizer()
        ids = tok.encode(_PROMPT)
        sp = SamplingParams(max_tokens=8, temperature=0.0)
        pre = _engine(tiny_params)
        pre.add_request("r", ids, sp, prefill_only=True)
        toks, text = [], []
        _drain(pre, toks, text)
        seq = pre._handoff_ready["r"]
        chunks = _chunks_with_totals(pre.state, seq.block_table,
                                     pre.pcfg.page_size, chunk_pages=2)
        exp = pre.export_handoff("r")
        import dataclasses

        chunked = dataclasses.replace(exp, kv=b"", kv_chunks=chunks)
        dec = _engine(tiny_params)
        dec.import_sequence(chunked)
        got_toks, got_text = list(toks), list(text)
        _drain(dec, got_toks, got_text)
        dec2 = _engine(tiny_params)
        dec2.import_sequence(exp)
        ref_toks, ref_text = list(toks), list(text)
        _drain(dec2, ref_toks, ref_text)
        assert got_toks == ref_toks


# ---------------------------------------------------------------------------
# Engine-level streamed (decode-overlapped) export
# ---------------------------------------------------------------------------


class TestStreamedExport:
    def _prefill_ready(self, tiny_params, rid="r", max_tokens=96):
        eng = _engine(tiny_params)
        ids = ByteTokenizer().encode(_PROMPT)
        eng.add_request(rid, ids,
                        SamplingParams(max_tokens=max_tokens,
                                       temperature=0.0),
                        prefill_only=True)
        toks, text = [], []
        _drain(eng, toks, text)
        return eng, ids, toks, text

    def test_streamed_export_token_identical(self, tiny_params):
        """Greedy decode across a streamed two-phase handoff (overlap
        decode on the source, phased import on the target) is
        token-identical to in-place decode."""
        tok = ByteTokenizer()
        ids = tok.encode(_PROMPT)
        sp = SamplingParams(max_tokens=96, temperature=0.0)
        uni = _engine(tiny_params)
        uni.add_request("r", ids, sp)
        ref_toks, ref_text = [], []
        _drain(uni, ref_toks, ref_text)

        src, _, got_toks, got_text = self._prefill_ready(tiny_params)
        dst = _engine(tiny_params)
        session = src.export_handoff_begin("r", chunk_pages=2)
        assert session is not None

        def collect(outs):
            for o in outs:
                assert o.error is None
                if o.token_id is not None:
                    got_toks.append(o.token_id)
                got_text.append(o.text)

        collect(src.step())  # overlap: the sequence decodes while the
        src.export_handoff_pump(session)  # prefix moves
        isess = dst.import_stream_open("r", len(session.prefix_pages))
        dst.import_stream_add(isess, session.chunks)
        collect(src.step())  # more overlap
        exp, outputs = src.export_handoff_finish(session)
        assert exp is not None
        collect(outputs)  # overlap-window tokens surface at switchover
        assert got_toks, "no tokens decoded during the overlap window"
        assert not src.has_work()
        tail = exp.kv_chunks[len(session.chunks):]
        import dataclasses

        dst.import_stream_commit(
            isess, dataclasses.replace(exp, kv_chunks=tail))
        _drain(dst, got_toks, got_text)
        assert got_toks == ref_toks
        assert "".join(got_text) == "".join(ref_text)

    def test_streamed_export_int8_wire(self, tiny_params):
        """int8 wire quantization across a streamed handoff: on the tiny
        fixture the greedy output matches in-place decode exactly (the
        per-vector absmax error is below every argmax margin here); the
        general contract is bounded divergence, docs/DISAGG.md."""
        tok = ByteTokenizer()
        ids = tok.encode(_PROMPT)
        sp = SamplingParams(max_tokens=96, temperature=0.0)
        uni = _engine(tiny_params)
        uni.add_request("r", ids, sp)
        ref_toks, ref_text = [], []
        _drain(uni, ref_toks, ref_text)

        src, _, got_toks, got_text = self._prefill_ready(tiny_params)
        dst = _engine(tiny_params)
        session = src.export_handoff_begin("r", chunk_pages=2,
                                           wire_quant="int8")

        def collect(outs):
            for o in outs:
                assert o.error is None
                if o.token_id is not None:
                    got_toks.append(o.token_id)
                got_text.append(o.text)

        collect(src.step())
        src.export_handoff_pump(session)
        exp, outputs = src.export_handoff_finish(session)
        assert exp is not None and exp.wire_quant == "int8"
        collect(outputs)
        # >= 2x byte cut vs the f32 raw encoding of the same pages
        pages_covered = sum(c.page_count for c in exp.kv_chunks)
        raw_bytes = (TINY.num_layers * pages_covered * src.pcfg.page_size
                     * TINY.num_kv_heads * TINY.head_dim * 4 * 2)
        assert exp.kv_bytes() * 2 <= raw_bytes
        dst.import_sequence(exp)  # one-shot form exercises dequant too
        _drain(dst, got_toks, got_text)
        assert len(got_toks) == len(ref_toks)
        assert got_toks == ref_toks  # holds at tiny-fixture scale

    def test_streamed_commit_with_empty_tail(self, tiny_params):
        """Regression: a page-aligned sequence that decodes NOTHING
        during the overlap window commits with zero tail chunks — and
        phase-1 chunks legitimately carry total=0 (the patched totals
        only exist in the source-side export). Completeness must come
        from page coverage, or such migrations can never succeed."""
        ids = list(range(1, 33))  # 32 tokens = exactly 4 full pages
        sp = SamplingParams(max_tokens=64, temperature=0.0)
        uni = _engine(tiny_params)
        uni.add_request("r", ids, sp)
        ref_toks, ref_text = [], []
        _drain(uni, ref_toks, ref_text)

        src = _engine(tiny_params)
        src.add_request("r", ids, sp, prefill_only=True)
        got_toks, got_text = [], []
        _drain(src, got_toks, got_text)
        session = src.export_handoff_begin("r", chunk_pages=2)
        assert session is not None
        src.export_handoff_pump(session)  # no step(): zero overlap decode
        assert all(c.total == 0 for c in session.chunks)
        dst = _engine(tiny_params)
        isess = dst.import_stream_open("r", len(session.prefix_pages))
        dst.import_stream_add(isess, session.chunks)
        exp, outputs = src.export_handoff_finish(session)
        assert exp is not None and not outputs
        tail = exp.kv_chunks[len(session.chunks):]
        assert tail == []
        import dataclasses

        dst.import_stream_commit(
            isess, dataclasses.replace(exp, kv_chunks=tail))
        _drain(dst, got_toks, got_text)
        assert got_toks == ref_toks
        assert "".join(got_text) == "".join(ref_text)

    def test_streamed_export_abort_midstream_releases_everything(
            self, tiny_params):
        src, _, _, _ = self._prefill_ready(tiny_params)
        free0 = src.allocator.num_free()
        session = src.export_handoff_begin("r", chunk_pages=2)
        assert session is not None
        src.step()
        assert src.abort("r")
        src.export_handoff_pump(session)  # detects the dead sequence
        assert session.dead
        exp, outputs = src.export_handoff_finish(session)
        assert exp is None
        assert not src.has_work()
        # every page the aborted request held is allocatable again
        assert src.allocator.num_free() >= free0

    def test_streamed_export_refuses_short_budget(self, tiny_params):
        """A budget that would finish inside the overlap window decodes
        in place instead (begin returns None; monolithic path applies)."""
        eng = _engine(tiny_params)
        ids = ByteTokenizer().encode(_PROMPT)
        eng.add_request("r", ids,
                        SamplingParams(max_tokens=10, temperature=0.0),
                        prefill_only=True)
        toks, text = [], []
        _drain(eng, toks, text)
        assert eng.export_handoff_begin("r") is None
        assert eng.export_handoff("r") is not None  # monolithic still works

    def test_import_commit_failure_releases_pages(self, tiny_params):
        """A commit whose stream is incomplete aborts the session: every
        reserved page returns, nothing is published."""
        src, ids, _, _ = self._prefill_ready(tiny_params)
        dst = _engine(tiny_params)
        session = src.export_handoff_begin("r", chunk_pages=2)
        src.step()
        src.export_handoff_pump(session)
        free0 = dst.allocator.num_free()
        isess = dst.import_stream_open("r", len(session.prefix_pages))
        dst.import_stream_add(isess, session.chunks)
        exp, _ = src.export_handoff_finish(session)
        assert exp is not None
        import dataclasses

        with pytest.raises(CacheDeserializationError):
            # tail chunks withheld -> incomplete stream at commit
            dst.import_stream_commit(
                isess, dataclasses.replace(exp, kv_chunks=[]))
        assert dst.allocator.num_free() == free0
        assert not dst.has_work()


# ---------------------------------------------------------------------------
# Wire framing + channels
# ---------------------------------------------------------------------------


def _export(draft: bool = False) -> SequenceExport:
    return SequenceExport(
        request_id="req-1",
        token_ids=[1, 2, 3, 4, 5],
        prompt_len=5,
        seq_len=5,
        next_token=42,
        params=SamplingParams(max_tokens=16, temperature=0.0, top_p=0.9,
                              stop_sequences=("END",)),
        output_text="heé",  # non-ASCII survives the wire
        emitted_upto=2,
        emitted_tokens=1,
        pending_ids=[200],
        kv=b"\x00\x01\xffkv-payload",
        draft_kv=b"draft" if draft else None,
        source_engine="engine-0",
    )


class TestKvHandoffWire:
    @pytest.mark.parametrize("draft", [False, True])
    def test_wire_roundtrip(self, draft):
        exp = _export(draft)
        got = export_from_wire(export_to_wire(exp))
        assert got.request_id == exp.request_id
        assert got.token_ids == exp.token_ids
        assert got.prompt_len == exp.prompt_len
        assert got.seq_len == exp.seq_len
        assert got.next_token == exp.next_token
        assert got.params == exp.params
        assert got.output_text == exp.output_text
        assert got.emitted_upto == exp.emitted_upto
        assert got.emitted_tokens == exp.emitted_tokens
        assert got.pending_ids == exp.pending_ids
        assert got.kv == exp.kv
        assert got.draft_kv == exp.draft_kv
        assert got.source_engine == exp.source_engine

    def test_greedy_temperature_zero_survives(self):
        # proto3 implicit presence drops 0.0 off the wire; decode must
        # fill it back (temperature 0 = greedy is the acceptance path)
        exp = _export()
        assert export_from_wire(export_to_wire(exp)).params.temperature == 0.0

    def test_channels(self):
        exp = _export()
        assert InProcessChannel().transfer(exp) is exp  # zero-copy
        got = ProtowireChannel().transfer(exp)
        assert got is not exp and got.kv == exp.kv
        assert make_channel("inproc").name == "inproc"
        assert make_channel("protowire").name == "protowire"
        with pytest.raises(ConfigError):
            make_channel("carrier-pigeon")


# ---------------------------------------------------------------------------
# Roles: parsing, topology validation, scheduling
# ---------------------------------------------------------------------------


class TestRoles:
    def test_parse_default_unified(self):
        assert parse_roles("", 3) == ["unified"] * 3

    def test_parse_mixed(self):
        assert parse_roles("Prefill, decode ,unified", 3) == [
            "prefill", "decode", "unified",
        ]

    @pytest.mark.parametrize("spec,n", [
        ("prefill,decode", 3),      # count mismatch
        ("prefill,warp-core", 2),   # unknown role
        ("decode,decode", 2),       # decode with no prefill
        ("prefill,prefill", 2),     # prefill with nowhere to hand off
        ("decode,unified", 2),      # decode fed by nobody
    ])
    def test_parse_rejects(self, spec, n):
        with pytest.raises(ConfigError):
            parse_roles(spec, n)

    def test_config_wires_roles_and_validates(self):
        from distributed_inference_server_tpu.serving.config import (
            ServerConfig,
        )

        cfg = ServerConfig.load(environ={
            "DIS_TPU_SERVER__NUM_ENGINES": "2",
            "DIS_TPU_SERVER__ENGINE_ROLES": "prefill,decode",
            "DIS_TPU_DISAGG__CHANNEL": "protowire",
            "DIS_TPU_DISAGG__HANDOFF_RETRIES": "3",
        })
        assert cfg.engine_roles() == ["prefill", "decode"]
        s = cfg.disagg_settings()
        assert s.channel == "protowire" and s.handoff_retries == 3
        with pytest.raises(ConfigError):
            ServerConfig.load(environ={
                "DIS_TPU_SERVER__NUM_ENGINES": "2",
                "DIS_TPU_SERVER__ENGINE_ROLES": "decode,decode",
            })
        with pytest.raises(ConfigError):
            ServerConfig.load(environ={
                "DIS_TPU_DISAGG__CHANNEL": "smoke-signal",
            })

    def _status(self, eid, role, load=0, healthy=True):
        return EngineStatus(
            engine_id=eid, role=role, healthy=healthy, active_requests=load,
            waiting_requests=0, total_processed=0,
        )

    def test_choose_engine_role_filter(self):
        statuses = [
            self._status("p0", "prefill", load=5),
            self._status("d0", "decode", load=0),
            self._status("u0", "unified", load=9),
        ]
        # admission: decode engines excluded even when least loaded
        got = choose_engine(SchedulingStrategy.LEAST_LOADED, statuses, 0,
                            roles=("prefill", "unified"))
        assert got == "p0"
        # unrestricted call keeps the legacy behavior
        assert choose_engine(
            SchedulingStrategy.LEAST_LOADED, statuses, 0
        ) == "d0"


# ---------------------------------------------------------------------------
# Engine-level handoff
# ---------------------------------------------------------------------------


class TestEngineHandoff:
    def test_export_import_token_identical(self, tiny_params):
        tok = ByteTokenizer()
        ids = tok.encode(_PROMPT)
        sp = SamplingParams(max_tokens=10, temperature=0.0)

        uni = _engine(tiny_params)
        uni.add_request("r", ids, sp)
        ref_toks, ref_text = [], []
        _drain(uni, ref_toks, ref_text)

        pre, dec = _engine(tiny_params), _engine(tiny_params)
        pre.add_request("r", ids, sp, prefill_only=True)
        got_toks, got_text = [], []
        _drain(pre, got_toks, got_text)
        assert pre.handoff_ready_ids() == ["r"]
        exp = pre.export_handoff("r")
        assert not pre.has_work()
        assert exp.seq_len == len(ids) and exp.prompt_len == len(ids)
        dec.import_sequence(exp)
        _drain(dec, got_toks, got_text)
        assert got_toks == ref_toks
        assert "".join(got_text) == "".join(ref_text)

    def test_import_through_protowire_channel_identical(self, tiny_params):
        tok = ByteTokenizer()
        ids = tok.encode(_PROMPT)
        sp = SamplingParams(max_tokens=6, temperature=0.0)
        pre, dec, dec2 = (_engine(tiny_params) for _ in range(3))
        pre.add_request("r", ids, sp, prefill_only=True)
        toks, text = [], []
        _drain(pre, toks, text)
        exp = pre.export_handoff("r")
        a_toks, a_text = list(toks), list(text)
        b_toks, b_text = list(toks), list(text)
        dec.import_sequence(InProcessChannel().transfer(exp))
        _drain(dec, a_toks, a_text)
        dec2.import_sequence(ProtowireChannel().transfer(exp))
        _drain(dec2, b_toks, b_text)
        assert a_toks == b_toks and "".join(a_text) == "".join(b_text)

    def test_abort_of_handoff_ready_releases_pages(self, tiny_params):
        eng = _engine(tiny_params)
        free0 = eng.allocator.num_free()
        eng.add_request("r", ByteTokenizer().encode(_PROMPT),
                        SamplingParams(max_tokens=4, temperature=0.0),
                        prefill_only=True)
        while not eng.handoff_ready_ids():
            eng.step()
        assert eng.allocator.num_free() < free0
        assert eng.abort("r")
        assert eng.handoff_ready_ids() == []
        assert not eng.has_work()
        assert eng.allocator.num_free() == free0
        assert eng.export_handoff("r") is None

    def test_import_capacity_rejection(self, tiny_params):
        eng = _engine(tiny_params)
        exp = _export()
        # seq_len inconsistent with resident tokens
        bad = SequenceExport(**{**exp.__dict__, "seq_len": 3})
        with pytest.raises(CacheDeserializationError):
            eng.import_sequence(bad)


# ---------------------------------------------------------------------------
# Serving-level acceptance
# ---------------------------------------------------------------------------


class _Sink:
    def __init__(self):
        self.toks, self.text = [], ""
        self.done = None
        self.errors = []
        self.ev = threading.Event()

    def on_token(self, token_id, text, token_index, logprob=None):
        if token_id is not None:
            self.toks.append(token_id)
        self.text += text

    def on_done(self, finish_reason, usage):
        self.done = (finish_reason, usage)
        self.ev.set()

    def on_error(self, message, code):
        self.errors.append((message, code))
        self.ev.set()


class _FailingChannel(KVTransferChannel):
    """Injected fault: every transfer raises (acceptance criterion —
    the request must fall back to in-place decode, not drop)."""

    name = "failing"

    def __init__(self):
        self.calls = 0

    def transfer(self, exp):
        self.calls += 1
        raise RuntimeError("injected channel failure")


def _run_request(srv, rid, max_tokens=10):
    sink = _Sink()
    srv.dispatcher.submit(ServerRequest(
        rid, ByteTokenizer().encode(_PROMPT),
        SamplingParams(max_tokens=max_tokens, temperature=0.0), sink,
    ))
    assert sink.ev.wait(90), "request did not complete"
    return sink


@pytest.fixture(scope="module")
def reference_run(tiny_params):
    srv = InferenceServer(
        lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    try:
        sink = _run_request(srv, "ref")
        assert not sink.errors, sink.errors
        return sink
    finally:
        srv.shutdown(drain_timeout_s=5.0)


@pytest.fixture(scope="module")
def disagg_server(tiny_params):
    srv = InferenceServer(
        lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
        num_engines=2, auto_restart=False,
        engine_roles=["prefill", "decode"],
        disagg_settings=DisaggSettings(handoff_timeout_s=30.0),
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


class TestDisaggServing:
    def test_prefill_decode_token_identical_to_unified(
        self, disagg_server, reference_run
    ):
        """Acceptance: 1 prefill + 1 decode == single unified engine,
        token for token (greedy)."""
        got = _run_request(disagg_server, "d-identity")
        assert not got.errors, got.errors
        assert got.toks == reference_run.toks
        assert got.text == reference_run.text
        assert got.done[0] == reference_run.done[0]
        assert got.done[1].prompt_tokens == reference_run.done[1].prompt_tokens
        assert (got.done[1].completion_tokens
                == reference_run.done[1].completion_tokens)
        snap = disagg_server.metrics.snapshot(
            tuple(disagg_server.scheduler.statuses())
        ).to_dict()
        assert snap["disagg"]["handoffs"].get("ok", 0) >= 1
        assert snap["disagg"]["handoff_bytes"] > 0
        roles = {w["engine_id"]: w["role"] for w in snap["worker_statuses"]}
        assert roles == {"engine-0": "prefill", "engine-1": "decode"}

    def test_decode_engine_finishes_the_request(self, disagg_server,
                                                reference_run):
        """The decode replica, not the prefill one, carries the decode:
        total_processed lands on engine-1."""
        _run_request(disagg_server, "d-owner")
        statuses = {s.engine_id: s for s in disagg_server.scheduler.statuses()}
        assert statuses["engine-1"].total_processed >= 1

    def test_handoff_failure_falls_back_in_place(self, disagg_server,
                                                 reference_run):
        """Acceptance: injected channel error → in-place decode on the
        prefill engine, request completes identically, fallback visible
        in metrics."""
        chan = disagg_server.disagg.channel
        failing = _FailingChannel()
        disagg_server.disagg.channel = failing
        try:
            got = _run_request(disagg_server, "d-fallback")
        finally:
            disagg_server.disagg.channel = chan
        assert not got.errors, got.errors
        assert got.toks == reference_run.toks
        assert got.text == reference_run.text
        assert failing.calls >= 1
        snap = disagg_server.metrics.snapshot().to_dict()
        assert snap["disagg"]["handoffs"].get("fallback", 0) >= 1
        assert snap["disagg"]["handoffs"].get("retry", 0) >= 1

    def test_prometheus_text_carries_handoff_metrics(self, disagg_server):
        text = disagg_server.metrics.prometheus_text().decode()
        assert "kv_handoff_latency_seconds" in text
        assert "kv_handoff_bytes_total" in text
        assert "kv_handoff_stall_seconds" in text
        assert "kv_handoff_chunks_total" in text
        assert 'engines_by_role{role="prefill"}' in text

    def test_streamed_handoff_serving_token_identical(self, tiny_params):
        """Serving-level acceptance for the STREAMED (two-phase) path: a
        completion long enough to stream migrates with chunks > 0 and is
        token-identical to a unified engine; the stall metric is
        populated."""
        uni = InferenceServer(
            lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
            num_engines=1, auto_restart=False,
        )
        uni.start()
        try:
            ref = _run_request(uni, "s-ref", max_tokens=96)
        finally:
            uni.shutdown(drain_timeout_s=5.0)
        assert not ref.errors, ref.errors

        srv = InferenceServer(
            lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
            num_engines=2, auto_restart=False,
            engine_roles=["prefill", "decode"],
            disagg_settings=DisaggSettings(handoff_timeout_s=30.0,
                                           channel="protowire"),
        )
        srv.start()
        try:
            got = _run_request(srv, "s-stream", max_tokens=96)
            snap = srv.metrics.snapshot(
                tuple(srv.scheduler.statuses())).to_dict()
            statuses = {s.engine_id: s for s in srv.scheduler.statuses()}
        finally:
            srv.shutdown(drain_timeout_s=5.0)
        assert not got.errors, got.errors
        assert got.toks == ref.toks
        assert got.text == ref.text
        d = snap["disagg"]
        assert d["handoffs"].get("ok", 0) >= 1, d
        assert d["handoff_chunks"] >= 1, d
        assert d["handoff_stall_avg_ms"] > 0, d
        # the decode replica finished the request
        assert statuses["engine-1"].total_processed >= 1

    def test_protowire_channel_end_to_end(self, tiny_params, reference_run):
        srv = InferenceServer(
            lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
            num_engines=2, auto_restart=False,
            engine_roles=["prefill", "decode"],
            disagg_settings=DisaggSettings(channel="protowire",
                                           handoff_timeout_s=30.0),
        )
        srv.start()
        try:
            got = _run_request(srv, "d-wire")
            assert not got.errors, got.errors
            assert got.toks == reference_run.toks
            snap = srv.metrics.snapshot().to_dict()
            assert snap["disagg"]["handoffs"].get("ok", 0) >= 1
        finally:
            srv.shutdown(drain_timeout_s=5.0)
