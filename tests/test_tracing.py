"""Request-lifecycle tracing (S12, requirements.md:122 [spec]): span
model, ring sink, and end-to-end span trees through the serving spine."""

import asyncio

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.utils.tracing import Tracer


def test_span_parenting_and_ring():
    t = Tracer(capacity=8)
    with t.span("request", request_id="r1") as root:
        root.event("queued")
        with t.span("engine.infer", parent=root.context()) as child:
            child.set(tokens=5)
    spans = t.recent()
    assert [s.name for s in spans] == ["engine.infer", "request"]
    child, root = spans
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id is None
    assert root.duration_ms >= child.duration_ms >= 0
    assert root.events and root.events[0][1] == "queued"


def test_span_error_status_and_capacity():
    t = Tracer(capacity=3)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    assert t.recent()[-1].status == "error"
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.recent()) == 3  # bounded ring


def test_trace_filter():
    t = Tracer()
    with t.span("a") as a:
        pass
    with t.span("b"):
        pass
    only_a = t.recent(trace_id=a.trace_id)
    assert [s.name for s in only_a] == ["a"]


@pytest.fixture(scope="module")
def server():
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.server import InferenceServer

    def factory():
        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=PagedCacheConfig(num_pages=64, page_size=8,
                                                max_pages_per_seq=16)),
            dtype=jnp.float32,
        )

    srv = InferenceServer(factory, ByteTokenizer(), model_name="tiny",
                          num_engines=1, auto_restart=False)
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def test_request_produces_span_tree(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "trace me", "max_tokens": 4, "temperature": 0.0},
        )
        assert resp.status == 200
        tr = await (await client.get("/server/trace?n=50")).json()
        return tr["spans"]

    spans = _run(server, go)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert "request.generate" in by_name
    assert "engine.infer" in by_name
    assert "batch.dispatch" in by_name
    root = by_name["request.generate"][-1]
    engine = by_name["engine.infer"][-1]
    assert engine["trace_id"] == root["trace_id"]
    assert engine["parent_id"] == root["span_id"]
    assert root["status"] == "ok"
    assert any(e["name"] == "queued" for e in root["events"])
    assert any(e["name"] == "dispatched" for e in root["events"])
    assert any(e["name"] == "first_token" for e in engine["events"])
    assert engine["attributes"]["completion_tokens"] == 4
