"""Request-lifecycle tracing (S12, requirements.md:122 [spec]): span
model, ring sink, and end-to-end span trees through the serving spine."""

import asyncio

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.utils.tracing import Tracer


def test_span_parenting_and_ring():
    t = Tracer(capacity=8)
    with t.span("request", request_id="r1") as root:
        root.event("queued")
        with t.span("engine.infer", parent=root.context()) as child:
            child.set(tokens=5)
    spans = t.recent()
    # recent() sorts by START time (ingested remote spans arrive late,
    # so ring order is not start order): root starts before its child
    assert [s.name for s in spans] == ["request", "engine.infer"]
    root, child = spans
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id is None
    assert root.duration_ms >= child.duration_ms >= 0
    assert root.events and root.events[0][1] == "queued"


def test_span_error_status_and_capacity():
    t = Tracer(capacity=3)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    assert t.recent()[-1].status == "error"
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.recent()) == 3  # bounded ring


def test_trace_filter():
    t = Tracer()
    with t.span("a") as a:
        pass
    with t.span("b"):
        pass
    only_a = t.recent(trace_id=a.trace_id)
    assert [s.name for s in only_a] == ["a"]


def test_structured_event_attrs():
    """Span.event(name, **attrs): attributes ride the event through
    to_dict (the PR 5 postmortem trap — the no-kwargs signature turned
    crash-path events into TypeErrors)."""
    t = Tracer()
    with t.span("request") as s:
        s.event("redispatched", from_engine="e0", to_engine="e1",
                attempt=1)
        s.event("bare")
    d = t.recent()[0].to_dict()
    ev = {e["name"]: e for e in d["events"]}
    assert ev["redispatched"]["attributes"] == {
        "from_engine": "e0", "to_engine": "e1", "attempt": 1}
    assert "attributes" not in ev["bare"]  # bare events stay compact


def test_request_id_filter_and_start_order():
    t = Tracer()
    with t.span("late", request_id="r1"):
        pass
    with t.span("other", request_id="r2"):
        pass
    spans = t.recent(request_id="r1")
    assert [s.name for s in spans] == ["late"]
    # ingested spans with earlier start sort before ring-later spans
    early = t.start("early", parent=None)
    early.start_ns = 1
    early.end_ns = 2
    early.set(request_id="r1")
    t.ingest(early)
    assert [s.name for s in t.recent(request_id="r1")] == ["early", "late"]


class TestDropAccounting:
    def test_ring_overflow_counts_and_hooks(self):
        t = Tracer(capacity=2)
        drops = []
        t.on_drop = lambda reason, n: drops.append((reason, n))
        for i in range(5):
            with t.span(f"s{i}"):
                pass
        assert t.dropped()["ring"] == 3
        assert drops == [("ring", 1)] * 3

    def test_exporter_failure_counts(self):
        t = Tracer()

        def boom(span):
            raise RuntimeError("exporter down")

        t.exporters.append(boom)
        with t.span("s"):
            pass
        assert t.dropped()["exporter"] == 1
        assert len(t.recent()) == 1  # the ring sink still got it

    def test_drop_hook_failure_never_raises(self):
        t = Tracer(capacity=1)
        t.on_drop = lambda *a: (_ for _ in ()).throw(RuntimeError("x"))
        for i in range(3):
            with t.span(f"s{i}"):
                pass
        assert t.dropped()["ring"] == 2


class TestSpanWire:
    """TraceSpan/FleetSpans wire round-trips + cross-process merge
    (docs/OBSERVABILITY.md): the worker re-bases monotonic -> epoch,
    the host re-bases back and stamps the member."""

    def _finished_span(self, tracer, **attrs):
        s = tracer.start("fleet.serve", **attrs)
        s.event("first_token", index=0)
        tracer.finish(s)
        return s

    def test_span_wire_roundtrip_via_protowire(self):
        import time as _time

        from distributed_inference_server_tpu.serving import protowire
        from distributed_inference_server_tpu.serving.fleet import (
            span_from_wire,
            span_to_wire,
        )

        t = Tracer()
        src = self._finished_span(t, request_id="r1", engine_id="e0")
        off = _time.time_ns() - _time.monotonic_ns()
        frame = protowire.encode("FleetSpans", {
            "member_id": "w1",
            "spans": [span_to_wire(src, off)],
            "dropped": 2,
        })
        d = protowire.decode("FleetSpans", frame)
        assert d["member_id"] == "w1" and d["dropped"] == 2
        got = span_from_wire(d["spans"][0], off, member_id="w1")
        assert got.name == src.name
        assert got.trace_id == src.trace_id
        assert got.span_id == src.span_id
        assert got.parent_id == src.parent_id
        assert got.status == src.status
        assert got.attributes["request_id"] == "r1"
        assert got.attributes["member"] == "w1"  # stamped on ingest
        # same epoch offset both sides -> timestamps identical; the
        # duration is exact regardless of offsets
        assert got.start_ns == src.start_ns
        assert got.end_ns - got.start_ns == src.end_ns - src.start_ns
        (ts, name, attrs), = got.events
        assert name == "first_token" and attrs == {"index": 0}
        assert ts - got.start_ns == src.events[0][0] - src.start_ns

    def test_remote_span_merge_and_orphans(self):
        """FleetServer.ingest_spans merges a member's FleetSpans frame
        into the host tracer — spans from a DEAD member (orphans whose
        parents never arrive) still land, filterable by trace, with
        wire drops counted."""
        import time as _time

        from distributed_inference_server_tpu.serving.fleet import (
            FleetRegistry,
            FleetServer,
            span_to_wire,
        )

        host = Tracer()
        server = FleetServer(FleetRegistry(), scheduler=None,
                             tracer=host)
        worker = Tracer()
        root = worker.start("request.generate", request_id="rX")
        child = worker.start("fleet.serve", parent=root.context(),
                             request_id="rX")
        worker.finish(child)
        # orphan: its parent (root) is never shipped — the member died
        off = _time.time_ns() - _time.monotonic_ns()
        server.ingest_spans({
            "member_id": "dead-w1",
            "spans": [span_to_wire(child, off)],
            "dropped": 3,
        }, "dead-w1")
        merged = host.recent(trace_id=root.trace_id)
        assert [s.name for s in merged] == ["fleet.serve"]
        assert merged[0].parent_id == root.span_id  # link preserved
        assert merged[0].attributes["member"] == "dead-w1"
        assert host.dropped()["wire"] == 3

    def test_undecodable_span_drops_not_batch(self):
        from distributed_inference_server_tpu.serving.fleet import (
            FleetRegistry,
            FleetServer,
            span_to_wire,
        )
        import time as _time

        host = Tracer()
        server = FleetServer(FleetRegistry(), scheduler=None, tracer=host)
        t = Tracer()
        ok = self._finished_span(t, request_id="r2")
        off = _time.time_ns() - _time.monotonic_ns()
        server.ingest_spans({
            "member_id": "w1",
            "spans": [{"events": 42}, span_to_wire(ok, off)],
            "dropped": 0,
        }, "w1")
        assert [s.name for s in host.recent()] == ["fleet.serve"]
        assert host.dropped()["wire"] == 1

    def test_worker_buffer_bounded_and_shipped(self):
        """FleetWorker buffers finished spans (bounded, drop-counted)
        and ships one capped FleetSpans frame per beat."""
        from distributed_inference_server_tpu.serving.fleet import (
            FleetSettings,
        )
        from distributed_inference_server_tpu.serving.remote_runner import (
            FleetWorker,
        )

        t = Tracer()
        w = FleetWorker(scheduler=None,
                        settings=FleetSettings(connect="127.0.0.1:1"),
                        member_id="w1", tracer=t)
        sent = []
        w._send = lambda name, obj: sent.append((name, obj))
        for i in range(w.SPAN_BUFFER + 5):
            with t.span(f"s{i}"):
                pass
        assert len(w._span_buf) == w.SPAN_BUFFER
        assert t.dropped()["wire"] == 5
        assert w.ship_spans_once()
        assert len(sent) == 1
        name, obj = sent[0]
        assert name == "FleetSpans" and obj["member_id"] == "w1"
        assert len(obj["spans"]) == w.SPANS_PER_FRAME
        # 5 buffer-overflow sheds + the per-frame cap overflow
        assert obj["dropped"] == 5 + (w.SPAN_BUFFER - w.SPANS_PER_FRAME)
        assert not w._span_buf  # drained
        # nothing pending -> no frame
        sent.clear()
        assert w.ship_spans_once() and sent == []

    def test_worker_ship_failure_counts_wire_drops(self):
        from distributed_inference_server_tpu.serving.fleet import (
            FleetSettings,
        )
        from distributed_inference_server_tpu.serving.remote_runner import (
            FleetWorker,
        )

        t = Tracer()
        w = FleetWorker(scheduler=None,
                        settings=FleetSettings(connect="127.0.0.1:1"),
                        member_id="w1", tracer=t)
        with t.span("s"):
            pass
        assert not w.ship_spans_once()  # not connected -> send raises
        assert t.dropped()["wire"] == 1

    def test_worker_stop_detaches_span_exporter(self):
        """Review regression: chaos rebuilds a FleetWorker per crash
        iteration against the SAME tracer — a stopped worker must not
        leave its buffer exporter behind (dead 512-span pins + phantom
        wire drops on every finished span)."""
        from distributed_inference_server_tpu.serving.fleet import (
            FleetSettings,
        )
        from distributed_inference_server_tpu.serving.remote_runner import (
            FleetWorker,
        )

        t = Tracer()
        before = len(t.exporters)
        workers = [
            FleetWorker(scheduler=None,
                        settings=FleetSettings(connect="127.0.0.1:1"),
                        member_id=f"w{i}", tracer=t)
            for i in range(3)
        ]
        assert len(t.exporters) == before + 3
        for w in workers:
            w.stop()
        assert len(t.exporters) == before
        with t.span("s"):
            pass
        assert t.dropped()["wire"] == 0  # no dead buffers counting


def test_fault_observer_registry_fans_out_and_unregisters():
    """Review regression: the chaos fleet topology runs two servers in
    one interpreter — fault arm/disarm events must reach EVERY
    registered recorder, and a removed observer stops receiving."""
    from distributed_inference_server_tpu.serving import faults

    seen_a, seen_b = [], []
    cb_a = lambda name, **attrs: seen_a.append(name)  # noqa: E731
    cb_b = lambda name, **attrs: seen_b.append(name)  # noqa: E731
    faults.add_observer(cb_a)
    faults.add_observer(cb_b)
    try:
        faults.install(faults.parse_spec("runner.step:nth=1", seed=1))
        faults.clear()
        assert seen_a == ["faults_armed", "faults_cleared"]
        assert seen_b == ["faults_armed", "faults_cleared"]
        faults.remove_observer(cb_b)
        faults.install(faults.parse_spec("runner.step:nth=1", seed=1))
        faults.clear()
        assert len(seen_a) == 4 and len(seen_b) == 2
    finally:
        faults.clear()
        faults.remove_observer(cb_a)
        faults.remove_observer(cb_b)


@pytest.fixture(scope="module")
def server():
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.server import InferenceServer

    def factory():
        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=PagedCacheConfig(num_pages=64, page_size=8,
                                                max_pages_per_seq=16)),
            dtype=jnp.float32,
        )

    srv = InferenceServer(factory, ByteTokenizer(), model_name="tiny",
                          num_engines=1, auto_restart=False)
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def test_request_produces_span_tree(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "trace me", "max_tokens": 4, "temperature": 0.0},
        )
        assert resp.status == 200
        tr = await (await client.get("/server/trace?n=50")).json()
        return tr["spans"]

    spans = _run(server, go)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert "request.generate" in by_name
    assert "engine.infer" in by_name
    assert "batch.dispatch" in by_name
    root = by_name["request.generate"][-1]
    engine = by_name["engine.infer"][-1]
    assert engine["trace_id"] == root["trace_id"]
    assert engine["parent_id"] == root["span_id"]
    assert root["status"] == "ok"
    assert any(e["name"] == "queued" for e in root["events"])
    assert any(e["name"] == "dispatched" for e in root["events"])
    assert any(e["name"] == "first_token" for e in engine["events"])
    assert engine["attributes"]["completion_tokens"] == 4


def test_trace_endpoint_filters_and_validation(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "filter me", "max_tokens": 3,
                  "temperature": 0.0},
        )
        body = await resp.json()
        rid = body["id"].split("-", 1)[-1]
        by_rid = await (await client.get(
            f"/server/trace?request_id={rid}&n=100")).json()
        bad_n = await client.get("/server/trace?n=0")
        bad_n2 = await client.get("/server/trace?n=999999")
        tl = await (await client.get(f"/server/requests/{rid}")).json()
        listing = await (await client.get("/server/requests")).json()
        missing = await client.get("/server/requests/nope")
        return rid, by_rid["spans"], bad_n.status, bad_n2.status, tl, \
            listing, missing.status

    rid, spans, bad_n, bad_n2, tl, listing, missing = _run(server, go)
    # request_id filter: the root AND the engine span carry the attr
    names = {s["name"] for s in spans}
    assert "request.generate" in names and "engine.infer" in names
    assert all(s["attributes"]["request_id"] == rid for s in spans)
    starts = [s["start_ns"] for s in spans]
    assert starts == sorted(starts)  # sorted by start
    assert bad_n == 400 and bad_n2 == 400
    # flight recorder: phases partition the wall clock; TTFT/TBT ride
    assert tl["status"] == "ok" and tl["tokens"] == 3
    total = sum(tl["phases"].values())
    assert abs(total - tl["wall_s"]) <= 0.10 * tl["wall_s"] + 1e-6
    assert tl["ttft_s"] > 0 and tl["trace_id"] == spans[0]["trace_id"]
    assert any(e["name"] == "terminal" for e in tl["events"])
    assert any(r["request_id"] == rid for r in listing["requests"])
    assert missing == 404


def test_stats_tracing_block(server):
    # force a counted drop, then read it back through both surfaces
    server.tracer.record_drop("wire", 2)

    async def go(client):
        stats = await (await client.get("/server/stats")).json()
        prom = await (await client.get("/metrics")).text()
        return stats, prom

    stats, prom = _run(server, go)
    blk = stats["tracing"]
    assert blk["spans_dropped"]["wire"] >= 2
    assert blk["tracer_dropped"]["wire"] >= 2
    assert blk["phase_requests"] >= 1
    assert "decode" in blk["phase_seconds"]
    assert 'trace_spans_dropped_total{reason="wire"}' in prom
    assert 'request_phase_seconds_bucket' in prom
    assert blk["flight_recorder"]["tracked"] >= 1


class TestOTLPExporter:
    """Real OpenTelemetry export (S12): spans leave the process as OTLP/
    HTTP JSON — verified against a local collector endpoint."""

    def _collector(self):
        import http.server
        import json as _json
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append(_json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, received

    def test_spans_reach_collector_in_otlp_format(self):
        from distributed_inference_server_tpu.utils.otlp import OTLPExporter
        from distributed_inference_server_tpu.utils.tracing import Tracer

        srv, received = self._collector()
        try:
            tracer = Tracer()
            exp = OTLPExporter(
                f"http://127.0.0.1:{srv.server_port}/v1/traces",
                service_name="test-svc", flush_interval_s=0.1,
            ).attach(tracer)
            with tracer.span("request", model="tiny") as root:
                root.event("queued")
                with tracer.span("inference", parent=root.context(),
                                 tokens=5):
                    pass
            exp.shutdown()
            assert exp.exported == 2
            assert exp.dropped == 0
            spans = []
            for body in received:
                rs = body["resourceSpans"][0]
                svc = {a["key"]: a["value"] for a in
                       rs["resource"]["attributes"]}
                assert svc["service.name"]["stringValue"] == "test-svc"
                spans.extend(rs["scopeSpans"][0]["spans"])
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) == {"request", "inference"}
            root_s = by_name["request"]
            child = by_name["inference"]
            assert len(root_s["traceId"]) == 32
            assert len(root_s["spanId"]) == 16
            assert child["traceId"] == root_s["traceId"]
            assert child["parentSpanId"] == root_s["spanId"]
            assert child["attributes"][0] == {
                "key": "tokens", "value": {"intValue": "5"}}
            assert root_s["events"][0]["name"] == "queued"
            assert int(root_s["endTimeUnixNano"]) >= int(
                root_s["startTimeUnixNano"])
            assert root_s["status"]["code"] == 1
        finally:
            srv.shutdown()

    def test_dead_collector_is_fail_open(self):
        from distributed_inference_server_tpu.utils.otlp import OTLPExporter
        from distributed_inference_server_tpu.utils.tracing import Tracer

        tracer = Tracer()
        exp = OTLPExporter("http://127.0.0.1:1/v1/traces",
                           flush_interval_s=0.05, timeout_s=0.2)
        exp.attach(tracer)
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        exp.shutdown()
        assert exp.dropped == 5
        assert exp.exported == 0
        # the in-memory ring still has everything
        assert len(tracer.recent(10)) == 5

    def test_server_wires_exporter_from_config(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.engine.engine import (
            EngineConfig,
            LLMEngine,
        )
        from distributed_inference_server_tpu.engine.kv_cache import (
            PagedCacheConfig,
        )
        from distributed_inference_server_tpu.models import llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.server import (
            InferenceServer,
        )

        srv, received = self._collector()
        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)

        def factory():
            return LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(max_batch=2, prefill_buckets=(16,),
                             paged=PagedCacheConfig(
                                 num_pages=32, page_size=8,
                                 max_pages_per_seq=4)),
                dtype=jnp.float32,
            )

        server = InferenceServer(
            factory, ByteTokenizer(), model_name="tiny",
            num_engines=1, auto_restart=False,
            otlp_endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
        )
        try:
            server.start()
            assert server.otlp is not None
            with server.tracer.span("probe"):
                pass
        finally:
            server.shutdown(drain_timeout_s=5.0)
            srv.shutdown()
        assert server.otlp.exported >= 1
        names = [
            s["name"]
            for body in received
            for s in body["resourceSpans"][0]["scopeSpans"][0]["spans"]
        ]
        assert "probe" in names
