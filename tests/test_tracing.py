"""Request-lifecycle tracing (S12, requirements.md:122 [spec]): span
model, ring sink, and end-to-end span trees through the serving spine."""

import asyncio

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.utils.tracing import Tracer


def test_span_parenting_and_ring():
    t = Tracer(capacity=8)
    with t.span("request", request_id="r1") as root:
        root.event("queued")
        with t.span("engine.infer", parent=root.context()) as child:
            child.set(tokens=5)
    spans = t.recent()
    assert [s.name for s in spans] == ["engine.infer", "request"]
    child, root = spans
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id is None
    assert root.duration_ms >= child.duration_ms >= 0
    assert root.events and root.events[0][1] == "queued"


def test_span_error_status_and_capacity():
    t = Tracer(capacity=3)
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("x")
    assert t.recent()[-1].status == "error"
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.recent()) == 3  # bounded ring


def test_trace_filter():
    t = Tracer()
    with t.span("a") as a:
        pass
    with t.span("b"):
        pass
    only_a = t.recent(trace_id=a.trace_id)
    assert [s.name for s in only_a] == ["a"]


@pytest.fixture(scope="module")
def server():
    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
    )
    from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.server import InferenceServer

    def factory():
        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=PagedCacheConfig(num_pages=64, page_size=8,
                                                max_pages_per_seq=16)),
            dtype=jnp.float32,
        )

    srv = InferenceServer(factory, ByteTokenizer(), model_name="tiny",
                          num_engines=1, auto_restart=False)
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def test_request_produces_span_tree(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "trace me", "max_tokens": 4, "temperature": 0.0},
        )
        assert resp.status == 200
        tr = await (await client.get("/server/trace?n=50")).json()
        return tr["spans"]

    spans = _run(server, go)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert "request.generate" in by_name
    assert "engine.infer" in by_name
    assert "batch.dispatch" in by_name
    root = by_name["request.generate"][-1]
    engine = by_name["engine.infer"][-1]
    assert engine["trace_id"] == root["trace_id"]
    assert engine["parent_id"] == root["span_id"]
    assert root["status"] == "ok"
    assert any(e["name"] == "queued" for e in root["events"])
    assert any(e["name"] == "dispatched" for e in root["events"])
    assert any(e["name"] == "first_token" for e in engine["events"])
    assert engine["attributes"]["completion_tokens"] == 4


class TestOTLPExporter:
    """Real OpenTelemetry export (S12): spans leave the process as OTLP/
    HTTP JSON — verified against a local collector endpoint."""

    def _collector(self):
        import http.server
        import json as _json
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append(_json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, received

    def test_spans_reach_collector_in_otlp_format(self):
        from distributed_inference_server_tpu.utils.otlp import OTLPExporter
        from distributed_inference_server_tpu.utils.tracing import Tracer

        srv, received = self._collector()
        try:
            tracer = Tracer()
            exp = OTLPExporter(
                f"http://127.0.0.1:{srv.server_port}/v1/traces",
                service_name="test-svc", flush_interval_s=0.1,
            ).attach(tracer)
            with tracer.span("request", model="tiny") as root:
                root.event("queued")
                with tracer.span("inference", parent=root.context(),
                                 tokens=5):
                    pass
            exp.shutdown()
            assert exp.exported == 2
            assert exp.dropped == 0
            spans = []
            for body in received:
                rs = body["resourceSpans"][0]
                svc = {a["key"]: a["value"] for a in
                       rs["resource"]["attributes"]}
                assert svc["service.name"]["stringValue"] == "test-svc"
                spans.extend(rs["scopeSpans"][0]["spans"])
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) == {"request", "inference"}
            root_s = by_name["request"]
            child = by_name["inference"]
            assert len(root_s["traceId"]) == 32
            assert len(root_s["spanId"]) == 16
            assert child["traceId"] == root_s["traceId"]
            assert child["parentSpanId"] == root_s["spanId"]
            assert child["attributes"][0] == {
                "key": "tokens", "value": {"intValue": "5"}}
            assert root_s["events"][0]["name"] == "queued"
            assert int(root_s["endTimeUnixNano"]) >= int(
                root_s["startTimeUnixNano"])
            assert root_s["status"]["code"] == 1
        finally:
            srv.shutdown()

    def test_dead_collector_is_fail_open(self):
        from distributed_inference_server_tpu.utils.otlp import OTLPExporter
        from distributed_inference_server_tpu.utils.tracing import Tracer

        tracer = Tracer()
        exp = OTLPExporter("http://127.0.0.1:1/v1/traces",
                           flush_interval_s=0.05, timeout_s=0.2)
        exp.attach(tracer)
        for i in range(5):
            with tracer.span(f"s{i}"):
                pass
        exp.shutdown()
        assert exp.dropped == 5
        assert exp.exported == 0
        # the in-memory ring still has everything
        assert len(tracer.recent(10)) == 5

    def test_server_wires_exporter_from_config(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.engine.engine import (
            EngineConfig,
            LLMEngine,
        )
        from distributed_inference_server_tpu.engine.kv_cache import (
            PagedCacheConfig,
        )
        from distributed_inference_server_tpu.models import llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.server import (
            InferenceServer,
        )

        srv, received = self._collector()
        params = llama.init_params(jax.random.PRNGKey(0), TINY,
                                   dtype=jnp.float32)

        def factory():
            return LLMEngine(
                params, TINY, ByteTokenizer(),
                EngineConfig(max_batch=2, prefill_buckets=(16,),
                             paged=PagedCacheConfig(
                                 num_pages=32, page_size=8,
                                 max_pages_per_seq=4)),
                dtype=jnp.float32,
            )

        server = InferenceServer(
            factory, ByteTokenizer(), model_name="tiny",
            num_engines=1, auto_restart=False,
            otlp_endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
        )
        try:
            server.start()
            assert server.otlp is not None
            with server.tracer.span("probe"):
                pass
        finally:
            server.shutdown(drain_timeout_s=5.0)
            srv.shutdown()
        assert server.otlp.exported >= 1
        names = [
            s["name"]
            for body in received
            for s in body["resourceSpans"][0]["scopeSpans"][0]["spans"]
        ]
        assert "probe" in names
