"""Ring attention (context parallelism) vs dense causal attention.

The dense gqa_attention over a contiguous cache is ground truth; the ring
(seq-sharded, ppermute-rotated) result must match for causal ragged
batches, compose with tensor parallelism, and actually emit
collective-permute in the compiled HLO."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.ops.attention import gqa_attention
from distributed_inference_server_tpu.ops.ring_attention import (
    ring_attention_sharded,
)
from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh


def _dense_reference(q, k, v, valid_len):
    """Causal self-attention over full sequences via the cache-form op."""
    B, T = q.shape[:2]
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    return gqa_attention(q, k, v, positions, valid_len)


def _case(rng, B, T, H, KV, D):
    ks = jax.random.split(rng, 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, KV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, KV, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("seq_shards", [2, 4, 8])
def test_ring_matches_dense_full_batch(seq_shards):
    mesh = make_mesh(MeshSpec(seq=seq_shards))
    B, T, H, KV, D = 2, 32, 4, 2, 16
    q, k, v = _case(jax.random.PRNGKey(seq_shards), B, T, H, KV, D)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    got = ring_attention_sharded(mesh, q, k, v, positions, positions)
    want = _dense_reference(q, k, v, jnp.full((B,), T, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_ragged_padding_tails():
    """Rows shorter than T: padding marked with negative positions must be
    excluded on both the query and key sides."""
    mesh = make_mesh(MeshSpec(seq=4))
    B, T, H, KV, D = 2, 32, 4, 2, 16
    q, k, v = _case(jax.random.PRNGKey(9), B, T, H, KV, D)
    valid = jnp.asarray([13, 32], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    pos = jnp.where(pos < valid[:, None], pos, -1)  # mark padding
    got = ring_attention_sharded(mesh, q, k, v, pos, pos)
    want = _dense_reference(q, k, v, valid)
    # compare only valid query rows (padding outputs are discarded anyway)
    for b in range(B):
        n = int(valid[b])
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(want[b, :n]),
            rtol=2e-5, atol=2e-5,
        )
    # padding queries emit exactly zero
    assert np.abs(np.asarray(got[0, 13:])).max() == 0.0


def test_ring_composes_with_tensor_parallel():
    mesh = make_mesh(MeshSpec(tensor=2, seq=4))
    B, T, H, KV, D = 2, 16, 4, 2, 16
    q, k, v = _case(jax.random.PRNGKey(3), B, T, H, KV, D)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    got = ring_attention_sharded(mesh, q, k, v, positions, positions)
    want = _dense_reference(q, k, v, jnp.full((B,), T, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_ring_emits_collective_permute():
    mesh = make_mesh(MeshSpec(seq=8))
    B, T, H, KV, D = 1, 16, 2, 2, 8
    q, k, v = _case(jax.random.PRNGKey(5), B, T, H, KV, D)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    with mesh:
        hlo = (
            jax.jit(
                lambda *a: ring_attention_sharded(mesh, *a)
            )
            .lower(q, k, v, positions, positions)
            .compile()
            .as_text()
        )
    assert "collective-permute" in hlo
