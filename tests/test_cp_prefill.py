"""Context-parallel prefill (parallel/cp.py) vs the single-device forward.

Last-token logits and the produced KV cache must match the dense path for
ragged batches, including composition with tensor parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.parallel import (
    MeshSpec,
    cp_prefill,
    make_mesh,
    shard_params,
)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def _dense_last_logits(params, ids, valid):
    B, T = ids.shape
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    write_pos = jnp.where(positions < valid[:, None], positions, T)
    logits, new_cache = llama.forward(
        params, TINY, ids, positions, cache, write_pos, valid
    )
    last = jnp.take_along_axis(
        logits, (valid - 1)[:, None, None], axis=1
    )[:, 0]
    return last, new_cache


@pytest.mark.parametrize("spec", [MeshSpec(seq=4), MeshSpec(seq=8),
                                  MeshSpec(tensor=2, seq=4)])
def test_cp_prefill_matches_dense(params, spec):
    mesh = make_mesh(spec)
    B, T = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, TINY.vocab_size)
    valid = jnp.asarray([T, 19], jnp.int32)

    want, dense_cache = _dense_last_logits(params, ids, valid)
    p = shard_params(params, mesh, TINY) if spec.tensor > 1 else params
    with mesh:
        got, k, v = cp_prefill(p, TINY, mesh, ids, valid)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    # KV caches agree on valid slots (slot == position layout)
    for b in range(B):
        n = int(valid[b])
        np.testing.assert_allclose(
            np.asarray(k[:, b, :n]), np.asarray(dense_cache.k[:, b, :n]),
            rtol=2e-4, atol=2e-4,
        )


def test_cp_prefill_rejects_indivisible_buffer(params):
    mesh = make_mesh(MeshSpec(seq=8))
    ids = jnp.zeros((1, 12), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        cp_prefill(params, TINY, mesh, ids, jnp.asarray([12]))
