"""Context-parallel prefill (parallel/cp.py) vs the single-device forward.

Last-token logits and the produced KV cache must match the dense path for
ragged batches, including composition with tensor parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.parallel import (
    MeshSpec,
    cp_prefill,
    make_mesh,
    shard_params,
)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def _dense_last_logits(params, ids, valid):
    B, T = ids.shape
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    cache = llama.KVCache.create(TINY, B, T, dtype=jnp.float32)
    write_pos = jnp.where(positions < valid[:, None], positions, T)
    logits, new_cache = llama.forward(
        params, TINY, ids, positions, cache, write_pos, valid
    )
    last = jnp.take_along_axis(
        logits, (valid - 1)[:, None, None], axis=1
    )[:, 0]
    return last, new_cache


@pytest.mark.parametrize("spec", [MeshSpec(seq=4), MeshSpec(seq=8),
                                  MeshSpec(tensor=2, seq=4)])
def test_cp_prefill_matches_dense(params, spec):
    mesh = make_mesh(spec)
    B, T = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, TINY.vocab_size)
    valid = jnp.asarray([T, 19], jnp.int32)

    want, dense_cache = _dense_last_logits(params, ids, valid)
    p = shard_params(params, mesh, TINY) if spec.tensor > 1 else params
    with mesh:
        got, k, v = cp_prefill(p, TINY, mesh, ids, valid)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    # KV caches agree on valid slots (slot == position layout)
    for b in range(B):
        n = int(valid[b])
        np.testing.assert_allclose(
            np.asarray(k[:, b, :n]), np.asarray(dense_cache.k[:, b, :n]),
            rtol=2e-4, atol=2e-4,
        )


def test_cp_prefill_rejects_indivisible_buffer(params):
    mesh = make_mesh(MeshSpec(seq=8))
    ids = jnp.zeros((1, 12), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        cp_prefill(params, TINY, mesh, ids, jnp.asarray([12]))


class TestCPxPP:
    """Ring CP composed with pipeline parallelism in one unified
    {seq, stage} shard_map (parallel/cp.py:cp_pp_prefill, VERDICT r4
    #5) — last-token logits and KV match the dense path."""

    @pytest.mark.parametrize("spec,mb", [
        (MeshSpec(seq=2, stage=2), 1),
        (MeshSpec(seq=2, stage=2), 2),
        (MeshSpec(seq=4, stage=2), 1),
        (MeshSpec(seq=2, stage=2, tensor=2), 1),
    ])
    def test_cp_pp_prefill_matches_dense(self, params, spec, mb):
        from distributed_inference_server_tpu.parallel.cp import (
            cp_pp_prefill,
        )

        mesh = make_mesh(spec)
        B, T = 2, 32
        ids = jax.random.randint(
            jax.random.PRNGKey(2), (B, T), 0, TINY.vocab_size
        )
        valid = jnp.asarray([29, 17], jnp.int32)
        want, dense_cache = _dense_last_logits(params, ids, valid)
        p = shard_params(params, mesh, TINY) if spec.tensor > 1 else params
        with mesh:
            got, k, v = cp_pp_prefill(
                p, TINY, mesh, ids, valid, num_microbatches=mb
            )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        for b in range(B):
            n = int(valid[b])
            np.testing.assert_allclose(
                np.asarray(k[:, b, :n]),
                np.asarray(dense_cache.k[:, b, :n]),
                rtol=2e-4, atol=2e-4,
            )
            np.testing.assert_allclose(
                np.asarray(v[:, b, :n]),
                np.asarray(dense_cache.v[:, b, :n]),
                rtol=2e-4, atol=2e-4,
            )

    def test_cp_pp_gemma2_windows(self, params):
        """Per-layer sliding windows (Gemma-2 schedule) ride the stage
        slices: each stage picks ITS layers' windows."""
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )
        from distributed_inference_server_tpu.parallel.cp import (
            cp_pp_prefill,
        )

        cfg = TINY_GEMMA2
        g2 = llama.init_params(jax.random.PRNGKey(3), cfg, jnp.float32)
        B, T = 1, 32
        ids = jax.random.randint(
            jax.random.PRNGKey(4), (B, T), 0, cfg.vocab_size
        )
        valid = jnp.asarray([27], jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
        cache = llama.KVCache.create(cfg, B, T, dtype=jnp.float32)
        write_pos = jnp.where(positions < valid[:, None], positions, T)
        logits, _ = llama.forward(
            g2, cfg, ids, positions, cache, write_pos, valid
        )
        want = jnp.take_along_axis(
            logits, (valid - 1)[:, None, None], axis=1
        )[:, 0]
        mesh = make_mesh(MeshSpec(seq=2, stage=2))
        with mesh:
            got, _, _ = cp_pp_prefill(g2, cfg, mesh, ids, valid)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_cp_pp_rejects_bad_geometry(self, params):
        from distributed_inference_server_tpu.parallel.cp import (
            cp_pp_prefill,
        )

        mesh = make_mesh(MeshSpec(seq=2, stage=2))
        with pytest.raises(ValueError, match="not divisible"):
            cp_pp_prefill(params, TINY, mesh, jnp.zeros((1, 13), jnp.int32),
                          jnp.asarray([13]))
        with pytest.raises(ValueError, match="microbatches"):
            cp_pp_prefill(params, TINY, mesh, jnp.zeros((3, 16), jnp.int32),
                          jnp.asarray([16, 16, 16]), num_microbatches=2)

    def test_ulysses_rejected_on_stage_mesh(self, params):
        from distributed_inference_server_tpu.parallel.cp import (
            cp_paged_prefill_any,
        )

        mesh = make_mesh(MeshSpec(seq=2, stage=2))
        pool = jnp.zeros((TINY.num_layers, 64, TINY.num_kv_heads,
                          TINY.head_dim))
        with pytest.raises(ValueError, match="ring"):
            cp_paged_prefill_any(
                params, TINY, mesh, jnp.zeros((1, 16), jnp.int32),
                jnp.asarray([16]), pool, pool,
                jnp.zeros((1, 16), jnp.int32), sp_impl="ulysses",
            )
