"""Ragged mixed-batch engine stepping (ISSUE 12,
EngineConfig.mixed_step_tokens): token identity vs the quantum path it
replaces, decode liveness during prompt loading, traffic accounting, the
degradation prefill-share hook, and construction-time validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def make_engine(tiny_params, mixed_step_tokens=0, max_batch=4,
                num_pages=64, page_size=4, max_pages_per_seq=24, **kw):
    return LLMEngine(
        tiny_params,
        TINY,
        ByteTokenizer(),
        EngineConfig(
            max_batch=max_batch,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(
                num_pages=num_pages, page_size=page_size,
                max_pages_per_seq=max_pages_per_seq,
            ),
            decode_block_size=4,
            mixed_step_tokens=mixed_step_tokens,
            **kw,
        ),
        dtype=jnp.float32,
    )


def drain(engine, toks=None, max_steps=800):
    toks = {} if toks is None else toks
    steps = 0
    while engine.has_work():
        steps += 1
        assert steps < max_steps, "engine did not drain"
        for out in engine.step():
            assert out.error is None, out.error
            if out.token_id is not None:
                toks.setdefault(out.request_id, []).append(out.token_id)
    return toks


def test_long_prompt_mixed_token_identical_to_quantum(tiny_params):
    """The acceptance-criteria identity: chat decodes in flight, a long
    prompt arrives, and every request's emitted tokens are identical
    between the mixed step and the quantum-interleave path."""
    rng = np.random.default_rng(3)
    chats = [rng.integers(1, 200, size=6).tolist() for _ in range(2)]
    long_prompt = rng.integers(1, 200, size=60).tolist()

    def run(mixed):
        eng = make_engine(tiny_params, mixed_step_tokens=20 if mixed else 0)
        toks = {}
        for i, ids in enumerate(chats):
            eng.add_request(f"c{i}", ids,
                            SamplingParams(max_tokens=12, temperature=0.0))
        for _ in range(3):  # chats are mid-decode when the prompt lands
            for out in eng.step():
                if out.token_id is not None:
                    toks.setdefault(out.request_id, []).append(out.token_id)
        eng.add_request("long", long_prompt,
                        SamplingParams(max_tokens=8, temperature=0.0))
        drain(eng, toks)
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want
    stats = eng.mixed_stats()
    assert stats["steps"] > 0
    assert stats["prefill_tokens"] >= len(long_prompt) - 1
    assert stats["decode_tokens"] > 0
    assert 0.0 < stats["batch_density"] <= 1.0


def test_mixed_decodes_advance_every_step_during_prefill(tiny_params):
    """The perf contract behind flat TBT: while a long prompt loads,
    every mixed step advances the seated decode rows — the quantum path
    stalls them for the duration of each prefill dispatch."""
    eng = make_engine(tiny_params, mixed_step_tokens=12)
    rng = np.random.default_rng(5)
    eng.add_request("chat", rng.integers(1, 200, size=6).tolist(),
                    SamplingParams(max_tokens=40, temperature=0.0))
    for _ in range(3):
        eng.step()
    eng.add_request("long", rng.integers(1, 200, size=64).tolist(),
                    SamplingParams(max_tokens=2, temperature=0.0))
    eng.step()  # admit + first mixed dispatch
    before = eng.mixed_stats()
    eng.step()
    after = eng.mixed_stats()
    # each step while the prompt loads is one mixed dispatch that
    # schedules both kinds of tokens
    assert after["steps"] == before["steps"] + 1
    assert after["decode_tokens"] == before["decode_tokens"] + 1
    assert after["prefill_tokens"] > before["prefill_tokens"]
    drain(eng)


def test_mixed_multi_prompt_batch_and_prefix_reuse(tiny_params):
    """Several prompts prefill together inside the packed budget, and
    prefix-cache sharing still applies underneath the mixed step."""
    rng = np.random.default_rng(9)
    shared = rng.integers(1, 200, size=16).tolist()
    prompts = [shared + rng.integers(1, 200, size=4 + i).tolist()
               for i in range(3)]

    def run(mixed):
        eng = make_engine(tiny_params, mixed_step_tokens=24 if mixed else 0)
        toks = {}
        # p0 completes first so its prefix pages publish; p1/p2 then
        # prefill TOGETHER inside one packed budget, sharing them
        eng.add_request("p0", prompts[0],
                        SamplingParams(max_tokens=6, temperature=0.0))
        drain(eng, toks)
        for i, ids in enumerate(prompts[1:], start=1):
            eng.add_request(f"p{i}", ids,
                            SamplingParams(max_tokens=6, temperature=0.0))
        drain(eng, toks)
        return toks, eng.cache_stats().hits

    want, _ = run(False)
    got, hits = run(True)
    assert got == want
    assert hits > 0  # later prompts shared the warm prefix pages


def test_mixed_prefill_frac_shrinks_share(tiny_params):
    """The degradation hook: a shrunken prefill share loads fewer prompt
    tokens per mixed dispatch (decode rows are untouched)."""
    rng = np.random.default_rng(11)
    prompt = rng.integers(1, 200, size=64).tolist()

    def tokens_first_step(frac):
        eng = make_engine(tiny_params, mixed_step_tokens=20)
        eng.set_mixed_prefill_frac(frac)
        eng.add_request("p", prompt,
                        SamplingParams(max_tokens=2, temperature=0.0))
        eng.step()
        n = eng.mixed_stats()["prefill_tokens"]
        drain(eng)
        return n

    full = tokens_first_step(1.0)
    half = tokens_first_step(0.5)
    assert half < full
    assert half >= 1  # progress is guaranteed at every rung


def test_mixed_preemption_under_page_pressure(tiny_params):
    """Page pressure inside a mixed step drains the pipeline and preempts
    instead of wedging; every request still completes."""
    eng = make_engine(tiny_params, mixed_step_tokens=12, max_batch=2,
                      num_pages=14, max_pages_per_seq=10)
    rng = np.random.default_rng(13)
    for i in range(3):
        eng.add_request(f"r{i}", rng.integers(1, 200, size=10).tolist(),
                        SamplingParams(max_tokens=10, temperature=0.0))
    toks = drain(eng)
    assert len(toks) == 3
    assert all(len(v) == 10 for v in toks.values())


def test_mixed_abort_mid_prefill(tiny_params):
    eng = make_engine(tiny_params, mixed_step_tokens=12)
    rng = np.random.default_rng(17)
    eng.add_request("gone", rng.integers(1, 200, size=40).tolist(),
                    SamplingParams(max_tokens=4, temperature=0.0))
    eng.add_request("stay", rng.integers(1, 200, size=8).tolist(),
                    SamplingParams(max_tokens=4, temperature=0.0))
    eng.step()  # first mixed dispatch in flight
    assert eng.abort("gone")
    toks = drain(eng)
    assert "gone" not in toks and len(toks["stay"]) == 4
    s = eng.cache_stats()
    assert s.pages_total - s.pages_free == s.pages_cached  # all released


def test_mixed_prefill_only_parks_handoff_ready(tiny_params):
    """Disaggregated prefill still works under the mixed step: the first
    token emits and the sequence parks for export."""
    eng = make_engine(tiny_params, mixed_step_tokens=12)
    rng = np.random.default_rng(19)
    eng.add_request("h", rng.integers(1, 200, size=20).tolist(),
                    SamplingParams(max_tokens=8, temperature=0.0),
                    prefill_only=True)
    steps = 0
    while not eng.handoff_ready_ids():
        eng.step()
        steps += 1
        assert steps < 100
    assert eng.handoff_ready_ids() == ["h"]
    exp = eng.export_handoff("h")
    assert exp is not None and exp.seq_len == 20


def test_mixed_warmup_covers_programs(tiny_params):
    eng = make_engine(tiny_params, mixed_step_tokens=12)
    eng.warmup()
    assert eng._mixed_fn is not None  # the mixed program compiled
    assert not eng.has_work()


def test_mixed_stats_none_when_off(tiny_params):
    eng = make_engine(tiny_params, mixed_step_tokens=0)
    assert eng.mixed_stats() is None


class TestConstructionValidation:
    def test_must_exceed_max_batch(self, tiny_params):
        with pytest.raises(ValueError, match="must exceed max_batch"):
            make_engine(tiny_params, mixed_step_tokens=4, max_batch=4)

    def test_rejects_speculation(self, tiny_params):
        draft = llama.init_params(jax.random.PRNGKey(1), TINY,
                                  dtype=jnp.float32)
        with pytest.raises(ValueError, match="speculative"):
            LLMEngine(
                tiny_params, TINY, ByteTokenizer(),
                EngineConfig(
                    max_batch=2, prefill_buckets=(8, 32),
                    paged=PagedCacheConfig(num_pages=32, page_size=4,
                                           max_pages_per_seq=8),
                    mixed_step_tokens=8,
                ),
                dtype=jnp.float32,
                draft_params=draft, draft_cfg=TINY,
            )
