"""distlint (tools/lint): per-rule positive/negative fixtures, the
suppression and baseline machinery, the proto parser, and — the tier-1
gate — a full run over the real repo asserting zero non-baselined
findings (ISSUE 2 acceptance; docs/LINTS.md)."""

from __future__ import annotations

from pathlib import Path

from tools.lint import proto as protodef
from tools.lint import rules as rules_mod
from tools.lint.core import (
    RULES,
    apply_baseline,
    apply_suppressions,
    load_baseline,
    module_from_source,
    run_lint,
)
from tools.lint.rules import compare_wire_schema

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG = "distributed_inference_server_tpu"


def check(rule: str, path: str, src: str):
    """Run one module-scope rule over fixture source, suppressions applied."""
    mod = module_from_source(path, src)
    findings = list(RULES[rule].check(mod))
    active, _ = apply_suppressions({path: mod}, findings)
    return active


# ---------------------------------------------------------------------------
# DL001 — blocking calls on async / serving-spine paths
# ---------------------------------------------------------------------------


def test_dl001_flags_sleep_in_async_def():
    out = check("DL001", f"{PKG}/serving/app.py", (
        "import time\n"
        "async def handler():\n"
        "    time.sleep(1)\n"
    ))
    assert [f.line for f in out] == [3]
    assert out[0].severity == "P0"


def test_dl001_flags_unawaited_event_wait_in_async_def():
    out = check("DL001", f"{PKG}/engine/x.py", (
        "async def f(ev):\n"
        "    ev.wait(5)\n"
    ))
    assert len(out) == 1


def test_dl001_flags_sync_sleep_on_serving_spine():
    out = check("DL001", f"{PKG}/serving/dispatcher.py", (
        "import time\n"
        "def loop():\n"
        "    time.sleep(0.01)\n"
    ))
    assert len(out) == 1 and out[0].severity == "P1"


def test_dl001_clean():
    # awaited sleep, Event.wait on a thread, sleep outside serving/
    assert not check("DL001", f"{PKG}/serving/app.py", (
        "import asyncio\n"
        "async def handler():\n"
        "    await asyncio.sleep(1)\n"
    ))
    assert not check("DL001", f"{PKG}/serving/dispatcher.py", (
        "def loop(self):\n"
        "    self._stop.wait(0.01)\n"
    ))
    assert not check("DL001", f"{PKG}/utils/profiler.py", (
        "import time\n"
        "def capture():\n"
        "    time.sleep(0.5)\n"
    ))


def test_dl001_suppression_comment():
    assert not check("DL001", f"{PKG}/serving/server.py", (
        "import time\n"
        "def drain():\n"
        "    time.sleep(0.05)  # distlint: ignore[DL001]\n"
    ))


# ---------------------------------------------------------------------------
# DL002 — guarded state mutated outside the lock
# ---------------------------------------------------------------------------

_DL002_POS = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
    def add(self, x):
        with self._lock:
            self._items.append(x)
    def racy(self, x):
        self._items.append(x)
"""


def test_dl002_flags_unlocked_mutation():
    out = check("DL002", f"{PKG}/serving/x.py", _DL002_POS)
    assert len(out) == 1
    assert out[0].context == "C.racy"
    assert "_items" in out[0].message


def test_dl002_clean_when_locked_and_for_locked_suffix():
    assert not check("DL002", f"{PKG}/serving/x.py", (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n"
        "    def add(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n"
        "    def also_fine(self, x):\n"
        "        with self._lock:\n"
        "            self._items = [x]\n"
        # *_locked convention: caller holds the lock
        "    def _add_locked(self, x):\n"
        "        self._items.append(x)\n"
    ))


def test_dl002_ignores_classes_without_locks():
    assert not check("DL002", f"{PKG}/serving/x.py", (
        "class C:\n"
        "    def __init__(self):\n"
        "        self._items = []\n"
        "    def add(self, x):\n"
        "        self._items.append(x)\n"
    ))


# ---------------------------------------------------------------------------
# DL003 — lock held across await / blocking call
# ---------------------------------------------------------------------------


def test_dl003_flags_sleep_under_lock():
    out = check("DL003", f"{PKG}/serving/x.py", (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(1)\n"
    ))
    assert len(out) == 1 and out[0].severity == "P0"


def test_dl003_flags_await_under_lock():
    out = check("DL003", f"{PKG}/serving/x.py", (
        "async def f(self):\n"
        "    with self._lock:\n"
        "        await self.q.get()\n"
    ))
    assert len(out) == 1 and "await" in out[0].message


def test_dl003_condition_wait_on_held_lock_is_exempt():
    assert not check("DL003", f"{PKG}/serving/disagg.py", (
        "class C:\n"
        "    def worker(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(0.1)\n"
    ))


def test_dl003_other_objects_wait_under_lock_flagged():
    out = check("DL003", f"{PKG}/serving/x.py", (
        "class C:\n"
        "    def f(self):\n"
        "        with self._cv:\n"
        "            self._stop.wait(1.0)\n"
    ))
    assert len(out) == 1


# ---------------------------------------------------------------------------
# DL004 — silently swallowed broad excepts
# ---------------------------------------------------------------------------


def test_dl004_flags_silent_pass():
    out = check("DL004", f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    ))
    assert len(out) == 1


def test_dl004_flags_bare_except():
    out = check("DL004", f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        return None\n"
    ))
    assert len(out) == 1 and "bare except" in out[0].message


def test_dl004_clean_variants():
    # logging, metric increment, re-raise, and forwarding `e` all count
    assert not check("DL004", f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        logger.exception('boom')\n"
    ))
    assert not check("DL004", f"{PKG}/serving/x.py", (
        "def f(self):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        self.metrics.record_error('site')\n"
    ))
    assert not check("DL004", f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        raise RuntimeError('wrapped')\n"
    ))
    assert not check("DL004", f"{PKG}/serving/x.py", (
        "def f(self, sink):\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        sink.on_error(str(e), 'code')\n"
    ))
    # narrow excepts are out of scope
    assert not check("DL004", f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except ValueError:\n"
        "        pass\n"
    ))


# ---------------------------------------------------------------------------
# DL005 — proto <-> protowire drift (pure comparator + parser)
# ---------------------------------------------------------------------------

_TOY_PROTO = """
syntax = "proto3";
package t;

enum Color {
  COLOR_UNSPECIFIED = 0;
  RED = 1;           // "red"
  DARK_BLUE = 2;     // "dark_blue"
}

message Outer {
  string name = 1;
  optional uint32 count = 2;
  repeated float vals = 3;
  Inner inner = 4;
  Color color = 5;
  message Inner {
    bytes data = 1;
  }
  oneof kind {
    Inner a = 6;
    string b = 7;
  }
}
"""

_TOY_MESSAGES = {
    "Outer": {
        1: ("name", "string", "one"),
        2: ("count", "uint32", "opt"),
        3: ("vals", "float", "rep"),
        4: ("inner", "msg:Outer.Inner", "opt"),
        5: ("color", "enum:Color", "one"),
        6: ("a", "msg:Outer.Inner", "opt"),
        7: ("b", "string", "opt"),
    },
    "Outer.Inner": {1: ("data", "bytes", "one")},
}
_TOY_ENUMS = {"Color": {1: "red", 2: "dark_blue"}}


def test_proto_parser_structure():
    schema = protodef.parse(_TOY_PROTO)
    assert set(schema.messages) == {"Outer", "Outer.Inner"}
    outer = schema.messages["Outer"]
    assert outer.fields[1].label == "one"
    assert outer.fields[2].label == "opt"
    assert outer.fields[3].label == "rep"
    assert outer.fields[6].label == "opt"  # oneof member
    assert schema.enums["Color"].values == {
        0: "COLOR_UNSPECIFIED", 1: "RED", 2: "DARK_BLUE"}
    kind, t = protodef.resolve_type(schema, "Outer", "Inner")
    assert (kind, t) == ("msg", "msg:Outer.Inner")


def test_dl005_clean_on_matching_tables():
    schema = protodef.parse(_TOY_PROTO)
    assert compare_wire_schema(schema, _TOY_MESSAGES, _TOY_ENUMS) == []


def test_dl005_detects_drift():
    schema = protodef.parse(_TOY_PROTO)
    # field number missing
    broken = {k: dict(v) for k, v in _TOY_MESSAGES.items()}
    del broken["Outer"][3]
    msgs = [m for _, m in compare_wire_schema(schema, broken, _TOY_ENUMS)]
    assert any("vals = 3" in m for m in msgs)
    # type drift
    broken = {k: dict(v) for k, v in _TOY_MESSAGES.items()}
    broken["Outer"][2] = ("count", "int64", "opt")
    msgs = [m for _, m in compare_wire_schema(schema, broken, _TOY_ENUMS)]
    assert any("type drift" in m for m in msgs)
    # cardinality drift
    broken = {k: dict(v) for k, v in _TOY_MESSAGES.items()}
    broken["Outer"][2] = ("count", "uint32", "one")
    msgs = [m for _, m in compare_wire_schema(schema, broken, _TOY_ENUMS)]
    assert any("cardinality drift" in m for m in msgs)
    # enum JSON-string drift
    msgs = [m for _, m in compare_wire_schema(
        schema, _TOY_MESSAGES, {"Color": {1: "red", 2: "blue"}})]
    assert any("JSON string drift" in m for m in msgs)


def test_dl005_flags_kvchunk_field_drift():
    """ISSUE 4 satellite: a drift in the streamed-handoff KvChunk table
    (type change, renumbered field, dropped crc) is caught against the
    real inference.proto — the varint would still decode, into the wrong
    thing, silently corrupting every streamed migration."""
    schema = protodef.parse_file(
        REPO_ROOT / PKG / "serving" / "inference.proto")
    messages, enums = rules_mod.load_protowire_tables(REPO_ROOT)
    broken = {k: dict(v) for k, v in messages.items()}
    broken["KvChunk"][6] = ("crc32", "int64", "one")  # type drift
    msgs = [m for a, m in compare_wire_schema(schema, broken, enums)
            if a == "KvChunk"]
    assert any("crc32" in m and "type drift" in m for m in msgs), msgs
    broken = {k: dict(v) for k, v in messages.items()}
    del broken["KvChunk"][7]  # payload dropped from the codec
    msgs = [m for a, m in compare_wire_schema(schema, broken, enums)
            if a == "KvChunk"]
    assert any("payload" in m for m in msgs), msgs
    broken = {k: dict(v) for k, v in messages.items()}
    # field 19 is unused in the real header (9 became total_chunks when
    # the fleet KV data plane extended it — ISSUE 13)
    broken["KvHandoffHeader"][19] = ("chunk_pages", "uint32", "one")
    msgs = [m for a, m in compare_wire_schema(schema, broken, enums)
            if a == "KvHandoffHeader"]
    assert any("not in inference.proto" in m for m in msgs), msgs


def test_dl005_real_schema_agrees():
    """The repo's actual proto and codec tables (also enforced by the
    project-scope rule inside the full run below; asserted directly here
    so a drift failure names this test)."""
    schema = protodef.parse_file(
        REPO_ROOT / PKG / "serving" / "inference.proto")
    messages, enums = rules_mod.load_protowire_tables(REPO_ROOT)
    assert compare_wire_schema(schema, messages, enums) == []


# ---------------------------------------------------------------------------
# DL006 — metric hygiene (synthetic collector + usage modules)
# ---------------------------------------------------------------------------

_METRICS_SRC = """
from prometheus_client import Counter, Gauge
class MetricsCollector:
    def __init__(self, registry=None):
        self.reqs = Counter("reqs_total", "requests", registry=registry)
        self.depth = Gauge("queue_depth", "depth", registry=registry)
        self.ghost = Counter("ghost_total", "never emitted",
                             registry=registry)
    def record_request(self):
        self.reqs.inc()
    def set_depth(self, n):
        self.depth.set(n)
    def dead_method(self):
        self.reqs.inc()
"""

_USER_SRC = """
class Handler:
    def __init__(self, metrics):
        self.metrics = metrics
    def handle(self):
        self.metrics.record_request()
    def update(self, n):
        self.metrics.set_depth(n)
"""


def _dl006(metrics_src, user_src):
    mpath = f"{PKG}/serving/metrics.py"
    mods = [module_from_source(mpath, metrics_src),
            module_from_source(f"{PKG}/serving/handler.py", user_src)]
    return list(RULES["DL006"].check_project(mods, REPO_ROOT))


def test_dl006_flags_unemitted_metric_and_dead_method():
    out = _dl006(_METRICS_SRC, _USER_SRC)
    msgs = [f.message for f in out]
    assert any("ghost" in m and "never emitted" in m for m in msgs)
    assert any("dead_method" in m for m in msgs)
    assert len(out) == 2


def test_dl006_flags_typoed_emission_site():
    out = _dl006(_METRICS_SRC, _USER_SRC.replace(
        "record_request()", "record_requests()"))
    assert any("record_requests" in f.message and "does not exist"
               in f.message for f in out)


def test_dl006_clean():
    clean_metrics = _METRICS_SRC.replace(
        """        self.ghost = Counter("ghost_total", "never emitted",
                             registry=registry)
""", "").replace("""    def dead_method(self):
        self.reqs.inc()
""", "")
    assert _dl006(clean_metrics, _USER_SRC) == []


def test_dl006_flags_duplicate_prometheus_name():
    dup = _METRICS_SRC.replace('Gauge("queue_depth"', 'Gauge("reqs_total"')
    out = _dl006(dup, _USER_SRC)
    assert any("duplicate prometheus metric name" in f.message for f in out)


# ---------------------------------------------------------------------------
# DL007 — device work in the per-token decode loop
# ---------------------------------------------------------------------------


def test_dl007_flags_jnp_in_hot_function():
    out = check("DL007", f"{PKG}/engine/engine.py", (
        "import jax.numpy as jnp\n"
        "class LLMEngine:\n"
        "    def _emit_token(self, seq, t, outputs):\n"
        "        pad = jnp.zeros((4,))\n"
        "        return pad\n"
    ))
    assert len(out) == 1 and out[0].severity == "P0"


def test_dl007_flags_host_sync_in_hot_function():
    out = check("DL007", f"{PKG}/engine/engine.py", (
        "class LLMEngine:\n"
        "    def _process_block(self, outputs):\n"
        "        x = self.arr.block_until_ready()\n"
        "        y = self.val.item()\n"
    ))
    assert len(out) == 2


def test_dl007_no_false_positive_on_double_buffered_export():
    """ISSUE 4 satellite: the streamed-handoff export machinery
    (export_handoff_pump / _finish and kv_cache's double-buffered pull
    loop) lives OUTSIDE the per-token hot set — np.asarray pulls and
    copy_to_host_async dispatches there are the intended design, and
    DL007 must not flag them. A genuinely hot-loop sync still needs an
    inline justification to pass (suppression round-trip below)."""
    assert not check("DL007", f"{PKG}/engine/engine.py", (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "class LLMEngine:\n"
        "    def export_handoff_pump(self, session):\n"
        "        pending = self._pull(session.groups[0])\n"
        "        for n, group in enumerate(session.groups):\n"
        "            nxt = None\n"
        "            if n + 1 < len(session.groups):\n"
        "                nxt = self._pull(session.groups[n + 1])\n"
        "            hosts = [np.asarray(a) for a in pending]\n"
        "            session.chunks.append(self._encode(hosts))\n"
        "            pending = nxt\n"
        "    def _pull(self, group):\n"
        "        arrs = (self.state.k[:, jnp.asarray(group)],)\n"
        "        for a in arrs:\n"
        "            a.copy_to_host_async()\n"
        "        return arrs\n"
    ))
    # the same sync INSIDE a hot function is flagged, and an inline
    # justification suppresses it
    flagged = check("DL007", f"{PKG}/engine/engine.py", (
        "class LLMEngine:\n"
        "    def _process_block(self, outputs):\n"
        "        x = self.arr.item()\n"
    ))
    assert len(flagged) == 1
    assert not check("DL007", f"{PKG}/engine/engine.py", (
        "class LLMEngine:\n"
        "    def _process_block(self, outputs):\n"
        "        x = self.arr.item()  "
        "# distlint: ignore[DL007] — block boundary sync\n"
    ))


def test_dl007_mixed_step_reap_is_hot():
    """ISSUE 12 satellite: the mixed-step reap loop runs once per mixed
    dispatch and walks completed prompts through the emission path — it
    is policed exactly like the decode loop (no device work / host sync
    beyond the one np.asarray block-boundary read)."""
    out = check("DL007", f"{PKG}/engine/engine.py", (
        "import jax.numpy as jnp\n"
        "class LLMEngine:\n"
        "    def _reap_mixed_prefill(self, group, chunk_lens, p_toks,\n"
        "                            p_lps, outputs):\n"
        "        pad = jnp.zeros((4,))\n"
        "        x = self.arr.item()\n"
        "        return pad, x\n"
    ))
    assert len(out) == 2 and all(f.severity == "P0" for f in out)
    # the block-boundary np.asarray read is the intended design
    assert not check("DL007", f"{PKG}/engine/engine.py", (
        "import numpy as np\n"
        "class LLMEngine:\n"
        "    def _reap_mixed_prefill(self, group, chunk_lens, p_toks,\n"
        "                            p_lps, outputs):\n"
        "        toks = np.asarray(p_toks)\n"
        "        return toks\n"
    ))
    # the mixed LAUNCH function is NOT hot: its jnp uploads are the
    # per-dispatch design, like _launch's
    assert not check("DL007", f"{PKG}/engine/engine.py", (
        "import jax.numpy as jnp\n"
        "class LLMEngine:\n"
        "    def _mixed_step(self, outputs):\n"
        "        return jnp.zeros((4,))\n"
    ))


def test_dl007_clean():
    # numpy host work in hot functions is fine; jnp outside them is fine
    assert not check("DL007", f"{PKG}/engine/engine.py", (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "class LLMEngine:\n"
        "    def _process_block(self, outputs):\n"
        "        toks = np.asarray(self.toks_d)\n"
        "    def _launch(self):\n"
        "        return jnp.zeros((4,))\n"
    ))
    # rule only applies to engine/engine.py
    assert not check("DL007", f"{PKG}/serving/x.py", (
        "import jax.numpy as jnp\n"
        "def _emit_token():\n"
        "    return jnp.zeros(1)\n"
    ))


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------


def test_baseline_consumes_matching_findings():
    mod = module_from_source(f"{PKG}/serving/x.py", (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    ))
    findings = list(RULES["DL004"].check(mod))
    assert len(findings) == 1
    f = findings[0]
    entry = {"rule": f.rule, "path": f.path, "context": f.context,
             "line": f.line_text}
    new, matched, stale = apply_baseline(findings, [entry])
    assert new == [] and len(matched) == 1 and stale == []
    # a second identical finding needs a second entry (multiset consume)
    new, matched, _ = apply_baseline(findings * 2, [entry])
    assert len(new) == 1 and len(matched) == 1
    # stale entries surface for baseline shrinking
    _, _, stale = apply_baseline([], [entry])
    assert stale == [entry]


def test_baseline_match_survives_line_motion_but_not_edit():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    f0 = list(RULES["DL004"].check(
        module_from_source(f"{PKG}/serving/x.py", src)))[0]
    moved = list(RULES["DL004"].check(module_from_source(
        f"{PKG}/serving/x.py", "import os\n\n" + src)))[0]
    assert f0.key == moved.key and f0.line != moved.line
    edited = list(RULES["DL004"].check(module_from_source(
        f"{PKG}/serving/x.py", src.replace("def f", "def h"))))[0]
    assert f0.key != edited.key  # context changed -> re-triage


# ---------------------------------------------------------------------------
# the tier-1 gate: the real repo is clean
# ---------------------------------------------------------------------------


def test_repo_has_zero_nonbaselined_findings():
    """`python -m tools.lint.run` must exit 0: every finding is either
    fixed, suppressed inline with a justification, or grandfathered in
    tools/lint/baseline.json (which may only shrink — docs/LINTS.md)."""
    active, _suppressed = run_lint(REPO_ROOT)
    new, _matched, _stale = apply_baseline(active, load_baseline())
    assert new == [], "\n".join(f.render() for f in new)


def test_repo_p0_findings_are_never_baselined():
    """P0 severities (async blocking, lock-across-blocking, wire drift,
    hot-loop device work) must be fixed or suppressed-with-justification,
    not grandfathered."""
    baseline = load_baseline()
    p0_rules = {n for n, r in RULES.items() if r.severity == "P0"}
    offenders = [e for e in baseline if e.get("rule") in p0_rules]
    assert offenders == []


# ---------------------------------------------------------------------------
# DL008 — interprocedural thread-confinement (callgraph + threads layer)
# ---------------------------------------------------------------------------

from pathlib import Path as _Path  # noqa: E402

_NO_DOCS_ROOT = _Path("/nonexistent-distlint-fixture-root")


def pcheck(rule: str, sources, root=None):
    """Run one project-scope rule over fixture sources ({path: src}),
    suppressions applied."""
    mods = {p: module_from_source(p, s) for p, s in sources.items()}
    findings = list(RULES[rule].check_project(list(mods.values()),
                                              root or _NO_DOCS_ROOT))
    active, _ = apply_suppressions(mods, findings)
    return active


# modeled on the PR 5 `_fail_all_of`/`submit` double-resolve race: one
# attribute written by the spawned engine thread AND by submit(), which
# any other thread calls, with no common lock
_DL008_POS = """
import threading
class Runner:
    def __init__(self):
        self._inflight = {}
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run, name="engine")
        self._thread.start()
    def submit(self, reqs):
        for r in reqs:
            self._inflight[r.request_id] = r
    def _run(self):
        while True:
            self._fail_all_of(list(self._inflight.values()))
    def _fail_all_of(self, reqs):
        for r in reqs:
            self._inflight.pop(r.request_id, None)
"""


def test_dl008_flags_double_resolve_write_pattern():
    out = pcheck("DL008", {f"{PKG}/serving/runner.py": _DL008_POS})
    assert len(out) == 1
    f = out[0]
    assert "_inflight" in f.message and "no common lock" in f.message
    assert "thread:engine" in f.message
    assert f.context == "Runner.submit"


def test_dl008_clean_with_common_lock_and_locked_convention():
    out = pcheck("DL008", {f"{PKG}/serving/runner.py": """
import threading
class Runner:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = {}
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run, name="engine")
        self._thread.start()
    def submit(self, reqs):
        with self._lock:
            for r in reqs:
                self._inflight[r.request_id] = r
    def _run(self):
        with self._lock:
            self._fail_all_locked()
    def _fail_all_locked(self):
        self._inflight.clear()
"""})
    assert out == []


def test_dl008_thread_confined_marker_and_suppression():
    src = """
import threading
class Engine:
    def __init__(self):
        self.state = {}
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()
    def poke(self):
        self.state["x"] = 1
    def _run(self):
        self.state.clear()
"""
    assert len(pcheck("DL008", {f"{PKG}/engine/x.py": src})) == 1
    marked = src.replace("class Engine:",
                         "# distlint: thread-confined\nclass Engine:")
    assert pcheck("DL008", {f"{PKG}/engine/x.py": marked}) == []
    # inline suppression at the anchor write site also silences
    suppressed = src.replace(
        'self.state["x"] = 1',
        'self.state["x"] = 1  # distlint: ignore[DL008]')
    assert pcheck("DL008", {f"{PKG}/engine/x.py": suppressed}) == []


def test_dl008_threading_primitive_methods_exempt():
    out = pcheck("DL008", {f"{PKG}/serving/x.py": """
import threading
class C:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()
    def shutdown(self):
        self._stop.set()
    def reset(self):
        self._stop.clear()
    def _run(self):
        self._stop.clear()
"""})
    assert out == []


def test_dl008_site_suppression_does_not_mask_other_sites():
    """An ignore[DL008] on one write site waives exactly that site: a
    racy write of the same attribute elsewhere still flags (and the
    finding re-anchors there). The attribute-wide waiver is the ignore
    on the __init__ declaration."""
    src = """
import threading
class Runner:
    def __init__(self):
        self._inflight = {}
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run, name="engine")
        self._thread.start()
    def submit(self, reqs):
        for r in reqs:
            self._inflight[r.request_id] = r  # distlint: ignore[DL008]
    def cancel_all(self):
        self._inflight.clear()
    def _run(self):
        self._inflight.clear()
"""
    out = pcheck("DL008", {f"{PKG}/serving/runner.py": src})
    assert len(out) == 1
    assert out[0].context == "Runner.cancel_all"  # re-anchored
    waived = src.replace(
        "self._inflight = {}",
        "self._inflight = {}  # distlint: ignore[DL008]")
    assert pcheck("DL008", {f"{PKG}/serving/runner.py": waived}) == []


def test_thread_root_marker_label_collision_stays_distinct():
    """A # distlint: thread-root marker whose label collides with an
    existing spawn root must NOT merge the two ownership domains — the
    race between them would silently disappear."""
    out = pcheck("DL008", {f"{PKG}/serving/x.py": """
import threading
class C:
    def __init__(self):
        self.jobs = {}
        self._thread = None
    def start(self, pool):
        self._thread = threading.Thread(target=self._run, name="pump")
        self._thread.start()
        pool.submit(self._drain)
    def _run(self):
        self.jobs["a"] = 1
    # distlint: thread-root[pump]
    def _drain(self):
        self.jobs.clear()
"""})
    assert len(out) == 1 and "jobs" in out[0].message


def test_spawn_root_fallback_labels_stay_distinct():
    """Two same-named classes in different modules spawning same-named
    threads must produce two distinct ownership roots — merging them
    would hide races between the two real threads."""
    from tools.lint import callgraph, threads

    src = """
import threading
class C:
    def __init__(self):
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._run, name="C._run")
        self._thread.start()
    def _run(self):
        pass
"""
    mods = [module_from_source(f"{PKG}/serving/{p}.py", src)
            for p in ("a", "b")]
    roots = threads.spawn_roots(callgraph.build_summary(mods))
    spawned = {label: fns for label, fns in roots.items()
               if label != "asyncio"}
    assert len(spawned) == 2
    assert all(len(fns) == 1 for fns in spawned.values())


def test_dl008_async_defs_are_a_thread_root():
    # an async handler (asyncio root) racing a spawned thread, no lock
    out = pcheck("DL008", {f"{PKG}/serving/x.py": """
import threading
class C:
    def __init__(self):
        self.pending = {}
        self._thread = None
    def start(self):
        self._thread = threading.Thread(target=self._drain)
        self._thread.start()
    async def handle(self, rid, req):
        self.pending[rid] = req
    def _drain(self):
        self.pending.clear()
"""})
    assert len(out) == 1 and "asyncio" in out[0].message


# ---------------------------------------------------------------------------
# DL009 — lock-order cycles
# ---------------------------------------------------------------------------


def test_dl009_flags_interprocedural_cycle():
    out = pcheck("DL009", {f"{PKG}/serving/x.py": """
import threading
class A:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
    def f(self):
        with self._lock_a:
            with self._lock_b:
                pass
    def g(self):
        with self._lock_b:
            self.helper()
    def helper(self):
        with self._lock_a:
            pass
"""})
    assert len(out) == 1
    assert "lock-order cycle" in out[0].message
    assert "A._lock_a" in out[0].message and "A._lock_b" in out[0].message


def test_dl009_clean_on_consistent_order():
    out = pcheck("DL009", {f"{PKG}/serving/x.py": """
import threading
class A:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
    def f(self):
        with self._lock_a:
            with self._lock_b:
                pass
    def g(self):
        with self._lock_a:
            self.helper()
    def helper(self):
        with self._lock_b:
            pass
"""})
    assert out == []


def test_dl009_plain_lock_reacquire_flagged_rlock_clean():
    src = """
import threading
class B:
    def __init__(self):
        self._lock = threading.{factory}()
    def outer(self):
        with self._lock:
            self.inner()
    def inner(self):
        with self._lock:
            pass
"""
    out = pcheck("DL009",
                 {f"{PKG}/serving/x.py": src.format(factory="Lock")})
    assert len(out) == 1 and "self-deadlock" in out[0].message
    assert pcheck("DL009",
                  {f"{PKG}/serving/x.py": src.format(factory="RLock")}) == []


# ---------------------------------------------------------------------------
# DL010 — internal-API call conformance
# ---------------------------------------------------------------------------

_TRACING_FIXTURE = """
import time
class Span:
    def set(self, **attrs):
        return self
    def event(self, name, **attrs):
        pass
    def context(self):
        return (self.trace_id, self.span_id)
class Tracer:
    def start(self, name, parent=None, **attributes):
        pass
    def finish(self, span, status="ok"):
        pass
"""

# the pre-structured-events signature (the PR 5 trap): kept as a fixture
# so DL010 provably still catches kwargs against a kwargs-less target
_TRACING_FIXTURE_LEGACY = _TRACING_FIXTURE.replace(
    "def event(self, name, **attrs):", "def event(self, name):")


def test_dl010_flags_pr5_span_event_kwargs_shape_on_legacy_signature():
    """The exact PR 5 bug: against the OLD no-kwargs ``Span.event``, a
    ``reason=`` kwarg is a runtime TypeError that turned an invisible
    redispatch into a client-visible failure — DL010 flags it."""
    out = pcheck("DL010", {
        f"{PKG}/utils/tracing.py": _TRACING_FIXTURE_LEGACY,
        f"{PKG}/serving/dispatcher.py": """
class Dispatcher:
    def redispatch(self, request, from_engine, reason):
        if request.span is not None:
            request.span.event("redispatched", reason=reason)
        return True
""",
    })
    assert len(out) == 1
    assert "unexpected keyword argument 'reason'" in out[0].message
    assert out[0].context == "Dispatcher.redispatch"
    assert out[0].severity == "P0"


def test_dl010_structured_event_attrs_conform():
    """Against the CURRENT ``Span.event(name, **attrs)`` signature the
    same kwargs shape is legal — and the old bare-name call shape still
    lints clean too (both shapes are live in the codebase)."""
    out = pcheck("DL010", {
        f"{PKG}/utils/tracing.py": _TRACING_FIXTURE,
        f"{PKG}/serving/dispatcher.py": """
class Dispatcher:
    def redispatch(self, request, from_engine, reason):
        if request.span is not None:
            request.span.event("redispatched", reason=reason)
            request.span.event("queued")
        return True
""",
    })
    assert out == []


def test_dl010_clean_conforming_span_calls():
    out = pcheck("DL010", {
        f"{PKG}/utils/tracing.py": _TRACING_FIXTURE,
        f"{PKG}/serving/dispatcher.py": """
class Dispatcher:
    def redispatch(self, request, from_engine, reason):
        if request.span is not None:
            request.span.set(redispatch_from=from_engine,
                             redispatch_reason=reason)
            request.span.event("redispatched")
        return True
""",
    })
    assert out == []


def test_dl010_flags_unknown_method_and_arity():
    out = pcheck("DL010", {
        f"{PKG}/utils/tracing.py": _TRACING_FIXTURE,
        f"{PKG}/serving/x.py": """
class H:
    def f(self, span):
        span.add_event("x")
        span.event("a", "b")
""",
    })
    msgs = sorted(f.message for f in out)
    assert any("no method 'add_event'" in m for m in msgs)
    assert any("takes 1 positional argument(s), got 2" in m for m in msgs)


def test_dl010_annotation_typed_receiver():
    # receiver typed via annotation, not named after the convention
    out = pcheck("DL010", {
        f"{PKG}/utils/tracing.py": _TRACING_FIXTURE,
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.utils.tracing import Span
class H:
    def f(self, s: Span):
        s.event("ok", 2)
""",
    })
    assert len(out) == 1 and "takes 1 positional" in out[0].message


def test_dl010_metrics_module_alias_members_not_flagged():
    out = pcheck("DL010", {
        f"{PKG}/serving/metrics.py": """
class EngineStatus:
    pass
class MetricsCollector:
    def record_error(self, site):
        pass
""",
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving import metrics
class H:
    def __init__(self, metrics):
        self.metrics = metrics
    def ok(self):
        self.metrics.record_error("site")
    def make(self):
        return metrics.EngineStatus()
""",
    })
    assert out == []


def test_dl010_faults_module_function_conformance():
    out = pcheck("DL010", {
        f"{PKG}/serving/faults.py": """
def fire(point):
    return False
def flag(point):
    return False
""",
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving import faults
def f():
    faults.fire("a.b", 3)
    faults.flagg("a.b")
""",
    })
    msgs = sorted(f.message for f in out)
    assert any("takes 1 positional argument(s), got 2" in m for m in msgs)
    assert any("no module-level 'flagg'" in m for m in msgs)


# ---------------------------------------------------------------------------
# DL011 — fault-point drift
# ---------------------------------------------------------------------------


def test_dl011_flags_unknown_point_against_real_catalog():
    out = pcheck("DL011", {f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving import faults
def f():
    faults.fire("bogus.point")
    faults.fire("runner.step")
"""}, root=REPO_ROOT)
    assert len(out) == 1
    assert "bogus.point" in out[0].message
    assert "RESILIENCE.md" in out[0].message


def test_dl011_spec_strings_and_fstrings_checked():
    out = pcheck("DL011", {f"{PKG}/serving/x.py": """
def scenarios(n):
    specs = ["bogus.crash:nth=1", f"runner.inbox:nth={n}"]
    return specs
"""}, root=REPO_ROOT)
    assert len(out) == 1 and "bogus.crash" in out[0].message


def test_dl011_multi_segment_points_supported_consistently():
    """All four point grammars accept dotted points of any depth — a
    catalog entry one regex can represent but another cannot would be a
    permanently unfixable finding."""
    faults_src = '''
"""Registry.

Point catalog:

``disagg.chunk.late``  three segments, fired below
"""
def fire(point):
    return False
'''
    out = pcheck("DL011", {
        f"{PKG}/serving/faults.py": faults_src,
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving import faults
def f():
    faults.fire("disagg.chunk.late")
    spec = "disagg.chunk.late:nth=1"
    return spec
""",
    })
    assert out == []


def test_dl011_dead_catalog_entry_flagged():
    faults_src = '''
"""Fault registry.

Point catalog:

``a.b``     a live point
``dead.pt`` nobody fires this
"""
def fire(point):
    return False
'''
    out = pcheck("DL011", {
        f"{PKG}/serving/faults.py": faults_src,
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving import faults
def f():
    faults.fire("a.b")
""",
    })
    assert len(out) == 1
    assert "dead.pt" in out[0].message and "never fired" in out[0].message


# ---------------------------------------------------------------------------
# DL012 — config-key drift
# ---------------------------------------------------------------------------

_CONFIG_FIXTURE = f"{PKG}/serving/config.py"
_SCHEMA_SRC = """
_SCHEMA = {
    "server": {"port": (int, 8000), "host": (str, "0.0.0.0")},
    "queue": {"high_watermark": (int, 1000)},
}
"""


def test_dl012_flags_unknown_key_and_section():
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: _SCHEMA_SRC + """
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    a = cfg.get("server", "port")
    b = cfg.get("server", "bogus")
    c = cfg.get("sever", "port")
    d = {{}}.get("anything", "else")
    return a, b, c, d
""",
    })
    msgs = sorted(f.message for f in out)
    assert len(out) == 2
    assert any("server.bogus" in m for m in msgs)
    # receiver TYPED as ServerConfig -> unknown sections flag too
    assert any("unknown config section 'sever'" in m for m in msgs)


def test_dl012_config_named_dict_does_not_misfire():
    """A plain dict that happens to be named ``cfg`` (tokenizer JSON) is
    checked only when the section arg names a real section — and never
    for unknown sections."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: _SCHEMA_SRC,
        f"{PKG}/models/x.py": """
def f(cfg):
    a = cfg.get("bos_token", "")
    b = cfg.get("sever", "port")
    c = cfg.get("server", "bogus")
    return a, b, c
""",
    })
    assert len(out) == 1 and "server.bogus" in out[0].message


def test_dl012_env_tokens_checked_everywhere():
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: _SCHEMA_SRC,
        f"{PKG}/serving/x.py": """
import os
def f():
    ok = os.environ.get("DIS_TPU_SERVER__PORT")
    bad = os.environ.get("DIS_TPU_SERVER__PROT")
    other = os.environ.get("DIS_TPU_PLATFORM")
    return ok, bad, other
""",
    })
    assert len(out) == 1
    assert "DIS_TPU_SERVER__PROT" in out[0].message


def test_dl012_fleet_mesh_keys():
    """The KV-mesh knobs (config ``fleet.mesh_enabled`` /
    ``kv_rate_window_s`` / ``kv_rate_prior``) are schema keys like any
    other: correct accesses pass, a typo'd variant flags, and the env
    spellings resolve."""
    mesh_schema = """
_SCHEMA = {
    "fleet": {
        "mesh_enabled": (bool, False),
        "kv_rate_window_s": (float, 30.0),
        "kv_rate_prior": (float, 125000000.0),
    },
}
"""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: mesh_schema,
        f"{PKG}/serving/x.py": """
import os
def f(cfg):
    a = cfg.get("fleet", "mesh_enabled")
    b = cfg.get("fleet", "kv_rate_window_s")
    c = cfg.get("fleet", "kv_rate_prior")
    d = os.environ.get("DIS_TPU_FLEET__MESH_ENABLED")
    bad = cfg.get("fleet", "mesh_enable")
    return a, b, c, d, bad
""",
    })
    assert len(out) == 1 and "fleet.mesh_enable" in out[0].message


def test_dl012_fleet_ha_keys():
    """The registry-HA knobs (config ``fleet.registries`` / ``lease_s``
    / ``lease_suspect_s`` / ``standby_http``) are schema keys like any
    other: correct accesses pass, a typo'd variant flags, and the env
    spellings resolve."""
    ha_schema = """
_SCHEMA = {
    "fleet": {
        "registries": (tuple, []),
        "lease_s": (float, 3.0),
        "lease_suspect_s": (float, 1.5),
        "standby_http": (bool, True),
    },
}
"""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: ha_schema,
        f"{PKG}/serving/x.py": """
import os
def f(cfg):
    a = cfg.get("fleet", "registries")
    b = cfg.get("fleet", "lease_s")
    c = cfg.get("fleet", "lease_suspect_s")
    d = os.environ.get("DIS_TPU_FLEET__STANDBY_HTTP")
    bad = cfg.get("fleet", "lease_suspect")
    return a, b, c, d, bad
""",
    })
    assert len(out) == 1 and "fleet.lease_suspect" in out[0].message


def test_dl012_schema_internal_literals():
    out = pcheck("DL012", {_CONFIG_FIXTURE: _SCHEMA_SRC + """
HOT_RELOADABLE = {("server", "port"), ("queue", "high_watermrk")}
def validate(r):
    if r["server"]["prot"] <= 0:
        raise ValueError
"""})
    msgs = sorted(f.message for f in out)
    assert len(out) == 2
    assert any("queue.high_watermrk" in m for m in msgs)
    assert any("server.prot" in m for m in msgs)


# ---------------------------------------------------------------------------
# DL013 — span/event-name catalog drift
# ---------------------------------------------------------------------------

_DL013_CATALOG = """# Observability

| name | kind | emitted by |
|------|------|------------|
| `request.<endpoint>` | span | handler |
| `engine.infer` | span | runner |
| `queued` | event | handler |
| `admit` | timeline | recorder |
"""


def _dl013_root(tmp_path, catalog=_DL013_CATALOG):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(catalog)
    return tmp_path


def test_dl013_flags_uncataloged_span_and_event(tmp_path):
    out = pcheck("DL013", {
        f"{PKG}/serving/x.py": """
class H:
    def go(self, span):
        s = self.tracer.start("mystery.span")
        span.event("mystery_event")
""",
    }, root=_dl013_root(tmp_path))
    emission = [f for f in out if f.path.endswith("x.py")]
    msgs = sorted(f.message for f in emission)
    assert any("'mystery.span'" in m for m in msgs)
    assert any("'mystery_event'" in m for m in msgs)
    assert len(emission) == 2
    # the unused catalog rows flag as dead entries, anchored in the doc
    assert all(f.path == "docs/OBSERVABILITY.md"
               for f in out if f not in emission)


def test_dl013_clean_and_fstring_head_matches_placeholder(tmp_path):
    out = pcheck("DL013", {
        f"{PKG}/serving/x.py": """
class H:
    def go(self, span, engine_span, endpoint):
        self.tracer.start(f"request.{endpoint}")
        with self.tracer.span("engine.infer"):
            pass
        span.event("queued")
        engine_span.event("queued")
""",
    }, root=_dl013_root(tmp_path))
    assert out == []


def test_dl013_dead_catalog_entry_flagged(tmp_path):
    out = pcheck("DL013", {
        f"{PKG}/serving/x.py": """
class H:
    def go(self, span, endpoint):
        self.tracer.start(f"request.{endpoint}")
        span.event("queued")
""",
    }, root=_dl013_root(tmp_path))
    assert len(out) == 1
    assert "never emitted" in out[0].message
    assert "'engine.infer'" in out[0].message
    assert out[0].path == "docs/OBSERVABILITY.md"


def test_dl013_timeline_rows_and_non_span_receivers_ignored(tmp_path):
    # `admit` is a kind=timeline row (documentation only) and calls on
    # non-span receivers (`recorder.note`, a random obj.event) are out
    # of scope — neither may produce findings
    out = pcheck("DL013", {
        f"{PKG}/serving/x.py": """
class H:
    def go(self, span, endpoint, recorder, widget):
        self.tracer.start(f"request.{endpoint}")
        self.tracer.span("engine.infer")
        span.event("queued")
        recorder.note("r1", "something_else")
        widget.event("not_a_span_event")
""",
    }, root=_dl013_root(tmp_path))
    assert out == []


def test_dl013_no_catalog_means_no_findings():
    # fixture roots without docs/OBSERVABILITY.md (every other pcheck
    # call in this file) must not explode or flag
    out = pcheck("DL013", {
        f"{PKG}/serving/x.py": """
class H:
    def go(self):
        self.tracer.start("anything.goes")
""",
    })
    assert out == []


def test_dl013_real_repo_catalog_is_in_sync():
    findings = list(RULES["DL013"].check_project(
        list(run_lint.__globals__["collect_modules"](REPO_ROOT).values()),
        REPO_ROOT,
    ))
    assert findings == [], [f.render() for f in findings]


def test_dl012_real_repo_schema_parses():
    from tools.lint.rules import DL012
    from tools.lint.core import collect_modules

    mods = collect_modules(REPO_ROOT,
                           files=[f"{PKG}/serving/config.py"])
    schema = DL012._parse_schema(mods[f"{PKG}/serving/config.py"])
    assert schema and "server" in schema and "port" in schema["server"]
    # ISSUE 12: the mixed-step knob is a real schema entry, so every
    # config.get("engine", "mixed_step_tokens") site is drift-checked
    assert "mixed_step_tokens" in schema["engine"]
    # ISSUE 13: the fleet KV data-plane knobs are real schema entries
    for key in ("kv_enabled", "kv_data_port", "kv_page_cost",
                "kv_max_streams", "kv_connect_timeout_s"):
        assert key in schema["fleet"], key
    # ISSUE 15: the gray-failure sections are real schema entries, so
    # every health.* / admission.* get site is drift-checked
    for key in ("enabled", "stall_s", "latency_ratio", "wire_failures",
                "breaker_open_s", "retry_budget_ratio", "slo_burn_high"):
        assert key in schema["health"], key
    for key in ("shed_enabled", "deadline_ms", "deadline_factor",
                "brownout", "retry_after_cap_s"):
        assert key in schema["admission"], key


def test_dl012_health_admission_keys_checked():
    """The gray-failure config keys (ISSUE 15, serving/health.py): a
    correct get (and the env-token spelling) is clean, typo'd keys in
    either new section flag."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: """
_SCHEMA = {
    "health": {"stall_s": (float, 5.0), "wire_failures": (int, 3)},
    "admission": {"deadline_ms": (float, 0.0), "brownout": (bool, True)},
}
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
import os
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    ok = cfg.get("health", "stall_s")
    ok2 = cfg.get("admission", "brownout")
    env = os.environ.get("DIS_TPU_HEALTH__WIRE_FAILURES")
    bad = cfg.get("health", "stall_seconds")
    bad2 = cfg.get("admission", "deadline_mss")
    return ok, ok2, env, bad, bad2
""",
    })
    assert len(out) == 2
    msgs = "\n".join(f.message for f in out)
    assert "health.stall_seconds" in msgs
    assert "admission.deadline_mss" in msgs


def test_dl012_mixed_step_key_checked():
    """The new engine.mixed_step_tokens key: a correct get is clean, a
    typo'd key flags against the schema."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: """
_SCHEMA = {
    "engine": {"mixed_step_tokens": (int, 0), "max_batch": (int, 64)},
}
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    ok = cfg.get("engine", "mixed_step_tokens")
    bad = cfg.get("engine", "mixed_step_tokenz")
    return ok, bad
""",
    })
    assert len(out) == 1
    assert "engine.mixed_step_tokenz" in out[0].message


def test_dl012_loop_keys_checked():
    """The kernel-looping knobs (config ``engine.loop_to_completion`` /
    ``engine.loop_max_steps``, ISSUE 19): correct gets and the env
    spelling are clean, a typo'd key flags against the schema."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: """
_SCHEMA = {
    "engine": {
        "loop_to_completion": (bool, False),
        "loop_max_steps": (int, 256),
    },
}
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
import os
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    ok = cfg.get("engine", "loop_to_completion")
    env = os.environ.get("DIS_TPU_ENGINE__LOOP_MAX_STEPS")
    bad = cfg.get("engine", "loop_max_stepz")
    return ok, env, bad
""",
    })
    assert len(out) == 1
    assert "engine.loop_max_stepz" in out[0].message


def test_dl012_fleet_kv_keys_checked():
    """The fleet.kv_* keys (ISSUE 13, serving/fleet_kv.py): a correct
    get (and the env-token spelling) is clean, a typo'd key flags."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: """
_SCHEMA = {
    "fleet": {"kv_page_cost": (float, 0.6), "kv_max_streams": (int, 4)},
}
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
import os
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    ok = cfg.get("fleet", "kv_page_cost")
    env = os.environ.get("DIS_TPU_FLEET__KV_MAX_STREAMS")
    bad = cfg.get("fleet", "kv_page_costs")
    return ok, env, bad
""",
    })
    assert len(out) == 1
    assert "fleet.kv_page_costs" in out[0].message


def test_dl012_latent_keys_checked():
    """The latent page codec knob (config ``cache.latent_rank``,
    ISSUE 20) and the extended quant values: a correct get (and the env
    spelling) is clean, a typo'd key flags against the schema."""
    out = pcheck("DL012", {
        _CONFIG_FIXTURE: """
_SCHEMA = {
    "cache": {"latent_rank": (int, 0), "host_tier_quant": (str, "none")},
    "disagg": {"wire_quant": (str, "none")},
}
class ServerConfig:
    def get(self, section, key):
        return None
""",
        f"{PKG}/serving/x.py": f"""
import os
from {PKG.replace('/', '.')}.serving.config import ServerConfig
def f(cfg: ServerConfig):
    ok = cfg.get("cache", "latent_rank")
    wq = cfg.get("disagg", "wire_quant")
    env = os.environ.get("DIS_TPU_CACHE__LATENT_RANK")
    bad = cfg.get("cache", "latent_rankz")
    return ok, wq, env, bad
""",
    })
    assert len(out) == 1
    assert "cache.latent_rankz" in out[0].message


# ---------------------------------------------------------------------------
# interprocedural infrastructure: targets, cache, CLI
# ---------------------------------------------------------------------------


def test_extra_targets_are_linted():
    from tools.lint.core import collect_modules

    mods = collect_modules(REPO_ROOT)
    assert "tools/chaos_fleet.py" in mods
    assert "tools/lint/callgraph.py" in mods
    assert "tools/lint/threads.py" in mods


def test_changed_files_filter_covers_extra_targets():
    from tools.lint.run import _is_lint_target

    assert _is_lint_target(f"{PKG}/serving/runner.py")
    assert _is_lint_target("tools/chaos_fleet.py")
    assert _is_lint_target("tools/lint/rules.py")
    assert not _is_lint_target("tests/test_distlint.py")
    assert not _is_lint_target("tools/soak_engine.py")
    assert not _is_lint_target("README.md")


def test_callgraph_build_is_memoized_and_keyed_on_content():
    from tools.lint import callgraph

    m1 = module_from_source(f"{PKG}/serving/a.py", "def f():\n    pass\n")
    s1 = callgraph.build_summary([m1])
    s2 = callgraph.build_summary([m1])
    assert s1 is s2  # in-process memo hit
    m2 = module_from_source(f"{PKG}/serving/a.py",
                            "def f():\n    return 1\n")
    assert callgraph.build_summary([m2]) is not s1  # content key changed


def test_github_format_emits_workflow_annotations(tmp_path, monkeypatch,
                                                  capsys):
    from tools.lint import run as run_mod

    (tmp_path / "pkg").mkdir()
    bad = tmp_path / "pkg" / "bad.py"
    bad.write_text(
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    monkeypatch.setattr(run_mod, "REPO_ROOT", tmp_path)
    rc = run_mod.main(["--format=github", "--no-baseline",
                       "--rule", "DL004", "pkg/bad.py"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "::error file=pkg/bad.py,line=4,title=distlint DL004" in out


def test_interprocedural_rules_registered():
    for name in ("DL008", "DL009", "DL010", "DL011", "DL012", "DL013"):
        assert name in RULES
        assert RULES[name].scope == "project"


# ---------------------------------------------------------------------------
# DL014 — performance-telemetry catalog drift
# ---------------------------------------------------------------------------

_DL014_CATALOG = """# Observability

## Performance telemetry

| name | kind | meaning |
|------|------|---------|
| `engines` | perf-field | per-engine step clock |
| `windows` | perf-field | windowed stats |
| `slo_requests_total` | metric | verdict counts |
| `ttft_ms` | digest | windowed TTFT |
"""

_DL014_TELEDIGEST = '''
PERF_FIELDS = ("engines", "windows")
TELEMETRY_METRICS = ("slo_requests_total",)
DIGEST_NAMES = ("ttft_ms",)
'''

_DL014_METRICS = '''
from prometheus_client import Counter
class MetricsCollector:
    def __init__(self, r=None):
        self.slo_requests = Counter(
            "slo_requests_total", "d", ["tenant", "verdict"], registry=r)
'''


def _dl014_root(tmp_path, catalog=_DL014_CATALOG):
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(catalog)
    return tmp_path


def test_dl014_clean(tmp_path):
    out = pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST,
        f"{PKG}/serving/metrics.py": _DL014_METRICS,
    }, root=_dl014_root(tmp_path))
    assert out == []


def test_dl014_flags_undocumented_code_entry(tmp_path):
    out = pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST.replace(
            '("engines", "windows")', '("engines", "windows", "mystery")'
        ),
        f"{PKG}/serving/metrics.py": _DL014_METRICS,
    }, root=_dl014_root(tmp_path))
    assert len(out) == 1
    assert "'mystery'" in out[0].message
    assert out[0].path.endswith("teledigest.py")


def test_dl014_flags_dead_catalog_row(tmp_path):
    out = pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST,
        f"{PKG}/serving/metrics.py": _DL014_METRICS,
    }, root=_dl014_root(
        tmp_path,
        _DL014_CATALOG + "| `ghost_field` | perf-field | gone |\n"))
    assert len(out) == 1
    assert "'ghost_field'" in out[0].message
    assert out[0].path == "docs/OBSERVABILITY.md"


def test_dl014_flags_kind_disagreement(tmp_path):
    # cataloged as a digest, declared as a perf-field
    out = pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST.replace(
            'DIGEST_NAMES = ("ttft_ms",)',
            'DIGEST_NAMES = ()\nPERF_FIELDS2 = ()'
        ).replace('("engines", "windows")',
                  '("engines", "windows", "ttft_ms")'),
        f"{PKG}/serving/metrics.py": _DL014_METRICS,
    }, root=_dl014_root(tmp_path))
    assert any("catalogs disagree" in f.message for f in out)


def test_dl014_flags_unregistered_cataloged_metric(tmp_path):
    out = pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST,
        f"{PKG}/serving/metrics.py": (
            "class MetricsCollector:\n"
            "    pass\n"),
    }, root=_dl014_root(tmp_path))
    assert any("never registered" in f.message for f in out)


def test_dl014_no_teledigest_or_docs_means_no_findings(tmp_path):
    # fixture roots without the module or the catalog must not flag
    assert pcheck("DL014", {
        f"{PKG}/serving/metrics.py": _DL014_METRICS,
    }, root=_dl014_root(tmp_path)) == []
    assert pcheck("DL014", {
        f"{PKG}/serving/teledigest.py": _DL014_TELEDIGEST,
    }) == []


def test_dl014_real_repo_catalog_is_in_sync():
    findings = list(RULES["DL014"].check_project(
        list(run_lint.__globals__["collect_modules"](REPO_ROOT).values()),
        REPO_ROOT,
    ))
    assert findings == [], [f.render() for f in findings]


def test_dl014_registered():
    assert "DL014" in RULES
    assert RULES["DL014"].scope == "project"


# ---------------------------------------------------------------------------
# DL015 — exactly-once in-flight registry lifecycle (v3)
# ---------------------------------------------------------------------------

# acceptance fixture: PR 2's bug shape verbatim — submit_resume registers
# an on_done continuation in _pending_resumes, the crash sweep _fail_all
# drains _inflight but NOT _pending_resumes, so a member death leaves the
# resume's callback never run and the drain wedges
_DL015_PR2 = """
class EngineRunner:
    def __init__(self):
        self._inflight = {}
        self._pending_resumes = {}
    def submit(self, req):
        self._inflight[req.request_id] = req
    def submit_resume(self, exp, req, on_done):
        self._pending_resumes[req.request_id] = on_done
    def _drain_resume(self, rid):
        cb = self._pending_resumes.pop(rid, None)
        if cb is not None:
            cb(True, None)
    def _fail_all(self, err):
        for rid in list(self._inflight):
            req = self._inflight.pop(rid, None)
            if req is not None:
                req.sink.on_error(err)
"""


def test_dl015_pr2_fixture_resume_leak_past_fail_all_is_p0():
    out = pcheck("DL015", {f"{PKG}/serving/runner.py": _DL015_PR2})
    assert len(out) == 1, [f.render() for f in out]
    f = out[0]
    assert f.severity == "P0"
    assert "_pending_resumes" in f.message
    assert "crash path" in f.message
    # _inflight IS drained by _fail_all, so only the resume map flags
    assert "_inflight" not in f.message


def test_dl015_pr2_fixed_shape_is_clean():
    fixed = _DL015_PR2.replace(
        "            if req is not None:\n"
        "                req.sink.on_error(err)\n",
        "            if req is not None:\n"
        "                req.sink.on_error(err)\n"
        "        for rid in list(self._pending_resumes):\n"
        "            cb = self._pending_resumes.pop(rid, None)\n"
        "            if cb is not None:\n"
        "                cb(False, err)\n",
    )
    assert pcheck("DL015", {f"{PKG}/serving/runner.py": fixed}) == []


# acceptance fixture: PR 7's bug shape verbatim — _settle pops the entry
# FIRST and hands it to submit() after, so while the submit runs the
# request is in neither the registry nor the engine and a concurrent
# crash sweep cannot resolve it
_DL015_PR7 = """
class Dispatcher:
    def __init__(self):
        self._inflight = {}
    def enqueue(self, req):
        self._inflight[req.request_id] = req
    def _settle(self, rid):
        req = self._inflight.pop(rid, None)
        if req is None:
            return
        self.runner.submit(req)
    def _fail_all(self, err):
        for rid in list(self._inflight):
            self._inflight.pop(rid, None)
"""


def test_dl015_pr7_fixture_settle_pop_before_submit_is_p0():
    out = pcheck("DL015", {f"{PKG}/serving/dispatcher.py": _DL015_PR7})
    assert len(out) == 1, [f.render() for f in out]
    f = out[0]
    assert f.severity == "P0"
    assert "popped before the handoff" in f.message
    assert "_settle" in (f.context or "")


def test_dl015_pr7_handoff_first_shape_is_clean():
    fixed = _DL015_PR7.replace(
        "        req = self._inflight.pop(rid, None)\n"
        "        if req is None:\n"
        "            return\n"
        "        self.runner.submit(req)\n",
        "        req = self._inflight.pop(rid, None)\n"
        "        if req is None:\n"
        "            return\n",
    )
    assert pcheck("DL015", {f"{PKG}/serving/dispatcher.py": fixed}) == []


def test_dl015_state_map_with_crash_method_is_not_a_registry():
    # _members is membership STATE (expiry-pruned, no per-entry
    # continuation): the in-flight naming gate keeps it out even though
    # the class has a close() and add+del sites
    src = """
class Registry:
    def __init__(self):
        self._members = {}
    def observe(self, mid, rec):
        self._members[mid] = rec
    def prune(self, mid):
        del self._members[mid]
    def close(self):
        pass
"""
    assert pcheck("DL015", {f"{PKG}/serving/fleet.py": src}) == []


def test_dl015_marker_opts_in_and_no_resolve_anywhere_is_p0():
    src = """
class Router:
    def __init__(self):
        # distlint: registry
        self._routes = {}
    def learn(self, key, ep):
        self._routes[key] = ep
"""
    out = pcheck("DL015", {f"{PKG}/serving/fleet.py": src})
    assert len(out) == 1
    assert out[0].severity == "P0"
    assert "no pop/del/clear resolve site" in out[0].message


def test_dl015_read_before_pop_without_lock_is_p1():
    src = """
class Channel:
    def __init__(self):
        self._pending = {}
    def add(self, rid, cb):
        self._pending[rid] = cb
    def resolve(self, rid):
        cb = self._pending.get(rid)
        if cb is None:
            return
        self._pending.pop(rid, None)
        cb(True)
    def _fail_all(self):
        for rid in list(self._pending):
            self._pending.pop(rid, None)
"""
    out = pcheck("DL015", {f"{PKG}/serving/fleet_kv.py": src})
    assert len(out) == 1
    assert out[0].severity == "P1"
    assert "not pop-first gated" in out[0].message


def test_dl015_shared_lock_makes_check_then_act_atomic():
    src = """
import threading
class Channel:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}
    def add(self, rid, cb):
        with self._lock:
            self._pending[rid] = cb
    def resolve(self, rid):
        with self._lock:
            cb = self._pending.get(rid)
            if cb is None:
                return
            self._pending.pop(rid, None)
        cb(True)
    def _fail_all(self):
        with self._lock:
            for rid in list(self._pending):
                self._pending.pop(rid, None)
"""
    assert pcheck("DL015", {f"{PKG}/serving/fleet_kv.py": src}) == []


def test_dl015_locked_suffix_functions_are_exempt():
    src = """
class Rec:
    def __init__(self):
        self._streams = {}
    def add(self, rid, s):
        self._streams[rid] = s
    def _get_or_create_locked(self, rid):
        s = self._streams.get(rid)
        if s is None:
            self._streams.pop(rid, None)
        return s
    def _fail_all(self):
        for rid in list(self._streams):
            self._streams.pop(rid, None)
"""
    assert pcheck("DL015", {f"{PKG}/serving/flightrec.py": src}) == []


def test_dl015_registered():
    assert "DL015" in RULES
    assert RULES["DL015"].scope == "project"
    assert RULES["DL015"].severity == "P0"


# ---------------------------------------------------------------------------
# DL016 — exception-edge resource leak (v3)
# ---------------------------------------------------------------------------


def test_dl016_risky_call_between_dial_and_store_flags():
    src = """
import socket
class Channel:
    def _connect(self):
        sock = socket.create_connection(("h", 1), timeout=1.0)
        sock.setsockopt(1, 2, 3)
        self._sock = sock
"""
    out = pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": src})
    assert len(out) == 1
    assert "dialed socket" in out[0].message
    assert "setsockopt" in out[0].message


def test_dl016_try_except_close_protects_the_edge():
    src = """
import socket
class Channel:
    def _connect(self):
        sock = socket.create_connection(("h", 1), timeout=1.0)
        try:
            sock.setsockopt(1, 2, 3)
        except OSError:
            sock.close()
            raise
        self._sock = sock
"""
    assert pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": src}) == []


def test_dl016_socket_never_settled_flags():
    src = """
import socket
class Channel:
    def _probe(self):
        sock = socket.create_connection(("h", 1), timeout=1.0)
        sock.send(b"hi")
"""
    out = pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": src})
    assert len(out) == 1
    assert "never released" in out[0].message


def test_dl016_breaker_token_risky_send_flags_and_handler_protects():
    leaky = """
class Channel:
    def _start(self):
        if not self.breaker.try_acquire():
            return False
        self.send_header()
        self.breaker.record_success()
        return True
"""
    out = pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": leaky})
    assert len(out) == 1
    assert "breaker half-open token" in out[0].message
    guarded = """
class Channel:
    def _start(self):
        if not self.breaker.try_acquire():
            return False
        try:
            self.send_header()
        except OSError:
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        return True
"""
    assert pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": guarded}) == []


def test_dl016_with_statement_consumes_the_acquire():
    src = """
import socket
class Channel:
    def _probe(self):
        with socket.create_connection(("h", 1), timeout=1.0) as sock:
            sock.send(b"hi")
"""
    assert pcheck("DL016", {f"{PKG}/serving/fleet_kv.py": src}) == []


def test_dl016_only_serving_modules_are_checked():
    src = """
import socket
def probe():
    sock = socket.create_connection(("h", 1), timeout=1.0)
    sock.send(b"hi")
"""
    assert pcheck("DL016", {f"{PKG}/engine/util.py": src}) == []


def test_dl016_registered():
    assert "DL016" in RULES
    assert RULES["DL016"].scope == "project"


# ---------------------------------------------------------------------------
# DL017 — wire-handler exhaustiveness (v3)
# ---------------------------------------------------------------------------

_DL017_WIRE = """
FRAME_KINDS = {1: "Ping", 2: "Pong", 3: "Data"}

def recv_frame(sock):
    kind = sock.read_u8()
    name = FRAME_KINDS.get(kind)
    return (name, {}) if name else None
"""

_DL017_READER = """
from x.wire import recv_frame

def read_loop(sock):
    while True:
        frame = recv_frame(sock)
        if frame is None:
            break
        name, obj = frame
        if name == "Ping":
            sock.pong()
        elif name == "Pong":
            pass
"""


def test_dl017_missing_arm_flags_with_marker_suggestion():
    out = pcheck("DL017", {
        f"{PKG}/serving/wire.py": _DL017_WIRE,
        f"{PKG}/serving/client.py": _DL017_READER,
    })
    assert len(out) == 1
    assert "'Data'" in out[0].message
    assert "wire-ignores[Data]" in out[0].message


def test_dl017_wire_ignores_marker_clears_the_arm():
    marked = _DL017_READER.replace(
        "def read_loop(sock):",
        "# distlint: wire-ignores[Data]\ndef read_loop(sock):")
    assert pcheck("DL017", {
        f"{PKG}/serving/wire.py": _DL017_WIRE,
        f"{PKG}/serving/client.py": marked,
    }) == []


def test_dl017_dead_arm_for_unknown_kind_flags():
    reader = _DL017_READER.replace(
        'elif name == "Pong":',
        'elif name == "Goodbye":\n'
        "            pass\n"
        '        elif name == "Data":\n'
        "            pass\n"
        '        elif name == "Pong":')
    out = pcheck("DL017", {
        f"{PKG}/serving/wire.py": _DL017_WIRE,
        f"{PKG}/serving/client.py": reader,
    })
    assert len(out) == 1
    assert "'Goodbye'" in out[0].message


def test_dl017_else_raise_default_is_intolerant():
    reader = _DL017_READER.replace(
        'elif name == "Pong":\n'
        "            pass",
        'elif name == "Pong":\n'
        "            pass\n"
        '        elif name == "Data":\n'
        "            pass\n"
        "        else:\n"
        "            raise ValueError(name)")
    out = pcheck("DL017", {
        f"{PKG}/serving/wire.py": _DL017_WIRE,
        f"{PKG}/serving/client.py": reader,
    })
    assert len(out) == 1
    assert "tolerate" in out[0].message


def test_dl017_non_dispatch_forwarder_is_skipped():
    # a helper that recv()s and forwards whole frames without
    # dispatching on the kind is not a reader loop
    fwd = """
from x.wire import recv_frame

def pump(sock, out):
    while True:
        frame = recv_frame(sock)
        if frame is None:
            break
        out.put(frame)
"""
    assert pcheck("DL017", {
        f"{PKG}/serving/wire.py": _DL017_WIRE,
        f"{PKG}/serving/relay.py": fwd,
    }) == []


def test_dl017_registered():
    assert "DL017" in RULES
    assert RULES["DL017"].scope == "project"


# ---------------------------------------------------------------------------
# DL018 — fault-point coverage drift (v3)
# ---------------------------------------------------------------------------

_DL018_FAULTS = '''
"""Fault injection.

``wire.send``      send dies on the wire
``engine.step``    crash mid-step
"""

def fire(point):
    pass
'''

_DL018_CHAOS = """
SCENARIOS = {"wire_death": "wire.send:nth=1"}
"""

_DL018_FAULTS_PATH = f"{PKG}/serving/faults.py"


def test_dl018_uncovered_point_flags_and_a_test_covers_it(tmp_path):
    sources = {
        _DL018_FAULTS_PATH: _DL018_FAULTS,
        "tools/chaos_fleet.py": _DL018_CHAOS,
    }
    (tmp_path / "tests").mkdir()
    out = pcheck("DL018", sources, root=tmp_path)
    assert len(out) == 1
    assert "'engine.step'" in out[0].message
    # a committed test arming the point clears the finding
    (tmp_path / "tests" / "test_cov.py").write_text(
        'faults.install(parse_spec("engine.step:nth=1", seed=1))\n')
    assert pcheck("DL018", sources, root=tmp_path) == []


def test_dl018_point_kwarg_in_tests_counts_as_exercised(tmp_path):
    sources = {
        _DL018_FAULTS_PATH: _DL018_FAULTS,
        "tools/chaos_fleet.py": _DL018_CHAOS,
    }
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_cov.py").write_text(
        'FaultRule(point="engine.step", nth=1)\n')
    assert pcheck("DL018", sources, root=tmp_path) == []


def test_dl018_file_restricted_run_is_silent(tmp_path):
    # without the faults module or the chaos module in view, coverage
    # cannot be judged — a --changed run must not false-positive
    assert pcheck("DL018", {
        _DL018_FAULTS_PATH: _DL018_FAULTS,
    }, root=tmp_path) == []


def test_dl018_real_repo_catalog_is_fully_exercised():
    findings = list(RULES["DL018"].check_project(
        list(run_lint.__globals__["collect_modules"](REPO_ROOT).values()),
        REPO_ROOT,
    ))
    assert findings == [], [f.render() for f in findings]


def test_dl018_registered():
    assert "DL018" in RULES
    assert RULES["DL018"].scope == "project"


# ---------------------------------------------------------------------------
# cache pruning (tools/lint/.cache; v3 satellite)
# ---------------------------------------------------------------------------


def test_prune_cache_evicts_corrupt_mismatched_and_old(tmp_path, monkeypatch):
    import os
    import pickle

    from tools.lint import callgraph

    monkeypatch.setattr(callgraph, "CACHE_DIR", tmp_path)

    def entry(name_key, stored_key, age):
        p = tmp_path / f"callgraph-{name_key}.pkl"
        with p.open("wb") as f:
            pickle.dump((stored_key, callgraph.ProjectSummary()), f)
        t = 1_700_000_000 - age
        os.utime(p, (t, t))
        return p

    # six valid entries, oldest first by age
    valid = [entry(f"key{i:02d}x", f"key{i:02d}x-full", age=i * 100)
             for i in range(6)]
    # a truncated/corrupt pickle and a key-mismatched one
    corrupt = tmp_path / "callgraph-deadbeef.pkl"
    corrupt.write_bytes(b"not a pickle")
    mismatched = entry("aaaa", "bbbb-full", age=1)

    evicted = callgraph.prune_cache(keep=4)
    # corrupt + mismatched always go; of the 6 valid, the 2 oldest go
    assert corrupt.name in evicted and mismatched.name in evicted
    assert not corrupt.exists() and not mismatched.exists()
    survivors = sorted(p.name for p in tmp_path.glob("callgraph-*.pkl"))
    assert survivors == sorted(p.name for p in valid[:4])


def test_prune_cache_keep_keys_survive_the_age_cut(tmp_path, monkeypatch):
    import os
    import pickle

    from tools.lint import callgraph

    monkeypatch.setattr(callgraph, "CACHE_DIR", tmp_path)
    for i in range(5):
        p = tmp_path / f"callgraph-key{i:02d}x.pkl"
        with p.open("wb") as f:
            pickle.dump((f"key{i:02d}x-full", callgraph.ProjectSummary()), f)
        t = 1_700_000_000 - i * 100
        os.utime(p, (t, t))
    # the OLDEST entry is the one just written by this run: it must
    # survive a keep=1 prune (an entry never evicts itself)
    callgraph.prune_cache(keep=1, keep_keys=("key04x",))
    names = {p.name for p in tmp_path.glob("callgraph-*.pkl")}
    assert "callgraph-key04x.pkl" in names
    assert "callgraph-key00x.pkl" in names  # newest valid survives keep=1


def test_build_summary_writes_and_prunes_through_the_real_path(
        tmp_path, monkeypatch):
    from tools.lint import callgraph

    monkeypatch.setattr(callgraph, "CACHE_DIR", tmp_path)
    stale = tmp_path / "callgraph-feedface.pkl"
    stale.write_bytes(b"junk")
    mods = [module_from_source(f"{PKG}/serving/m{i}.py", "x = 1\n")
            for i in range(12)]  # >= 10 modules => disk persistence
    callgraph._MEMO.clear()
    callgraph.build_summary(mods, use_disk_cache=True)
    names = [p.name for p in tmp_path.glob("callgraph-*.pkl")]
    assert len(names) == 1  # the fresh entry; the junk one was evicted
    assert not stale.exists()


# ---------------------------------------------------------------------------
# --timings (v3 satellite)
# ---------------------------------------------------------------------------


def test_run_lint_collects_per_rule_timings():
    timings = {}
    run_lint(REPO_ROOT, files=[f"{PKG}/serving/faults.py"],
             rules=["DL001", "DL004"], timings=timings)
    assert set(timings) == {"<collect>", "DL001", "DL004"}
    assert all(v >= 0.0 for v in timings.values())


def test_cli_timings_flag_prints_a_table(capsys):
    from tools.lint.run import main

    rc = main(["--rule", "DL010", "--timings",
               f"{PKG}/serving/faults.py"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "distlint timings" in out
    assert "DL010" in out and "total" in out
