"""Per-request flight recorder (serving/flightrec.py,
docs/OBSERVABILITY.md): phase attribution partitions the wall clock,
memory is bounded twice (requests + events), terminal events are
exactly-once, and the disabled path touches nothing on the spine."""

from __future__ import annotations

import time

from distributed_inference_server_tpu.core.models import FinishReason, Usage
from distributed_inference_server_tpu.engine.engine import (
    SamplingParams,
    StepOutput,
)
from distributed_inference_server_tpu.serving.flightrec import (
    PHASES,
    FlightRecorder,
)
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.runner import (
    EngineRunner,
    ServerRequest,
)
from distributed_inference_server_tpu.utils.tracing import Tracer


def _drive_request(rec, rid="r1", tokens=20, fetch_s=0.0, stall_s=0.0):
    rec.admit(rid, endpoint="generate", prompt_tokens=8,
              trace_id="t" * 16)
    rec.note(rid, "schedule", engine="engine-0", strategy="least_loaded")
    if fetch_s:
        rec.note(rid, "prefix_fetch", outcome="ok", seconds=fetch_s)
    for _ in range(tokens):
        rec.token(rid)
    if stall_s:
        rec.note(rid, "handoff_resume", target="engine-1",
                 stall_s=stall_s)
    return rec.finish(rid, "ok")


class TestPhaseModel:
    def test_phases_partition_wall_clock(self):
        rec = FlightRecorder()
        phases = _drive_request(rec, tokens=40)
        tl = rec.timeline("r1")
        assert set(phases) == set(PHASES)
        total = sum(phases.values())
        # exact partition by construction (clamps never trigger here)
        assert abs(total - tl["wall_s"]) < 1e-6
        assert tl["status"] == "ok" and tl["tokens"] == 40
        assert tl["ttft_s"] is not None and tl["ttft_s"] >= 0
        assert tl["trace_id"] == "t" * 16

    def test_windowed_costs_subtract_from_containing_phase(self):
        rec = FlightRecorder()
        rec.admit("r1")
        rec.note("r1", "schedule", engine="e0")
        time.sleep(0.03)
        # the fetch window lands inside dispatch -> first_token
        rec.note("r1", "prefix_fetch", outcome="ok", seconds=0.02)
        rec.token("r1")
        time.sleep(0.02)
        rec.token("r1")
        rec.note("r1", "handoff_resume", target="e1", stall_s=0.01)
        phases = rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        assert abs(phases["peer_fetch"] - 0.02) < 1e-6
        assert abs(phases["handoff_stall"] - 0.01) < 1e-6
        assert abs(sum(phases.values()) - tl["wall_s"]) < 1e-6
        # the subtraction really happened: prefill excludes the fetch
        assert phases["prefill"] <= tl["wall_s"] - 0.02

    def test_windows_clamp_to_their_span(self):
        # a reported stall larger than the decode window must not make
        # the partition exceed the wall clock
        rec = FlightRecorder()
        rec.admit("r1")
        rec.note("r1", "schedule", engine="e0")
        rec.token("r1")
        rec.token("r1")
        rec.note("r1", "handoff_resume", target="e1", stall_s=999.0)
        phases = rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        assert sum(phases.values()) <= tl["wall_s"] + 1e-6

    def test_mixed_step_prefill_attribution_window_subtraction(self):
        """ISSUE 12: when prefill rides the MIXED step, a request's
        prompt loads across several mixed dispatches while OTHER rows'
        decode tokens interleave on the wall clock — but the per-request
        phase model is unchanged: prefill is still dispatch ->
        first_token minus the fetch windows inside it, the partition
        stays exact, and a peer-fetch window that landed mid-mixed-
        prefill subtracts from prefill, never from decode."""
        rec = FlightRecorder()
        rec.admit("r1", endpoint="generate")
        rec.note("r1", "schedule", engine="e0", strategy="least_loaded")
        # the prompt spreads over mixed dispatches: wall time passes
        # before the first token, with a fetch window inside it
        time.sleep(0.02)
        rec.note("r1", "prefix_fetch", outcome="ok", seconds=0.015)
        time.sleep(0.02)
        rec.token("r1")  # first token: prefill complete
        time.sleep(0.01)
        rec.token("r1")
        phases = rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        # exact partition (window-subtraction did not tear it)
        assert abs(sum(phases.values()) - tl["wall_s"]) < 1e-6
        # the fetch window subtracted from PREFILL, exactly
        assert abs(phases["peer_fetch"] - 0.015) < 1e-6
        assert phases["prefill"] >= 0.04 - 0.015 - 1e-3
        assert phases["prefill"] <= tl["ttft_s"] - 0.015 + 1e-6
        # decode is untouched by the prefill-side window
        assert phases["decode"] >= 0.01 - 1e-3

    def test_looped_block_bursts_keep_partition_exact(self):
        """ISSUE 19 (kernel looping): a run-to-completion decode block
        surfaces a whole block's tokens as one burst at reconcile, and
        block lengths vary (eos / budget / pages / cap exits) — so the
        per-request token cadence is lumpy and the decode window spans
        host-silent stretches. The phase model needs no loop awareness:
        decode is still first_token -> finish minus the windows inside
        it, and the partition stays exact under bursts of any shape."""
        rec = FlightRecorder()
        rec.admit("r1", endpoint="generate")
        rec.note("r1", "schedule", engine="e0", strategy="least_loaded")
        time.sleep(0.01)
        rec.token("r1", 1)  # prefill's token: prefill complete
        # looped blocks reconcile at irregular intervals with
        # variable-size bursts (cap exit, pages exit, final eos)
        for burst, gap in ((8, 0.02), (3, 0.01), (5, 0.015)):
            time.sleep(gap)
            rec.token("r1", burst)
        # a handoff window lands INSIDE the looped-decode stretch
        rec.note("r1", "handoff_resume", target="e1", stall_s=0.012)
        phases = rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        assert tl["tokens"] == 1 + 8 + 3 + 5
        # exact partition: bursts and silent stretches don't tear it
        assert abs(sum(phases.values()) - tl["wall_s"]) < 1e-6
        # the stall window subtracted from DECODE, exactly
        assert abs(phases["handoff_stall"] - 0.012) < 1e-6
        assert phases["decode"] >= 0.045 - 0.012 - 1e-3
        # prefill is untouched by the decode-side window
        assert phases["prefill"] >= 0.01 - 1e-3
        assert phases["prefill"] <= tl["ttft_s"] + 1e-6

    def test_zero_token_error_request(self):
        rec = FlightRecorder()
        rec.admit("r1")
        rec.note("r1", "schedule", engine="e0")
        phases = rec.finish("r1", "error", code="worker_failure")
        assert phases["decode"] == phases["detok"] == 0.0
        tl = rec.timeline("r1")
        assert tl["status"] == "error" and tl["code"] == "worker_failure"

    def test_never_dispatched_request_is_all_queue_wait(self):
        """Review regression: a request that starves in the queue
        (queue_timeout / no_workers — no schedule note ever) must
        attribute its whole window to queue_wait, not to a phantom
        prefill — the misattribution would invert exactly the answer
        this feature exists to give."""
        rec = FlightRecorder()
        rec.admit("r1")
        time.sleep(0.02)
        phases = rec.finish("r1", "error", code="queue_timeout")
        tl = rec.timeline("r1")
        assert phases["prefill"] == 0.0
        assert abs(phases["queue_wait"] - tl["wall_s"]) < 1e-6

    def test_phase_metrics_exported(self):
        m = MetricsCollector()
        rec = FlightRecorder(metrics=m)
        _drive_request(rec)
        snap = m.snapshot().to_dict()
        assert snap["tracing"]["phase_requests"] == 1
        assert set(snap["tracing"]["phase_seconds"]) == set(PHASES)
        prom = m.prometheus_text().decode()
        assert 'request_phase_seconds_count{phase="decode"} 1.0' in prom


class TestBoundedMemory:
    def test_request_eviction_counted(self):
        rec = FlightRecorder(max_requests=4)
        for i in range(10):
            rec.admit(f"r{i}")
            rec.finish(f"r{i}", "ok")
        assert rec.stats()["tracked"] == 4
        assert rec.stats()["evicted"] == 6
        assert rec.timeline("r0") is None  # evicted
        assert rec.timeline("r9") is not None

    def test_event_cap_drops_counted_terminal_always_lands(self):
        rec = FlightRecorder(max_events=5)
        rec.admit("r1")
        for i in range(20):
            rec.note("r1", "schedule", engine=f"e{i}")
        rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        assert tl["events_dropped"] > 0
        assert tl["events"][-1]["name"] == "terminal"

    def test_decode_blocks_not_per_token(self):
        rec = FlightRecorder(block_tokens=16)
        rec.admit("r1")
        rec.note("r1", "schedule", engine="e0")
        for _ in range(40):
            rec.token("r1")
        rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        blocks = [e for e in tl["events"] if e["name"] == "decode_block"]
        # 40 tokens -> 2 full blocks + the terminal flush block
        assert len(blocks) == 3
        assert sum(b["attributes"]["tokens"] for b in blocks) == 40
        assert tl["tokens"] == 40


class TestContracts:
    def test_finish_is_first_wins(self):
        rec = FlightRecorder()
        rec.admit("r1")
        rec.token("r1")
        assert rec.finish("r1", "ok") is not None
        assert rec.finish("r1", "error", code="late") is None
        tl = rec.timeline("r1")
        assert tl["status"] == "ok" and "code" not in tl

    def test_tokens_after_terminal_ignored(self):
        rec = FlightRecorder()
        rec.admit("r1")
        rec.token("r1")
        rec.finish("r1", "ok")
        rec.token("r1")
        assert rec.timeline("r1")["tokens"] == 1

    def test_auto_created_timeline_for_direct_submits(self):
        # requests that bypass the handler (chaos harness, redispatch
        # onto a fresh replica) still get a usable timeline
        rec = FlightRecorder()
        rec.note("r1", "schedule", engine="e0")
        rec.token("r1")
        rec.finish("r1", "ok")
        tl = rec.timeline("r1")
        assert tl is not None and tl["tokens"] == 1

    def test_global_events_merge_into_overlapping_windows(self):
        rec = FlightRecorder()
        rec.admit("r1")
        rec.note_global("rerole", direction="to_prefill")
        rec.finish("r1", "ok")
        # a request admitted AFTER the rerole does not see it
        rec.admit("r2")
        rec.finish("r2", "ok")
        assert any(e["name"] == "rerole"
                   for e in rec.timeline("r1")["fleet_events"])
        assert "fleet_events" not in rec.timeline("r2")

    def test_recent_listing_newest_first(self):
        rec = FlightRecorder()
        for i in range(3):
            rec.admit(f"r{i}")
        listing = rec.recent(2)
        assert [r["request_id"] for r in listing] == ["r2", "r1"]


class TestSpineFastPath:
    """The disabled path: a runner without a recorder/tracer must not
    touch any ring or timeline on the per-token path."""

    def _runner(self, tracer=None, recorder=None):
        # never started: we drive _dispatch directly on this thread,
        # exactly as the engine thread would
        return EngineRunner("e0", engine_factory=None, tracer=tracer,
                            recorder=recorder)

    def _req(self, rid="r1"):
        class Sink:
            def __init__(self):
                self.tokens, self.dones, self.errors = [], 0, []

            def on_token(self, token_id, text, token_index, logprob=None):
                self.tokens.append(token_id)

            def on_done(self, reason, usage):
                self.dones += 1

            def on_error(self, message, code):
                self.errors.append(code)

        sink = Sink()
        req = ServerRequest(rid, [1, 2, 3], SamplingParams(max_tokens=4),
                            sink)
        return req, sink

    def test_disabled_no_ring_writes_no_timelines(self):
        tracer = Tracer()
        recorder = FlightRecorder()
        r = self._runner(tracer=None, recorder=None)
        req, sink = self._req()
        r._inflight[req.request_id] = req
        r._dispatch([StepOutput("r1", token_id=7, text="x")])
        r._dispatch([StepOutput("r1", finished=True,
                                finish_reason=FinishReason.STOP,
                                usage=Usage.of(3, 1))])
        assert sink.dones == 1 and sink.tokens == [7]
        assert tracer.recent() == []  # nothing ever exported
        assert recorder.stats()["tracked"] == 0  # nothing recorded

    def test_enabled_records_tokens_and_terminal(self):
        recorder = FlightRecorder()
        r = self._runner(recorder=recorder)
        req, sink = self._req()
        r._inflight[req.request_id] = req
        r._dispatch([StepOutput("r1", token_id=7, text="x")])
        r._dispatch([StepOutput("r1", finished=True,
                                finish_reason=FinishReason.STOP,
                                usage=Usage.of(3, 1))])
        tl = recorder.timeline("r1")
        assert tl["tokens"] == 1 and tl["status"] == "ok"
        assert any(e["name"] == "first_token" for e in tl["events"])

    def test_error_output_records_terminal(self):
        recorder = FlightRecorder()
        r = self._runner(recorder=recorder)
        req, sink = self._req()
        r._inflight[req.request_id] = req
        r._dispatch([StepOutput("r1", error="boom", finished=True)])
        tl = recorder.timeline("r1")
        assert tl["status"] == "error"
        assert tl["code"] == "inference_failed"
        assert sink.errors == ["inference_failed"]


class TestSloVerdicts:
    """SLO accounting at finish() (serving/teledigest.py SloSettings;
    docs/OBSERVABILITY.md "Performance telemetry")."""

    def _slo(self, **kw):
        from distributed_inference_server_tpu.serving.teledigest import (
            SloSettings,
        )

        return SloSettings(**kw)

    def test_verdict_stamped_and_counted(self):
        m = MetricsCollector()
        rec = FlightRecorder(metrics=m,
                             slo=self._slo(ttft_ms=10_000.0))
        _drive_request(rec, tokens=8)
        tl = rec.timeline("r1")
        assert tl["slo"]["verdict"] == "ok"
        counts, goodput = m.slo_counts()
        assert counts == {"default": {"ok": 1}}
        assert goodput == {"default": 8}
        text = m.prometheus_text().decode()
        assert ('slo_requests_total{tenant="default",verdict="ok"} 1.0'
                in text)
        assert 'slo_goodput_tokens_total{tenant="default"} 8.0' in text

    def test_violation_and_listing_filter(self):
        m = MetricsCollector()
        # 0ms TTFT objective: everything violates
        rec = FlightRecorder(metrics=m, slo=self._slo(ttft_ms=1e-9))
        _drive_request(rec, rid="bad", tokens=4)
        rec.admit("never-slo")  # live request: no verdict yet
        tl = rec.timeline("bad")
        assert tl["slo"]["verdict"] == "violated"
        assert tl["slo"]["ttft_violated"] is True
        # goodput counts only SLO-met requests
        _, goodput = m.slo_counts()
        assert goodput == {}
        # ?verdict= filter: only the violated timeline lists
        listed = rec.recent(50, verdict="violated")
        assert [e["request_id"] for e in listed] == ["bad"]
        assert listed[0]["verdict"] == "violated"
        assert rec.recent(50, verdict="ok") == []
        # unfiltered listing still carries the verdict field
        allr = {e["request_id"]: e for e in rec.recent(50)}
        assert allr["bad"]["verdict"] == "violated"
        assert "verdict" not in allr["never-slo"]

    def test_tenant_rides_admit_attrs(self):
        m = MetricsCollector()
        rec = FlightRecorder(
            metrics=m, slo=self._slo(tenant_ttft_ms={"gold": 1e-9}))
        rec.admit("g1", tenant="gold")
        rec.token("g1")
        rec.finish("g1", "ok")
        rec.admit("d1", tenant="silver")  # no applicable objective
        rec.token("d1")
        assert rec.finish("d1", "ok") is not None
        counts, _ = m.slo_counts()
        assert counts == {"gold": {"violated": 1}}
        assert rec.timeline("d1").get("slo") is None

    def test_error_request_with_slo_is_violation(self):
        m = MetricsCollector()
        rec = FlightRecorder(metrics=m, slo=self._slo(ttft_ms=60_000.0))
        rec.admit("e1")
        rec.note("e1", "schedule", engine="e0")
        rec.token("e1")
        rec.finish("e1", "error", code="engine_crashed")
        assert rec.timeline("e1")["slo"]["verdict"] == "violated"

    def test_no_slo_config_means_no_verdicts(self):
        m = MetricsCollector()
        rec = FlightRecorder(metrics=m)
        _drive_request(rec, tokens=4)
        assert "slo" not in rec.timeline("r1")
        counts, _ = m.slo_counts()
        assert counts == {}

    def test_tbt_digest_fed_at_finish(self):
        m = MetricsCollector()
        rec = FlightRecorder(metrics=m)
        _drive_request(rec, tokens=16)
        wires = m.perf.wire_digests()
        assert wires["tbt_ms"]["epochs"]
        assert wires["queue_wait_ms"]["epochs"]
