"""Model hot-swap (Req 13, requirements.md:178-182 [spec]; Properties
28-29): atomic switch for new requests, in-flight completion on the old
model, old model retained on load failure, fresh KV cache after swap."""

from __future__ import annotations

import asyncio
import threading
import time

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=16)


def _factory(seed: int, zero_final_norm: bool = False):
    """zero_final_norm makes a *behaviorally distinguishable* model: all
    logits collapse to 0 so greedy always emits token id 0, whereas
    random-weight TINY models echo the last prompt byte."""

    def make() -> LLMEngine:
        params = llama.init_params(
            jax.random.PRNGKey(seed), TINY, dtype=jnp.float32
        )
        if zero_final_norm:
            params["final_norm"] = params["final_norm"] * 0.0
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64), paged=_PAGED),
            dtype=jnp.float32,
        )

    return make


def _resolver(name: str):
    if name == "model-a":
        return _factory(0)
    if name == "model-b":
        return _factory(0, zero_final_norm=True)
    if name == "model-broken":
        def broken():
            raise RuntimeError("weights corrupted")

        return broken
    raise KeyError(f"unknown model {name!r}")


@pytest.fixture()
def server():
    srv = InferenceServer(
        _factory(0), ByteTokenizer(), model_name="model-a",
        num_engines=1, auto_restart=False, model_resolver=_resolver,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


async def _gen(client, prompt="swap test", max_tokens=8):
    resp = await client.post(
        "/generate",
        json={"prompt": prompt, "max_tokens": max_tokens,
              "temperature": 0.0},
    )
    body = await resp.json()
    return resp.status, body


def test_swap_switches_new_requests(server):
    """Property 28: requests submitted after the swap completes are
    served by the new model (design.md:848-852 [spec])."""
    async def go(client):
        _, before = await _gen(client)
        resp = await client.post("/admin/model-swap",
                                 json={"model": "model-b"})
        assert resp.status == 200
        assert (await resp.json())["model"] == "model-b"
        status, after = await _gen(client)
        assert status == 200
        return before, after

    before, after = _run(server, go)
    # different weights -> different greedy continuation; name updated
    assert after["model"] == "model-b"
    assert after["choices"][0]["text"] != before["choices"][0]["text"]


def test_swap_failure_keeps_old_model(server):
    """Property 29: a failed swap leaves the server serving the original
    model without interruption (design.md:854-858 [spec])."""
    async def go(client):
        _, before = await _gen(client)
        resp = await client.post("/admin/model-swap",
                                 json={"model": "model-broken"})
        assert resp.status == 500
        err = (await resp.json())["error"]
        assert "weights corrupted" in err["message"]
        status, after = await _gen(client)
        assert status == 200
        return before, after

    before, after = _run(server, go)
    assert after["model"] == "model-a"  # Req 13.4: old model retained
    assert after["choices"][0]["text"] == before["choices"][0]["text"]


def test_swap_unknown_model_rejected(server):
    async def go(client):
        resp = await client.post("/admin/model-swap",
                                 json={"model": "nope"})
        assert resp.status == 500
        resp2 = await client.post("/admin/model-swap", json={})
        assert resp2.status == 400

    _run(server, go)


def test_inflight_finishes_on_old_model(server):
    """Property 28: a request in flight at swap time completes on the old
    model — its tokens equal the old model's greedy continuation
    (design.md:848-852: pre-swap requests are served by the original)."""
    async def go(client):
        _, want = await _gen(client, prompt="long one", max_tokens=48)

        # restart server state: swap back to model-a is not needed (we
        # never swapped); now race a long generation against a swap
        loop = asyncio.get_running_loop()
        gen_task = loop.create_task(
            _gen(client, prompt="long one", max_tokens=48)
        )
        await asyncio.sleep(0.05)  # let it enter the engine
        swap_resp = await client.post("/admin/model-swap",
                                      json={"model": "model-b"})
        assert swap_resp.status == 200
        status, got = await gen_task
        assert status == 200
        return want, got

    want, got = _run(server, go)
    assert got["choices"][0]["text"] == want["choices"][0]["text"]
    assert got["choices"][0]["finish_reason"] == "length"


def test_runner_swap_drains_old_engine_directly():
    """Runner-level: old engine keeps stepping until drained, then is
    dropped; new engine serves afterwards with an empty cache."""
    from distributed_inference_server_tpu.engine.engine import SamplingParams
    from distributed_inference_server_tpu.serving.runner import (
        EngineRunner,
        ServerRequest,
    )

    tokens_a: list = []
    done = threading.Event()

    class Sink:
        def __init__(self, out, ev):
            self.out, self.ev = out, ev

        def on_token(self, token_id, text, token_index, logprob=None):
            self.out.append(token_id)

        def on_done(self, finish_reason, usage):
            self.ev.set()

        def on_error(self, message, code):
            self.ev.set()
            raise AssertionError(f"unexpected error: {message}")

    runner = EngineRunner("e0", _factory(0))
    runner.start()
    try:
        tok = ByteTokenizer()
        runner.submit([ServerRequest(
            "r1", tok.encode("drain me please"),
            SamplingParams(max_tokens=32, temperature=0.0), Sink(tokens_a, done),
        )])
        time.sleep(0.1)  # request is mid-decode
        swapped = threading.Event()
        runner.swap_model(_factory(9), lambda ok, err: swapped.set())
        assert swapped.wait(120), "swap did not complete"
        assert done.wait(120), "in-flight request did not finish"
        assert len(tokens_a) >= 31  # finished on the old model
        # old engine eventually drained away
        deadline = time.monotonic() + 10
        while runner._draining and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not runner._draining
        # new engine serves (fresh cache)
        assert runner._engine.cache_stats().hits == 0
        tokens_b: list = []
        done_b = threading.Event()
        runner.submit([ServerRequest(
            "r2", tok.encode("hello"),
            SamplingParams(max_tokens=4, temperature=0.0),
            Sink(tokens_b, done_b),
        )])
        assert done_b.wait(120)
        assert len(tokens_b) >= 3
    finally:
        runner.shutdown()
