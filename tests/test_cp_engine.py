"""Engine-level context-parallel prefill tests (VERDICT r1 item 6): long
prompts reach ring attention (ops/ring_attention.py) THROUGH the serving
engine — prefill over the ``seq`` mesh axis lands in the page pool and
decode proceeds from pages — not as a standalone demo.

The reference hard-capped context at 8192 tokens with no sequence scaling
(``validator.rs:20``; SURVEY.md §5 "long-context: entirely absent").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.parallel import MeshSpec, make_mesh

PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)
LONG_PROMPT = "ring attention spans chips for long prompts!"  # 44 tokens


def _generate(engine, prompt: str, rid: str = "r", max_tokens: int = 8):
    tok = ByteTokenizer()
    engine.add_request(
        rid, tok.encode(prompt),
        SamplingParams(max_tokens=max_tokens, temperature=0.0),
    )
    text = []
    while engine.has_work():
        for out in engine.step():
            assert out.error is None, out.error
            text.append(out.text)
    return "".join(text)


def _engine(mesh=None, cfg=TINY, **ecfg_kw):
    params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    ecfg = EngineConfig(
        max_batch=2, prefill_buckets=(16,), paged=PAGED, **ecfg_kw
    )
    return LLMEngine(params, cfg, ByteTokenizer(), ecfg,
                     dtype=jnp.float32, mesh=mesh)


class TestCPEngine:
    def test_long_prompt_via_ring_prefill_matches_unsharded(self):
        # prompt (44 tokens) > largest bucket (16) -> CP path on a seq=4
        # mesh; greedy output must match the plain single-device engine
        plain = _generate(_engine(), LONG_PROMPT)
        cp = _generate(
            _engine(mesh=make_mesh(MeshSpec(seq=4))), LONG_PROMPT
        )
        assert plain == cp
        assert len(cp) > 0

    def test_cp_composes_with_tp(self):
        plain = _generate(_engine(), LONG_PROMPT)
        both = _generate(
            _engine(mesh=make_mesh(MeshSpec(seq=2, tensor=2))), LONG_PROMPT
        )
        assert plain == both

    def test_short_prompt_on_cp_mesh_uses_bucket_path(self):
        # short prompts stay on the chunked-bucket path (no CP program
        # compiled for them)
        eng = _engine(mesh=make_mesh(MeshSpec(seq=4)))
        out = _generate(eng, "short", max_tokens=4)
        assert len(out) > 0
        assert not eng._cp_fns  # CP never invoked

    def test_explicit_cp_min_tokens(self):
        eng = _engine(mesh=make_mesh(MeshSpec(seq=4)), cp_min_tokens=8)
        out = _generate(eng, "0123456789", max_tokens=4)  # 10 >= 8
        assert len(out) > 0
        assert eng._cp_fns  # CP path compiled and used

    def test_mixed_long_and_short_requests(self):
        tok = ByteTokenizer()
        mesh = make_mesh(MeshSpec(seq=4))
        eng = _engine(mesh=mesh)
        ref = _engine()
        outs: dict = {}
        for e, store in ((ref, "ref"), (eng, "cp")):
            e.add_request("long", tok.encode(LONG_PROMPT),
                          SamplingParams(max_tokens=6, temperature=0.0))
            e.add_request("short", tok.encode("hi"),
                          SamplingParams(max_tokens=6, temperature=0.0))
            got = {"long": [], "short": []}
            while e.has_work():
                for out in e.step():
                    assert out.error is None, out.error
                    got[out.request_id].append(out.text)
            outs[store] = {k: "".join(v) for k, v in got.items()}
        assert outs["ref"] == outs["cp"]

    def test_decode_continues_from_cp_pages(self):
        # the pool KV written by ring prefill is what decode reads: check
        # more than one decode block's worth of tokens stream out
        eng = _engine(mesh=make_mesh(MeshSpec(seq=4)), decode_block_size=4)
        out = _generate(eng, LONG_PROMPT, max_tokens=12)
        assert len(out) > 0

    def test_cp_bucket_shapes(self):
        eng = _engine(mesh=make_mesh(MeshSpec(seq=4)))
        assert eng._cp_bucket(17) == 32
        assert eng._cp_bucket(32) == 32
        assert eng._cp_bucket(33) == 64
        assert eng._cp_bucket(5) == 16

    def test_seq_with_stage_uses_ring(self):
        """CP x PP (VERDICT r4 #5): a seq x stage mesh runs RING prefill
        through the unified {seq, stage} shard_map
        (parallel/cp.py:cp_pp_prefill) — the designed data path, not the
        chunked fallback — and matches the plain engine bit-for-bit."""
        eng = _engine(mesh=make_mesh(MeshSpec(seq=2, stage=2)),
                      pp_microbatches=2)
        assert eng._cp_threshold() is not None  # ring path engaged
        plain = _generate(_engine(), LONG_PROMPT)
        got = _generate(eng, LONG_PROMPT)
        assert eng._cp_fns, "ring program was never compiled"
        assert got == plain

    def test_ulysses_with_stage_takes_chunked_fallback(self):
        """Ulysses is seq-only (all-to-all head scatter does not compose
        with the stage tick loop): ulysses + stage keeps the PP-capable
        chunked-prefill fallback, matching the plain engine."""
        eng = _engine(mesh=make_mesh(MeshSpec(seq=2, stage=2)),
                      pp_microbatches=2, sp_impl="ulysses")
        assert eng._cp_threshold() is None  # fallback engaged
        plain = _generate(_engine(), LONG_PROMPT)
        got = _generate(eng, LONG_PROMPT)
        assert not eng._cp_fns  # ring never compiled
        assert got == plain


class TestGemma2CP:
    """Gemma-2-class models under context parallelism (VERDICT r2 missing
    #5): the per-layer alternating local/global windows ride the layer
    scan into the CP attends as traced scalars, and score soft-capping
    runs inside the blockwise softmax — long Gemma-2 prompts take ring
    prefill instead of being excluded."""

    def test_gemma2_long_prompt_ring_matches_unsharded(self):
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        plain = _generate(_engine(cfg=TINY_GEMMA2), LONG_PROMPT)
        cp_eng = _engine(mesh=make_mesh(MeshSpec(seq=4)), cfg=TINY_GEMMA2)
        cp = _generate(cp_eng, LONG_PROMPT)
        assert cp_eng._cp_fns, "CP path was never taken for Gemma-2"
        assert plain == cp
        assert len(cp) > 0

    def test_gemma2_ulysses_matches_unsharded(self):
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        plain = _generate(_engine(cfg=TINY_GEMMA2), LONG_PROMPT)
        cp_eng = _engine(mesh=make_mesh(MeshSpec(seq=2)), cfg=TINY_GEMMA2,
                         sp_impl="ulysses")
        cp = _generate(cp_eng, LONG_PROMPT)
        assert cp_eng._cp_fns, "CP path was never taken"
        assert plain == cp

    def test_mistral_uniform_window_ring_matches_unsharded(self):
        """Uniform sliding window (Mistral-class) through the same traced
        path."""
        from distributed_inference_server_tpu.models.configs import (
            TINY_SWA,
        )

        plain = _generate(_engine(cfg=TINY_SWA), LONG_PROMPT)
        cp = _generate(
            _engine(mesh=make_mesh(MeshSpec(seq=4)), cfg=TINY_SWA),
            LONG_PROMPT,
        )
        assert plain == cp


class TestCPWithDraft:
    """Speculative decoding composed with ring-CP prefill: the draft's
    pool prefills through the same cp program (same slots), so
    speculative rounds can attend the full long prompt."""

    def _spec_engine(self, params, draft, mesh=None, **kw):
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(
                max_batch=2, prefill_buckets=(16,), paged=PAGED,
                decode_block_size=3, **kw,
            ),
            dtype=jnp.float32, mesh=mesh,
            draft_params=draft, draft_cfg=TINY,
        )

    def test_long_prompt_spec_on_seq_mesh_matches_plain(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        draft = llama.init_params(jax.random.PRNGKey(7), TINY, jnp.float32)
        plain = _generate(self._spec_engine(params, draft), LONG_PROMPT)
        cp_eng = self._spec_engine(
            params, draft, mesh=make_mesh(MeshSpec(seq=2))
        )
        got = _generate(cp_eng, LONG_PROMPT)
        assert cp_eng._cp_fns, "CP path was never taken"
        assert got == plain

    @pytest.mark.skip(
        reason="seed-known failure on this jax/jaxlib (0.4.37): the "
        "speculative block under a seq x stage mesh hits XLA "
        "'PartitionId instruction is not supported for SPMD "
        "partitioning' on the CPU backend — triaged in ISSUE 1 "
        "(disaggregated serving PR); needs a toolchain bump, not a "
        "code fix"
    )
    def test_long_prompt_spec_on_seq_stage_mesh(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        draft = llama.init_params(jax.random.PRNGKey(7), TINY, jnp.float32)
        plain = _generate(self._spec_engine(params, draft), LONG_PROMPT)
        eng = self._spec_engine(
            params, draft, mesh=make_mesh(MeshSpec(seq=2, stage=2)),
            pp_microbatches=2,
        )
        got = _generate(eng, LONG_PROMPT)
        assert eng._cp_fns, "ring path was never taken"
        assert got == plain
