"""Latent-KV compression (ISSUE 20; TPLA stage (a), docs/CACHING.md
"Latent KV pages"): the rank-r latent page codec behind the
``"latent"``/``"latent_int8"`` wire/tier encodings.

Covers, bottom-up:

- codec unit behavior: calibration shapes/orthonormality, deterministic
  recalibration, bounded round-trip reconstruction error across
  ranks × dtypes × page counts, byte-shrink vs int8, the QuantPool
  pass-through DECISION (native codes ship unchanged whatever the wire
  setting), and the rejection matrix (missing codec, rank mismatch);
- the encoded bytes-per-page cost-model fix (ISSUE 20 satellite): the
  `FetchCosts.wire_frac` regression proving int8 alone flips a
  ``plan_route`` fetch decision that raw-page pricing would route warm;
- token-identity e2e on all four KV paths — disagg handoff, host-tier
  reload, peer prefix fetch, and the mesh wire (KvChunk protowire
  frames) — each with zero-leak ``audit_pages()`` teardowns;
- the ``kv.latent_decode`` fault point: a latent decode failure aborts
  the import like any validation failure, exactly once, zero page leak
  (DL011/DL018 coverage).

Deterministic seeded random throughout (no hypothesis in the image)."""

from __future__ import annotations

import dataclasses
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.core.errors import (
    CacheDeserializationError,
)
from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    _KIND_LATENT,
    _KIND_QPOOL,
    KvImportSession,
    LatentCodec,
    PageAllocator,
    PagedCacheConfig,
    PagedKVState,
    WIRE_QUANTS,
    chain_hashes,
    default_latent_rank,
    deserialize_kv,
    encoded_page_fraction,
    payload_kind,
    serialize_kv,
    serialize_kv_chunks,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving import faults, protowire
from distributed_inference_server_tpu.serving.faults import parse_spec
from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.scheduler import (
    FetchCosts,
    plan_route,
)

TOK = ByteTokenizer()
PS = 4
D = TINY.head_dim  # 16 on the tiny fixture


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def make_engine(tiny_params, latent_rank=4, num_pages=64, **over):
    return LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=4,
            prefill_buckets=(8, 64),
            paged=PagedCacheConfig(
                num_pages=num_pages, page_size=PS, max_pages_per_seq=16
            ),
            latent_rank=latent_rank,
            native_allocator=False,
            **over,
        ),
        dtype=jnp.float32,
    )


def run_one(engine, rid, prompt, max_tokens=6):
    engine.add_request(rid, prompt, SamplingParams(max_tokens=max_tokens,
                                                   temperature=0.0))
    tokens = []
    for _ in range(500):
        if not engine.has_work():
            break
        for out in engine.step():
            assert out.error is None, out.error
            if out.token_id is not None:
                tokens.append(out.token_id)
    assert not engine.has_work()
    return tokens


PREFIX = list(range(40, 60))  # 5 full pages at PS=4
PROMPT = PREFIX + [7, 8]
HASHES = chain_hashes(PROMPT, PS, max_pages=(len(PROMPT) - 1) // PS)


def _latent_state(rng, cfg, rank, dtype=jnp.float32):
    """A float pool whose content lies in a rank-``rank`` subspace per
    (layer, kv-head) — what a trained model's K/V activations look like
    to the codec — plus the codec calibrated on that content."""
    state = PagedKVState.create(TINY, cfg, dtype=dtype)
    L, S, KV, d = state.k.shape
    basis_k = rng.standard_normal((L, KV, d, rank))
    basis_v = rng.standard_normal((L, KV, d, rank))
    k = np.einsum("lskr,lkdr->lskd", rng.standard_normal((L, S, KV, rank)),
                  basis_k)
    v = np.einsum("lskr,lkdr->lskd", rng.standard_normal((L, S, KV, rank)),
                  basis_v)
    state.k = jnp.asarray(k, dtype=dtype)
    state.v = jnp.asarray(v, dtype=dtype)
    codec = LatentCodec.calibrate(k, v, rank)
    return state, codec


def _with_totals(chunks):
    return [dataclasses.replace(c, total=len(chunks)) for c in chunks]


# ---------------------------------------------------------------------------
# Codec unit behavior
# ---------------------------------------------------------------------------


class TestCodecUnit:
    def test_calibrate_shapes_and_orthonormal(self):
        rng = np.random.default_rng(1)
        L, N, KV, rank = TINY.num_layers, 24, TINY.num_kv_heads, 4
        k = rng.standard_normal((L, N, KV, D))
        v = rng.standard_normal((L, N, KV, D))
        codec = LatentCodec.calibrate(k, v, rank)
        assert codec.rank == rank and codec.head_dim == D
        assert codec.k_proj.shape == (L, KV, D, rank)
        for proj in (codec.k_proj, codec.v_proj):
            gram = np.einsum("lkdr,lkds->lkrs", proj, proj)
            np.testing.assert_allclose(
                gram, np.broadcast_to(np.eye(rank), gram.shape), atol=1e-6)

    def test_calibration_is_deterministic(self):
        rng = np.random.default_rng(2)
        k = rng.standard_normal((2, 16, 2, D))
        v = rng.standard_normal((2, 16, 2, D))
        a = LatentCodec.calibrate(k, v, 4)
        b = LatentCodec.calibrate(k.copy(), v.copy(), 4)
        assert np.array_equal(a.k_proj, b.k_proj)
        assert np.array_equal(a.v_proj, b.v_proj)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("rank", [2, 4, 8])
    @pytest.mark.parametrize("n_pages", [1, 3, 5])
    def test_roundtrip_error_bounded(self, dtype, rank, n_pages):
        """Content in the codec's span reconstructs within the code
        dtype's precision across ranks × pool dtypes × page counts (the
        tolerance harness of the acceptance criteria)."""
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(rank), cfg,
                                     rank, dtype)
        pages = list(range(2, 2 + n_pages))
        blob = serialize_kv(state, pages, PS, n_pages * PS,
                            wire_quant="latent", codec=codec)
        fresh = PagedKVState.create(TINY, cfg, dtype=dtype)
        restored, _ = deserialize_kv(fresh, blob, pages, PS, codec=codec)
        slots = np.concatenate(
            [np.arange(p * PS, (p + 1) * PS) for p in pages])
        orig = np.asarray(state.k[:, slots], dtype=np.float32)
        got = np.asarray(restored.k[:, slots], dtype=np.float32)
        # f16 latent codes: relative error ~1e-3; bf16 pools are the
        # looser of pool-write and code precision (~1%)
        tol = 0.02 if dtype == jnp.bfloat16 else 2e-3
        scale = np.abs(orig).max() + 1e-6
        assert np.abs(got - orig).max() <= tol * scale

    def test_latent_int8_roundtrip_bounded(self):
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(7), cfg, 4)
        blob = serialize_kv(state, [1, 2], PS, 8,
                            wire_quant="latent_int8", codec=codec)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        restored, _ = deserialize_kv(fresh, blob, [1, 2], PS, codec=codec)
        slots = np.arange(PS, 3 * PS)
        orig = np.asarray(state.k[:, slots])
        got = np.asarray(restored.k[:, slots])
        # int8 over the codes: ~1/127 relative per latent coordinate
        scale = np.abs(orig).max() + 1e-6
        assert np.abs(got - orig).max() <= 0.05 * scale

    def test_latent_bytes_beat_int8_by_2x(self):
        """The acceptance byte math at the bench-default rank: latent
        moves ≥2× fewer payload bytes than int8 on the same pages."""
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(3), cfg,
                                     default_latent_rank(D))
        pages = [0, 1, 2, 3]
        int8 = serialize_kv(state, pages, PS, 16, wire_quant="int8")
        latent = serialize_kv(state, pages, PS, 16, wire_quant="latent",
                              codec=codec)
        latent8 = serialize_kv(state, pages, PS, 16,
                               wire_quant="latent_int8", codec=codec)
        assert len(int8) >= 2 * len(latent)
        # at rank r the int8-over-codes form costs r+4 bytes per vector
        # vs 2r for f16 codes: a tie at r=4, a strict win past it
        assert len(latent8) <= len(latent)
        state8, codec8 = _latent_state(np.random.default_rng(4), cfg, 8)
        wide = serialize_kv(state8, pages, PS, 16, wire_quant="latent",
                            codec=codec8)
        wide8 = serialize_kv(state8, pages, PS, 16,
                             wire_quant="latent_int8", codec=codec8)
        assert len(wide8) < len(wide)

    def test_encoded_page_fraction_math(self):
        # TINY f32: D=16, itemsize=4 → raw vector 64B
        assert encoded_page_fraction("none", 4, D) == 1.0
        assert encoded_page_fraction("int8", 4, D) == pytest.approx(0.3125)
        assert encoded_page_fraction("latent", 4, D, 4) == pytest.approx(
            0.125)
        assert encoded_page_fraction("latent_int8", 4, D,
                                     4) == pytest.approx(0.125)
        r = default_latent_rank(D)
        assert encoded_page_fraction("latent", 4, D, r) <= \
            encoded_page_fraction("int8", 4, D) / 2

    def test_default_latent_rank(self):
        assert default_latent_rank(16) == 4
        assert default_latent_rank(128) == 32
        assert default_latent_rank(4) == 2  # floor

    def test_quantpool_pass_through_decision(self):
        """DECISION: natively quantized pools ship their exact codes
        whatever the wire setting — latent never re-encodes a QuantPool
        (re-projecting int8 codes would compound two lossy steps)."""
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state = PagedKVState.create(TINY, cfg, dtype=jnp.float32,
                                    kv_quant="int8")
        assert payload_kind(state.k, "latent") == _KIND_QPOOL
        assert payload_kind(state.k, "latent_int8") == _KIND_QPOOL
        blob = serialize_kv(state, [0, 1], PS, 8, wire_quant="latent")
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32,
                                    kv_quant="int8")
        restored, _ = deserialize_kv(fresh, blob, [0, 1], PS)
        slots = np.arange(2 * PS)
        np.testing.assert_array_equal(
            np.asarray(restored.k.data[:, slots]),
            np.asarray(state.k.data[:, slots]))
        np.testing.assert_array_equal(
            np.asarray(restored.k.scale[:, slots]),
            np.asarray(state.k.scale[:, slots]))

    def test_missing_codec_rejected(self):
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(5), cfg, 4)
        with pytest.raises(ValueError, match="codec"):
            serialize_kv(state, [0], PS, 4, wire_quant="latent")
        blob = serialize_kv(state, [0], PS, 4, wire_quant="latent",
                            codec=codec)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        with pytest.raises(CacheDeserializationError, match="LatentCodec"):
            deserialize_kv(fresh, blob, [0], PS)

    def test_rank_mismatch_rejected(self):
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        rng = np.random.default_rng(6)
        state, codec4 = _latent_state(rng, cfg, 4)
        blob = serialize_kv(state, [0], PS, 4, wire_quant="latent",
                            codec=codec4)
        k = rng.standard_normal((TINY.num_layers, 16, TINY.num_kv_heads, D))
        codec8 = LatentCodec.calibrate(k, k, 8)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        with pytest.raises(CacheDeserializationError, match="rank"):
            deserialize_kv(fresh, blob, [0], PS, codec=codec8)

    def test_latent_into_quantpool_rejected(self):
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(8), cfg, 4)
        blob = serialize_kv(state, [0], PS, 4, wire_quant="latent",
                            codec=codec)
        qpool = PagedKVState.create(TINY, cfg, dtype=jnp.float32,
                                    kv_quant="int8")
        with pytest.raises(CacheDeserializationError):
            deserialize_kv(qpool, blob, [0], PS, codec=codec)

    def test_wire_quants_extended(self):
        assert WIRE_QUANTS == ("none", "int8", "latent", "latent_int8")


# ---------------------------------------------------------------------------
# Cost model: encoded bytes-per-page (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


def _status(eid, active=0, digest=None):
    return EngineStatus(
        engine_id=eid, healthy=True, active_requests=active,
        waiting_requests=0, total_processed=0, memory_used_pages=0,
        memory_total_pages=100, prefix_digest=digest, page_size=PS,
        digest_depth=8,
    )


RPROMPT = list(range(33))  # 8 full pages + 1
RHASHES = chain_hashes(RPROMPT, PS, max_pages=8)


class TestWireFracCostModel:
    def test_int8_alone_flips_a_fetch_decision(self):
        """REGRESSION (the pre-existing inaccuracy): the wire term used
        to charge raw pages whatever the encoding, though an int8 wire
        moves 3.2× fewer bytes (BENCH_NOTES_r09.md). Scaling by the
        encoded fraction flips this borderline decision from warm to
        fetch with nothing else changed."""
        statuses = [
            _status("warm", active=1, digest=frozenset(RHASHES)),
            _status("cold"),
        ]
        # gain 8 pages, load differential 1 request = 4.0 pages of
        # queueing: raw wire 1.5*8 = 12 > 4 → stay warm; int8 wire
        # 1.5*0.3125*8 = 3.75 < 4 → fetch pays
        base = dict(min_pages=2, page_cost=1.5, load_cost_pages=4.0)
        raw = plan_route(statuses, RHASHES,
                         costs=FetchCosts(**base, wire_frac=1.0))
        assert raw.decision == "warm"
        int8_frac = encoded_page_fraction("int8", 4, D)
        quant = plan_route(statuses, RHASHES,
                           costs=FetchCosts(**base, wire_frac=int8_frac))
        assert quant.decision == "fetch"
        assert (quant.engine_id, quant.peer_id) == ("cold", "warm")

    def test_latent_wire_cheaper_still(self):
        """At the default latent rank the same decision flips at an
        even higher page_cost — the latent wire is the cheapest."""
        statuses = [
            _status("warm", active=1, digest=frozenset(RHASHES)),
            _status("cold"),
        ]
        frac = encoded_page_fraction("latent", 4, D, default_latent_rank(D))
        base = dict(min_pages=2, page_cost=3.5, load_cost_pages=4.0)
        assert plan_route(
            statuses, RHASHES,
            costs=FetchCosts(**base, wire_frac=encoded_page_fraction(
                "int8", 4, D))).decision == "warm"
        assert plan_route(
            statuses, RHASHES,
            costs=FetchCosts(**base, wire_frac=frac)).decision == "fetch"


# ---------------------------------------------------------------------------
# Engine e2e: token identity on all four KV paths
# ---------------------------------------------------------------------------


class TestEngineE2E:
    @pytest.mark.parametrize("wire_quant", ["latent", "latent_int8"])
    def test_handoff_token_identity_and_bytes(self, tiny_params,
                                              wire_quant):
        """Path 1 (disagg handoff): a latent-wire migrated sequence
        decodes token-identically to the never-migrated reference, and
        the export moves ≥2× fewer bytes than the int8 wire."""
        sp = SamplingParams(max_tokens=8, temperature=0.0)
        ref = make_engine(tiny_params)
        want = run_one(ref, "ref", PROMPT, max_tokens=8)

        src = make_engine(tiny_params)
        src.add_request("r", PROMPT, sp, prefill_only=True)
        got = []
        while src.has_work() and not src.handoff_ready_ids():
            for o in src.step():
                assert o.error is None, o.error
                if o.token_id is not None:
                    got.append(o.token_id)
        exp = src.export_handoff("r", wire_quant=wire_quant)
        assert exp is not None and exp.wire_quant == wire_quant

        # byte comparison against an identical int8 export (the codec
        # calibration is deterministic, so src2 is bit-equivalent)
        src2 = make_engine(tiny_params)
        src2.add_request("r", PROMPT, sp, prefill_only=True)
        while src2.has_work() and not src2.handoff_ready_ids():
            src2.step()
        exp8 = src2.export_handoff("r", wire_quant="int8")
        assert len(exp8.kv) >= 2 * len(exp.kv)

        dst = make_engine(tiny_params)
        dst.import_sequence(exp)
        while dst.has_work():
            for o in dst.step():
                assert o.error is None, o.error
                if o.token_id is not None:
                    got.append(o.token_id)
        assert got == want
        assert src.audit_pages() == [] and dst.audit_pages() == []
        # byte accounting reached the counters and the stats block
        label = wire_quant
        assert src.payload_byte_counters()[label] == len(exp.kv)
        stats = src.latent_stats()
        assert stats["rank"] == 4 and stats["saved_bytes"] > 0

    def test_host_tier_reload_token_identity(self, tiny_params):
        """Path 2 (host-tier reload): a prefix demoted to the host tier
        in latent encoding re-seats on device token-identically."""
        cold = make_engine(tiny_params)
        want = run_one(cold, "cold", PROMPT)

        warm = make_engine(tiny_params, num_pages=10,
                           host_tier_bytes=1 << 22,
                           host_tier_quant="latent")
        run_one(warm, "warm", PROMPT)
        rng = np.random.default_rng(3)
        for i in range(8):  # cycle the 10-page pool: the prefix demotes
            run_one(warm, f"churn{i}",
                    rng.integers(100, 200, size=7).tolist(), max_tokens=2)
        warm.host_tier.flush()
        assert warm.host_tier_stats()["pages"] > 0
        assert run_one(warm, "probe", PROMPT) == want
        assert warm.audit_pages() == []
        # stored latent pages are smaller, so the byte budget holds
        # more of them than raw would
        assert warm.payload_byte_counters()["latent"] > 0

    def test_peer_fetch_token_identity(self, tiny_params):
        """Path 3 (peer prefix fetch): a latent-wire fetched prefix
        seats and decodes token-identically on the cold replica."""
        cold = make_engine(tiny_params)
        want = run_one(cold, "cold", PROMPT)

        warm = make_engine(tiny_params)
        run_one(warm, "warm", PROMPT)
        depth, chunks = warm.export_prefix_chunks(
            HASHES, chunk_pages=2, wire_quant="latent")
        assert depth == len(HASHES)
        d8, chunks8 = warm.export_prefix_chunks(
            HASHES, chunk_pages=2, wire_quant="int8")
        assert sum(len(c.payload) for c in chunks8) >= \
            2 * sum(len(c.payload) for c in chunks)

        target = make_engine(tiny_params)
        seated = target.import_prefix(PROMPT[: depth * PS], chunks)
        assert seated == depth
        assert run_one(target, "probe", PROMPT) == want
        assert target.audit_pages() == [] and warm.audit_pages() == []

    def test_mesh_fetch_token_identity(self, tiny_params):
        """Path 4 (fleet/mesh wire): latent chunks are self-describing
        through the protowire KvChunk framing both data channels use
        (serving/fleet_kv.py, fleet_mesh.py) — no schema change, DL005
        untouched — and seat token-identically after the wire."""
        cold = make_engine(tiny_params)
        want = run_one(cold, "cold", PROMPT)
        warm = make_engine(tiny_params)
        run_one(warm, "warm", PROMPT)
        depth, chunks = warm.export_prefix_chunks(
            HASHES, chunk_pages=2, wire_quant="latent")

        from distributed_inference_server_tpu.engine.kv_cache import KvChunk
        wired = []
        for c in chunks:
            d = protowire.decode("KvChunk", protowire.encode("KvChunk", {
                "handoff_id": "mesh", "index": c.index, "total": c.total,
                "page_start": c.page_start, "page_count": c.page_count,
                "crc32": c.crc32, "payload": c.payload,
            }))
            wired.append(KvChunk(index=d["index"], total=d["total"],
                                 page_start=d["page_start"],
                                 page_count=d["page_count"],
                                 payload=d["payload"], crc32=d["crc32"]))
        random.Random(11).shuffle(wired)  # transports may reorder
        target = make_engine(tiny_params)
        target.import_prefix(PROMPT[: depth * PS], wired)
        assert run_one(target, "probe", PROMPT) == want
        assert target.audit_pages() == []

    def test_quantpool_engine_gates_codec_off(self, tiny_params):
        """A natively quantized engine never calibrates a codec (like
        the host tier, the latent encode targets float pools only) and
        its exports pass native codes through."""
        eng = make_engine(tiny_params, kv_quant="int8")
        assert eng.latent_codec is None and eng.latent_stats() is None
        want = run_one(eng, "a", PROMPT)
        src = make_engine(tiny_params, kv_quant="int8")
        src.add_request("r", PROMPT,
                        SamplingParams(max_tokens=6, temperature=0.0),
                        prefill_only=True)
        while src.has_work() and not src.handoff_ready_ids():
            src.step()
        exp = src.export_handoff("r", wire_quant="latent")
        dst = make_engine(tiny_params, kv_quant="int8")
        got = []
        dst.import_sequence(exp)
        while dst.has_work():
            for o in dst.step():
                if o.token_id is not None:
                    got.append(o.token_id)
        assert got == want[-len(got):]
        assert src.audit_pages() == [] and dst.audit_pages() == []

    def test_no_codec_degrades_to_raw_wire(self, tiny_params):
        """latent requested on an engine with latent_rank=0: the export
        degrades to the raw wire (one warning) instead of failing —
        mixed fleets where only some replicas carry a codec keep
        moving KV."""
        src = make_engine(tiny_params, latent_rank=0)
        assert src.latent_codec is None
        src.add_request("r", PROMPT,
                        SamplingParams(max_tokens=6, temperature=0.0),
                        prefill_only=True)
        while src.has_work() and not src.handoff_ready_ids():
            src.step()
        exp = src.export_handoff("r", wire_quant="latent")
        assert exp is not None and exp.wire_quant == "none"
        dst = make_engine(tiny_params, latent_rank=0)
        dst.import_sequence(exp)  # raw payload needs no codec
        assert src.audit_pages() == []


# ---------------------------------------------------------------------------
# Fault point kv.latent_decode (DL011/DL018)
# ---------------------------------------------------------------------------


class TestLatentDecodeFault:
    def test_decode_fault_degrades_exactly_once(self, tiny_params):
        """An armed ``kv.latent_decode:nth=1`` aborts the import like
        any chunk-validation failure — every reserved page released,
        audit clean — and the NEXT import (the retry after the
        exactly-once degrade) succeeds token-identically."""
        cold = make_engine(tiny_params)
        want = run_one(cold, "cold", PROMPT)
        warm = make_engine(tiny_params)
        run_one(warm, "warm", PROMPT)
        depth, chunks = warm.export_prefix_chunks(
            HASHES, chunk_pages=2, wire_quant="latent")

        target = make_engine(tiny_params)
        free0 = target.allocator.num_free()
        faults.install(parse_spec("kv.latent_decode:nth=1", seed=7))
        with pytest.raises(CacheDeserializationError):
            target.import_prefix(PROMPT[: depth * PS], chunks)
        assert target.allocator.num_free() == free0  # zero page leak
        assert target.audit_pages() == []
        # the nth=1 rule is one-shot: the degrade happened exactly once
        # and the retry goes through on the SAME armed registry
        seated = target.import_prefix(PROMPT[: depth * PS], chunks)
        assert seated == depth
        assert run_one(target, "probe", PROMPT) == want
        assert target.audit_pages() == []


# ---------------------------------------------------------------------------
# Chunked latent wire: session-level reorder / truncation / crc
# ---------------------------------------------------------------------------


class TestLatentChunkValidation:
    def _chunks(self, rank=4, wire_quant="latent"):
        cfg = PagedCacheConfig(num_pages=16, page_size=PS,
                               max_pages_per_seq=8)
        state, codec = _latent_state(np.random.default_rng(9), cfg, rank)
        pages = [3, 7, 1, 4]
        chunks = _with_totals(list(serialize_kv_chunks(
            state, pages, PS, chunk_pages=1, wire_quant=wire_quant,
            codec=codec)))
        return cfg, state, codec, pages, chunks

    @pytest.mark.parametrize("wire_quant", ["latent", "latent_int8"])
    def test_reorder_seats_identically(self, wire_quant):
        cfg, state, codec, pages, chunks = self._chunks(
            wire_quant=wire_quant)
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        sess = KvImportSession(fresh, PageAllocator(cfg), PS, codec=codec)
        sess.reserve(len(pages))
        for c in reversed(chunks):
            sess.add_chunk(c)
        restored, got = sess.finish(fresh, list(range(len(pages) * PS)))
        slots = np.concatenate(
            [np.arange(p * PS, (p + 1) * PS) for p in got])
        src = np.concatenate(
            [np.arange(p * PS, (p + 1) * PS) for p in pages])
        err = np.abs(np.asarray(restored.k[:, slots])
                     - np.asarray(state.k[:, src]))
        assert err.max() <= 0.05 * (np.abs(np.asarray(state.k)).max())

    def test_truncated_and_corrupt_chunks_release_everything(self):
        import zlib

        cfg, state, codec, pages, chunks = self._chunks()

        def rejects(bad):
            fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
            alloc = PageAllocator(cfg)
            free0 = alloc.num_free()
            sess = KvImportSession(fresh, alloc, PS, codec=codec)
            sess.reserve(len(pages))
            with pytest.raises(CacheDeserializationError):
                for c in bad:
                    sess.add_chunk(c)
                sess.finish(fresh, list(range(len(pages) * PS)))
            sess.abort()
            assert alloc.num_free() == free0

        rejects(chunks[:-1])  # stream truncation: a chunk never lands
        rejects([dataclasses.replace(chunks[0],
                                     crc32=chunks[0].crc32 ^ 1)]
                + chunks[1:])  # torn payload
        cut = chunks[0].payload[: len(chunks[0].payload) // 2]
        rejects([dataclasses.replace(chunks[0], payload=cut,
                                     crc32=zlib.crc32(cut) & 0xFFFFFFFF)]
                + chunks[1:])  # short payload with a VALID crc
        rejects([chunks[0]] + chunks)  # duplicate index

    def test_codecless_session_rejects_kind3(self):
        cfg, state, codec, pages, chunks = self._chunks()
        fresh = PagedKVState.create(TINY, cfg, dtype=jnp.float32)
        alloc = PageAllocator(cfg)
        free0 = alloc.num_free()
        sess = KvImportSession(fresh, alloc, PS)  # no codec
        sess.reserve(len(pages))
        with pytest.raises(CacheDeserializationError, match="LatentCodec"):
            sess.add_chunk(chunks[0])
        sess.abort()
        assert alloc.num_free() == free0
