"""Graceful-degradation ladder tests (design.md:925-943 [spec];
requirements.md:130-134): pure threshold logic plus applied side effects
on dispatcher/batcher, including reversal when pressure drops."""

from __future__ import annotations

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core.errors import QueueFull
from distributed_inference_server_tpu.core.types import Priority
from distributed_inference_server_tpu.engine.engine import SamplingParams
from distributed_inference_server_tpu.serving.degradation import (
    DegradationController,
    DegradationLevel,
    level_for_pressure,
)
from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.scheduler import AdaptiveScheduler


class _NullSink:
    def on_token(self, *a): ...

    def on_done(self, *a): ...

    def on_error(self, *a): ...


def _req(rid="r"):
    return ServerRequest(rid, [1], SamplingParams(), _NullSink())


def _controller():
    d = Dispatcher(AdaptiveScheduler())
    d._accepting = True
    return DegradationController(d, d.scheduler), d


class TestLevelForPressure:
    @pytest.mark.parametrize(
        "pressure,expected",
        [
            (0.0, DegradationLevel.NORMAL),
            (0.69, DegradationLevel.NORMAL),
            (0.70, DegradationLevel.REDUCED_BATCH_SIZE),
            (0.79, DegradationLevel.REDUCED_BATCH_SIZE),
            (0.80, DegradationLevel.AGGRESSIVE_CACHE_EVICTION),
            (0.89, DegradationLevel.AGGRESSIVE_CACHE_EVICTION),
            (0.90, DegradationLevel.REJECT_LOW_PRIORITY),
            (0.94, DegradationLevel.REJECT_LOW_PRIORITY),
            (0.95, DegradationLevel.EMERGENCY),
            (1.0, DegradationLevel.EMERGENCY),
        ],
    )
    def test_thresholds(self, pressure, expected):
        assert level_for_pressure(pressure) == expected

    @settings(max_examples=100, deadline=None)
    @given(p=st.floats(0.0, 1.5))
    def test_monotone(self, p):
        """Higher pressure never maps to a lower level."""
        assert level_for_pressure(p) >= level_for_pressure(max(0.0, p - 0.1))


class TestControllerActions:
    def test_reduced_batch_size_applied_and_reverted(self):
        c, d = _controller()
        original = d.batcher.effective_max_batch()
        c.evaluate(pressure=0.75)
        assert d.batcher.effective_max_batch() == original // 2
        c.evaluate(pressure=0.10)
        assert d.batcher.effective_max_batch() == original

    def test_degradation_composes_with_hot_reload(self):
        """Hot-reloading batcher config while degraded neither cancels the
        throttle nor gets reverted on recovery (single-owner divisor)."""
        from distributed_inference_server_tpu.serving.batcher import BatcherConfig

        c, d = _controller()
        c.evaluate(pressure=0.75)  # degraded: divisor 2
        d.batcher.config = BatcherConfig(window_ms=50.0, max_batch_size=64)
        assert d.batcher.effective_max_batch() == 32  # still halved
        c.evaluate(pressure=0.10)  # recovered
        assert d.batcher.effective_max_batch() == 64  # reload preserved

    def test_reject_low_priority(self):
        c, d = _controller()
        c.evaluate(pressure=0.92)
        assert c.level == DegradationLevel.REJECT_LOW_PRIORITY
        d.submit(_req("normal-ok"), Priority.NORMAL)  # still accepted
        with pytest.raises(QueueFull):
            d.submit(_req("low-rejected"), Priority.LOW)

    def test_emergency_rejects_all(self):
        c, d = _controller()
        c.evaluate(pressure=0.99)
        assert c.level == DegradationLevel.EMERGENCY
        with pytest.raises(QueueFull):
            d.submit(_req("high-rejected"), Priority.HIGH)

    def test_recovery_lifts_gates(self):
        c, d = _controller()
        c.evaluate(pressure=0.99)
        c.evaluate(pressure=0.30)
        assert c.level == DegradationLevel.NORMAL
        d.submit(_req("accepted-again"), Priority.LOW)

    def test_memory_pressure_no_engines_is_zero(self):
        c, _ = _controller()
        assert c.memory_pressure() == 0.0

    def test_loop_cap_frac_follows_the_ladder(self):
        """ISSUE 19: each rung shrinks the run-to-completion loop cap
        alongside the mixed prefill share (LOOP_CAP_FRAC), and recovery
        restores it — pressure hands control back to the host sooner
        without abandoning looped dispatch."""

        class _Runner:
            engine_id = "e0"

            def __init__(self):
                self.loop_fracs = []
                self.mixed_fracs = []

            def set_loop_cap_frac(self, f):
                self.loop_fracs.append(f)

            def set_mixed_prefill_frac(self, f):
                self.mixed_fracs.append(f)

            def evict_cache(self, target, drop_host_tier=False): ...

        c, d = _controller()
        r = _Runner()
        d.scheduler.register(r)
        c.evaluate(pressure=0.75)   # REDUCED_BATCH_SIZE
        c.evaluate(pressure=0.92)   # REJECT_LOW_PRIORITY
        c.evaluate(pressure=0.10)   # recovery
        assert r.loop_fracs == [0.5, 0.25, 1.0]
        # the two levers move together, rung for rung
        assert r.mixed_fracs == [0.5, 0.25, 1.0]

    def test_loop_cap_frac_noop_without_setter(self):
        """Engines without loop_to_completion (or the mixed step) are
        skipped, not crashed — the ladder getattr-gates both setters."""

        class _Bare:
            engine_id = "bare"

            def evict_cache(self, target, drop_host_tier=False): ...

        c, d = _controller()
        d.scheduler.register(_Bare())
        c.evaluate(pressure=0.92)
        c.evaluate(pressure=0.10)
        assert c.level == DegradationLevel.NORMAL
