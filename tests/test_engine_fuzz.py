"""Engine invariant fuzz: randomized workloads against LLMEngine with
pools small enough to force preemption, mixed request shapes, aborts at
random moments, and the round's feature matrix (speculative drafts,
sliding-window reclaim, CP meshes).

Invariants checked after every drain:
- every request terminates exactly once (finished or error, never both,
  never twice, none lost);
- completed greedy requests produce exactly max_tokens tokens (or stop
  early only via EOS — excluded by the tokenizer used here);
- the allocator returns to its initial free-page count (no leaks, no
  double frees) after cache eviction;
- host/device bookkeeping drains clean (no seated slots, no pending
  blocks, empty waiting queue).

This is the serving counterpart of the native tier's differential/TSan
suites: the reference's property tests covered data structures
(SURVEY §4.2); the continuous-batching engine is where this repo's
complexity actually lives.
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.engine.speculative import SpecConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY, TINY_SWA
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

TOK = ByteTokenizer()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


@pytest.fixture(scope="module")
def draft_params():
    return llama.init_params(jax.random.PRNGKey(9), TINY, dtype=jnp.float32)


def _drive(eng, rnd, n_requests=14, max_steps=3000, abort_frac=0.25,
           prompt_max=40):
    """Feed randomized requests with interleaved aborts; return the
    terminal record per request id."""
    outcomes: dict = {}
    emitted: dict = {}
    pending = list(range(n_requests))
    live: list = []
    steps = 0
    while (pending or eng.has_work()) and steps < max_steps:
        steps += 1
        # random admission
        if pending and rnd.random() < 0.4:
            i = pending.pop()
            rid = f"r{i}"
            n = rnd.randint(1, prompt_max)
            ids = [rnd.randint(1, 250) for _ in range(n)]
            eng.add_request(rid, ids, SamplingParams(
                max_tokens=rnd.randint(1, 24), temperature=0.0))
            live.append(rid)
        # random abort
        if live and rnd.random() < abort_frac * 0.3:
            rid = rnd.choice(live)
            if eng.abort(rid):
                outcomes.setdefault(rid, []).append("aborted")
                live.remove(rid)
        for out in eng.step():
            if out.token_id is not None:
                emitted[out.request_id] = emitted.get(out.request_id, 0) + 1
            if out.finished:
                kind = "error" if out.error is not None else (
                    out.finish_reason.value if out.finish_reason else "?")
                outcomes.setdefault(out.request_id, []).append(kind)
                if out.request_id in live:
                    live.remove(out.request_id)
    assert steps < max_steps, "engine failed to drain (livelock?)"
    return outcomes, emitted


def _check_invariants(eng, outcomes, n_requests, free0):
    # termination: exactly one terminal event per request
    assert len(outcomes) == n_requests, (
        f"lost requests: {set(f'r{i}' for i in range(n_requests)) - set(outcomes)}"
    )
    for rid, events in outcomes.items():
        assert len(events) == 1, f"{rid} terminated twice: {events}"
        assert events[0] in ("length", "stop", "aborted"), (rid, events)
    # bookkeeping drained
    assert eng.num_active() == 0
    assert eng.num_waiting() == 0
    assert not eng._pending
    assert not eng._by_id
    # page conservation: after dropping the prefix cache every page is free
    eng.allocator.evict_below(0.0)
    assert eng.allocator.num_free() == free0, (
        f"page leak: {free0 - eng.allocator.num_free()} pages missing"
    )


def _fuzz(eng, seed, n_requests=14, **kw):
    free0 = eng.allocator.num_free()
    rnd = random.Random(seed)
    outcomes, _ = _drive(eng, rnd, n_requests=n_requests, **kw)
    _check_invariants(eng, outcomes, n_requests, free0)


class TestEngineFuzz:
    def test_baseline_with_preemption_pressure(self, tiny_params):
        # pool of 24 pages x 4 tokens: a handful of 40-token prompts
        # exceed it — preemption and retry paths must hold invariants
        eng = LLMEngine(
            tiny_params, TINY, TOK,
            EngineConfig(
                max_batch=4, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=24, page_size=4,
                                       max_pages_per_seq=16),
                decode_block_size=3,
            ),
            dtype=jnp.float32,
        )
        _fuzz(eng, seed=1)

    def test_speculative_with_aborts(self, tiny_params, draft_params):
        eng = LLMEngine(
            tiny_params, TINY, TOK,
            EngineConfig(
                max_batch=3, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=32, page_size=4,
                                       max_pages_per_seq=16),
                decode_block_size=2,
            ),
            dtype=jnp.float32,
            draft_params=draft_params, draft_cfg=TINY,
            spec=SpecConfig(num_draft_tokens=3),
        )
        _fuzz(eng, seed=2, n_requests=10)

    def test_sliding_window_reclaim_under_churn(self, tiny_params):
        eng = LLMEngine(
            tiny_params, TINY_SWA, TOK,
            EngineConfig(
                max_batch=3, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=24, page_size=4,
                                       max_pages_per_seq=24),
                decode_block_size=4,
            ),
            dtype=jnp.float32,
        )
        _fuzz(eng, seed=3, n_requests=10)

    def test_cp_mesh_long_prompts(self, tiny_params):
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        eng = LLMEngine(
            tiny_params, TINY, TOK,
            EngineConfig(
                max_batch=3, prefill_buckets=(16,),
                paged=PagedCacheConfig(num_pages=48, page_size=4,
                                       max_pages_per_seq=24),
                decode_block_size=3,
            ),
            dtype=jnp.float32, mesh=make_mesh(MeshSpec(seq=4)),
        )
        # prompts up to 64 tokens: many take the ring-prefill path
        _fuzz(eng, seed=4, n_requests=8, prompt_max=64)

    def test_gemma2_alternating_windows_under_churn(self):
        """Gemma-2 engine (alternating local/global layers, no page
        reclaim, softcaps) holds the same invariants under randomized
        churn — the traced per-layer window path at fuzz pressure."""
        from distributed_inference_server_tpu.models.configs import (
            TINY_GEMMA2,
        )

        gparams = llama.init_params(
            jax.random.PRNGKey(0), TINY_GEMMA2, dtype=jnp.float32
        )
        eng = LLMEngine(
            gparams, TINY_GEMMA2, TOK,
            EngineConfig(
                max_batch=3, prefill_buckets=(8, 32),
                paged=PagedCacheConfig(num_pages=24, page_size=4,
                                       max_pages_per_seq=16),
                decode_block_size=3,
            ),
            dtype=jnp.float32,
        )
        _fuzz(eng, seed=5, n_requests=10)
