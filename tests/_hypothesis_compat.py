"""Hypothesis shim for images that do not ship it (seed-known, triaged
in ISSUE 1).

When hypothesis is installed this re-exports the real ``given`` /
``settings`` / ``strategies``. When it is absent, ``@given`` tests
self-skip at call time while every plain test in the same module still
runs — a module-level ``pytest.importorskip`` would silently disable
dozens of non-property tests (dispatcher, scheduler, sampling, ...)
along with the handful that actually need hypothesis.
"""

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAS_HYPOTHESIS = True
except ModuleNotFoundError:
    import pytest

    HAS_HYPOTHESIS = False

    def given(*_args, **_kwargs):
        def deco(_fn):
            def _skipped(*args, **kwargs):
                pytest.skip(
                    "hypothesis not installed in this image "
                    "(seed-known, triaged in ISSUE 1)"
                )

            return _skipped

        return deco

    def settings(*_args, **_kwargs):
        return lambda fn: fn

    class _DummyStrategy:
        """Chainable stand-in: strategies are constructed and composed
        (.map/.filter/...) at module import, but the decorated tests
        never run, so no value is ever drawn."""

        def __getattr__(self, _name):
            return self

        def __call__(self, *a, **k):
            return self

    class _Strategies:
        def __getattr__(self, _name):
            return _DummyStrategy()

    st = _Strategies()
