"""Run-to-completion looped decode blocks (ISSUE 19, kernel looping;
EngineConfig.loop_to_completion): greedy token identity against the
fixed-K path across mixed bursts, mid-block EOS, free-list exhaustion,
aborts and handoff overlap; the on-device page free-list's draw/claim/
reconcile conservation; speculative decoding composed INSIDE the loop;
and the degradation cap hook."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.engine.speculative import SpecConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

TOK = ByteTokenizer()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


@pytest.fixture(scope="module")
def draft_params():
    return llama.init_params(jax.random.PRNGKey(9), TINY, dtype=jnp.float32)


def make_engine(tiny_params, loop=False, loop_max_steps=64, num_pages=64,
                page_size=4, max_pages_per_seq=24, max_batch=4,
                tokenizer=None, draft=None, **kw):
    return LLMEngine(
        tiny_params,
        TINY,
        tokenizer or ByteTokenizer(),
        EngineConfig(
            max_batch=max_batch,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(
                num_pages=num_pages, page_size=page_size,
                max_pages_per_seq=max_pages_per_seq,
            ),
            decode_block_size=4,
            loop_to_completion=loop,
            loop_max_steps=loop_max_steps,
            **kw,
        ),
        dtype=jnp.float32,
        draft_params=draft,
        draft_cfg=TINY if draft is not None else None,
        spec=SpecConfig(num_draft_tokens=3) if draft is not None else None,
    )


def drain(engine, toks=None, max_steps=800):
    toks = {} if toks is None else toks
    steps = 0
    while engine.has_work():
        steps += 1
        assert steps < max_steps, "engine did not drain"
        for out in engine.step():
            assert out.error is None, (out.request_id, out.error)
            if out.token_id is not None:
                toks.setdefault(out.request_id, []).append(out.token_id)
    return toks, steps


def _diff(got, want):
    return {k: (got.get(k), want.get(k))
            for k in set(got) | set(want) if got.get(k) != want.get(k)}


# ---------------------------------------------------------------------------
# greedy bit-identity: looped blocks vs the fixed-K path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [3, 11, 29])
def test_loop_greedy_identity_fuzz(tiny_params, seed):
    """The acceptance-criteria identity, fuzzed: random prompt lengths
    and budgets decode bit-identically with loop_to_completion on and
    off, and the page books conserve either way."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, 200, size=int(n)).tolist()
               for n in rng.integers(3, 20, size=4)]
    budgets = [int(b) for b in rng.integers(2, 16, size=4)]

    def run(loop):
        eng = make_engine(tiny_params, loop=loop)
        for i, (ids, mt) in enumerate(zip(prompts, budgets)):
            eng.add_request(f"r{i}", ids,
                            SamplingParams(max_tokens=mt, temperature=0.0))
        toks, _ = drain(eng)
        assert eng.audit_pages() == []
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    stats = eng.loop_stats()
    assert stats["blocks"] >= 1
    # each request's FIRST token is sampled by prefill, the rest by the
    # looped blocks
    assert stats["decode_tokens"] == (sum(len(v) for v in got.values())
                                      - len(got))
    assert stats["exits"]["budget"] >= 1


def test_loop_collapses_dispatches_and_steps(tiny_params):
    """The perf contract: a pure-decode drain that takes the fixed path
    one block per engine step finishes in far fewer engine steps looped
    — the stop condition runs on-device, not on the host."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 200, size=6).tolist() for _ in range(3)]

    def run(loop):
        eng = make_engine(tiny_params, loop=loop)
        for i, ids in enumerate(prompts):
            eng.add_request(f"r{i}", ids,
                            SamplingParams(max_tokens=24, temperature=0.0))
        toks, steps = drain(eng)
        return toks, steps, eng

    want, steps_off, _ = run(False)
    got, steps_on, eng = run(True)
    assert got == want, _diff(got, want)
    assert steps_on < steps_off
    sc = eng.step_clock_stats()["kinds"]["loop"]
    assert sc["dispatches"] >= 1
    # the looped dispatches carried every token past each row's first
    # (prefill samples that one)
    assert sc["tokens"] == sum(len(v) for v in got.values()) - len(got)
    assert eng.step_clock_stats()["kinds"]["decode_block"]["dispatches"] == 0


def test_loop_stats_none_when_off(tiny_params):
    eng = make_engine(tiny_params, loop=False)
    assert eng.loop_stats() is None


def test_loop_max_steps_validated(tiny_params):
    with pytest.raises(ValueError, match="loop_max_steps"):
        make_engine(tiny_params, loop=True, loop_max_steps=0)


# ---------------------------------------------------------------------------
# stop conditions: EOS, budget, pages, cap
# ---------------------------------------------------------------------------


class _EosTok(ByteTokenizer):
    def __init__(self, eos):
        super().__init__()
        self.eos_ids = (eos,)


def test_mid_block_eos_identity():
    """A row that hits EOS mid-loop freezes on-device (exit reason eos)
    and emits exactly the same tokens as the fixed path."""
    # PRNGKey(0) params echo the last prompt byte forever (constant
    # stream: EOS would fire at the prefill-sampled token, never inside
    # the loop) — PRNGKey(2) diverges deep into the stream
    params = llama.init_params(jax.random.PRNGKey(2), TINY,
                               dtype=jnp.float32)
    probe = make_engine(params)
    prompt = [104, 101, 108, 108, 111]  # "hello", no BOS
    probe.add_request("p", prompt,
                      SamplingParams(max_tokens=12, temperature=0.0))
    ptoks, _ = drain(probe)
    assert len(ptoks["p"]) == 12
    # the row finishes at the EOS value's FIRST occurrence, so pick the
    # token whose first occurrence lands deepest into the stream
    firsts = {}
    for j, t in enumerate(ptoks["p"]):
        firsts.setdefault(t, j)
    eos = max(firsts, key=firsts.get)
    assert firsts[eos] >= 2  # EOS must fire inside the decode loop

    def run(loop):
        eng = make_engine(params, loop=loop, tokenizer=_EosTok(eos))
        eng.add_request("e", prompt,
                        SamplingParams(max_tokens=12, temperature=0.0))
        # a second row keeps the block alive past the EOS row's freeze
        eng.add_request("other", TOK.encode("keep going"),
                        SamplingParams(max_tokens=12, temperature=0.0))
        toks, _ = drain(eng)
        assert eng.audit_pages() == []
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    assert len(got["e"]) < 12  # EOS cut the budget short
    assert eng.loop_stats()["exits"]["eos"] >= 1


def test_free_list_exhaustion_repages_and_stays_identical(tiny_params):
    """A tight pool starves the device free-list mid-loop: rows freeze
    with exit reason 'pages', re-stage, and the drain still produces
    bit-identical tokens with zero page leaks."""
    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, 200, size=n).tolist() for n in (5, 9, 13)]

    def run(loop):
        eng = make_engine(tiny_params, loop=loop, num_pages=18)
        for i, ids in enumerate(prompts):
            eng.add_request(f"r{i}", ids,
                            SamplingParams(max_tokens=20, temperature=0.0))
        toks, _ = drain(eng)
        assert eng.audit_pages() == []
        assert eng.allocator.device_held() == 0
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    assert eng.loop_stats()["exits"]["pages"] >= 1


def test_cache_full_drain_then_preempt_under_loop(tiny_params):
    """When even the host-side first-write guarantee cannot be met the
    loop path preempts the youngest row exactly like _maybe_launch —
    every request still finishes and the books conserve."""
    rng = np.random.default_rng(17)
    eng = make_engine(tiny_params, loop=True, num_pages=12,
                      max_pages_per_seq=8)
    for i in range(3):
        eng.add_request(f"r{i}", rng.integers(1, 200, size=6).tolist(),
                        SamplingParams(max_tokens=18, temperature=0.0))
    toks, _ = drain(eng)
    assert set(toks) == {"r0", "r1", "r2"}
    assert all(len(v) == 18 for v in toks.values())
    assert eng.audit_pages() == []
    ev = eng.step_clock_stats()["events"]
    assert ev["cache_full"] >= 1 and ev["preempt"] >= 1


def test_cap_exit_resumes_next_step(tiny_params):
    """A block that hits loop_max_steps hands control back with exit
    reason 'cap'; the rows simply resume at the next engine step and
    the tokens stay identical."""
    rng = np.random.default_rng(19)
    prompts = [rng.integers(1, 200, size=7).tolist() for _ in range(2)]

    def run(loop, cap=3):
        eng = make_engine(tiny_params, loop=loop, loop_max_steps=cap)
        for i, ids in enumerate(prompts):
            eng.add_request(f"r{i}", ids,
                            SamplingParams(max_tokens=14, temperature=0.0))
        toks, _ = drain(eng)
        assert eng.audit_pages() == []
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    assert eng.loop_stats()["exits"]["cap"] >= 1
    assert eng.loop_stats()["blocks"] >= 2


def test_set_loop_cap_frac_shrinks_cap(tiny_params):
    """The degradation hook: the effective iteration cap shrinks with
    the frac (floor 1) and restores on the way back down."""
    eng = make_engine(tiny_params, loop=True, loop_max_steps=40)
    assert eng.loop_stats()["cap"] == 40
    eng.set_loop_cap_frac(0.25)
    assert eng.loop_stats()["cap"] == 10
    assert eng.loop_stats()["cap_frac"] == 0.25
    eng.set_loop_cap_frac(0.0)  # floored, never zero
    assert eng.loop_stats()["cap"] >= 1
    eng.set_loop_cap_frac(1.0)
    assert eng.loop_stats()["cap"] == 40


# ---------------------------------------------------------------------------
# aborts and handoff overlap
# ---------------------------------------------------------------------------


def test_abort_mid_block_releases_everything(tiny_params):
    """Aborting between looped launches: the dead row's device appends
    reconcile as orphans, its pages free, and the surviving rows'
    tokens are unaffected (identical to a run that never saw the
    aborted request decode past the same point)."""
    rng = np.random.default_rng(23)
    prompts = [rng.integers(1, 200, size=6).tolist() for _ in range(3)]

    eng = make_engine(tiny_params, loop=True, loop_max_steps=2)
    for i, ids in enumerate(prompts):
        eng.add_request(f"r{i}", ids,
                        SamplingParams(max_tokens=16, temperature=0.0))
    toks: dict = {}
    for _ in range(2):  # a couple of capped blocks, everyone mid-decode
        for out in eng.step():
            if out.token_id is not None:
                toks.setdefault(out.request_id, []).append(out.token_id)
    assert eng.abort("r1")
    drain(eng, toks)
    assert eng.audit_pages() == []
    assert eng.allocator.device_held() == 0
    assert len(toks["r0"]) == 16 and len(toks["r2"]) == 16
    assert len(toks.get("r1", [])) < 16


def test_streamed_export_overlap_under_loop(tiny_params):
    """The engine.py streamed-export overlap window with looped decode:
    the sequence keeps decoding through looped blocks while its prefix
    serializes, and the migrated decode is token-identical to in-place
    (the same contract the fixed path proves in test_disagg)."""
    ids = TOK.encode("the quick brown fox jumps over the lazy dog")
    sp = SamplingParams(max_tokens=40, temperature=0.0)

    uni = make_engine(tiny_params, loop=True)
    uni.add_request("r", ids, sp)
    ref, _ = drain(uni)

    # loop cap small so the overlap window spans several looped blocks
    src = make_engine(tiny_params, loop=True, loop_max_steps=2)
    src.add_request("r", ids, sp, prefill_only=True)
    got: dict = {}
    while src.has_work() and not src.handoff_ready_ids():
        for o in src.step():  # prefill + first token, then parked
            assert o.error is None
            if o.token_id is not None:
                got.setdefault(o.request_id, []).append(o.token_id)
    dst = make_engine(tiny_params, loop=True)
    session = src.export_handoff_begin("r", chunk_pages=2)
    assert session is not None

    def collect(outs):
        for o in outs:
            assert o.error is None
            if o.token_id is not None:
                got.setdefault(o.request_id, []).append(o.token_id)

    collect(src.step())  # overlap: looped decode while the prefix moves
    src.export_handoff_pump(session)
    isess = dst.import_stream_open("r", len(session.prefix_pages))
    dst.import_stream_add(isess, session.chunks)
    collect(src.step())  # more overlap
    exp, outputs = src.export_handoff_finish(session)
    assert exp is not None
    collect(outputs)
    assert not src.has_work()
    assert src.audit_pages() == []
    tail = exp.kv_chunks[len(session.chunks):]
    dst.import_stream_commit(isess, dataclasses.replace(exp,
                                                        kv_chunks=tail))
    drain(dst, got)
    assert dst.audit_pages() == []
    assert got == ref, _diff(got, ref)


# ---------------------------------------------------------------------------
# mixed-step K-block fusion
# ---------------------------------------------------------------------------


def test_mixed_burst_identity_and_k_fusion(tiny_params):
    """A long prompt lands mid-decode: with loop_to_completion the
    mixed step advances every decode row decode_block_size tokens per
    dispatch (not one), with bit-identical tokens to the quantum
    baseline."""
    rng = np.random.default_rng(31)
    chats = [rng.integers(1, 200, size=6).tolist() for _ in range(2)]
    long_prompt = rng.integers(1, 200, size=60).tolist()

    def run(loop):
        # loop cap 1 keeps the chats mid-decode when the prompt lands
        eng = make_engine(tiny_params, loop=loop, loop_max_steps=1,
                          mixed_step_tokens=20 if loop else 0)
        toks: dict = {}
        for i, ids in enumerate(chats):
            eng.add_request(f"c{i}", ids,
                            SamplingParams(max_tokens=30, temperature=0.0))
        for _ in range(3):
            for out in eng.step():
                if out.token_id is not None:
                    toks.setdefault(out.request_id, []).append(out.token_id)
        eng.add_request("long", long_prompt,
                        SamplingParams(max_tokens=8, temperature=0.0))
        drain(eng, toks)
        assert eng.audit_pages() == []
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    ms = eng.mixed_stats()
    assert ms["decode_tokens"] > 0
    # K-block fusion: decode tokens advanced per mixed dispatch averages
    # well above the fixed path's 1 (K = decode_block_size = 4, minus
    # rows that hit their budget mid-block)
    assert ms["decode_tokens"] / ms["steps"] > 1.0


def test_mixed_dispatch_count_collapses_k_fold(tiny_params):
    """The dispatch-count contract behind the bench: decoding the same
    burst, the fused mixed path uses ~K x fewer mixed dispatches per
    decode token than the per-token baseline."""
    rng = np.random.default_rng(37)
    chat = rng.integers(1, 200, size=6).tolist()
    long_prompt = rng.integers(1, 200, size=90).tolist()

    def dispatches_per_decode_token(loop):
        eng = make_engine(tiny_params, loop=loop, loop_max_steps=1,
                          mixed_step_tokens=20)
        eng.add_request("chat", chat,
                        SamplingParams(max_tokens=40, temperature=0.0))
        for _ in range(2):
            eng.step()
        eng.add_request("long", long_prompt,
                        SamplingParams(max_tokens=2, temperature=0.0))
        drain(eng)
        ms = eng.mixed_stats()
        sc = eng.step_clock_stats()["kinds"]["mixed"]
        assert sc["dispatches"] == ms["steps"]
        return ms["steps"] / max(1, ms["decode_tokens"])

    base = dispatches_per_decode_token(False)
    fused = dispatches_per_decode_token(True)
    # the fixed path spends one mixed dispatch per decode token; fusion
    # amortizes each dispatch over K=4 decode tokens
    assert base >= 0.99
    assert fused <= base / 2


# ---------------------------------------------------------------------------
# speculation inside the loop
# ---------------------------------------------------------------------------


def test_spec_in_loop_identity(tiny_params, draft_params):
    """Draft+verify composed INSIDE the looped program emits exactly
    the two-dispatch fixed spec path's greedy tokens."""
    rng = np.random.default_rng(41)
    prompts = [rng.integers(1, 200, size=n).tolist() for n in (5, 9, 13)]

    def run(loop):
        eng = make_engine(tiny_params, loop=loop, draft=draft_params)
        for i, ids in enumerate(prompts):
            eng.add_request(f"r{i}", ids,
                            SamplingParams(max_tokens=12, temperature=0.0))
        toks, _ = drain(eng)
        assert eng.audit_pages() == []
        return toks, eng

    want, _ = run(False)
    got, eng = run(True)
    assert got == want, _diff(got, want)
    assert eng.loop_stats()["blocks"] >= 1


def test_spec_composes_with_mixed_under_loop(tiny_params, draft_params):
    """ISSUE 19 lifts the mixed-vs-speculation exclusion: with
    loop_to_completion both knobs construct and the run matches the
    plain engine's greedy tokens (greedy spec == greedy plain)."""
    rng = np.random.default_rng(43)
    chats = [rng.integers(1, 200, size=6).tolist() for _ in range(2)]
    long_prompt = rng.integers(1, 200, size=60).tolist()

    def run(spec_mixed_loop):
        if spec_mixed_loop:
            eng = make_engine(tiny_params, loop=True,
                              mixed_step_tokens=20, draft=draft_params)
        else:
            eng = make_engine(tiny_params)
        toks: dict = {}
        for i, ids in enumerate(chats):
            eng.add_request(f"c{i}", ids,
                            SamplingParams(max_tokens=12, temperature=0.0))
        for _ in range(3):
            for out in eng.step():
                if out.token_id is not None:
                    toks.setdefault(out.request_id, []).append(out.token_id)
        eng.add_request("long", long_prompt,
                        SamplingParams(max_tokens=8, temperature=0.0))
        drain(eng, toks)
        assert eng.audit_pages() == []
        return toks

    want = run(False)
    got = run(True)
    assert got == want, _diff(got, want)


def test_spec_mixed_still_excluded_without_loop(tiny_params, draft_params):
    with pytest.raises(ValueError, match="loop_to_completion"):
        make_engine(tiny_params, mixed_step_tokens=20, draft=draft_params)
