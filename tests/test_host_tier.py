"""Tiered prefix cache (ISSUE 5): host-RAM second tier of the paged KV
cache plus cache-aware routing. Covers HostTier policy (byte budget,
chain protection, front-biased eviction, in-flight window), allocator
demotion hooks, engine-level offload→reload token identity (f32 and
int8 host tiers), reload racing an abort, the degradation ladder's
demote-vs-drop rungs, and the scheduler's cache_aware / rebalanced
memory_aware strategies."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    _KIND_RAW,
    HostTier,
    PageAllocator,
    PagedCacheConfig,
    chain_hashes,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.degradation import (
    DegradationController,
    DegradationLevel,
)
from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.scheduler import (
    SchedulingStrategy,
    choose_engine,
    prefix_match_depth,
)

TOK = ByteTokenizer()
PS = 4


def _page(val: float, nbytes: int = 64) -> tuple:
    """One fake demoted page: (k, v) host arrays totalling ``2*nbytes``,
    slot axis at axis 1 (one slot — the policy tests use page_size=1)."""
    a = np.full((nbytes // 4, 1), val, np.float32)
    return (a, a * 2)


def _offer(t: HostTier, h: int, depth: int, root: int, kind: int,
           arrs: tuple) -> None:
    """Single-page group offer through the batched ingest API."""
    t.offer([(h, depth, root)], kind, arrs, page_size=1)


# ---------------------------------------------------------------------------
# HostTier policy (no engine)
# ---------------------------------------------------------------------------


class TestHostTierPolicy:
    def test_offer_get_roundtrip(self):
        t = HostTier(budget_bytes=1 << 20)
        _offer(t, 11, 0, 11, _KIND_RAW, _page(1.0))
        e = t.get(11)
        assert e is not None and e.kind == _KIND_RAW
        np.testing.assert_array_equal(e.parts[0], _page(1.0)[0])
        assert t.get(99) is None
        s = t.stats()
        assert (s.hits, s.misses, s.offloads) == (1, 1, 1)
        assert s.pages == 1 and s.bytes_used == sum(
            p.nbytes for p in e.parts
        )

    def test_group_offer_slices_pages_ignores_padding(self):
        """One demotion burst: the group's slot axis is sliced per entry
        and jit-bucket padding slots past the last real page are
        ignored."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=0)
        ps = 2
        # 3 real pages in a 4-slot bucket (last slot = padding)
        k = np.concatenate(
            [np.full((4, ps), float(d), np.float32) for d in (1, 2, 3, 3)],
            axis=1,
        )
        t.offer([(1, 0, 1), (2, 1, 1), (3, 2, 1)], _KIND_RAW,
                (k, k * 2), page_size=ps)
        assert t.stats().pages == 3 and t.stats().offloads == 3
        for h, val in ((1, 1.0), (2, 2.0), (3, 3.0)):
            e = t.get(h)
            np.testing.assert_array_equal(
                e.parts[0], np.full((4, ps), val, np.float32)
            )
            np.testing.assert_array_equal(
                e.parts[1], np.full((4, ps), 2 * val, np.float32)
            )

    def test_default_window_holds_a_full_gather_bucket(self):
        """The default in-flight window must be at least the offload
        hook's largest gather bucket: a full-bucket eviction burst stays
        un-materialized, so offer() never blocks on the device→host
        copies it just dispatched (the regression was window 4 < bucket
        32 — every burst over 4 pages drained its own group
        synchronously inside allocate())."""
        cap = LLMEngine._OFFLOAD_BUCKETS[-1]
        t = HostTier(budget_bytes=1 << 24)
        k = np.ones((2, cap), np.float32)
        t.offer([(100 + i, i, 100) for i in range(cap)], _KIND_RAW,
                (k, k * 2), page_size=1)
        assert t.stats().pages == 0  # whole burst still in flight
        assert t.has(100) and t.has(100 + cap - 1)
        assert t.get(100) is not None  # lookup still drains it

    def test_inflight_window_defers_materialization(self):
        """Within the window pages stay un-materialized (eviction never
        blocks on the device→host copy); a HIT drains groups only until
        the matched page materializes, and a MISS drains nothing — a
        cold prompt's lookup must not block on unrelated in-flight
        copies."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=2)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0))
        _offer(t, 2, 1, 1, _KIND_RAW, _page(2.0))
        assert t.stats().pages == 0  # both still in flight
        assert t.has(1) and t.has(2)  # but visible
        _offer(t, 3, 2, 1, _KIND_RAW, _page(3.0))
        assert t.stats().pages == 1  # window overflow drained the oldest
        assert t.get(99) is None  # miss: nothing drained
        assert t.stats().pages == 1
        assert t.get(2) is not None  # hit: drains up TO the matched group
        assert t.stats().pages == 2  # page 3 still in flight
        assert t.get(3) is not None
        assert t.stats().pages == 3

    def test_multi_group_burst_never_drains_itself(self):
        """An eviction burst larger than the window spans several
        offer() calls (new_burst=False continuations) — inside
        allocate() it must never materialize its OWN still-in-flight
        copies, even past the window; the NEXT burst drains the
        overshoot instead (by which time the copies have landed)."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=2)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0))
        t.offer([(2, 1, 1)], _KIND_RAW, _page(2.0), page_size=1,
                new_burst=False)
        t.offer([(3, 2, 1)], _KIND_RAW, _page(3.0), page_size=1,
                new_burst=False)
        assert t.stats().pages == 0  # 3 pages > window 2: no self-drain
        assert t.has(1) and t.has(3)
        _offer(t, 4, 0, 4, _KIND_RAW, _page(4.0))  # next burst
        assert t.stats().pages == 2  # overshoot drained to the window
        assert t.get(1) is not None and t.get(2) is not None

    def test_all_duplicate_burst_still_drains_overshoot(self):
        """A new burst whose pages all dedup away must still pull a
        previous burst's overshoot back down to the window — the early
        return on empty ``fresh`` must not skip the drain."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=2)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0))
        for h in (2, 3, 4):
            t.offer([(h, h - 1, 1)], _KIND_RAW, _page(float(h)),
                    page_size=1, new_burst=False)
        assert t.stats().pages == 0  # one 4-page burst: overshoot
        _offer(t, 1, 0, 1, _KIND_RAW, _page(9.0))  # all-dup new burst
        assert t.stats().pages == 2  # drained back to the window
        np.testing.assert_array_equal(  # and kept the first copy
            t.get(1).parts[0], _page(1.0)[0])

    def test_drain_to_window_materializes_ladder_overshoot(self):
        """The degradation ladder demotes in ONE burst that can exceed
        the window with no later traffic to drain it; drain_to_window
        (called by LLMEngine.evict_cache off the hot path) must
        materialize the overshoot so the gathered device arrays are
        released."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=2)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0))
        for h in (2, 3, 4, 5):
            t.offer([(h, h - 1, 1)], _KIND_RAW, _page(float(h)),
                    page_size=1, new_burst=False)
        assert t.stats().pages == 0
        t.drain_to_window()
        assert t.stats().pages == 3  # 5 in flight -> window of 2 left
        t.flush()
        assert t.stats().pages == 5

    def test_duplicate_offer_keeps_first_copy(self):
        t = HostTier(budget_bytes=1 << 20)
        _offer(t, 7, 0, 7, _KIND_RAW, _page(1.0))
        _offer(t, 7, 0, 7, _KIND_RAW, _page(9.0))
        np.testing.assert_array_equal(t.get(7).parts[0], _page(1.0)[0])

    def test_budget_eviction_is_front_biased(self):
        """Within one (probationary) chain the DEEPEST page is the
        victim: a chain is only matchable from its head, so a retained
        tail behind a dropped head would be dead weight."""
        nb = 128  # 2*128 bytes per page
        t = HostTier(budget_bytes=3 * 2 * nb, inflight_window=0)
        for d in range(5):  # chain of 5 pages, budget holds 3
            _offer(t, 100 + d, d, 100, _KIND_RAW, _page(float(d), nb))
        assert t.stats().pages == 3
        for d in range(3):  # head survives ...
            assert t.get(100 + d) is not None
        for d in (3, 4):  # ... tail evicted
            assert not t.has(100 + d)

    def test_matched_chain_protected_from_churn(self):
        """A chain that has seen a ``get`` is re-used traffic: one-touch
        churn chains must evict first even when the protected chain is
        older (plain LRU would be scan-poisoned here)."""
        nb = 128
        t = HostTier(budget_bytes=4 * 2 * nb, inflight_window=0)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0, nb))
        _offer(t, 2, 1, 1, _KIND_RAW, _page(2.0, nb))
        assert t.get(1) is not None  # protect chain root=1
        for d in range(6):  # churn: 6 one-touch chains
            _offer(t, 50 + d, 0, 50 + d, _KIND_RAW, _page(float(d), nb))
        assert t.has(1) and t.has(2)  # protected chain intact
        assert t.stats().pages == 4

    def test_repeated_hits_keep_heaps_bounded(self):
        """get() re-files a hit under a fresh stamp, and a tier that
        never exceeds its budget never pops stale entries — compaction
        must bound the lazy heaps by resident pages, not by lifetime
        hit count."""
        t = HostTier(budget_bytes=1 << 20, inflight_window=0)
        for d in range(4):
            _offer(t, 100 + d, d, 100, _KIND_RAW, _page(float(d)))
        for _ in range(1000):
            assert t.get(100) is not None
        assert (len(t._prob_heap) + len(t._prot_heap)
                <= 4 * t.stats().pages + 64)

    def test_single_page_over_budget_dropped(self):
        t = HostTier(budget_bytes=16, inflight_window=0)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0, 64))
        assert t.stats().pages == 0 and t.stats().evictions == 1

    def test_clear_drops_everything(self):
        t = HostTier(budget_bytes=1 << 20, inflight_window=2)
        _offer(t, 1, 0, 1, _KIND_RAW, _page(1.0))
        _offer(t, 2, 0, 2, _KIND_RAW, _page(2.0))
        _offer(t, 3, 0, 3, _KIND_RAW, _page(3.0))
        assert t.clear() == 3
        assert t.stats().pages == 0 and t.stats().bytes_used == 0
        assert not t.has(1)

    def test_rejects_unknown_quant_and_bad_budget(self):
        with pytest.raises(ValueError):
            HostTier(budget_bytes=1 << 20, quant="fp4")
        with pytest.raises(ValueError):
            HostTier(budget_bytes=0)


# ---------------------------------------------------------------------------
# Allocator demotion hook + LRU clock regression (satellite: Property 11)
# ---------------------------------------------------------------------------

PCFG = PagedCacheConfig(num_pages=8, page_size=4, max_pages_per_seq=4)


class TestAllocatorDemotion:
    def _cache_one(self, a, tokens):
        p = a.allocate(-(-len(tokens) // 4))
        a.publish(tokens, p)
        a.release(p)
        return p

    def test_offload_hook_fires_batched_before_reuse(self):
        """A multi-page reclaim demotes its victims as ONE batch — a
        single hook call with every PageVictim (page_id, hash, depth,
        root) while the pages' content is still intact, i.e. before
        allocate() returns the recycled ids to their next owner."""
        a = PageAllocator(PCFG)
        calls = []
        a.offload_hook = lambda victims: calls.append(list(victims))
        pages = self._cache_one(a, list(range(8)))  # 2-page chain
        a.allocate(6)  # drain free list
        got = a.allocate(2)  # forces both evictions
        assert sorted(got) == sorted(pages)
        hashes = chain_hashes(list(range(8)), 4)
        assert len(calls) == 1  # one burst -> one hook call
        assert [(v.hash, v.depth) for v in calls[0]] == [(hashes[0], 0),
                                                         (hashes[1], 1)]
        assert [v.page_id for v in calls[0]] == pages
        assert all(v.root == hashes[0] for v in calls[0])

    def test_offload_hook_failure_degrades_to_drop(self):
        a = PageAllocator(PCFG)

        def boom(*args):
            raise RuntimeError("host OOM")

        a.offload_hook = boom
        self._cache_one(a, [1] * 4)
        a.allocate(7)
        a.allocate(1)  # eviction survives the hook failure
        assert a.stats().evictions == 1

    def test_evict_below_demote_flag(self):
        a = PageAllocator(PCFG)
        calls = []
        a.offload_hook = lambda *c: calls.append(c)
        self._cache_one(a, [1] * 4)
        self._cache_one(a, [2] * 4)
        a.evict_below(0.0, demote=False)  # severe rung: drop outright
        assert calls == []
        self._cache_one(a, [3] * 4)
        a.evict_below(0.0)  # default rung: demote
        assert len(calls) == 1

    def test_matched_then_released_chain_outlives_older_one(self):
        """Satellite regression (Property 11): match_prefix must refresh
        the matched chain's clock, so a just-matched-then-released chain
        is evicted AFTER an older untouched one."""
        a = PageAllocator(PCFG)
        p_old = self._cache_one(a, [1] * 4)  # older, never matched
        p_new = self._cache_one(a, [2] * 4)
        shared, _ = a.match_prefix([2] * 4)  # touch the newer chain
        assert shared == p_new
        a.release(shared)
        a.allocate(6)  # drain free list
        assert a.allocate(1) == p_old  # untouched chain is the victim
        assert a.match_prefix([1] * 4) == ([], 0)
        s, m = a.match_prefix([2] * 4)
        assert m == 4  # matched chain survived


# ---------------------------------------------------------------------------
# Engine-level offload → reload (token identity, abort race, rungs)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def make_engine(tiny_params, host_tier_bytes=0, host_tier_quant="none",
                num_pages=10):
    return LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=2,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(
                num_pages=num_pages, page_size=PS, max_pages_per_seq=8
            ),
            host_tier_bytes=host_tier_bytes,
            host_tier_quant=host_tier_quant,
        ),
        dtype=jnp.float32,
    )


def run_one(engine, rid, prompt, max_tokens=6):
    engine.add_request(rid, prompt, SamplingParams(max_tokens=max_tokens,
                                                   temperature=0.0))
    tokens = []
    for _ in range(500):
        if not engine.has_work():
            break
        for out in engine.step():
            if out.token_id is not None:
                tokens.append(out.token_id)
            assert out.error is None, out.error
    assert not engine.has_work()
    return tokens


PREFIX = list(range(40, 60))  # 5 full pages
RNG = np.random.default_rng(3)


def churn(engine, n=6):
    """Unique 2-page prompts that cycle the 10-page pool past PREFIX."""
    for i in range(n):
        run_one(engine, f"churn{i}{id(engine)}",
                RNG.integers(100, 200, size=7).tolist(), max_tokens=2)


@pytest.mark.parametrize("quant", ["none", "int8"])
def test_offload_reload_token_identity(tiny_params, quant):
    """Greedy decode on a prompt whose prefix went HBM → host tier →
    back must be token-identical to a cold engine (f32 tier exactly;
    int8 asserts the same on this fixture — per-vector absmax over a
    4-token page keeps argmax stable on the tiny model)."""
    cold = make_engine(tiny_params)
    prompt = PREFIX + [7, 8]
    want = run_one(cold, "cold", prompt)

    eng = make_engine(tiny_params, host_tier_bytes=1 << 22,
                      host_tier_quant=quant)
    run_one(eng, "warm", prompt)  # populate the HBM prefix cache
    churn(eng)  # cycle the pool: prefix demotes to the host tier
    host0 = eng.host_tier_stats()
    assert host0["pages"] + len(eng.host_tier._inflight) > 0
    got = run_one(eng, "probe", prompt)
    host1 = eng.host_tier_stats()
    assert host1["hit_pages"] > 0, "probe did not reload from host tier"
    assert eng.drain_reload_durations(), "reload duration not recorded"
    assert got == want


def test_reload_reseats_into_hbm(tiny_params):
    """A host-tier reload re-publishes the pages: the NEXT probe hits
    them in HBM directly (no second reload)."""
    eng = make_engine(tiny_params, host_tier_bytes=1 << 22)
    prompt = PREFIX + [7, 8]
    run_one(eng, "warm", prompt)
    churn(eng)
    run_one(eng, "p1", prompt)
    hit_pages = eng.host_tier_stats()["hit_pages"]
    assert hit_pages > 0
    s0 = eng.cache_stats()
    run_one(eng, "p2", PREFIX + [9, 10])
    assert eng.cache_stats().hits > s0.hits  # HBM hit this time
    assert eng.host_tier_stats()["hit_pages"] == hit_pages  # no reload


def test_exact_rematch_counts_only_kept_pages(tiny_params):
    """Exact re-submission of a page-aligned prompt: the final page is
    never kept (>= 1 token is always recomputed), so it must not be
    counted as a prefix hit either — the hit counters feed
    kv_prefix_hits_total{tier=hbm} and must report pages actually
    re-used."""
    eng = make_engine(tiny_params)
    run_one(eng, "a", PREFIX, max_tokens=2)  # publish the 5-page chain
    s0 = eng.cache_stats()
    run_one(eng, "b", PREFIX, max_tokens=2)
    assert eng.cache_stats().hits - s0.hits == len(PREFIX) // PS - 1


def test_abort_races_reload(tiny_params):
    """Abort around the reload path: aborting a queued request before
    its prefill, and aborting right after the first token (pages
    released while freshly re-seated), must leak nothing — the prompt
    still completes correctly afterwards."""
    cold = make_engine(tiny_params)
    prompt = PREFIX + [7, 8]
    want = run_one(cold, "cold", prompt)

    eng = make_engine(tiny_params, host_tier_bytes=1 << 22)
    run_one(eng, "warm", prompt)
    churn(eng)
    # abort while queued: no step ran, nothing reloaded or leaked
    eng.add_request("a0", prompt, SamplingParams(max_tokens=4,
                                                 temperature=0.0))
    assert eng.abort("a0")
    assert not eng.has_work()
    # abort after the first step: prefill reloaded host pages and
    # re-seated them; releasing keeps them cached, not leaked
    eng.add_request("a1", prompt, SamplingParams(max_tokens=4,
                                                 temperature=0.0))
    eng.step()
    assert eng.abort("a1")
    assert not eng.has_work()
    s = eng.cache_stats()
    assert s.pages_free + s.pages_cached == s.pages_total  # nothing pinned
    assert run_one(eng, "after", prompt) == want


def test_degradation_rungs_demote_vs_drop(tiny_params):
    """Engine rungs: AGGRESSIVE eviction demotes HBM pages into the
    host tier; the EMERGENCY rung drops the host tier too."""
    eng = make_engine(tiny_params, host_tier_bytes=1 << 22)
    run_one(eng, "warm", PREFIX + [7, 8])
    assert eng.cache_stats().pages_cached > 0
    eng.evict_cache(0.0)  # AGGRESSIVE_CACHE_EVICTION rung
    eng.host_tier.flush()
    assert eng.cache_stats().pages_cached == 0
    assert eng.host_tier_stats()["pages"] > 0  # demoted, not dropped
    eng.evict_cache(0.0, drop_host_tier=True)  # EMERGENCY rung
    assert eng.host_tier_stats()["pages"] == 0


class _RungRecorder:
    engine_id = "e0"

    def __init__(self):
        self.calls = []

    def evict_cache(self, target_frac, drop_host_tier=False):
        self.calls.append((round(target_frac, 2), drop_host_tier))


def test_controller_rungs_route_drop_flag():
    """Ladder wiring: AGGRESSIVE_CACHE_EVICTION evicts with
    drop_host_tier=False (demote), EMERGENCY with True (host RAM is the
    next thing to run out)."""
    from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
    from distributed_inference_server_tpu.serving.scheduler import (
        AdaptiveScheduler,
    )

    sched = AdaptiveScheduler()
    rec = _RungRecorder()
    sched._engines["e0"] = rec
    ctl = DegradationController(Dispatcher(sched), sched)
    ctl.evaluate(pressure=0.85)
    assert ctl.level == DegradationLevel.AGGRESSIVE_CACHE_EVICTION
    assert rec.calls == [(0.7, False)]
    ctl.evaluate(pressure=0.99)
    assert ctl.level == DegradationLevel.EMERGENCY
    assert rec.calls[-1] == (0.7, True)
    ctl.evaluate(pressure=0.0)  # recovery: no further evictions
    assert len(rec.calls) == 2


# ---------------------------------------------------------------------------
# Scheduler: cache_aware strategy + rebalanced memory_aware (satellites)
# ---------------------------------------------------------------------------


def _status(eid, healthy=True, active=0, waiting=0, used=0, total=100,
            cached=0, digest=None, page_size=PS, role="unified"):
    return EngineStatus(
        engine_id=eid, healthy=healthy, active_requests=active,
        waiting_requests=waiting, total_processed=0,
        memory_used_pages=used, memory_total_pages=total,
        pages_cached=cached, prefix_digest=digest, page_size=page_size,
        role=role,
    )


PROMPT = list(range(32))  # 8 full pages
HASHES = chain_hashes(PROMPT, PS)


class TestCacheAwareRouting:
    def test_prefix_match_depth_consecutive_from_head(self):
        full = _status("e", digest=frozenset(HASHES))
        assert prefix_match_depth(full, HASHES) == len(HASHES)
        # a hole ends the match even if deeper hashes are present
        holed = _status("e", digest=frozenset(HASHES[:2] + HASHES[3:]))
        assert prefix_match_depth(holed, HASHES) == 2
        assert prefix_match_depth(_status("e"), HASHES) == 0
        assert prefix_match_depth(full, None) == 0

    def test_deepest_match_wins_over_load(self):
        statuses = [
            _status("deep", active=5, digest=frozenset(HASHES[:4])),
            _status("shallow", active=0, digest=frozenset(HASHES[:1])),
        ]
        assert choose_engine(SchedulingStrategy.CACHE_AWARE, statuses, 0,
                             prefix_hashes=HASHES) == "deep"

    def test_tie_breaks_load_then_id(self):
        statuses = [
            _status("busy", active=3, digest=frozenset(HASHES[:2])),
            _status("idle", active=1, digest=frozenset(HASHES[:2])),
        ]
        assert choose_engine(SchedulingStrategy.CACHE_AWARE, statuses, 0,
                             prefix_hashes=HASHES) == "idle"
        statuses = [
            _status("b", active=1, digest=frozenset(HASHES[:2])),
            _status("a", active=1, digest=frozenset(HASHES[:2])),
        ]
        assert choose_engine(SchedulingStrategy.CACHE_AWARE, statuses, 0,
                             prefix_hashes=HASHES) == "a"

    def test_no_match_degrades_to_least_loaded(self):
        statuses = [
            _status("e0", active=4),
            _status("e1", active=1, digest=frozenset({123456})),
        ]
        got = choose_engine(SchedulingStrategy.CACHE_AWARE, statuses, 0,
                            prefix_hashes=HASHES)
        assert got == choose_engine(SchedulingStrategy.LEAST_LOADED,
                                    statuses, 0) == "e1"

    def test_composes_with_disagg_roles(self):
        """The warm engine is picked among prefill/unified candidates; a
        warm DECODE engine is invisible to admission routing."""
        statuses = [
            _status("decode-warm", digest=frozenset(HASHES), role="decode"),
            _status("prefill-cold", role="prefill"),
        ]
        assert choose_engine(
            SchedulingStrategy.CACHE_AWARE, statuses, 0,
            roles=("prefill", "unified"), prefix_hashes=HASHES,
        ) == "prefill-cold"

    def test_unhealthy_excluded(self):
        statuses = [
            _status("warm-down", healthy=False, digest=frozenset(HASHES)),
            _status("cold-up"),
        ]
        assert choose_engine(SchedulingStrategy.CACHE_AWARE, statuses, 0,
                             prefix_hashes=HASHES) == "cold-up"


class TestMemoryAwareCachedPages:
    def test_cached_pages_count_as_free(self):
        """Satellite: a pool full of reclaimable cache is effectively
        free — memory_aware scores on used - cached."""
        statuses = [
            _status("cachey", used=90, cached=80),  # live 10
            _status("lively", used=40, cached=0),  # live 40
        ]
        assert choose_engine(SchedulingStrategy.MEMORY_AWARE, statuses,
                             0) == "cachey"

    def test_tie_break_order_pinned(self):
        """Effective-free ties break on load, then engine_id — in that
        order."""
        statuses = [
            _status("b", used=50, cached=30, active=2),  # live 20
            _status("a", used=20, cached=0, active=1),  # live 20
        ]
        assert choose_engine(SchedulingStrategy.MEMORY_AWARE, statuses,
                             0) == "a"  # load breaks the tie
        statuses = [
            _status("b", used=20, active=1),
            _status("a", used=20, active=1),
        ]
        assert choose_engine(SchedulingStrategy.MEMORY_AWARE, statuses,
                             0) == "a"  # id breaks the tie


# ---------------------------------------------------------------------------
# Injected host-copy failure (docs/RESILIENCE.md kv.host_copy)
# ---------------------------------------------------------------------------


class TestHostCopyFault:
    def test_injected_host_copy_drops_burst_at_hook_boundary(self):
        """An armed ``kv.host_copy`` makes the demotion offer raise
        before it mutates anything: the allocator's offload-hook
        boundary absorbs it (eviction itself never fails), the dropped
        burst leaves the tier untouched, and the next burst demotes
        normally once the fault is spent."""
        from distributed_inference_server_tpu.serving import faults

        t = HostTier(budget_bytes=1 << 20)
        a = PageAllocator(PagedCacheConfig(
            num_pages=1, page_size=1, max_pages_per_seq=1))
        a.offload_hook = lambda victims: t.offer(
            [(v.hash, v.depth, v.root) for v in victims], _KIND_RAW,
            _page(1.0), page_size=1)
        p = a.allocate(1)
        a.publish([5], p)
        a.release(p)  # published page parks in LRU, demotable
        faults.install(faults.parse_spec("kv.host_copy:nth=1", seed=2))
        try:
            p2 = a.allocate(1)  # evicts the page -> hook -> injected fault
        finally:
            faults.clear()
        assert p2 == p  # eviction degraded to a plain drop, never failed
        assert t.empty and t.offloads == 0
        a.publish([7], p2)
        a.release(p2)
        p3 = a.allocate(1)  # fault spent: this burst demotes for real
        assert p3 == p
        assert t.offloads == 1
