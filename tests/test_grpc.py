"""gRPC transport tests (S1's optional second API surface): the JSON-
over-gRPC service shares the HTTP spine's handler, so generation,
streaming, chat, embeddings, health, and the error-status mapping are
exercised end-to-end over a real insecure channel."""

from __future__ import annotations

import asyncio

import grpc
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.engine.engine import EngineConfig
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.grpc_server import (
    GrpcClient,
    build_grpc_server,
)
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=192, page_size=8, max_pages_per_seq=32)


def _factory():
    import jax

    from distributed_inference_server_tpu.engine.engine import LLMEngine

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(max_batch=4, prefill_buckets=(16, 64), paged=_PAGED),
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def server():
    srv = InferenceServer(
        _factory, ByteTokenizer(), model_name="tiny-grpc",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        gsrv = build_grpc_server(server.handler)
        await gsrv.start()
        client = GrpcClient(f"127.0.0.1:{gsrv.bound_port}")
        try:
            return await coro_fn(client)
        finally:
            await client.close()
            await gsrv.stop(grace=1.0)

    return asyncio.run(main())


def test_generate_unary(server):
    async def go(client):
        resp = await client.generate(
            {"prompt": "hello grpc", "max_tokens": 6, "temperature": 0.0}
        )
        assert resp["object"] == "text_completion"
        assert resp["usage"]["completion_tokens"] == 6
        assert resp["choices"][0]["finish_reason"] == "length"
    _run(server, go)


def test_generate_stream(server):
    async def go(client):
        events = []
        async for e in client.generate_stream(
            {"prompt": "stream over grpc", "max_tokens": 5,
             "temperature": 0.0}
        ):
            events.append(e)
        kinds = [e["type"] for e in events]
        assert kinds.count("token") >= 5
        assert kinds[-1] == "done"
        assert events[-1]["usage"]["completion_tokens"] == 5
    _run(server, go)


def test_chat_and_embeddings(server):
    async def go(client):
        chat = await client.chat({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 4, "temperature": 0.0,
        })
        assert chat["object"] == "chat.completion"
        emb = await client.embeddings({"input": ["one", "two"]})
        assert len(emb["data"]) == 2
        assert len(emb["data"][0]["embedding"]) == TINY.hidden_size
    _run(server, go)


def test_health(server):
    async def go(client):
        h = await client.health()
        assert h["status"] == "ok"
        assert h["engines"][0]["healthy"]
    _run(server, go)


def test_validation_error_maps_to_invalid_argument(server):
    async def go(client):
        with pytest.raises(grpc.aio.AioRpcError) as exc:
            await client.generate({"max_tokens": 4})  # no prompt
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "invalid_request_error" in exc.value.details()
    _run(server, go)


def test_malformed_payload_rejected(server):
    async def go(client):
        raw = client._channel.unary_unary(
            "/dis.tpu.InferenceService/Generate",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        with pytest.raises(grpc.aio.AioRpcError) as exc:
            await raw(b"not json")
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    _run(server, go)


def test_stream_cancel_aborts_generation(server):
    async def go(client):
        call = client.generate_stream(
            {"prompt": "cancel me", "max_tokens": 4000,
             "temperature": 0.0}
        )
        got = 0
        async for _ in call:
            got += 1
            if got >= 2:
                call.cancel()
                break
        # the request leaves the engines; poll (the abort propagates
        # through the dispatcher to the runner thread asynchronously,
        # and a loaded machine can take a while)
        deadline = asyncio.get_running_loop().time() + 30.0
        while True:
            statuses = server.handler.dispatcher.scheduler.statuses()
            if sum(s.active_requests for s in statuses) == 0:
                break
            assert asyncio.get_running_loop().time() < deadline, (
                "request still active after cancel")
            await asyncio.sleep(0.1)
    _run(server, go)


def _run_wire(server, coro_fn, wire):
    async def main():
        gsrv = build_grpc_server(server.handler)
        await gsrv.start()
        client = GrpcClient(f"127.0.0.1:{gsrv.bound_port}", wire=wire)
        try:
            return await coro_fn(client)
        finally:
            await client.close()
            await gsrv.stop(grace=1.0)

    return asyncio.run(main())


class TestProtobufWire:
    """Protobuf-binary wire (VERDICT r3 next #5): the same methods speak
    the inference.proto binary encoding, auto-detected per request, and
    produce payloads identical to the JSON wire."""

    def test_generate_roundtrip_proto(self, server):
        async def go(client):
            resp = await client.generate(
                {"prompt": "proto wire", "max_tokens": 5,
                 "temperature": 0.0}
            )
            assert resp["object"] == "text_completion"
            assert resp["usage"]["completion_tokens"] == 5
            assert resp["choices"][0]["finish_reason"] == "length"

        _run_wire(server, go, "proto")

    def test_generate_stream_proto(self, server):
        async def go(client):
            events = []
            async for e in client.generate_stream(
                {"prompt": "stream proto", "max_tokens": 4,
                 "temperature": 0.0}
            ):
                events.append(e)
            kinds = [e["type"] for e in events]
            assert kinds.count("token") >= 4
            assert kinds[-1] == "done"
            assert events[-1]["usage"]["completion_tokens"] == 4
            # sampled tokens carry logprobs through the proto wire
            # (held-back-text flushes legitimately ride without one)
            assert any(
                e.get("logprob") is not None
                for e in events if e["type"] == "token"
            )

        _run_wire(server, go, "proto")

    def test_chat_embeddings_health_proto(self, server):
        async def go(client):
            chat = await client.chat({
                "messages": [{"role": "user", "content": "hi"},
                             {"role": "system", "content": "brief"}],
                "max_tokens": 3, "temperature": 0.0,
            })
            assert chat["object"] == "chat.completion"
            assert chat["choices"][0]["message"]["role"] == "assistant"
            emb = await client.embeddings({"input": ["one", "two"]})
            assert len(emb["data"]) == 2
            assert len(emb["data"][0]["embedding"]) == TINY.hidden_size
            h = await client.health()
            assert h["status"] == "ok"
            assert h["engines"][0]["healthy"] is True

        _run_wire(server, go, "proto")

    def test_differential_json_vs_proto(self, server):
        """The SAME greedy request over both wires produces identical
        payloads (modulo the per-request id and created timestamp)."""
        req = {"prompt": "differential", "max_tokens": 6,
               "temperature": 0.0}

        async def go_json(client):
            return await client.generate(dict(req))

        async def go_proto(client):
            return await client.generate(dict(req))

        a = _run_wire(server, go_json, "json")
        b = _run_wire(server, go_proto, "proto")
        for d in (a, b):
            d.pop("id")
            d.pop("created")
        assert a == b

    def test_proto_temperature_zero_distinct_from_absent(self, server):
        """Explicit temperature=0 (greedy) survives the proto wire; an
        absent field takes the server default — proto3 optional
        presence, not implicit zero."""

        async def go(client):
            greedy1 = await client.generate(
                {"prompt": "presence", "max_tokens": 5,
                 "temperature": 0.0})
            greedy2 = await client.generate(
                {"prompt": "presence", "max_tokens": 5,
                 "temperature": 0.0})
            # greedy is deterministic: identical text both times
            assert greedy1["choices"][0]["text"] == \
                greedy2["choices"][0]["text"]
            # absent temperature -> the server default applies (the
            # request validates and generates; implicit-presence zero
            # would ALSO be valid, but absent max_tokens proves
            # presence: 0 max_tokens would be rejected, absent takes
            # the 256 default -> validator accepts)
            some = await client.generate(
                {"prompt": "presence", "max_tokens": 4})
            assert 1 <= some["usage"]["completion_tokens"] <= 4

        _run_wire(server, go, "proto")


def test_proto_contract_is_protoc_valid():
    """serving/inference.proto is the authoritative gRPC contract doc
    (VERDICT r2 weak #5); it must exist, name every method the generic
    handlers register, and compile under protoc when available."""
    import os
    import shutil
    import subprocess

    from distributed_inference_server_tpu.serving import grpc_server

    proto = os.path.join(
        os.path.dirname(grpc_server.__file__), "inference.proto"
    )
    assert os.path.exists(proto)
    text = open(proto).read()
    assert "package dis.tpu;" in text  # matches SERVICE constant
    assert grpc_server.SERVICE == "dis.tpu.InferenceService"
    for method in ("Generate", "GenerateStream", "Chat", "ChatStream",
                   "Embeddings", "Health"):
        assert f"rpc {method}(" in text, method
    protoc = shutil.which("protoc")
    if protoc:
        subprocess.run(
            [protoc, "--proto_path", os.path.dirname(proto),
             "--descriptor_set_out", os.devnull, "inference.proto"],
            check=True,
        )
