"""Conformance tests for request validation.

Ports the reference's validator property suite
(``crates/core/src/validator.rs:233-435``): valid-accepted, empty-rejected,
out-of-range-rejected with field-name assertions, oversized-rejected, and
token-count monotonicity — **Properties 1-3** (design.md:686-701).
"""

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core import (
    ChatMessage,
    ChatRequest,
    EmbeddingsRequest,
    EmptyPrompt,
    GenerateRequest,
    InvalidParameter,
    MissingField,
    RequestValidator,
    Role,
    TokenLimitExceeded,
    ValidatorConfig,
)

CASES = settings(max_examples=100, deadline=None)
V = RequestValidator()

# valid-input generators (mirroring validator.rs:243-302)
valid_prompt = st.text(min_size=1, max_size=1000).filter(lambda s: s.strip())
valid_temperature = st.floats(min_value=0.0, max_value=2.0, allow_nan=False)
valid_top_p = st.floats(min_value=0.0, max_value=1.0, allow_nan=False)
valid_max_tokens = st.integers(min_value=0, max_value=4096)

# adversarial generators (validator.rs:305-330)
blank_prompt = st.sampled_from(["", " ", "\t", "\n", "   \n\t "])
bad_temperature = st.one_of(
    st.floats(min_value=2.0001, max_value=100.0, allow_nan=False),
    st.floats(min_value=-100.0, max_value=-0.0001, allow_nan=False),
)
bad_top_p = st.one_of(
    st.floats(min_value=1.0001, max_value=100.0, allow_nan=False),
    st.floats(min_value=-100.0, max_value=-0.0001, allow_nan=False),
)
oversized_prompt = st.integers(min_value=35_000, max_value=40_000).map(
    lambda n: "x" * n
)


# -- Property 1: valid request acceptance ------------------------------------


@CASES
@given(
    prompt=valid_prompt,
    max_tokens=valid_max_tokens,
    temperature=valid_temperature,
    top_p=valid_top_p,
)
def test_valid_generate_accepted(prompt, max_tokens, temperature, top_p):
    req = GenerateRequest(
        prompt=prompt, max_tokens=max_tokens, temperature=temperature, top_p=top_p
    )
    validated = V.validate_generate(req)
    assert validated.into_inner() is req


# -- Property 2: invalid request rejection ----------------------------------


@CASES
@given(prompt=blank_prompt)
def test_empty_prompt_rejected(prompt):
    with pytest.raises(EmptyPrompt):
        V.validate_generate(GenerateRequest(prompt=prompt))


@CASES
@given(prompt=valid_prompt, temperature=bad_temperature)
def test_bad_temperature_rejected_with_field_name(prompt, temperature):
    with pytest.raises(InvalidParameter) as e:
        V.validate_generate(GenerateRequest(prompt=prompt, temperature=temperature))
    assert e.value.field == "temperature"  # field-name assertion (validator.rs:377-383)


@CASES
@given(prompt=valid_prompt, top_p=bad_top_p)
def test_bad_top_p_rejected_with_field_name(prompt, top_p):
    with pytest.raises(InvalidParameter) as e:
        V.validate_generate(GenerateRequest(prompt=prompt, top_p=top_p))
    assert e.value.field == "top_p"


@CASES
@given(prompt=valid_prompt, max_tokens=st.integers(min_value=4097, max_value=100_000))
def test_excess_max_tokens_rejected(prompt, max_tokens):
    with pytest.raises(InvalidParameter) as e:
        V.validate_generate(GenerateRequest(prompt=prompt, max_tokens=max_tokens))
    assert e.value.field == "max_tokens"


@CASES
@given(prompt=valid_prompt, max_tokens=st.integers(min_value=-100_000, max_value=-1))
def test_negative_max_tokens_rejected(prompt, max_tokens):
    # unrepresentable in the reference (usize); must be rejected here
    with pytest.raises(InvalidParameter) as e:
        V.validate_generate(GenerateRequest(prompt=prompt, max_tokens=max_tokens))
    assert e.value.field == "max_tokens"


# -- Property 3: token limit enforcement ------------------------------------


@CASES
@given(prompt=oversized_prompt)
def test_oversized_prompt_rejected(prompt):
    with pytest.raises(TokenLimitExceeded) as e:
        V.validate_generate(GenerateRequest(prompt=prompt))
    assert e.value.actual > e.value.limit
    assert e.value.limit == 8192


@CASES
@given(a=st.text(max_size=500), b=st.text(max_size=500))
def test_token_count_monotonic(a, b):
    # token_count(a + b) >= token_count(a) (validator.rs:422-433)
    assert V.token_count(a + b) >= V.token_count(a)
    assert V.token_count(a) == (0 if not a else (len(a) + 3) // 4)


# -- chat validation (validator.rs:129-154) ---------------------------------


def test_chat_empty_messages_rejected():
    with pytest.raises(MissingField) as e:
        V.validate_chat(ChatRequest(messages=[]))
    assert e.value.field == "messages"


def test_chat_all_blank_messages_rejected():
    req = ChatRequest(
        messages=[
            ChatMessage(Role.USER, "  "),
            ChatMessage(Role.ASSISTANT, "\n"),
        ]
    )
    with pytest.raises(EmptyPrompt):
        V.validate_chat(req)


@CASES
@given(contents=st.lists(valid_prompt, min_size=1, max_size=5))
def test_chat_token_sum(contents):
    req = ChatRequest(messages=[ChatMessage(Role.USER, c) for c in contents])
    total = sum(V.token_count(c) for c in contents)
    if total > 8192:
        with pytest.raises(TokenLimitExceeded):
            V.validate_chat(req)
    else:
        V.validate_chat(req)


def test_chat_oversized_total_rejected():
    msgs = [ChatMessage(Role.USER, "y" * 20_000), ChatMessage(Role.USER, "z" * 20_000)]
    with pytest.raises(TokenLimitExceeded):
        V.validate_chat(ChatRequest(messages=msgs))


# -- embeddings validation (validator.rs:195-225) ---------------------------


def test_embeddings_empty_list_rejected():
    with pytest.raises(MissingField):
        V.validate_embeddings(EmbeddingsRequest(input=[]))


def test_embeddings_blank_item_rejected_with_index():
    with pytest.raises(InvalidParameter) as e:
        V.validate_embeddings(EmbeddingsRequest(input=["ok", "  "]))
    assert e.value.field == "input[1]"


def test_embeddings_oversized_item_rejected():
    with pytest.raises(TokenLimitExceeded):
        V.validate_embeddings(EmbeddingsRequest(input=["x" * 40_000]))


@CASES
@given(inputs=st.lists(valid_prompt.filter(lambda s: len(s) < 1000), min_size=1, max_size=4))
def test_embeddings_valid_accepted(inputs):
    validated = V.validate_embeddings(EmbeddingsRequest(input=inputs))
    assert validated.into_inner().input_list() == inputs


# -- custom config ----------------------------------------------------------


def test_custom_config_limits():
    v = RequestValidator(ValidatorConfig(max_context_tokens=10, max_output_tokens=5))
    with pytest.raises(TokenLimitExceeded):
        v.validate_generate(GenerateRequest(prompt="x" * 100))
    with pytest.raises(InvalidParameter):
        v.validate_generate(GenerateRequest(prompt="hi", max_tokens=6))
    v.validate_generate(GenerateRequest(prompt="hi", max_tokens=5))
