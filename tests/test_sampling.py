"""Sampling op tests: greedy/temperature/top-p semantics on device."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.ops.sampling import (
    nucleus_cutoff,
    sample_tokens,
    top_p_filter_probs,
)


def _sorted_reference_kept(probs: np.ndarray, top_p: np.ndarray) -> np.ndarray:
    """The classic sort-based nucleus kept-mask: smallest descending prefix
    reaching top_p, extended to boundary-value ties, argmax always kept."""
    B, V = probs.shape
    kept = np.zeros((B, V), bool)
    for b in range(B):
        order = np.argsort(-probs[b], kind="stable")
        cum = np.cumsum(probs[b][order])
        keep_sorted = (cum - probs[b][order]) < top_p[b]
        keep_sorted[0] = True
        cutoff = probs[b][order][keep_sorted].min()
        kept[b] = probs[b] >= cutoff
    return kept


def test_zero_temperature_is_argmax():
    rng = jax.random.PRNGKey(0)
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 50)), jnp.float32)
    out = sample_tokens(rng, logits, jnp.zeros((4,)), jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(out), np.argmax(np.asarray(logits), -1))


def test_top_p_restricts_support():
    # one dominant token (prob ~0.97): top_p=0.5 must always pick it
    logits = jnp.asarray([[10.0, 2.0, 1.0, 0.0]] * 3, jnp.float32)
    for seed in range(5):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((3,)), jnp.full((3,), 0.5)
        )
        assert np.all(np.asarray(out) == 0)


def test_top_p_one_samples_full_distribution():
    # uniform logits, top_p=1: over many draws every token should appear
    logits = jnp.zeros((1, 4), jnp.float32)
    seen = set()
    for seed in range(64):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((1,)), jnp.ones((1,))
        )
        seen.add(int(out[0]))
    assert seen == {0, 1, 2, 3}


def test_top_p_zero_degrades_to_greedy():
    # top_p=0 is admitted by the validator (min_top_p=0.0); the top-1 token
    # must always stay in the nucleus
    logits = jnp.asarray([[1.0, 5.0, 2.0, 0.0]], jnp.float32)
    for seed in range(5):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((1,)), jnp.zeros((1,))
        )
        assert int(out[0]) == 1


def test_per_row_mixed_settings():
    logits = jnp.asarray(
        [[5.0, 0.0, 0.0, 0.0], [0.0, 5.0, 0.0, 0.0]], jnp.float32
    )
    out = sample_tokens(
        jax.random.PRNGKey(1),
        logits,
        jnp.asarray([0.0, 1.0]),  # row0 greedy, row1 sampled
        jnp.asarray([1.0, 0.3]),  # row1 nucleus keeps only token 1
    )
    assert int(out[0]) == 0
    assert int(out[1]) == 1


def test_nucleus_cutoff_matches_sorted_reference():
    """The binary-search cutoff keeps exactly the sorted-prefix nucleus
    (random rows are far from the 2^-26 threshold-resolution edge case)."""
    rng = np.random.default_rng(7)
    for trial in range(5):
        logits = rng.normal(scale=3.0, size=(8, 997)).astype(np.float32)
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        top_p = np.asarray([0.0, 0.1, 0.3, 0.5, 0.7, 0.9, 0.99, 1.0],
                           np.float32)
        cut = np.asarray(nucleus_cutoff(jnp.asarray(probs),
                                        jnp.asarray(top_p)))
        kept = probs >= cut
        ref = _sorted_reference_kept(probs, top_p)
        # top_p=1 compares separately: the sorted rule's f32 cumsum
        # saturates at 1.0 a few (~1e-8 prob) tail tokens early, while
        # the threshold rule correctly keeps the entire vocabulary
        np.testing.assert_array_equal(kept[:-1], ref[:-1])
        assert kept[-1].all()


def test_top_p_filter_probs_keeps_mass_and_argmax():
    rng = np.random.default_rng(3)
    probs = jax.nn.softmax(
        jnp.asarray(rng.normal(scale=2.0, size=(6, 301)), jnp.float32), -1
    )
    top_p = jnp.asarray([0.2, 0.5, 0.8, 0.95, 1.0, 0.0], jnp.float32)
    f = np.asarray(top_p_filter_probs(probs, top_p))
    p = np.asarray(probs)
    # kept mass reaches the threshold; argmax always kept
    assert (f.sum(-1) >= np.minimum(np.asarray(top_p), p.sum(-1)) - 1e-6).all()
    assert (f[np.arange(6), p.argmax(-1)] > 0).all()
    # top_p=1 keeps everything; top_p=0 keeps only argmax-tied tokens
    np.testing.assert_array_equal(f[4] > 0, p[4] > 0)
    assert (f[5] > 0).sum() == (p[5] == p[5].max()).sum()


def test_use_topp_false_matches_topp_one():
    """With every row at top_p=1, the compiled-out variant must sample the
    identical token for the same key (the nucleus is a no-op there)."""
    rng = np.random.default_rng(11)
    logits = jnp.asarray(rng.normal(size=(5, 203)), jnp.float32)
    temp = jnp.asarray([0.0, 0.5, 1.0, 1.5, 2.0], jnp.float32)
    top_p = jnp.ones((5,), jnp.float32)
    for seed in range(4):
        key = jax.random.PRNGKey(seed)
        a = sample_tokens(key, logits, temp, top_p, use_topp=True)
        b = sample_tokens(key, logits, temp, top_p, use_topp=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=100, deadline=None)
@given(
    seed=st.integers(0, 2**31 - 1),
    vocab=st.integers(2, 400),
    top_p=st.floats(0.0, 0.999),
    scale=st.floats(0.1, 8.0),
)
def test_nucleus_cutoff_property_matches_sorted_rule(seed, vocab, top_p, scale):
    """Property (100 cases, SURVEY §4.2 style): for any distribution and
    any top_p < 1, the binary-search kept set equals the sorted-prefix
    nucleus extended to boundary ties. top_p=1.0 is excluded — there the
    sorted rule's own f32 cumsum saturation drops ~1e-8-mass tail tokens
    that the threshold rule correctly keeps (covered by the directed
    test above)."""
    rng = np.random.default_rng(seed)
    logits = rng.normal(scale=scale, size=(1, vocab)).astype(np.float32)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    tp = np.asarray([top_p], np.float32)
    cut = np.asarray(nucleus_cutoff(jnp.asarray(probs), jnp.asarray(tp)))
    kept = probs >= cut
    ref = _sorted_reference_kept(probs, tp)
    np.testing.assert_array_equal(kept, ref)
