"""Sampling op tests: greedy/temperature/top-p semantics on device."""

import numpy as np
import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.ops.sampling import sample_tokens


def test_zero_temperature_is_argmax():
    rng = jax.random.PRNGKey(0)
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 50)), jnp.float32)
    out = sample_tokens(rng, logits, jnp.zeros((4,)), jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(out), np.argmax(np.asarray(logits), -1))


def test_top_p_restricts_support():
    # one dominant token (prob ~0.97): top_p=0.5 must always pick it
    logits = jnp.asarray([[10.0, 2.0, 1.0, 0.0]] * 3, jnp.float32)
    for seed in range(5):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((3,)), jnp.full((3,), 0.5)
        )
        assert np.all(np.asarray(out) == 0)


def test_top_p_one_samples_full_distribution():
    # uniform logits, top_p=1: over many draws every token should appear
    logits = jnp.zeros((1, 4), jnp.float32)
    seen = set()
    for seed in range(64):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((1,)), jnp.ones((1,))
        )
        seen.add(int(out[0]))
    assert seen == {0, 1, 2, 3}


def test_top_p_zero_degrades_to_greedy():
    # top_p=0 is admitted by the validator (min_top_p=0.0); the top-1 token
    # must always stay in the nucleus
    logits = jnp.asarray([[1.0, 5.0, 2.0, 0.0]], jnp.float32)
    for seed in range(5):
        out = sample_tokens(
            jax.random.PRNGKey(seed), logits, jnp.ones((1,)), jnp.zeros((1,))
        )
        assert int(out[0]) == 1


def test_per_row_mixed_settings():
    logits = jnp.asarray(
        [[5.0, 0.0, 0.0, 0.0], [0.0, 5.0, 0.0, 0.0]], jnp.float32
    )
    out = sample_tokens(
        jax.random.PRNGKey(1),
        logits,
        jnp.asarray([0.0, 1.0]),  # row0 greedy, row1 sampled
        jnp.asarray([1.0, 0.3]),  # row1 nucleus keeps only token 1
    )
    assert int(out[0]) == 0
    assert int(out[1]) == 1
