"""Ragged mixed-batch attention (ISSUE 12): the Pallas kernel
(ops/pallas/paged_attention.py:paged_attention_ragged) and the packed-token
XLA reference (ops/attention.py:ragged_gqa_attention) against each other
and against per-row gqa_attention ground truth — seeded ragged geometries,
page-boundary and chunk-boundary edges, empty-decode and empty-prefill
batches. Runs in Pallas interpret mode on the CPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.ops.attention import (
    gqa_attention,
    ragged_gqa_attention,
)
from distributed_inference_server_tpu.ops.pallas import paged_attention_ragged

PAGE = 8


def _make_case(seed, S, Bm, H, KV, D, P, q_lens, num_pages=64,
               history=None):
    """Random pool + packed ragged batch: row b contributes q_lens[b] new
    tokens on top of ``history[b]`` resident ones (random when None)."""
    rng = np.random.default_rng(seed)
    pool_k = rng.standard_normal((num_pages * PAGE, KV, D)).astype(np.float32)
    pool_v = rng.standard_normal((num_pages * PAGE, KV, D)).astype(np.float32)
    q = rng.standard_normal((S, H, D)).astype(np.float32)
    tables = rng.permutation(num_pages)[: Bm * P].reshape(Bm, P)
    if history is None:
        history = [
            int(rng.integers(0, P * PAGE - ql + 1)) if ql else 0
            for ql in q_lens
        ]
    valid = np.array(
        [h + ql for h, ql in zip(history, q_lens)], np.int32
    )
    tok_row = np.full((S,), -1, np.int32)
    q_pos = np.zeros((S,), np.int32)
    off = 0
    for b, ql in enumerate(q_lens):
        tok_row[off:off + ql] = b
        q_pos[off:off + ql] = np.arange(history[b], history[b] + ql)
        off += ql
    return q, pool_k, pool_v, tables, tok_row, q_pos, valid


def _gathered(pk, pv, tables):
    Bm, P = tables.shape
    slots = (
        tables[:, :, None] * PAGE + np.arange(PAGE)[None, None, :]
    ).reshape(Bm, P * PAGE)
    return pk[slots], pv[slots]


def _reference(q, pk, pv, tables, tok_row, q_pos, valid, **kw):
    k_seq, v_seq = _gathered(pk, pv, tables)
    return ragged_gqa_attention(
        jnp.asarray(q), jnp.asarray(k_seq), jnp.asarray(v_seq),
        jnp.asarray(tok_row), jnp.asarray(q_pos), jnp.asarray(valid), **kw
    )


def _kernel(q, pk, pv, tables, tok_row, q_pos, valid, q_block=8, **kw):
    return paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
        jnp.asarray(tables), jnp.asarray(tok_row), jnp.asarray(q_pos),
        jnp.asarray(valid), page_size=PAGE, q_block=q_block,
        interpret=True, **kw,
    )


def _assert_match(got, want, tok_row, rtol=2e-5, atol=2e-5):
    m = tok_row >= 0  # padding outputs are garbage by contract
    np.testing.assert_allclose(
        np.asarray(got)[m], np.asarray(want)[m], rtol=rtol, atol=atol
    )


class TestRaggedReference:
    """ragged_gqa_attention vs per-row gqa_attention ground truth."""

    def test_matches_per_row_gqa(self):
        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            0, 16, 3, 8, 4, 16, 4, [1, 10, 4]
        )
        k_seq, v_seq = _gathered(pk, pv, tables)
        got = np.asarray(_reference(q, pk, pv, tables, tok_row, q_pos,
                                    valid))
        # ground truth: run each row alone through gqa_attention
        off = 0
        for b, ql in enumerate([1, 10, 4]):
            want = gqa_attention(
                jnp.asarray(q[off:off + ql])[None],
                jnp.asarray(k_seq[b])[None], jnp.asarray(v_seq[b])[None],
                jnp.asarray(q_pos[off:off + ql])[None],
                jnp.asarray(valid[b:b + 1]),
            )[0]
            np.testing.assert_allclose(
                got[off:off + ql], np.asarray(want), rtol=2e-5, atol=2e-5
            )
            off += ql


class TestRaggedKernelVsReference:
    @pytest.mark.parametrize(
        "S,Bm,H,KV,D,P,q_lens",
        [
            # decode rows packed next to one prefill chunk
            (16, 4, 8, 4, 16, 4, [1, 1, 1, 13]),
            # empty-prefill: every row is a decode token, padding tail
            (16, 6, 4, 2, 32, 3, [1, 1, 1, 1, 1, 1]),
            # empty-decode: chunks only, crossing window boundaries
            (32, 3, 8, 4, 16, 4, [9, 17, 2]),
            # one row exactly fills the window (boundary-aligned chunk)
            (8, 2, 16, 2, 64, 2, [8, 0]),
            # MHA-ish KV=8 with a mid-size chunk mix
            (24, 5, 8, 8, 16, 3, [3, 1, 8, 1, 5]),
        ],
    )
    def test_seeded_geometries(self, S, Bm, H, KV, D, P, q_lens):
        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            S * 31 + Bm, S, Bm, H, KV, D, P, q_lens
        )
        got = _kernel(q, pk, pv, tables, tok_row, q_pos, valid)
        want = _reference(q, pk, pv, tables, tok_row, q_pos, valid)
        _assert_match(got, want, tok_row)

    def test_fuzz_seeded_ragged_mixes(self):
        """Randomized q_len mixes (decode-heavy, chunk-heavy, partial
        budgets) across seeds — the mixed step's real workload shape."""
        for seed in range(6):
            rng = np.random.default_rng(100 + seed)
            S, P = 24, 4
            q_lens, left, Bm = [], S, 0
            while left > 0 and Bm < 8:
                ql = int(rng.integers(1, min(left, 9) + 1))
                if rng.random() < 0.5:
                    ql = 1  # decode-weighted
                q_lens.append(ql)
                left -= ql
                Bm += 1
            q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
                seed, S, Bm, 8, 4, 16, P, q_lens
            )
            got = _kernel(q, pk, pv, tables, tok_row, q_pos, valid)
            want = _reference(q, pk, pv, tables, tok_row, q_pos, valid)
            _assert_match(got, want, tok_row)

    def test_page_boundary_history(self):
        """Chunks starting exactly at page boundaries, and one token
        short of them — the ragged kv_valid edge the mask must honor."""
        for hist in ([PAGE, 2 * PAGE], [PAGE - 1, 2 * PAGE + 1]):
            q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
                7, 16, 2, 8, 4, 16, 4, [6, 10], history=hist
            )
            got = _kernel(q, pk, pv, tables, tok_row, q_pos, valid)
            want = _reference(q, pk, pv, tables, tok_row, q_pos, valid)
            _assert_match(got, want, tok_row)

    def test_sliding_window_and_softcap(self):
        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            11, 16, 3, 8, 4, 16, 4, [1, 10, 4]
        )
        got = _kernel(q, pk, pv, tables, tok_row, q_pos, valid,
                      sliding_window=7, attn_softcap=30.0)
        want = _reference(q, pk, pv, tables, tok_row, q_pos, valid,
                          sliding_window=7, attn_softcap=30.0)
        _assert_match(got, want, tok_row)

    def test_bf16_io(self):
        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            13, 16, 4, 8, 4, 16, 4, [1, 1, 1, 13]
        )
        got = _kernel(
            q.astype(jnp.bfloat16), pk.astype(jnp.bfloat16),
            pv.astype(jnp.bfloat16), tables, tok_row, q_pos, valid,
        )
        assert got.dtype == jnp.bfloat16
        want = _reference(q, pk, pv, tables, tok_row, q_pos, valid)
        _assert_match(np.asarray(got, np.float32), want, tok_row,
                      rtol=5e-2, atol=5e-2)

    def test_all_padding_batch(self):
        """A fully-padded packed batch (no work at all) must not crash;
        outputs are garbage by contract."""
        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            17, 8, 2, 8, 4, 16, 2, [0, 0]
        )
        out = _kernel(q, pk, pv, tables, tok_row, q_pos, valid)
        assert out.shape == q.shape

    def test_subsumes_decode_kernel_contract(self):
        """All-decode packed batch equals paged_attention_decode on the
        same pool — the ONE-kernel subsumption the mixed step relies on."""
        from distributed_inference_server_tpu.ops.pallas import (
            paged_attention_decode,
        )

        q, pk, pv, tables, tok_row, q_pos, valid = _make_case(
            19, 8, 8, 8, 4, 16, 3, [1] * 8
        )
        got = _kernel(q, pk, pv, tables, tok_row, q_pos, valid)
        want = paged_attention_decode(
            jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(tables), jnp.asarray(valid), page_size=PAGE,
            interpret=True,
        )
        # packed order == row order for an all-decode batch
        _assert_match(got, np.asarray(want), tok_row)
