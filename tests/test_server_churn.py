"""Server-level churn: concurrent generation traffic through runtime
replica scaling and model hot-swaps — no request may be lost or left
hanging; the control-plane operations and the data path compose.

The reference spec'd each of these capabilities separately
(requirements.md:110 scaling, :178-182 swap [spec]); churn is where
their interactions live."""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=16)
_PARAMS = {}


def _factory(seed: int):
    def make() -> LLMEngine:
        if seed not in _PARAMS:
            _PARAMS[seed] = llama.init_params(
                jax.random.PRNGKey(seed), TINY, dtype=jnp.float32
            )
        return LLMEngine(
            _PARAMS[seed], TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=_PAGED),
            dtype=jnp.float32,
        )

    return make


def _resolver(name: str):
    return _factory({"model-a": 0, "model-b": 5}[name])


@pytest.fixture(scope="module")
def server():
    srv = InferenceServer(
        _factory(0), ByteTokenizer(), model_name="model-a",
        num_engines=1, auto_restart=False, model_resolver=_resolver,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=10.0)


def test_traffic_through_scale_and_swap_churn(server):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            async def gen(i):
                resp = await client.post("/generate", json={
                    "prompt": f"churn request number {i}",
                    "max_tokens": 8, "temperature": 0.0,
                })
                body = await resp.json()
                return resp.status, body

            async def churn():
                # scale out, swap, scale in, swap back — while traffic runs
                r = await client.post("/admin/scale",
                                      json={"num_engines": 2})
                assert r.status == 200
                r = await client.post("/admin/model-swap",
                                      json={"model": "model-b"})
                assert r.status == 200, await r.json()
                r = await client.post("/admin/scale",
                                      json={"num_engines": 1})
                assert r.status == 200
                r = await client.post("/admin/model-swap",
                                      json={"model": "model-a"})
                assert r.status == 200, await r.json()
                return None

            results, _ = await asyncio.gather(
                asyncio.gather(*(gen(i) for i in range(16))),
                churn(),
            )
            # every request terminated with a definite outcome; requests
            # racing a drain may see a clean 5xx, but none hang or vanish
            ok = sum(1 for s, _ in results if s == 200)
            for status, body in results:
                assert status in (200, 500, 503), body
                if status == 200:
                    assert body["usage"]["completion_tokens"] == 8
            assert ok >= 12, f"only {ok}/16 served through churn"
            # fleet settled: healthy, one replica, correct model name
            h = await (await client.get("/health")).json()
            assert h["status"] == "ok"
            assert len(h["engines"]) == 1
        finally:
            await client.close()

    asyncio.run(main())


def test_sustained_traffic_leaves_no_residue(server):
    """Leak soak: hundreds of requests (mixed streaming/unary, some
    cancelled) leave no per-request residue in the handler span map,
    dispatcher queue, batcher, or engines."""

    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            async def one(i):
                if i % 3 == 0:
                    resp = await client.post("/generate", json={
                        "prompt": f"soak {i}", "max_tokens": 3,
                        "temperature": 0.0, "stream": True,
                    })
                    async for _ in resp.content:
                        pass
                    return 200
                resp = await client.post("/generate", json={
                    "prompt": f"soak {i}", "max_tokens": 3,
                    "temperature": 0.0,
                })
                await resp.read()
                return resp.status

            for wave in range(6):
                results = await asyncio.gather(
                    *(one(wave * 40 + i) for i in range(40))
                )
                assert all(s == 200 for s in results), results
            # residue checks — poll briefly: the last responses return to
            # clients a beat before the runner thread finishes its own
            # bookkeeping (and a prior test's swap may still be draining)
            h = server.handler
            d = h.dispatcher
            loop = asyncio.get_running_loop()
            deadline = loop.time() + 30.0

            def residue():
                if h._spans_by_request or d.queue.total_depth() \
                        or d.batcher.pending_count():
                    return True
                return any(
                    r.active_count() or r._engine.num_active()
                    or r._engine._by_id
                    for r in d.scheduler.engines()
                )

            while residue():
                assert loop.time() < deadline, "per-request residue"
                await asyncio.sleep(0.2)
        finally:
            await client.close()

    asyncio.run(main())


def test_degradation_engages_on_live_pressure_and_recovers():
    """E2E pressure cycle: live sequences pin most of a tiny pool, the
    ladder rises and sheds load; when they finish, it lifts and normal
    service resumes. Cached-prefix pages must NOT trigger the ladder
    (they are reclaimable on demand)."""
    from distributed_inference_server_tpu.serving.degradation import (
        DegradationLevel,
    )

    tiny_pool = PagedCacheConfig(num_pages=12, page_size=4,
                                 max_pages_per_seq=12)

    def factory():
        if 0 not in _PARAMS:
            _PARAMS[0] = llama.init_params(
                jax.random.PRNGKey(0), TINY, dtype=jnp.float32
            )
        return LLMEngine(
            _PARAMS[0], TINY, ByteTokenizer(),
            EngineConfig(max_batch=2, prefill_buckets=(16,),
                         paged=tiny_pool, decode_block_size=2),
            dtype=jnp.float32,
        )

    srv = InferenceServer(
        factory, ByteTokenizer(), model_name="tiny-pressure",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    try:
        async def main():
            client = TestClient(TestServer(srv.build_app()))
            await client.start_server()
            try:
                # two long generations pin ~10 of 12 pages for a while
                tasks = [asyncio.create_task(client.post(
                    "/generate", json={
                        "prompt": "p" * 14, "max_tokens": 24,
                        "temperature": 0.0,
                    })) for _ in range(2)]
                peak = DegradationLevel.NORMAL
                deadline = asyncio.get_running_loop().time() + 60
                while asyncio.get_running_loop().time() < deadline:
                    peak = max(peak, srv.degradation.level)
                    if all(t.done() for t in tasks):
                        break
                    await asyncio.sleep(0.1)
                for t in tasks:
                    resp = await t
                    assert resp.status == 200
                assert peak > DegradationLevel.NORMAL, (
                    "ladder never engaged under live pressure")
                # pressure gone: ladder lifts within a few intervals
                deadline = asyncio.get_running_loop().time() + 20
                while srv.degradation.level != DegradationLevel.NORMAL:
                    assert asyncio.get_running_loop().time() < deadline, (
                        f"stuck at {srv.degradation.level}")
                    await asyncio.sleep(0.2)
                # and service is normal again
                r = await client.post("/generate", json={
                    "prompt": "after", "max_tokens": 2,
                    "temperature": 0.0})
                assert r.status == 200
            finally:
                await client.close()

        asyncio.run(main())
    finally:
        srv.shutdown(drain_timeout_s=10.0)
