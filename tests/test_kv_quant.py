"""Int8 KV cache (engine/kv_cache.py QuantPool): quantization machinery,
engine end-to-end, serialize round-trip, and guard rails."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    PagedCacheConfig,
    PagedKVState,
    QuantPool,
    dequantize_kv,
    pool_num_slots,
    quantize_kv,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

TOK = ByteTokenizer()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def test_quantize_dequantize_bounded_error():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(scale=3.0, size=(5, 7, 4, 16)), jnp.float32)
    codes, scale = quantize_kv(x)
    assert codes.dtype == jnp.int8 and scale.shape == (5, 7, 4)
    back = dequantize_kv(codes, scale, jnp.float32)
    # absmax scaling: error <= scale/2 per element
    err = np.abs(np.asarray(back) - np.asarray(x))
    bound = np.asarray(scale)[..., None] / 2 + 1e-6
    assert (err <= bound).all()
    # zero vectors reconstruct exactly
    z, zs = quantize_kv(jnp.zeros((2, 3, 4, 16)))
    assert np.asarray(dequantize_kv(z, zs)).sum() == 0


def test_quant_pool_create_and_slots():
    pcfg = PagedCacheConfig(num_pages=8, page_size=4, max_pages_per_seq=4)
    st = PagedKVState.create(TINY, pcfg, kv_quant="int8")
    assert isinstance(st.k, QuantPool)
    assert st.k.data.shape == (TINY.num_layers, 32, TINY.num_kv_heads,
                               TINY.head_dim)
    assert st.k.scale.shape == st.k.data.shape[:-1]
    assert pool_num_slots(st.k) == 32
    dense = PagedKVState.create(TINY, pcfg)
    assert pool_num_slots(dense.k) == 32
    with pytest.raises(ValueError):
        PagedKVState.create(TINY, pcfg, kv_quant="fp8")


def _make_engine(params, kv_quant="int8", **kw):
    kw.setdefault("attention_impl", "xla")
    return LLMEngine(
        params, TINY, TOK,
        EngineConfig(
            max_batch=4,
            prefill_buckets=(16,),
            paged=PagedCacheConfig(
                num_pages=24, page_size=4, max_pages_per_seq=8
            ),
            decode_block_size=4,
            kv_quant=kv_quant,
            **kw,
        ),
        dtype=jnp.float32,
    )


def _drain(engine):
    out = {}
    while engine.has_work():
        for o in engine.step():
            r = out.setdefault(o.request_id,
                               {"tokens": [], "finish": None})
            if o.token_id is not None:
                r["tokens"].append(o.token_id)
            if o.finished:
                r["finish"] = o.finish_reason
                r["error"] = o.error
    return out

def test_engine_generates_with_int8_kv(tiny_params):
    """End-to-end: int8-KV decode produces a full, error-free generation
    whose tokens mostly agree with the bf16-pool engine (quantization
    noise may flip a late argmax on random weights — the machinery is
    exercised either way)."""
    prompt = TOK.encode("kv quant check")
    e_quant = _make_engine(tiny_params)
    e_quant.add_request("q", prompt, SamplingParams(max_tokens=8,
                                                    temperature=0.0))
    rq = _drain(e_quant)["q"]
    assert rq["error"] is None and len(rq["tokens"]) >= 1

    e_dense = _make_engine(tiny_params, kv_quant="none")
    e_dense.add_request("d", prompt, SamplingParams(max_tokens=8,
                                                    temperature=0.0))
    rd = _drain(e_dense)["d"]
    # first token comes from the same prefill with quantized K/V of the
    # prompt only — expect agreement on at least the first token
    assert rq["tokens"][0] == rd["tokens"][0]


def test_engine_serialize_roundtrip_int8(tiny_params):
    """Property 12 under quantization: a sequence's pages serialize and
    restore bit-exactly at the quantized representation."""
    from distributed_inference_server_tpu.engine.kv_cache import (
        deserialize_kv,
        serialize_kv,
    )

    engine = _make_engine(tiny_params)
    prompt = TOK.encode("serialize me please")
    engine.add_request("s", prompt, SamplingParams(max_tokens=4,
                                                   temperature=0.0))
    _drain(engine)
    st = engine.state
    blob = serialize_kv(st, [0, 1], 4, token_count=8)
    st2, count = deserialize_kv(st, blob, [2, 3], 4)
    assert count == 8
    np.testing.assert_array_equal(
        np.asarray(st.k.data[:, 0:8]), np.asarray(st2.k.data[:, 8:16])
    )
    np.testing.assert_array_equal(
        np.asarray(st.k.scale[:, 0:8]), np.asarray(st2.k.scale[:, 8:16])
    )


def test_kv_quant_rejects_pallas_and_unknown(tiny_params):
    with pytest.raises(ValueError, match="XLA attention"):
        _make_engine(tiny_params, kv_quant="int8",
                     attention_impl="pallas")
    with pytest.raises(ValueError, match="unknown kv_quant"):
        _make_engine(tiny_params, kv_quant="fp8")


def test_int8_kv_under_tensor_parallel(tiny_params):
    """The quant pool's scale leaves shard on KV heads alongside the
    codes; TP generation must match the single-device int8 engine."""
    from distributed_inference_server_tpu.parallel.mesh import (
        MeshSpec,
        make_mesh,
    )

    mesh = make_mesh(MeshSpec(tensor=2))
    prompt = TOK.encode("tp kv quant")
    single = _make_engine(tiny_params)
    single.add_request("a", prompt, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
    rs = _drain(single)["a"]

    tp = LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=4, prefill_buckets=(16,),
            paged=PagedCacheConfig(num_pages=24, page_size=4,
                                   max_pages_per_seq=8),
            attention_impl="xla", decode_block_size=4, kv_quant="int8",
        ),
        dtype=jnp.float32, mesh=mesh,
    )
    tp.add_request("b", prompt, SamplingParams(max_tokens=6,
                                               temperature=0.0))
    rt = _drain(tp)["b"]
    assert rt["error"] is None
    assert rs["tokens"] == rt["tokens"]


def _mesh_engine(tiny_params, mesh, **kw):
    return LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=4, prefill_buckets=(16,),
            paged=PagedCacheConfig(num_pages=24, page_size=4,
                                   max_pages_per_seq=8),
            attention_impl="xla", decode_block_size=4, kv_quant="int8",
            **kw,
        ),
        dtype=jnp.float32, mesh=mesh,
    )


def test_int8_kv_under_pipeline_parallel(tiny_params):
    """VERDICT r4 #4: QuantPool pools thread through pp_paged_forward as
    pytrees with stage-sharded members; PP generation matches the
    single-device int8 engine token-for-token."""
    from distributed_inference_server_tpu.parallel.mesh import (
        MeshSpec,
        make_mesh,
    )

    prompt = TOK.encode("pp kv quant")
    single = _make_engine(tiny_params)
    single.add_request("a", prompt, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
    rs = _drain(single)["a"]

    pp = _mesh_engine(tiny_params, make_mesh(MeshSpec(stage=2)),
                      pp_microbatches=2)
    pp.add_request("b", prompt, SamplingParams(max_tokens=6,
                                               temperature=0.0))
    rt = _drain(pp)["b"]
    assert rt["error"] is None
    assert rs["tokens"] == rt["tokens"]


def test_int8_kv_under_ring_cp(tiny_params):
    """Ring prefill with an int8 pool: the dense ring K/V quantizes at
    the pool scatter (parallel/cp.py:_scatter_pool); decode reads the
    quantized pages. Long prompt on a seq mesh matches the single-device
    int8 engine."""
    from distributed_inference_server_tpu.parallel.mesh import (
        MeshSpec,
        make_mesh,
    )

    prompt = TOK.encode("int8 kv ring prefill!")  # 22 tokens > 16
    single = _make_engine(tiny_params)
    single.add_request("a", prompt, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
    rs = _drain(single)["a"]

    cp = _mesh_engine(tiny_params, make_mesh(MeshSpec(seq=2)))
    cp.add_request("b", prompt, SamplingParams(max_tokens=6,
                                               temperature=0.0))
    rt = _drain(cp)["b"]
    assert rt["error"] is None
    assert cp._cp_fns, "ring path was never taken"
    assert rs["tokens"] == rt["tokens"]


def test_int8_kv_under_cp_pp(tiny_params):
    """The full composition: ring CP x PP x int8 KV in one engine."""
    from distributed_inference_server_tpu.parallel.mesh import (
        MeshSpec,
        make_mesh,
    )

    prompt = TOK.encode("int8 kv ring prefill!")
    single = _make_engine(tiny_params)
    single.add_request("a", prompt, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
    rs = _drain(single)["a"]

    eng = _mesh_engine(tiny_params, make_mesh(MeshSpec(seq=2, stage=2)),
                       pp_microbatches=2)
    eng.add_request("b", prompt, SamplingParams(max_tokens=6,
                                                temperature=0.0))
    rt = _drain(eng)["b"]
    assert rt["error"] is None
    assert eng._cp_fns, "ring path was never taken"
    assert rs["tokens"] == rt["tokens"]


def test_kv_quant_pallas_env_resolution(tiny_params, monkeypatch):
    """DIS_TPU_KV_QUANT_PALLAS=1: the auto resolution probes the int8
    decode kernel (QuantPool-shaped pools) and serves decode on Pallas /
    prefill on XLA when Mosaic accepts; without the flag kv_quant stays
    XLA-only."""
    monkeypatch.delenv("DIS_TPU_KV_QUANT_PALLAS", raising=False)
    engine = _make_engine(tiny_params, attention_impl="auto")
    assert engine._resolved_impl() == "xla"

    monkeypatch.setenv("DIS_TPU_KV_QUANT_PALLAS", "1")
    # an explicit XLA pin always wins over the experimental flag
    pinned = _make_engine(tiny_params, attention_impl="xla")
    assert pinned._resolved_impl() == "xla"
    import jax as jax_mod

    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    # the engine resolves (and caches) during construction, so the probe
    # must be patched on the CLASS before building
    monkeypatch.setattr(
        LLMEngine, "_probe_pallas", lambda self: (True, False)
    )
    engine2 = _make_engine(tiny_params, attention_impl="auto")
    assert engine2._resolved_impl() == ("pallas", "xla")

    monkeypatch.setattr(
        LLMEngine, "_probe_pallas", lambda self: (False, False)
    )
    engine3 = _make_engine(tiny_params, attention_impl="auto")
    assert engine3._resolved_impl() == ("xla", "xla")


def test_engine_int8_pallas_path_end_to_end(tiny_params, monkeypatch):
    """The full DIS_TPU_KV_QUANT_PALLAS serving path — decode blocks
    launching the int8-pool Pallas kernel over QuantPool pools — produces
    the same greedy tokens as the int8 XLA path. Resolution is pinned
    during construction (backend patched to 'tpu' + probe stubbed) and
    then reverted, so the kernels execute in interpret mode on CPU."""
    import jax as jax_mod

    with monkeypatch.context() as m:
        m.setenv("DIS_TPU_KV_QUANT_PALLAS", "1")
        m.setattr(jax_mod, "default_backend", lambda: "tpu")
        m.setattr(LLMEngine, "_probe_pallas", lambda self: (True, False))
        eng = _make_engine(tiny_params, attention_impl="auto")
    assert eng._resolved_impl() == ("pallas", "xla")

    prompt = TOK.encode("pallas int8 path")
    eng.add_request("p", prompt, SamplingParams(max_tokens=8,
                                                temperature=0.0))
    rp = _drain(eng)["p"]
    assert rp["error"] is None

    ref = _make_engine(tiny_params)  # int8 + XLA attention
    ref.add_request("x", prompt, SamplingParams(max_tokens=8,
                                                temperature=0.0))
    rx = _drain(ref)["x"]
    assert rp["tokens"] == rx["tokens"]


def test_int8_kv_with_speculative_draft(tiny_params):
    """Speculative decoding over int8 KV pools (target AND draft pools
    quantize): greedy output matches the plain int8 engine — rejection
    sampling must hold bit-exactness on the quantized cache too."""
    draft = llama.init_params(jax.random.PRNGKey(9), TINY, jnp.float32)
    prompt = TOK.encode("spec over int8 kv")
    plain = _make_engine(tiny_params)
    plain.add_request("a", prompt, SamplingParams(max_tokens=8,
                                                  temperature=0.0))
    rp = _drain(plain)["a"]

    spec = LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=4, prefill_buckets=(16,),
            paged=PagedCacheConfig(num_pages=24, page_size=4,
                                   max_pages_per_seq=8),
            decode_block_size=3, kv_quant="int8", attention_impl="xla",
        ),
        dtype=jnp.float32, draft_params=draft, draft_cfg=TINY,
    )
    spec.add_request("b", prompt, SamplingParams(max_tokens=8,
                                                 temperature=0.0))
    rs = _drain(spec)["b"]
    assert rs["error"] is None
    assert rp["tokens"] == rs["tokens"]
