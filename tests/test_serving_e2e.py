"""End-to-end serving tests: HTTP → handler → dispatcher → engine → SSE.

Drives the full spine (SURVEY.md §3.2-3.4 call stacks) against a TINY
Llama-family model on the XLA CPU backend with real continuous batching —
the integration tier the reference spec'd but never built
(``design.md:1046-1053`` [spec]).
"""

from __future__ import annotations

import asyncio
import json

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.core.models import TokenEvent
from distributed_inference_server_tpu.engine.engine import EngineConfig
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.server import InferenceServer

# engine capacity: 32 pages/seq * 8 = 256 tokens max — small enough that an
# in-validator-range prompt can exceed it (failure-isolation test), big
# enough for the chat template (~180 byte-tokens)
_PAGED = PagedCacheConfig(num_pages=192, page_size=8, max_pages_per_seq=32)


def _engine_factory():
    import jax

    from distributed_inference_server_tpu.engine.engine import LLMEngine

    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    return LLMEngine(
        params,
        TINY,
        ByteTokenizer(),
        EngineConfig(max_batch=4, prefill_buckets=(16, 64), paged=_PAGED),
        dtype=jnp.float32,
    )


@pytest.fixture(scope="module")
def server():
    srv = InferenceServer(
        _engine_factory,
        ByteTokenizer(),
        model_name="tiny-test",
        num_engines=1,
        auto_restart=False,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server: InferenceServer, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def test_generate_roundtrip(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "hello world", "max_tokens": 8, "temperature": 0.0},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "text_completion"
        assert body["id"].startswith("cmpl-")
        assert body["model"] == "tiny-test"
        assert len(body["choices"]) == 1
        choice = body["choices"][0]
        assert choice["finish_reason"] in ("stop", "length", "stop_sequence")
        usage = body["usage"]
        assert usage["prompt_tokens"] == len("hello world") + 1  # +BOS
        assert usage["total_tokens"] == (
            usage["prompt_tokens"] + usage["completion_tokens"]
        )
        assert usage["completion_tokens"] <= 8

    _run(server, go)


def test_generate_streaming_sse(server):
    async def go(client):
        resp = await client.post(
            "/generate",
            json={"prompt": "stream me", "max_tokens": 6, "temperature": 0.0,
                  "stream": True},
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        raw = await resp.read()
        frames = [f for f in raw.decode().split("\n\n") if f]
        assert frames[-1] == "data: [DONE]"
        events = [
            TokenEvent.from_dict(json.loads(f[len("data: "):]))
            for f in frames[:-1]
        ]
        assert events, "no events streamed"
        assert events[-1].type == "done"
        assert events[-1].usage.completion_tokens <= 6
        token_events = [e for e in events[:-1] if e.type == "token"]
        assert all(e.index is not None for e in token_events)
        # every real token event carries the model logprob on the wire
        # (models.rs:272-277's optional field, populated by the engine);
        # held-back text flushes (token_id None) ride without one
        with_lp = [e for e in token_events if e.logprob is not None]
        assert with_lp, "no logprobs streamed"
        assert all(e.logprob <= 0.0 for e in with_lp)

    _run(server, go)


def test_chat_roundtrip(server):
    async def go(client):
        resp = await client.post(
            "/chat",
            json={
                "messages": [
                    {"role": "system", "content": "be brief"},
                    {"role": "user", "content": "hi"},
                ],
                "max_tokens": 4,
                "temperature": 0.0,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "chat.completion"
        assert body["choices"][0]["message"]["role"] == "assistant"

    _run(server, go)


def test_embeddings_roundtrip(server):
    async def go(client):
        resp = await client.post(
            "/embeddings", json={"input": ["alpha", "beta gamma"]}
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "list"
        assert len(body["data"]) == 2
        for i, item in enumerate(body["data"]):
            assert item["object"] == "embedding"
            assert item["index"] == i
            norm = sum(x * x for x in item["embedding"]) ** 0.5
            assert abs(norm - 1.0) < 1e-3

    _run(server, go)


def test_embeddings_single_string_input(server):
    async def go(client):
        resp = await client.post("/embeddings", json={"input": "just one"})
        assert resp.status == 200
        body = await resp.json()
        assert len(body["data"]) == 1

    _run(server, go)


def test_validation_errors_400(server):
    async def go(client):
        # empty prompt
        resp = await client.post("/generate", json={"prompt": "   "})
        assert resp.status == 400
        body = await resp.json()
        assert body["error"]["error_type"] == "invalid_request_error"
        # bad temperature
        resp = await client.post(
            "/generate", json={"prompt": "x", "temperature": 9.0}
        )
        assert resp.status == 400
        # malformed JSON
        resp = await client.post(
            "/generate", data=b"{nope", headers={"Content-Type": "application/json"}
        )
        assert resp.status == 400
        # missing field
        resp = await client.post("/generate", json={"max_tokens": 4})
        assert resp.status == 400

    _run(server, go)


def test_oversized_prompt_fails_alone(server):
    """A prompt that passes the validator but exceeds engine capacity
    errors that request only (Property 22) — concurrent request survives."""

    async def go(client):
        big = "x" * 400  # 401 tokens > 256-token engine cap; validator OK
        ok, bad = await asyncio.gather(
            client.post("/generate",
                        json={"prompt": "fine", "max_tokens": 4,
                              "temperature": 0.0}),
            client.post("/generate", json={"prompt": big, "max_tokens": 4}),
        )
        assert ok.status == 200
        assert bad.status == 500
        body = await bad.json()
        assert body["error"]["error_type"] == "server_error"

    _run(server, go)


def test_server_stats(server):
    async def go(client):
        resp = await client.get("/server/stats")
        assert resp.status == 200
        body = await resp.json()
        for key in (
            "total_requests", "active_requests", "tokens_per_second",
            "average_ttft_ms", "p99_latency_ms", "average_batch_size",
            "cache_hit_rate", "queue_depth", "worker_statuses",
        ):
            assert key in body
        assert body["total_requests"] >= 1
        assert len(body["worker_statuses"]) == 1
        assert body["worker_statuses"][0]["healthy"] is True

    _run(server, go)


def test_prometheus_metrics(server):
    async def go(client):
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = await resp.text()
        assert "tokens_generated_total" in text
        assert "request_latency_seconds" in text
        assert "engine_up" in text

    _run(server, go)


def test_health(server):
    async def go(client):
        resp = await client.get("/health")
        assert resp.status == 200
        body = await resp.json()
        assert body["status"] == "ok"
        assert body["accepting"] is True

    _run(server, go)


def test_concurrent_mixed_requests(server):
    """Continuous batching handles interleaved requests with different
    lengths; every request completes with consistent usage."""

    async def go(client):
        async def one(i: int):
            resp = await client.post(
                "/generate",
                json={"prompt": f"request number {i}", "max_tokens": 3 + i,
                      "temperature": 0.0},
            )
            assert resp.status == 200
            return await resp.json()

        bodies = await asyncio.gather(*[one(i) for i in range(6)])
        for i, body in enumerate(bodies):
            assert body["usage"]["completion_tokens"] <= 3 + i

    _run(server, go)


def test_greedy_determinism(server):
    """temperature=0 is greedy argmax: same prompt → same completion."""

    async def go(client):
        async def once():
            resp = await client.post(
                "/generate",
                json={"prompt": "determinism", "max_tokens": 8,
                      "temperature": 0.0},
            )
            return (await resp.json())["choices"][0]["text"]

        first = await once()
        second = await once()
        assert first == second

    _run(server, go)


def test_admin_scale_endpoint(server):
    async def go(client):
        # scale 1 -> 2 replicas
        resp = await client.post("/admin/scale", json={"num_engines": 2})
        body = await resp.json()
        assert resp.status == 200, body
        assert body["num_engines"] == 2
        # generation still works across the scaled fleet
        r = await client.post("/generate", json={
            "prompt": "scaled", "max_tokens": 3, "temperature": 0.0})
        assert r.status == 200
        # scale back down (drains)
        resp = await client.post("/admin/scale", json={"num_engines": 1})
        body = await resp.json()
        assert resp.status == 200 and body["num_engines"] == 1
        # validation
        bad = await client.post("/admin/scale", json={"num_engines": 0})
        assert bad.status == 400
    _run(server, go)


class TestOpenAIAliases:
    """/v1/* aliases accept OpenAI request spellings (notably "stop") and
    serve the same schemas — off-the-shelf OpenAI clients work
    unchanged."""

    def test_v1_completions_with_stop_string(self, server):
        async def go(client):
            ref = await (await client.post(
                "/generate",
                json={"prompt": "hello world", "max_tokens": 8,
                      "temperature": 0.0},
            )).json()
            stop = ref["choices"][0]["text"][2:4]
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "hello world", "max_tokens": 8,
                      "temperature": 0.0, "stop": stop},
            )
            assert resp.status == 200
            body = await resp.json()
            assert body["object"] == "text_completion"
            # OpenAI vocabulary: stop_sequence maps to "stop" on /v1
            assert body["choices"][0]["finish_reason"] == "stop"
            return ref, body, stop

        ref, body, stop = _run(server, go)
        # truncated at the stop's FIRST occurrence in the greedy text
        want = ref["choices"][0]["text"]
        assert body["choices"][0]["text"] == want[: want.find(stop)]

    def test_v1_bad_stop_type_names_the_client_field(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "x", "stop": 5},
            )
            assert resp.status == 400
            err = (await resp.json())["error"]
            assert '"stop"' in err["message"]
            assert "stop_sequences" not in err["message"]

        _run(server, go)

    def test_v1_chat_and_embeddings(self, server):
        async def go(client):
            chat = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 4, "stop": ["zzz_never"]},
            )
            assert chat.status == 200
            assert (await chat.json())["object"] == "chat.completion"
            emb = await client.post(
                "/v1/embeddings", json={"input": ["a"]}
            )
            assert emb.status == 200
            assert (await emb.json())["object"] == "list"

        _run(server, go)

    def test_v1_streaming_is_openai_chunks(self, server):
        """/v1 streams OpenAI objects (choices[].text / choices[].delta),
        NOT the internal TokenEvent frames — off-the-shelf SDK chunk
        parsing depends on it."""
        import json as _json

        async def go(client):
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "abc", "max_tokens": 3, "stream": True},
            )
            assert resp.status == 200
            comp = (await resp.read()).decode()
            resp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 3, "stream": True},
            )
            assert resp.status == 200
            chat = (await resp.read()).decode()
            return comp, chat

        comp, chat = _run(server, go)
        for body in (comp, chat):
            assert '"type": "token"' not in body  # no internal frames
            assert body.strip().endswith("data: [DONE]")
        frames = [_json.loads(line[6:]) for line in comp.splitlines()
                  if line.startswith("data: {")]
        assert all(f["object"] == "text_completion" for f in frames)
        assert "text" in frames[0]["choices"][0]
        assert frames[-1]["choices"][0]["finish_reason"] == "length"
        cframes = [_json.loads(line[6:]) for line in chat.splitlines()
                   if line.startswith("data: {")]
        assert all(f["object"] == "chat.completion.chunk" for f in cframes)
        assert cframes[0]["choices"][0]["delta"]["role"] == "assistant"
        assert cframes[-1]["choices"][0]["delta"] == {}
        assert cframes[-1]["choices"][0]["finish_reason"] == "length"


    def test_v1_max_completion_tokens_and_empty_stop(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_completion_tokens": 3},
            )
            assert resp.status == 200
            body = await resp.json()
            assert body["usage"]["completion_tokens"] <= 3
            bad = await client.post(
                "/v1/completions", json={"prompt": "x", "stop": [""]}
            )
            assert bad.status == 400
            assert "non-empty" in (await bad.json())["error"]["message"]
            for bad_n in (True, 0, "2", 17, -1):
                multi = await client.post(
                    "/v1/completions", json={"prompt": "x", "n": bad_n}
                )
                assert multi.status == 400, bad_n  # no silent one-choice
                assert '"n"' in (await multi.json())["error"]["message"]
            ok_n = await client.post(
                "/v1/completions",
                json={"prompt": "x", "n": 1, "max_tokens": 1},
            )
            assert ok_n.status == 200

        _run(server, go)

    def test_v1_chat_role_only_in_first_delta(self, server):
        import json as _json

        async def go(client):
            resp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 4, "stream": True},
            )
            return (await resp.read()).decode()

        body = _run(server, go)
        deltas = [
            _json.loads(line[6:])["choices"][0]["delta"]
            for line in body.splitlines() if line.startswith("data: {")
        ]
        token_deltas = [d for d in deltas if d.get("content") is not None]
        assert "role" in token_deltas[0]
        assert all("role" not in d for d in token_deltas[1:])


def _v1_chunks(body: str):
    import json as _json

    return [
        _json.loads(line[6:])
        for line in body.splitlines()
        if line.startswith("data: {")
    ]


class TestV1ParityTail:
    """OpenAI /v1 parity: n>1 fan-out, sampled-token logprobs, and
    stream_options.include_usage (VERDICT r3 missing #5 / next #4;
    multi-choice response schema models.rs:147-171)."""

    def test_n2_completions_nonstream(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "fan out", "n": 2, "max_tokens": 4,
                      "temperature": 0.0},
            )
            assert resp.status == 200
            body = await resp.json()
            assert [c["index"] for c in body["choices"]] == [0, 1]
            for c in body["choices"]:
                assert c["finish_reason"] in ("stop", "length")
                assert c["logprobs"] is None
            u = body["usage"]
            # prompt counted ONCE; completions summed over both choices
            assert u["prompt_tokens"] == len("fan out") + 1  # +BOS
            assert u["completion_tokens"] <= 8
            assert u["total_tokens"] == (
                u["prompt_tokens"] + u["completion_tokens"]
            )
            # greedy decoding: both choices must agree
            assert body["choices"][0]["text"] == body["choices"][1]["text"]

        _run(server, go)

    def test_n2_chat_stream_interleaves_choices(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "n": 2, "max_tokens": 3, "stream": True},
            )
            assert resp.status == 200
            return (await resp.read()).decode()

        body = _run(server, go)
        assert body.rstrip().endswith("data: [DONE]")
        chunks = _v1_chunks(body)
        by_idx = {0: [], 1: []}
        for ch in chunks:
            for c in ch["choices"]:
                by_idx[c["index"]].append(c)
        for idx in (0, 1):
            finishes = [c for c in by_idx[idx]
                        if c["finish_reason"] is not None]
            assert len(finishes) == 1, f"choice {idx} finish chunks"
            deltas = [c["delta"] for c in by_idx[idx]
                      if c["delta"].get("content") is not None]
            assert "role" in deltas[0]
            assert all("role" not in d for d in deltas[1:])

    def test_completions_logprobs_nonstream(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "lp", "max_tokens": 4, "logprobs": 0,
                      "temperature": 0.0},
            )
            assert resp.status == 200
            body = await resp.json()
            lp = body["choices"][0]["logprobs"]
            assert lp is not None
            k = len(lp["tokens"])
            assert k >= 1
            assert len(lp["token_logprobs"]) == k
            assert len(lp["text_offset"]) == k
            assert lp["top_logprobs"] is None
            assert all(v <= 0.0 for v in lp["token_logprobs"]
                       if v is not None)
            assert lp["text_offset"][0] == 0
            assert lp["text_offset"] == sorted(lp["text_offset"])

        _run(server, go)

    def test_chat_logprobs_nonstream_and_stream(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 3, "logprobs": True},
            )
            assert resp.status == 200
            body = await resp.json()
            content = body["choices"][0]["logprobs"]["content"]
            assert content
            for entry in content:
                assert set(entry) == {"token", "logprob", "bytes",
                                      "top_logprobs"}
                assert entry["top_logprobs"] == []
                assert isinstance(entry["bytes"], list)
            sresp = await client.post(
                "/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 3, "logprobs": True, "stream": True},
            )
            return (await sresp.read()).decode()

        body = _run(server, go)
        chunks = _v1_chunks(body)
        token_chunks = [
            c for ch in chunks for c in ch["choices"]
            if c.get("delta", {}).get("content") is not None
        ]
        assert token_chunks
        with_lp = [c for c in token_chunks if c["logprobs"] is not None]
        assert with_lp, "no logprobs in stream chunks"
        for c in with_lp:
            for entry in c["logprobs"]["content"]:
                assert "token" in entry and "logprob" in entry

    def test_stream_include_usage(self, server):
        async def go(client):
            resp = await client.post(
                "/v1/completions",
                json={"prompt": "use me", "max_tokens": 3, "stream": True,
                      "stream_options": {"include_usage": True}},
            )
            assert resp.status == 200
            return (await resp.read()).decode()

        body = _run(server, go)
        chunks = _v1_chunks(body)
        # every chunk carries a usage key; all null except the final one
        assert all("usage" in ch for ch in chunks)
        final = chunks[-1]
        assert final["choices"] == []
        u = final["usage"]
        assert u["prompt_tokens"] == len("use me") + 1  # +BOS
        assert 1 <= u["completion_tokens"] <= 3
        assert u["total_tokens"] == (
            u["prompt_tokens"] + u["completion_tokens"]
        )
        assert all(ch["usage"] is None for ch in chunks[:-1])

    def test_stream_error_still_emits_usage_chunk(self, server):
        """An error event terminates its choice, so include_usage's final
        usage chunk must still arrive when a choice errors (review
        finding: remaining was only decremented on done events)."""

        async def go(client):
            big = "x" * 400  # 401 tokens > 256-token engine cap
            resp = await client.post(
                "/v1/completions",
                json={"prompt": big, "max_tokens": 3, "stream": True,
                      "stream_options": {"include_usage": True}},
            )
            assert resp.status == 200
            return (await resp.read()).decode()

        body = _run(server, go)
        assert body.rstrip().endswith("data: [DONE]")
        chunks = _v1_chunks(body)
        assert any("error" in ch for ch in chunks)
        final = chunks[-1]
        assert final["choices"] == []
        assert final["usage"] is not None

    def test_unsupported_shape_fields_rejected(self, server):
        async def go(client):
            cases = [
                ("/v1/completions", {"prompt": "x", "echo": True}),
                ("/v1/completions", {"prompt": "x", "best_of": 3}),
                # best_of < n is self-contradictory (OpenAI 400s it too)
                ("/v1/completions", {"prompt": "x", "n": 4, "best_of": 1}),
                ("/v1/completions", {"prompt": "x", "suffix": "tail"}),
                ("/v1/completions", {"prompt": "x", "logprobs": 3}),
                ("/v1/completions",
                 {"prompt": "x",
                  "stream_options": {"include_usage": True}}),
                ("/v1/chat/completions",
                 {"messages": [{"role": "user", "content": "x"}],
                  "logprobs": True, "top_logprobs": 2}),
                ("/v1/chat/completions",
                 {"messages": [{"role": "user", "content": "x"}],
                  "top_logprobs": 0}),
            ]
            for path, payload in cases:
                resp = await client.post(path, json=payload)
                assert resp.status == 400, (path, payload)
                msg = (await resp.json())["error"]["message"]
                assert msg, (path, payload)
            # best_of == n degenerates to "return all n" and is allowed
            ok = await client.post(
                "/v1/completions",
                json={"prompt": "x", "n": 2, "best_of": 2,
                      "max_tokens": 1},
            )
            assert ok.status == 200
            assert len((await ok.json())["choices"]) == 2

        _run(server, go)
