"""Speculative decoding (Req 12, requirements.md:166-170 [spec]).

Greedy speculative output must be bit-identical to vanilla greedy
decoding regardless of draft quality; the tracker must auto-disable below
the acceptance threshold (Req 12.5) and report speedup (Req 12.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.engine.speculative import (
    AcceptanceTracker,
    SpecConfig,
    speculative_generate,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.generate import generate


@pytest.fixture(scope="module")
def target_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


@pytest.fixture(scope="module")
def bad_draft_params():
    # different weights -> frequent disagreement with the target
    return llama.init_params(jax.random.PRNGKey(7), TINY, dtype=jnp.float32)


def _vanilla_greedy(params, prompt, max_new, max_seq):
    B, T0 = prompt.shape
    return np.asarray(
        generate(
            params, TINY, prompt, jnp.full((B,), T0, jnp.int32),
            jax.random.PRNGKey(0), jnp.zeros((B,)), jnp.ones((B,)),
            max_new_tokens=max_new, max_seq=max_seq,
        ).tokens
    )


@pytest.mark.parametrize("draft_key", ["same", "different"])
def test_greedy_spec_matches_vanilla(target_params, bad_draft_params,
                                     draft_key):
    """Exactness: with a perfect draft (same model) and a bad draft,
    greedy speculative decoding emits the same tokens as vanilla greedy."""
    draft = target_params if draft_key == "same" else bad_draft_params
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                TINY.vocab_size)
    want = _vanilla_greedy(target_params, prompt, 10, 64)
    got = speculative_generate(
        draft, TINY, target_params, TINY, prompt,
        max_new_tokens=10, max_seq=64,
        spec=SpecConfig(num_draft_tokens=3),
    )
    assert got.tolist() == want.tolist()


def test_perfect_draft_full_acceptance(target_params):
    """Draft == target at temperature 0 accepts every proposal."""
    prompt = jnp.ones((1, 4), jnp.int32)
    tracker = AcceptanceTracker(SpecConfig(num_draft_tokens=4, window=4))
    speculative_generate(
        target_params, TINY, target_params, TINY, prompt,
        max_new_tokens=12, max_seq=64,
        spec=SpecConfig(num_draft_tokens=4), tracker=tracker,
    )
    assert tracker.rate() == 1.0
    assert tracker.speedup() > 2.0  # gamma+1 tokens per target forward
    assert tracker.enabled


def test_tracker_auto_disable():
    cfg = SpecConfig(num_draft_tokens=4, disable_threshold=0.5, window=4)
    t = AcceptanceTracker(cfg)
    for _ in range(3):
        t.update(1, 4)  # 25% acceptance, window not yet full
        assert t.enabled
    t.update(1, 4)  # window full, rate 0.25 < 0.5 -> disable
    assert not t.enabled
    assert t.rate() == 0.25
    t.reset()
    assert t.enabled


def test_disabled_tracker_degrades_to_single_token(target_params,
                                                   bad_draft_params):
    """With speculation disabled the loop still produces correct greedy
    output (gamma degraded to 1)."""
    cfg = SpecConfig(num_draft_tokens=4, disable_threshold=2.0, window=1)
    tracker = AcceptanceTracker(cfg)
    tracker.update(0, 4)  # instantly disabled (threshold 2.0 unreachable)
    assert not tracker.enabled
    prompt = jnp.ones((1, 4), jnp.int32)
    want = _vanilla_greedy(target_params, prompt, 8, 64)
    got = speculative_generate(
        bad_draft_params, TINY, target_params, TINY, prompt,
        max_new_tokens=8, max_seq=64, spec=cfg, tracker=tracker,
    )
    assert got.tolist() == want.tolist()


def test_sampled_spec_preserves_support(target_params, bad_draft_params):
    """Temperature sampling through the speculative path emits tokens and
    stays finite/within vocab (distribution-exactness is guaranteed by the
    rejection-sampling construction; greedy exactness is tested above)."""
    prompt = jnp.ones((2, 4), jnp.int32)
    got = speculative_generate(
        bad_draft_params, TINY, target_params, TINY, prompt,
        max_new_tokens=12, max_seq=64,
        spec=SpecConfig(num_draft_tokens=3), temperature=0.8,
        rng=jax.random.PRNGKey(5),
    )
    assert got.shape == (2, 12)
    assert (got >= 0).all() and (got < TINY.vocab_size).all()


class TestProbationReenable:
    """Req 12.5 'per request pattern': after auto-disable, the tracker
    re-enables on a cooldown with a fresh window — a traffic pattern
    that speculates well again stays enabled; a still-bad one
    re-disables within one window."""

    def _bad_rounds(self, t, n):
        for _ in range(n):
            t.update(0, 4)  # 0% acceptance

    def test_disable_then_probation_reenable(self):
        clock = {"t": 0.0}
        t = AcceptanceTracker(
            SpecConfig(window=8, disable_threshold=0.5,
                       reenable_after_s=10.0),
            clock=lambda: clock["t"],
        )
        self._bad_rounds(t, 8)
        assert not t.enabled
        assert not t.consume_probation()
        clock["t"] = 5.0
        assert not t.enabled  # cooldown not elapsed
        clock["t"] = 10.0
        # the pure getter reports re-enabled without mutating state...
        assert t.enabled
        assert t.rate() == 0.0  # window NOT cleared by the read
        # ...the engine-thread consume performs the actual reset
        assert t.consume_probation()
        assert t.rate() == 1.0  # fresh window
        # still-bad pattern re-disables within one window
        self._bad_rounds(t, 8)
        assert not t.enabled and not t.consume_probation()

    def test_zero_cooldown_stays_disabled_until_reset(self):
        clock = {"t": 0.0}
        t = AcceptanceTracker(
            SpecConfig(window=4, disable_threshold=0.5,
                       reenable_after_s=0.0),
            clock=lambda: clock["t"],
        )
        self._bad_rounds(t, 4)
        clock["t"] = 1e9
        assert not t.enabled
        t.reset()
        assert t.enabled


def test_nucleus_aware_acceptance_is_distribution_exact():
    """Statistical exactness of nucleus-aware verification: with draft
    proposals sampled from the draft's filtered q̃, the first token each
    round must be distributed exactly as NUCLEUS sampling from the
    target, p̃ = norm(top_p_filter(p)) — the whole point of rejection
    sampling. Also: acceptance must be high enough that top-p rows emit
    >1 token per round on average (the old forced-rejection path pinned
    this to exactly 1)."""
    from distributed_inference_server_tpu.engine.speculative import (
        accept_and_resample,
    )
    from distributed_inference_server_tpu.ops.sampling import nucleus_probs

    V, gamma, N = 8, 2, 40_000
    key = jax.random.PRNGKey(3)
    kp, kq, kd, ku, kr = jax.random.split(key, 5)
    p = jax.nn.softmax(jax.random.normal(kp, (V,)) * 1.5)
    q = jax.nn.softmax(jax.random.normal(kq, (V,)) * 1.5)
    topp = jnp.full((N,), 0.9, jnp.float32)

    q_f = nucleus_probs(q[None], jnp.asarray([0.9]))[0]  # draft's q̃
    draft_qs = jnp.broadcast_to(q_f, (N, gamma, V))
    draft_toks = jax.random.categorical(
        kd, jnp.log(draft_qs + 1e-30), axis=-1
    ).astype(jnp.int32)
    target_ps = jnp.broadcast_to(p, (N, gamma + 1, V))

    tokens, num_accepted = accept_and_resample(
        target_ps, draft_toks, draft_qs, ku, kr, top_p=topp,
    )
    p_f = np.asarray(nucleus_probs(p[None], jnp.asarray([0.9]))[0])

    first = np.asarray(tokens[:, 0])
    hist = np.bincount(first, minlength=V) / N
    # outside-nucleus tokens must never be emitted
    assert hist[p_f == 0].sum() == 0.0
    np.testing.assert_allclose(hist, p_f, atol=0.02)

    # >1 expected emitted tokens per round for top-p rows
    emitted = np.asarray(num_accepted) + 1
    assert emitted.mean() > 1.2, emitted.mean()
