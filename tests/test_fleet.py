"""Fleet control plane (ISSUE 9): the fleet wire, the federated
registry's alive/suspect/dead state machine, RemoteRunner exactly-once
failure semantics, role-rebalance hysteresis, and the serving e2e —
join, token-identical remote serving, and death -> crash-safe
redispatch (docs/FLEET.md).
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.core.models import FinishReason
from distributed_inference_server_tpu.engine.engine import SamplingParams
from distributed_inference_server_tpu.serving import faults, protowire
from distributed_inference_server_tpu.serving.config import (
    ServerConfig,
    parse_tenant_weights,
)
from distributed_inference_server_tpu.serving.fleet import (
    FleetRegistry,
    FleetSettings,
    FleetWireError,
    MEMBER_ALIVE,
    MEMBER_DEAD,
    MEMBER_SUSPECT,
    RoleBalancer,
    parse_connect,
    recv_frame,
    send_frame,
    status_from_wire,
    status_to_wire,
)
from distributed_inference_server_tpu.serving.metrics import (
    EngineStatus,
    MetricsCollector,
)
from distributed_inference_server_tpu.serving.remote_runner import RemoteRunner
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.scheduler import plan_route


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


class _Sink:
    def __init__(self):
        self.toks, self.text = [], ""
        self.errors, self.dones = [], 0
        self.ev = threading.Event()

    def on_token(self, token_id, text, token_index, logprob=None):
        if token_id is not None:
            self.toks.append(token_id)
        self.text += text

    def on_done(self, reason, usage):
        self.dones += 1
        self.ev.set()

    def on_error(self, message, code):
        self.errors.append((message, code))
        self.ev.set()


def _req(rid="r1", first_token=False, prompt=(1, 2, 3)):
    sink = _Sink()
    req = ServerRequest(rid, list(prompt),
                        SamplingParams(max_tokens=8, temperature=0.0), sink)
    if first_token:
        req.first_token_at = time.monotonic()
    return req, sink


def _status(engine_id="e0", healthy=True, role="unified", waiting=0,
            active=0, remote=False, digest=(), data_plane=False):
    return EngineStatus(
        engine_id=engine_id, healthy=healthy, active_requests=active,
        waiting_requests=waiting, total_processed=0, role=role,
        prefix_digest=frozenset(digest), page_size=8, digest_depth=8,
        remote=remote, data_plane=data_plane,
    )


# ---------------------------------------------------------------------------
# The fleet wire
# ---------------------------------------------------------------------------


class TestFleetWire:
    def _pair(self):
        a, b = socket.socketpair()
        return a, b

    def test_frame_round_trip_all_kinds(self):
        a, b = self._pair()
        try:
            beats = {"member_id": "w1", "seq": 7,
                     "engines": [status_to_wire(_status(digest=(11, 12)))]}
            send_frame(a, "FleetHeartbeat", beats)
            send_frame(a, "FleetSubmit", {
                "request_id": "r1", "engine_id": "e0",
                "prompt_ids": [1, 2, 3], "max_tokens": 8,
                "temperature": 0.25, "top_p": 0.9,
                "stop_sequences": ["x"], "tenant": "acme",
            })
            send_frame(a, "FleetEvent", {
                "request_id": "r1", "engine_id": "e0", "kind": "token",
                "token_id": 42, "text": "hi", "token_index": 3,
            })
            name, hb = recv_frame(b)
            assert name == "FleetHeartbeat" and hb["member_id"] == "w1"
            assert hb["engines"][0]["prefix_digest"] == [11, 12]
            name, sub = recv_frame(b)
            assert name == "FleetSubmit"
            assert sub["prompt_ids"] == [1, 2, 3]
            assert sub["temperature"] == 0.25  # double: bit-exact
            assert sub["tenant"] == "acme"
            name, ev = recv_frame(b)
            assert name == "FleetEvent" and ev["token_id"] == 42
        finally:
            a.close()
            b.close()

    def test_event_without_token_id_decodes_absent(self):
        a, b = self._pair()
        try:
            send_frame(a, "FleetEvent", {
                "request_id": "r1", "engine_id": "e0", "kind": "token",
                "text": "tail", "token_index": 9,
            })
            _, ev = recv_frame(b)
            assert "token_id" not in ev  # optional: absent, not 0
        finally:
            a.close()
            b.close()

    def test_eof_returns_none(self):
        a, b = self._pair()
        a.close()
        try:
            assert recv_frame(b) is None
        finally:
            b.close()

    def test_malformed_frame_raises(self):
        a, b = self._pair()
        try:
            a.sendall(b"\x00\x00\x00\x04\x99abcd")  # unknown frame kind
            with pytest.raises(FleetWireError):
                recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_status_wire_round_trip(self):
        s = _status(engine_id="engine-0", role="decode",
                    digest=(5, 6, 7), waiting=3, active=2)
        d = status_to_wire(s)
        back = status_from_wire(
            protowire.decode("EngineStatus",
                             protowire.encode("EngineStatus", d)), "w1")
        assert back.engine_id == "w1:engine-0"
        assert back.remote is True
        assert back.role == "decode"
        assert back.prefix_digest == frozenset((5, 6, 7))
        assert back.waiting_requests == 3
        assert back.page_size == 8 and back.digest_depth == 8

    def test_parse_connect(self):
        assert parse_connect("10.0.0.2:9000") == ("10.0.0.2", 9000)
        for bad in ("nope", ":123", "h:", "h:x"):
            with pytest.raises(ConfigError):
                parse_connect(bad)


# ---------------------------------------------------------------------------
# FleetRegistry state machine
# ---------------------------------------------------------------------------


class TestFleetRegistry:
    def _registry(self, **kw):
        settings = FleetSettings(heartbeat_interval_s=0.05,
                                 suspect_after_s=0.2, dead_after_s=0.5, **kw)
        m = MetricsCollector()
        transitions = []
        reg = FleetRegistry(settings, metrics=m,
                            on_state_change=lambda *t: transitions.append(t))
        return reg, m, transitions

    def test_join_then_age_out_then_rejoin(self):
        reg, m, transitions = self._registry()
        assert reg.observe("w1", [_status()]) == MEMBER_DEAD  # join
        assert reg.member_state("w1") == MEMBER_ALIVE
        now = time.monotonic()
        assert reg.sweep(now + 0.3) == [("w1", MEMBER_ALIVE, MEMBER_SUSPECT)]
        assert reg.sweep(now + 0.6) == [("w1", MEMBER_SUSPECT, MEMBER_DEAD)]
        assert reg.member_state("w1") == MEMBER_DEAD
        # rejoin: the next beat revives it and reports the prior state
        assert reg.observe("w1", [_status()]) == MEMBER_DEAD
        assert reg.member_state("w1") == MEMBER_ALIVE
        assert ("w1", MEMBER_DEAD, MEMBER_ALIVE) in transitions
        prom = m.prometheus_text().decode()
        assert 'fleet_members{state="alive"} 1.0' in prom
        assert 'fleet_heartbeats_total{outcome="rejoin"}' in prom

    def test_one_missed_beat_is_not_suspicion(self):
        reg, _, _ = self._registry()
        reg.observe("w1", [_status()])
        assert reg.sweep(time.monotonic() + 0.1) == []
        assert reg.member_state("w1") == MEMBER_ALIVE

    def test_disconnect_is_immediately_dead(self):
        reg, m, transitions = self._registry()
        reg.observe("w1", [_status()])
        reg.disconnect("w1")
        assert reg.member_state("w1") == MEMBER_DEAD
        assert ("w1", MEMBER_ALIVE, MEMBER_DEAD) in transitions
        assert ('fleet_members{state="dead"} 1.0'
                in m.prometheus_text().decode())

    def test_heartbeat_fault_drops_the_beat(self):
        reg, m, _ = self._registry()
        reg.observe("w1", [_status()])
        faults.install(faults.parse_spec("fleet.heartbeat:nth=1,times=3",
                                         seed=1))
        for _ in range(3):
            assert reg.observe("w1", [_status()]) is None
        faults.clear()
        # dropped beats never refreshed last_beat: aging continues
        assert reg.sweep(time.monotonic() + 0.3)
        snap = m.prometheus_text().decode()
        assert 'fleet_heartbeats_total{outcome="dropped"} 3.0' in snap

    def test_first_join_counts_ok_not_rejoin(self):
        """Review fix: a brand-new member's first beat is a join, not a
        revival — operators alert on rejoin as a partition-recovery
        signal."""
        reg, m, transitions = self._registry()
        reg.observe("w1", [_status()])
        prom = m.prometheus_text().decode()
        assert 'fleet_heartbeats_total{outcome="ok"} 1.0' in prom
        assert 'outcome="rejoin"' not in prom
        assert transitions == []  # nothing existed to revive

    def test_dead_members_pruned_after_retention(self):
        """Review fix: restarted workers mint fresh host:pid ids — dead
        entries must age out of the member table and the gauge."""
        reg, m, _ = self._registry()
        reg.observe("w1", [_status()])
        reg.disconnect("w1")
        now = time.monotonic()
        reg.sweep(now + 1.0)  # within retention: still visible
        assert reg.member_state("w1") == MEMBER_DEAD
        reg.sweep(now + reg.settings.dead_after_s
                  + reg.settings.dead_retention_s + 1.0)
        assert reg.member_state("w1") is None
        assert ('fleet_members{state="dead"} 0.0'
                in m.prometheus_text().decode())

    def test_stats_shape(self):
        reg, _, _ = self._registry()
        reg.observe("w1", [_status(role="decode")])
        stats = reg.stats()
        assert stats["member_counts"] == {"alive": 1, "suspect": 0,
                                          "dead": 0}
        (member,) = stats["members"]
        assert member["member_id"] == "w1"
        assert member["engines"] == {"e0": "decode"}
        assert member["last_beat_age_s"] >= 0


# ---------------------------------------------------------------------------
# RemoteRunner: exactly-once failure semantics over the wire
# ---------------------------------------------------------------------------


class _WireLog:
    """Collects frames a RemoteRunner sends; can be told to die."""

    def __init__(self):
        self.frames = []
        self.dead = False

    def send(self, name, obj):
        if self.dead:
            raise OSError("wire down")
        self.frames.append((name, obj))


def _remote(wire=None):
    wire = wire or _WireLog()
    r = RemoteRunner("w1:e0", "e0", wire.send)
    r.update_status(_status(engine_id="w1:e0", remote=True))
    return r, wire


class _StubKvChannel:
    """KvDataChannel double for routing-gate tests: the capability
    surface RemoteRunner.supports_kv_import consults (an OPEN circuit
    breaker reads wire_available() False, serving/health.py)."""

    def __init__(self, available=True):
        self.available = available

    def wire_available(self):
        return self.available


class TestRemoteRunner:
    def test_submit_encodes_frames_and_events_resolve(self):
        r, wire = _remote()
        req, sink = _req()
        r.submit([req])
        assert wire.frames[0][0] == "FleetSubmit"
        assert wire.frames[0][1]["engine_id"] == "e0"
        assert r.active_count() == 1
        r.on_event({"request_id": "r1", "kind": "token", "token_id": 9,
                    "text": "a", "token_index": 0})
        r.on_event({"request_id": "r1", "kind": "done",
                    "finish_reason": "stop", "prompt_tokens": 3,
                    "completion_tokens": 1})
        assert sink.ev.is_set() and sink.dones == 1
        assert sink.toks == [9]
        assert r.active_count() == 0
        # orphan events after the terminal are dropped, never double
        r.on_event({"request_id": "r1", "kind": "done",
                    "finish_reason": "stop"})
        assert sink.dones == 1

    def test_error_event_resolves_once(self):
        r, _ = _remote()
        req, sink = _req(first_token=True)
        r.submit([req])
        r.on_event({"request_id": "r1", "kind": "error",
                    "message": "boom", "code": "inference_failed"})
        assert sink.errors == [("boom", "inference_failed")]
        assert r.active_count() == 0

    def test_detach_redispatches_zero_token_and_fails_midstream(self):
        r, _ = _remote()
        taken = []
        r.redispatch = lambda req, eid, msg: taken.append(req.request_id) or True
        fresh, fresh_sink = _req("fresh")
        mid, mid_sink = _req("mid", first_token=True)
        r.submit([fresh, mid])
        r.detach("member dead")
        assert taken == ["fresh"]  # zero-token: the dispatcher owns it
        assert not fresh_sink.errors
        assert mid_sink.errors and mid_sink.errors[0][1] == "engine_crashed"
        assert not r.is_healthy()
        # a detached proxy fails later submits immediately (to redispatch)
        late, late_sink = _req("late")
        r.submit([late])
        assert taken == ["fresh", "late"]

    def test_send_failure_degrades_to_redispatch(self):
        r, wire = _remote()
        wire.dead = True
        taken = []
        r.redispatch = lambda req, eid, msg: taken.append(req.request_id) or True
        req, sink = _req()
        r.submit([req])
        assert taken == ["r1"]
        assert not sink.errors

    def test_fleet_submit_fault_on_the_wire(self):
        r, wire = _remote()
        taken = []
        r.redispatch = lambda req, eid, msg: taken.append(req.request_id) or True
        faults.install(faults.parse_spec("fleet.submit:nth=1", seed=1))
        req, _ = _req()
        r.submit([req])
        faults.clear()
        assert taken == ["r1"]
        assert wire.frames == []  # died before the frame left

    def test_remote_worker_failure_takes_redispatch_path(self):
        r, _ = _remote()
        taken = []
        r.redispatch = lambda req, eid, msg: taken.append(req.request_id) or True
        req, sink = _req()
        r.submit([req])
        r.on_event({"request_id": "r1", "kind": "error",
                    "message": "remote out of capacity",
                    "code": "worker_failure"})
        assert taken == ["r1"]
        assert not sink.errors  # invisible to the client

    def test_exhausted_redispatch_fails_visibly_once(self):
        r, _ = _remote()
        r.redispatch = lambda req, eid, msg: False
        req, sink = _req()
        r.submit([req])
        r.detach("member dead")
        assert sink.errors == [("member dead", "worker_failure")]

    def test_abort_sends_frame_and_pops(self):
        r, wire = _remote()
        req, sink = _req()
        r.submit([req])
        r.abort("r1")
        assert r.active_count() == 0
        assert wire.frames[-1][1]["abort"] is True
        # events after the abort are orphans
        r.on_event({"request_id": "r1", "kind": "done",
                    "finish_reason": "stop"})
        assert sink.dones == 0

    def test_status_overlays_liveness_and_inflight(self):
        r, _ = _remote()
        req, _ = _req()
        r.submit([req])
        assert r.status().active_requests == 1
        r.set_member_state(MEMBER_SUSPECT)
        assert not r.is_healthy()
        assert r.status().healthy is False  # suspect leaves routing set
        r.set_member_state(MEMBER_ALIVE)
        assert r.is_healthy()
        assert r.audit() == []

    def test_two_phase_detach_keeps_siblings_out_of_redispatch(self):
        """Review fix: when a member dies, EVERY sibling proxy must be
        unhealthy before ANY request is redispatched — otherwise the
        bounded redispatch budget burns on the same dead member."""
        a, _ = _remote()
        b, _ = _remote()
        sibling_health_at_redispatch = []
        a.redispatch = lambda req, eid, msg: (
            sibling_health_at_redispatch.append(b.is_healthy()) or True)
        req, _ = _req()
        a.submit([req])
        # the session's ordering: mark ALL, then fail
        a.mark_detached("member dead")
        b.mark_detached("member dead")
        a.fail_inflight("member dead")
        assert sibling_health_at_redispatch == [False]

    def test_done_event_maps_finish_reason(self):
        r, _ = _remote()
        req, sink = _req()
        r.submit([req])
        r.on_event({"request_id": "r1", "kind": "done",
                    "finish_reason": "length", "prompt_tokens": 3,
                    "completion_tokens": 8})
        assert sink.dones == 1


# ---------------------------------------------------------------------------
# Remote-aware routing
# ---------------------------------------------------------------------------


class TestRemoteRouting:
    def test_plan_route_routes_warm_to_remote_but_never_fetches(self):
        hashes = (11, 12, 13, 14)
        remote_warm = _status("w1:e0", remote=True, digest=hashes)
        local_cold = _status("local", waiting=0)
        # the remote's heartbeated digest wins warm routing
        plan = plan_route([remote_warm, local_cold], hashes)
        assert plan.engine_id == "w1:e0" and plan.decision == "warm"
        # but a remote replica never SOURCES a fetch: with the only warm
        # copy remote, a loaded-vs-cold tradeoff must not pick "fetch"
        busy_remote = _status("w1:e0", remote=True, digest=hashes,
                              active=50, waiting=50)
        plan = plan_route([busy_remote, local_cold], hashes)
        assert plan.decision in ("warm", "recompute")  # never "fetch"

    def test_plan_route_never_fetches_onto_remote_target(self):
        hashes = (11, 12, 13, 14)
        local_warm_busy = _status("warm", digest=hashes, active=50,
                                  waiting=50)
        remote_cold = _status("w1:cold", remote=True)
        plan = plan_route([local_warm_busy, remote_cold], hashes)
        if plan.engine_id == "w1:cold":
            assert plan.decision != "fetch"


# ---------------------------------------------------------------------------
# RoleBalancer hysteresis
# ---------------------------------------------------------------------------


class _FakeRunner:
    def __init__(self, engine_id, role, healthy=True, waiting=0):
        self.engine_id = engine_id
        self.role = role
        self.healthy = healthy
        self.waiting = waiting

    def is_healthy(self):
        return self.healthy

    def set_role(self, role):
        self.role = role

    def status(self):
        return _status(self.engine_id, healthy=self.healthy, role=self.role,
                       waiting=self.waiting)


class _FakeScheduler:
    def __init__(self, runners):
        self._runners = runners

    def engines(self):
        return list(self._runners)

    def statuses(self):
        return [r.status() for r in self._runners]

    def get(self, engine_id):
        return next((r for r in self._runners if r.engine_id == engine_id),
                    None)


class _FakeDispatcher:
    def __init__(self, depth=0):
        self.depth = depth
        self.queue = self

    def total_depth(self):
        return self.depth


def _balancer(runners, depth=0, **kw):
    settings = FleetSettings(
        rerole=True, rerole_high_ratio=4.0, rerole_low_ratio=1.0,
        rerole_cooldown_s=kw.pop("cooldown", 0.0), **kw)
    sched = _FakeScheduler(runners)
    disp = _FakeDispatcher(depth)
    return RoleBalancer(sched, disp, settings, metrics=MetricsCollector()), disp


class TestRoleBalancer:
    def test_flip_to_prefill_on_deep_queue_and_back(self):
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([u, d], depth=10)
        assert bal.evaluate() == "to_prefill"
        assert u.role == "prefill"
        disp.depth = 0
        assert bal.evaluate() == "to_unified"
        assert u.role == "unified"
        counters = bal.metrics.fleet_counters()["reroles"]
        assert counters == {"to_prefill": 1, "to_unified": 1}

    def test_hysteresis_band_holds(self):
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([u, d], depth=10)
        bal.evaluate()
        assert u.role == "prefill"
        # inside the band (low < signal < high): no restore, no flap
        disp.depth = 3
        assert bal.evaluate() is None
        assert u.role == "prefill"

    def test_cooldown_bounds_flip_rate(self):
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([u, d], depth=10, cooldown=60.0)
        assert bal.evaluate() == "to_prefill"
        disp.depth = 0
        assert bal.evaluate() is None  # cooldown holds the restore
        assert u.role == "prefill"

    def test_never_rewrites_operator_roles(self):
        op_prefill = _FakeRunner("e0", "prefill")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([op_prefill, d], depth=0)
        assert bal.evaluate() is None  # nothing flipped, nothing restored
        assert op_prefill.role == "prefill"

    def test_no_flip_without_decode_capacity(self):
        u = _FakeRunner("e0", "unified")
        bal, _ = _balancer([u], depth=100)
        assert bal.evaluate() is None
        assert u.role == "unified"

    def test_rerole_flag_forces_the_signal(self):
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, _ = _balancer([u, d], depth=0)
        faults.install(faults.parse_spec("sched.rerole:nth=1", seed=1))
        assert bal.evaluate() == "to_prefill"
        faults.clear()

    def test_remote_decode_capacity_does_not_justify_a_flip(self):
        """Review fix: remote replicas are not KV handoff targets, so a
        member's decode engine must not drive a local unified engine
        into a prefill role that has nowhere to hand off."""
        u = _FakeRunner("e0", "unified")
        rd = _FakeRunner("w1:e1", "decode")
        rd.is_remote = True
        rd.status = lambda: _status("w1:e1", role="decode", remote=True)
        bal, _ = _balancer([u, rd], depth=100)
        assert bal.evaluate() is None
        assert u.role == "unified"

    def test_role_counts_exclude_remote_proxies(self):
        """Review fix: the engines_by_role gauge must mean the same
        thing whichever publisher wrote last — local replicas only."""
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        r = _FakeRunner("w1:e9", "unified")
        r.is_remote = True
        bal, _ = _balancer([u, d, r], depth=0)
        assert bal._role_counts() == {"unified": 1, "decode": 1}

    def test_remote_engines_are_never_flipped(self):
        u = _FakeRunner("w1:e0", "unified")
        u.is_remote = True
        d = _FakeRunner("e1", "decode")
        bal, _ = _balancer([u, d], depth=100)
        assert bal.evaluate() is None
        assert u.role == "unified"

    def test_restore_runs_even_with_decode_fleet_gone(self):
        """Review fix: losing the decode fleet must not strand a
        balancer-flipped engine in the prefill role — the no-decode
        guard gates only the to_prefill direction."""
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([u, d], depth=10)
        assert bal.evaluate() == "to_prefill"
        d.healthy = False  # the decode fleet dies
        disp.depth = 0
        assert bal.evaluate() == "to_unified"
        assert u.role == "unified"

    def test_stats_and_history(self):
        u = _FakeRunner("e0", "unified")
        d = _FakeRunner("e1", "decode")
        bal, disp = _balancer([u, d], depth=10)
        bal.evaluate()
        stats = bal.stats()
        assert stats["flipped"] == ["e0"]
        assert stats["history"][0]["direction"] == "to_prefill"
        assert stats["history"][0]["engine_id"] == "e0"


# ---------------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------------


class TestFleetConfig:
    def test_fleet_settings_mapping(self):
        cfg = ServerConfig.load(environ={
            "DIS_TPU_FLEET__ENABLED": "true",
            "DIS_TPU_FLEET__PORT": "7001",
            "DIS_TPU_FLEET__REROLE": "true",
            "DIS_TPU_FLEET__REROLE_HIGH_RATIO": "8.0",
        })
        s = cfg.fleet_settings()
        assert s.enabled and s.port == 7001
        assert s.rerole and s.rerole_high_ratio == 8.0
        # KV-mesh defaults: off, 30 s learning window, GbE-ish prior
        assert not s.mesh_enabled
        assert s.kv_rate_window_s == 30.0
        assert s.kv_rate_prior == 125_000_000.0

    def test_mesh_settings_mapping(self):
        cfg = ServerConfig.load(environ={
            "DIS_TPU_FLEET__MESH_ENABLED": "true",
            "DIS_TPU_FLEET__KV_RATE_WINDOW_S": "12.5",
            "DIS_TPU_FLEET__KV_RATE_PRIOR": "0",  # learned pricing off
        })
        s = cfg.fleet_settings()
        assert s.mesh_enabled
        assert s.kv_rate_window_s == 12.5
        assert s.kv_rate_prior == 0.0

    def test_queue_tenant_mapping(self):
        cfg = ServerConfig.load(environ={
            "DIS_TPU_QUEUE__TENANT_FAIRNESS": "true",
            "DIS_TPU_QUEUE__TENANT_WEIGHTS": "acme=3,free=1",
        })
        q = cfg.queue_config()
        assert q.tenant_fairness
        assert q.tenant_weights == {"acme": 3.0, "free": 1.0}

    @pytest.mark.parametrize("env", [
        {"DIS_TPU_FLEET__SUSPECT_AFTER_S": "0.1"},  # <= heartbeat
        {"DIS_TPU_FLEET__DEAD_AFTER_S": "1.0"},  # <= suspect
        {"DIS_TPU_FLEET__REROLE_LOW_RATIO": "9.0"},  # >= high
        {"DIS_TPU_FLEET__CONNECT": "nonsense"},
        {"DIS_TPU_FLEET__KV_RATE_WINDOW_S": "0"},  # must be positive
        {"DIS_TPU_FLEET__KV_RATE_PRIOR": "-1"},  # 0 disables, < 0 invalid
        {"DIS_TPU_QUEUE__TENANT_WEIGHTS": "a=-1"},
        {"DIS_TPU_QUEUE__TENANT_WEIGHTS": "a=x"},
        {"DIS_TPU_QUEUE__TENANT_WEIGHTS": "justname"},
    ])
    def test_validation_rejects(self, env):
        with pytest.raises(ConfigError):
            ServerConfig.load(environ=env)

    def test_parse_tenant_weights(self):
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("a=2, b=0.5") == {"a": 2.0, "b": 0.5}


# ---------------------------------------------------------------------------
# Serving e2e: join -> remote token-identity -> death -> redispatch
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fleet_pair():
    """Registry host (1 local engine) + in-process member (1 engine)
    joined over a real localhost fleet-wire connection."""
    import jax
    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.remote_runner import (
        FleetWorker,
    )
    from distributed_inference_server_tpu.serving.server import (
        InferenceServer,
    )

    params = llama.init_params(jax.random.PRNGKey(0), TINY,
                               dtype=jnp.float32)
    paged = PagedCacheConfig(num_pages=192, page_size=8,
                             max_pages_per_seq=32)

    def factory():
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=paged, warmup_compile=False),
            dtype=jnp.float32,
        )

    host = InferenceServer(
        factory, ByteTokenizer(), "tiny", num_engines=1,
        auto_restart=False,
        fleet_settings=FleetSettings(enabled=True,
                                     heartbeat_interval_s=0.1,
                                     suspect_after_s=0.4,
                                     dead_after_s=0.9),
    )
    host.start()
    member = InferenceServer(factory, ByteTokenizer(), "tiny",
                             num_engines=1, auto_restart=False)
    member.start()
    worker = FleetWorker(
        member.scheduler,
        FleetSettings(connect=f"127.0.0.1:{host.fleet_server.bound_port}",
                      heartbeat_interval_s=0.1),
        member_id="t-w1",
    )
    worker.start()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        if any(getattr(r, "is_remote", False) and r.is_healthy()
               for r in host.scheduler.engines()):
            break
        time.sleep(0.05)
    else:
        pytest.fail("fleet member never joined")
    yield host, member, worker
    faults.clear()
    worker.stop()
    member.shutdown(drain_timeout_s=5.0)
    host.shutdown(drain_timeout_s=5.0)


def _serve(runner, rid, prompt="fleet e2e prompt"):
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    req, sink = _req(rid, prompt=ByteTokenizer().encode(prompt))
    runner.submit([req])
    assert sink.ev.wait(90), f"{rid} never terminated"
    return sink


class TestFleetServingE2E:
    def test_remote_serving_token_identical_then_death_redispatch(
            self, fleet_pair):
        """ACCEPTANCE (ISSUE 9): a request served through a RemoteRunner
        is token-identical to a local run; killing the member with a
        zero-token request in flight completes it via redispatch with
        the registry reflecting the loss and a clean page audit."""
        host, member, worker = fleet_pair
        local = next(r for r in host.scheduler.engines()
                     if not getattr(r, "is_remote", False))
        remote = next(r for r in host.scheduler.engines()
                      if getattr(r, "is_remote", False))
        ref = _serve(local, "fe-ref")
        assert not ref.errors
        got = _serve(remote, "fe-remote")
        assert not got.errors
        assert got.toks == ref.toks and got.text == ref.text

        # /server/stats fleet block while alive
        stats = host._fleet_stats()
        assert stats["member_counts"]["alive"] == 1
        assert any(v == "unified" for v in stats["role_map"].values())
        assert stats["heartbeats"].get("ok", 0) > 0

        # kill the member mid-zero-token-request
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )

        kill_req, kill_sink = _req(
            "fe-kill", prompt=ByteTokenizer().encode("fleet e2e prompt"))
        remote.submit([kill_req])
        worker._crashed = True
        worker._close()
        assert kill_sink.ev.wait(90), "killed request never terminated"
        assert not kill_sink.errors, kill_sink.errors
        assert kill_sink.dones == 1
        assert kill_sink.toks == ref.toks  # redispatched, identical
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if host.fleet_registry.member_state("t-w1") == "dead":
                break
            time.sleep(0.05)
        assert host.fleet_registry.member_state("t-w1") == "dead"
        prom = host.metrics.prometheus_text().decode()
        assert 'fleet_members{state="dead"} 1.0' in prom
        snap = host.metrics.snapshot().to_dict()
        assert snap["resilience"]["redispatched"].get("ok", 0) >= 1
        assert local.audit() == []
        # dead member's proxies left the routing set
        assert not any(getattr(r, "is_remote", False)
                       for r in host.scheduler.engines())

    def test_done_usage_crosses_the_wire(self, fleet_pair):
        # runs before the kill test? module-scope fixture + ordering:
        # this test only needs the LOCAL engine, so it is order-proof
        host, _, _ = fleet_pair
        local = next(r for r in host.scheduler.engines()
                     if not getattr(r, "is_remote", False))
        sink = _serve(local, "fe-usage")
        assert sink.dones == 1


class TestTenantDepthGauge:
    def test_stale_tenant_series_are_removed_not_kept(self):
        """Review fix: tenant is a client-chosen string — a drained
        tenant's series must leave /metrics entirely, or label
        cardinality (and the per-publish write set) grows without
        bound."""
        m = MetricsCollector()
        m.set_tenant_depths({"a": 3, "b": 1})
        assert 'queue_tenant_depth{tenant="a"} 3.0' in (
            m.prometheus_text().decode())
        m.set_tenant_depths({"a": 2})
        prom = m.prometheus_text().decode()
        assert 'queue_tenant_depth{tenant="a"} 2.0' in prom
        assert 'tenant="b"' not in prom
        # publishing never touches more series than currently live + 1
        m.set_tenant_depths({})
        assert 'queue_tenant_depth{tenant=' not in (
            m.prometheus_text().decode())


class TestSchedulerUnregisterIf:
    def test_identity_checked_unregister_spares_the_new_proxy(self):
        """Review fix: a superseded session's late detach must not evict
        the fresh proxy a reconnect registered under the same id."""
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        sched = AdaptiveScheduler()
        old, _ = _remote()
        new, _ = _remote()
        sched.register(old)
        # reconnect replaces the registration...
        sched.register(new)
        # ...then the old session's detach races in
        assert sched.unregister_if(old.engine_id, old) is None
        assert sched.get(new.engine_id) is new
        # and the current owner CAN unregister itself
        assert sched.unregister_if(new.engine_id, new) is new
        assert sched.get(new.engine_id) is None


# ---------------------------------------------------------------------------
# Fleet KV data plane (serving/fleet_kv.py; docs/FLEET.md "KV data plane")
# ---------------------------------------------------------------------------


class TestKvDataPlaneRouting:
    def test_remote_data_plane_peer_sources_a_fetch(self):
        """A remote warm peer WITH a data channel sources a fetch onto
        the local cold target — the capability the data plane adds."""
        hashes = (11, 12, 13, 14)
        remote_warm = _status("w1:e0", remote=True, digest=hashes,
                              data_plane=True, active=9)
        local_cold = _status("local")
        plan = plan_route([remote_warm, local_cold], hashes)
        assert plan.decision == "fetch"
        assert plan.engine_id == "local"
        assert plan.peer_id == "w1:e0"

    def test_control_plane_only_remote_never_sources(self):
        """Without a data channel the old exclusion holds exactly."""
        hashes = (11, 12, 13, 14)
        remote_warm = _status("w1:e0", remote=True, digest=hashes,
                              active=9)
        local_cold = _status("local")
        plan = plan_route([remote_warm, local_cold], hashes)
        assert plan.decision in ("warm", "recompute")

    def test_local_peer_preferred_at_equal_depth(self):
        hashes = (11, 12, 13, 14)
        remote_warm = _status("w1:e0", remote=True, digest=hashes,
                              data_plane=True)
        local_warm = _status("peer", digest=hashes, active=9)
        local_cold = _status("local")
        plan = plan_route([remote_warm, local_warm, local_cold], hashes)
        if plan.decision == "fetch":
            assert plan.peer_id == "peer"  # cheaper wire at equal depth

    def test_remote_page_cost_prices_the_wire(self):
        """fleet.kv_page_cost is the honesty knob: a pricey cross-host
        wire flips the SAME topology from fetch to recompute."""
        from distributed_inference_server_tpu.serving.scheduler import (
            FetchCosts,
        )

        hashes = (11, 12, 13, 14)
        remote_warm = _status("w1:e0", remote=True, digest=hashes,
                              active=9, data_plane=True)
        local_cold = _status("local")
        cheap = plan_route([remote_warm, local_cold], hashes,
                           costs=FetchCosts(remote_page_cost=0.5))
        assert cheap.decision == "fetch"
        dear = plan_route([remote_warm, local_cold], hashes,
                          costs=FetchCosts(remote_page_cost=5.0))
        assert dear.decision != "fetch"

    def test_schedule_decode_includes_kv_capable_remote(self):
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        sched = AdaptiveScheduler()
        runner, _ = _remote()
        # feed the proxy a decode-role status under its fleet-namespaced
        # id (what the member's heartbeat would publish)
        runner.update_status(_status("w1:e0", role="decode", remote=True))
        sched.register(runner)
        # control-plane only: excluded, exactly as before
        assert sched.schedule_decode() is None
        channel = _StubKvChannel()  # the member advertised a channel
        runner.kv_channel = channel
        assert sched.schedule_decode() is runner
        # gray-failure gate (serving/health.py): an OPEN data-channel
        # breaker pulls the member out of handoff-target election
        channel.available = False
        assert sched.schedule_decode() is None
        channel.available = True
        assert sched.schedule_decode() is runner

    def test_has_decode_targets_counts_kv_capable_remote(self):
        from distributed_inference_server_tpu.serving.disagg import (
            DisaggController,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        sched = AdaptiveScheduler()
        ctrl = DisaggController(sched)
        runner, _ = _remote()
        runner.update_status(_status("w1:e0", role="decode", remote=True))
        sched.register(runner)
        assert not ctrl.has_decode_targets()
        channel = _StubKvChannel()
        runner.kv_channel = channel
        assert ctrl.has_decode_targets()
        # an OPEN breaker removes the member's decode capacity too
        channel.available = False
        assert not ctrl.has_decode_targets()


class _FakeKvRunner:
    """Member-side runner double for wire tests: serves the KV import/
    export surface synchronously (the real one posts to its inbox)."""

    def __init__(self, engine_id="e0"):
        self.engine_id = engine_id
        self.opened = {}
        self.committed = []
        self.aborted = []
        self.export_result = None  # (depth, chunks) | None
        self.export_error = None
        self.on_commit_req = None  # captures the member-side request

    def is_healthy(self):
        return True

    def submit_prefix_export(self, rid, hashes, chunk_pages, wire_quant,
                             on_done, trace=None):
        if self.export_error is not None:
            on_done(None, self.export_error)
        else:
            on_done(self.export_result, None)

    def submit_import_open(self, rid, prefix_pages, chunks, on_done):
        self.opened[rid] = (prefix_pages, list(chunks))
        on_done(True, None)

    def submit_import_commit(self, exp, req, on_done):
        self.committed.append(exp)
        self.on_commit_req = req
        on_done(True, None)

    def submit_resume(self, exp, req, on_done):
        self.committed.append(exp)
        self.on_commit_req = req
        on_done(True, None)

    def submit_import_abort(self, rid):
        self.aborted.append(rid)

    def abort(self, rid):
        self.aborted.append(("abort", rid))


class _FakeKvScheduler:
    def __init__(self, runner):
        self._runner = runner

    def get(self, engine_id):
        return self._runner if engine_id == self._runner.engine_id else None


def _kv_chunks(n=2, payload=b"x" * 64):
    from distributed_inference_server_tpu.engine.kv_cache import (
        KvChunk,
        chunk_crc,
    )

    return [KvChunk(index=i, total=n, page_start=i, page_count=1,
                    payload=payload, crc32=chunk_crc(payload))
            for i in range(n)]


@pytest.fixture()
def kv_wire():
    """A real KvDataServer (fake runner) + KvDataChannel over localhost
    TCP — the data-channel wire exercised end to end without engines."""
    from distributed_inference_server_tpu.serving.fleet_kv import (
        KvDataChannel,
        KvDataServer,
    )

    runner = _FakeKvRunner()
    server = KvDataServer(_FakeKvScheduler(runner), host="127.0.0.1")
    server.start()
    events = []
    lost = []
    channel = KvDataChannel(
        "w1", "127.0.0.1", server.bound_port, max_streams=2,
        on_event=events.append,
        on_lost_requests=lambda rids, reason: lost.append((rids, reason)),
    )
    yield channel, server, runner, events, lost
    channel.close()
    server.stop()


class TestKvDataChannelWire:
    def _wait(self, box, timeout=15.0):
        assert box["ev"].wait(timeout), "stream never resolved"
        return box

    def _cb_box(self):
        box = {"ev": threading.Event(), "args": None}

        def cb(*args):
            box["args"] = args
            box["ev"].set()

        return box, cb

    def test_fetch_round_trip(self, kv_wire):
        channel, _server, runner, _events, _lost = kv_wire
        chunks = _kv_chunks(3)
        runner.export_result = (3, chunks)
        box, cb = self._cb_box()
        channel.fetch_prefix("r1", "e0", [1, 2, 3], 8, "none", None, cb)
        self._wait(box)
        result, err = box["args"]
        assert err is None
        depth, got = result
        assert depth == 3
        assert [c.payload for c in got] == [c.payload for c in chunks]
        assert [c.crc32 for c in got] == [c.crc32 for c in chunks]

    def test_fetch_export_failure_resolves_stream(self, kv_wire):
        channel, _server, runner, _events, _lost = kv_wire
        runner.export_error = "chain evicted"
        box, cb = self._cb_box()
        channel.fetch_prefix("r1", "e0", [1], 8, "none", None, cb)
        self._wait(box)
        result, err = box["args"]
        assert result is None and "chain evicted" in err

    def test_open_commit_and_event_pump(self, kv_wire):
        """The full cross-host handoff shape on the wire: open the
        prefix, commit tail+state, then the member's sink events ride
        back as FleetEvent frames."""
        from distributed_inference_server_tpu.engine.engine import (
            SamplingParams,
            SequenceExport,
        )

        channel, _server, runner, events, _lost = kv_wire
        prefix = _kv_chunks(2)
        box, cb = self._cb_box()
        channel.import_open("r1", "e0", 4, "none", prefix, None, cb)
        self._wait(box)
        assert box["args"][0] is True
        assert runner.opened["r1"][0] == 4
        assert len(runner.opened["r1"][1]) == 2

        exp = SequenceExport(
            request_id="r1", token_ids=[1, 2, 3, 4], prompt_len=3,
            seq_len=4, next_token=9,
            params=SamplingParams(max_tokens=8, temperature=0.0),
            output_text="abc", emitted_upto=3, emitted_tokens=1,
            pending_ids=[], kv=b"", kv_chunks=_kv_chunks(1),
        )
        box2, cb2 = self._cb_box()
        channel.import_commit(exp, "e0", None, cb2)
        self._wait(box2)
        assert box2["args"][0] is True
        got = runner.committed[0]
        assert got.token_ids == [1, 2, 3, 4]
        assert got.next_token == 9
        assert len(got.kv_chunks) == 1
        # the member-side request streams events back over the channel
        runner.on_commit_req.sink.on_token(42, "hi", 4)
        runner.on_commit_req.sink.on_done("stop", None)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and len(events) < 2:
            time.sleep(0.02)
        kinds = [e["kind"] for e in events]
        assert kinds == ["token", "done"]
        assert events[0]["token_id"] == 42
        # done released the event-tracking entry
        assert channel.stats()["event_requests"] == 0

    def test_window_full_fails_fast(self, kv_wire):
        """The bounded in-flight window: the (N+1)th stream fails to
        its fallback instead of queueing behind bulk transfers."""
        channel, _server, runner, _events, _lost = kv_wire
        # stall resolution: the runner double never answers
        runner.submit_prefix_export = lambda *a, **k: None
        boxes = []
        for i in range(2):
            box, cb = self._cb_box()
            boxes.append(box)
            channel.fetch_prefix(f"r{i}", "e0", [1], 8, "none", None, cb)
        box3, cb3 = self._cb_box()
        channel.fetch_prefix("r2", "e0", [1], 8, "none", None, cb3)
        self._wait(box3)
        result, err = box3["args"]
        assert result is None and "window full" in err
        assert not boxes[0]["ev"].is_set()  # in-flight ones unaffected

    def test_connect_fault_fails_stream(self, kv_wire):
        """fleet.kv_connect (docs/RESILIENCE.md): the lazy dial dies —
        the stream resolves failed and the caller falls back."""
        channel, _server, _runner, _events, _lost = kv_wire
        faults.install(faults.parse_spec("fleet.kv_connect:nth=1", 7))
        box, cb = self._cb_box()
        channel.fetch_prefix("r1", "e0", [1], 8, "none", None, cb)
        self._wait(box)
        result, err = box["args"]
        assert result is None and err

    def test_chunk_fault_tears_stream(self, kv_wire):
        """fleet.kv_chunk: the Nth chunk dies on the wire — the stream
        resolves failed (open never lands on the member)."""
        channel, _server, runner, _events, _lost = kv_wire
        faults.install(faults.parse_spec("fleet.kv_chunk:nth=1", 7))
        box, cb = self._cb_box()
        channel.import_open("r1", "e0", 2, "none", _kv_chunks(2), None, cb)
        self._wait(box)
        assert box["args"][0] is False
        assert "r1" not in runner.opened

    def test_channel_death_fails_event_requests(self, kv_wire):
        """A data-channel death with a migrated request mid-decode
        reports the lost request ids so the proxy can fail them fast."""
        from distributed_inference_server_tpu.engine.engine import (
            SamplingParams,
            SequenceExport,
        )

        channel, server, runner, _events, lost = kv_wire
        exp = SequenceExport(
            request_id="r9", token_ids=[1, 2], prompt_len=1, seq_len=2,
            next_token=3,
            params=SamplingParams(max_tokens=8, temperature=0.0),
            output_text="", emitted_upto=0, emitted_tokens=1,
            pending_ids=[], kv=b"", kv_chunks=_kv_chunks(1),
        )
        box, cb = self._cb_box()
        channel.resume(exp, "e0", None, cb)
        self._wait(box)
        assert box["args"][0] is True
        assert channel.stats()["event_requests"] == 1
        server.stop()  # the host link dies under the decode
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and not lost:
            time.sleep(0.02)
        assert lost and lost[0][0] == ["r9"]
        # the member aborted its orphaned sequence
        assert ("abort", "r9") in runner.aborted

    def test_import_abort_reaches_member(self, kv_wire):
        channel, _server, runner, _events, _lost = kv_wire
        box, cb = self._cb_box()
        channel.import_open("r1", "e0", 2, "none", _kv_chunks(2), None, cb)
        self._wait(box)
        channel.import_abort("r1", "e0")
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and "r1" not in runner.aborted:
            time.sleep(0.02)
        assert "r1" in runner.aborted


# ---------------------------------------------------------------------------
# Cross-host handoff / remote fetch e2e (real engines, real data channel)
# ---------------------------------------------------------------------------


def _kv_pair(host_roles, member_roles, strategy=None, engine_kwargs=None):
    """Registry host + in-process member joined over real TCP (control
    wire AND KV data channel), with configurable topologies."""
    import jax
    import jax.numpy as jnp

    from distributed_inference_server_tpu.engine.engine import (
        EngineConfig,
        LLMEngine,
    )
    from distributed_inference_server_tpu.engine.kv_cache import (
        PagedCacheConfig,
    )
    from distributed_inference_server_tpu.models import llama
    from distributed_inference_server_tpu.models.configs import TINY
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
    from distributed_inference_server_tpu.serving.remote_runner import (
        FleetWorker,
    )
    from distributed_inference_server_tpu.serving.scheduler import (
        SchedulingStrategy,
    )
    from distributed_inference_server_tpu.serving.server import (
        InferenceServer,
    )

    params = llama.init_params(jax.random.PRNGKey(0), TINY,
                               dtype=jnp.float32)
    paged = PagedCacheConfig(num_pages=192, page_size=8,
                             max_pages_per_seq=32)

    def factory():
        return LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(max_batch=4, prefill_buckets=(16, 64),
                         paged=paged, warmup_compile=False,
                         **(engine_kwargs or {})),
            dtype=jnp.float32,
        )

    host = InferenceServer(
        factory, ByteTokenizer(), "tiny", num_engines=len(host_roles),
        engine_roles=list(host_roles), auto_restart=False,
        strategy=(SchedulingStrategy.parse(strategy) if strategy
                  else SchedulingStrategy.LEAST_LOADED),
        fleet_settings=FleetSettings(enabled=True,
                                     heartbeat_interval_s=0.1,
                                     suspect_after_s=0.6,
                                     dead_after_s=1.5),
    )
    host.start()
    member = InferenceServer(
        factory, ByteTokenizer(), "tiny", num_engines=len(member_roles),
        engine_roles=list(member_roles), auto_restart=False,
    )
    member.start()
    worker = FleetWorker(
        member.scheduler,
        FleetSettings(connect=f"127.0.0.1:{host.fleet_server.bound_port}",
                      heartbeat_interval_s=0.1),
        member_id="kv-w1",
    )
    worker.start()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        remote = next((r for r in host.scheduler.engines()
                       if getattr(r, "is_remote", False)
                       and r.is_healthy()
                       and getattr(r, "supports_kv_import", False)), None)
        if remote is not None:
            return host, member, worker
        time.sleep(0.05)
    pytest.fail("kv fleet member never joined with a data channel")


@pytest.fixture(scope="module")
def kv_handoff_pair():
    """Host: one PREFILL engine. Member: one DECODE engine. Every
    host-admitted request wants a cross-host migration over the data
    channel (docs/FLEET.md "KV data plane")."""
    host, member, worker = _kv_pair(["prefill"], ["decode"])
    yield host, member, worker
    faults.clear()
    worker.stop()
    member.shutdown(drain_timeout_s=5.0)
    host.shutdown(drain_timeout_s=5.0)


def _serve_tokens(runner, rid, prompt, max_tokens=48):
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    sink = _Sink()
    req = ServerRequest(
        rid, ByteTokenizer().encode(prompt),
        SamplingParams(max_tokens=max_tokens, temperature=0.0), sink)
    runner.submit([req])
    assert sink.ev.wait(120), f"{rid} never terminated"
    return sink


def _remote_handoffs(host):
    prom = host.metrics.prometheus_text().decode()
    import re

    m = re.search(r'kv_handoff_chunks_total\{scope="remote"\} ([0-9.]+)',
                  prom)
    return float(m.group(1)) if m else 0.0


class TestCrossHostHandoffE2E:
    """ACCEPTANCE (ISSUE 13): cross-host prefill→decode handoff over
    the member data channel — bit-identical to the local greedy stream,
    f32 and int8 wire; a mid-stream peer death degrades to
    decode-in-place exactly once with zero page leak."""

    PROMPT = "the kv bytes take the long way home"

    def _migrated_serve(self, host, rid, max_tokens=48, attempts=4):
        """Serve via the host's prefill runner until a migration lands
        (a fast in-place completion during the open window is a CORRECT
        degradation, not a failure — identity asserted every time)."""
        local = next(r for r in host.scheduler.engines()
                     if not getattr(r, "is_remote", False))
        before = _remote_handoffs(host)
        for i in range(attempts):
            sink = _serve_tokens(local, f"{rid}-{i}", self.PROMPT,
                                 max_tokens)
            assert not sink.errors, sink.errors
            if _remote_handoffs(host) > before:
                return sink, f"{rid}-{i}"
        pytest.fail(f"no cross-host migration in {attempts} attempts")

    def test_remote_handoff_token_identity_f32(self, kv_handoff_pair):
        host, member, _ = kv_handoff_pair
        # reference: the member's own engine decoding in place (same
        # seeded params — the wire must not perturb a single token)
        member_local = member.scheduler.engines()[0]
        ref = _serve_tokens(member_local, "kvho-ref", self.PROMPT)
        assert not ref.errors
        sink, rid = self._migrated_serve(host, "kvho-f32")
        assert sink.toks == ref.toks and sink.text == ref.text
        assert sink.dones == 1
        # phase attribution covers the REMOTE handoff_stall window
        tl = host.recorder.timeline(rid)
        assert tl is not None
        assert any(e["name"] == "handoff_resume" for e in tl["events"])
        assert tl["phases"]["handoff_stall"] > 0
        # metrics: ok outcome with remote-scoped chunks
        snap = host.metrics.snapshot().to_dict()
        assert snap["disagg"]["handoffs"].get("ok", 0) >= 1

    def test_remote_handoff_token_identity_int8_wire(self,
                                                     kv_handoff_pair):
        import dataclasses as _dc

        host, member, _ = kv_handoff_pair
        member_local = member.scheduler.engines()[0]
        ref = _serve_tokens(member_local, "kvho-ref8", self.PROMPT)
        old = host.disagg.settings
        host.disagg.settings = _dc.replace(old, wire_quant="int8")
        try:
            sink, _rid = self._migrated_serve(host, "kvho-int8")
        finally:
            host.disagg.settings = old
        # int8 wire quantization is exact for greedy tiny-f32 streams
        # (the same tolerance contract the in-process int8 tests pin)
        assert sink.toks == ref.toks and sink.text == ref.text

    def test_peer_death_mid_stream_decodes_in_place(self,
                                                    kv_handoff_pair):
        host, member, _ = kv_handoff_pair
        member_local = member.scheduler.engines()[0]
        ref = _serve_tokens(member_local, "kvho-refd", self.PROMPT)
        local = next(r for r in host.scheduler.engines()
                     if not getattr(r, "is_remote", False))
        faults.install(faults.parse_spec("fleet.kv_chunk:nth=1", 5))
        try:
            sink = _serve_tokens(local, "kvho-dead", self.PROMPT)
        finally:
            faults.clear()
        # exactly once, token-identical, in place
        assert not sink.errors and sink.dones == 1
        assert sink.toks == ref.toks and sink.text == ref.text
        # zero page leak on either side
        assert local.audit() == []
        assert member.scheduler.engines()[0].audit() == []


@pytest.fixture(scope="module")
def kv_fetch_pair():
    """Host: one unified cache_aware engine (the fetch target). Member:
    one unified engine (the warm fetch source). Python allocator tier —
    digests need its export surface."""
    host, member, worker = _kv_pair(
        ["unified"], ["unified"], strategy="cache_aware",
        engine_kwargs={"native_allocator": False},
    )
    yield host, member, worker
    faults.clear()
    worker.stop()
    member.shutdown(drain_timeout_s=5.0)
    host.shutdown(drain_timeout_s=5.0)


def _warm_member(host, member, prompt):
    """Warm the member's prefix cache over the control wire and wait
    until THIS prompt's chain head is in the heartbeated digest (a
    non-empty digest from an earlier prompt is not enough — routing
    would see depth 0 and never plan a fetch)."""
    from distributed_inference_server_tpu.engine.kv_cache import (
        chain_hashes,
    )
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    remote = next(r for r in host.scheduler.engines()
                  if getattr(r, "is_remote", False))
    for i in range(2):
        sink = _serve_tokens(remote, f"warm-{abs(hash(prompt)) % 997}-{i}",
                             prompt, max_tokens=8)
        assert not sink.errors
    head = chain_hashes(ByteTokenizer().encode(prompt), 8, max_pages=1)[0]
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        s = remote.status()
        if (s.prefix_digest and head in s.prefix_digest
                and getattr(s, "data_plane", False)):
            return remote
        time.sleep(0.05)
    pytest.fail("member digest never reached the routing snapshot")


def _dispatch_request(host, rid, prompt, max_tokens=16):
    from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

    sink = _Sink()
    host.dispatcher.submit(ServerRequest(
        rid, ByteTokenizer().encode(prompt),
        SamplingParams(max_tokens=max_tokens, temperature=0.0), sink))
    assert sink.ev.wait(120), f"{rid} never terminated"
    return sink


class TestRemoteFetchE2E:
    """ACCEPTANCE (ISSUE 13): cross-host peer prefix fetch — a remote
    warm member sources the chain onto the cold local target over the
    data channel, token-identically; peer death degrades to recompute
    exactly once with zero page leak."""

    def _fetch_counts(self, host):
        snap = host.metrics.snapshot().to_dict()
        return dict((snap.get("cache") or {}).get("peer_fetch") or {})

    def test_remote_fetch_token_identity_f32(self, kv_fetch_pair):
        host, member, _ = kv_fetch_pair
        prompt = "warm chains cross the wire " * 2
        _warm_member(host, member, prompt)
        before = self._fetch_counts(host).get("ok", 0)
        faults.install(faults.parse_spec("sched.fetch_decision:nth=1", 3))
        try:
            sink = _dispatch_request(host, "kvpf-f32", prompt)
        finally:
            faults.clear()
        assert not sink.errors and sink.dones == 1
        assert self._fetch_counts(host).get("ok", 0) == before + 1
        # identity: the member decodes the same prompt in place
        ref = _serve_tokens(member.scheduler.engines()[0], "kvpf-ref",
                            prompt, max_tokens=16)
        assert sink.toks == ref.toks and sink.text == ref.text
        # phase attribution covers the REMOTE peer_fetch window
        tl = host.recorder.timeline("kvpf-f32")
        assert tl is not None and tl["phases"]["peer_fetch"] > 0
        # scope=remote on the wire counters
        prom = host.metrics.prometheus_text().decode()
        assert 'kv_prefix_fetch_total{outcome="ok",scope="remote"}' in prom
        assert 'kv_prefix_fetch_bytes_total{scope="remote"}' in prom

    def test_remote_fetch_token_identity_int8_wire(self, kv_fetch_pair):
        import dataclasses as _dc

        host, member, _ = kv_fetch_pair
        prompt = "int8 codes ride the member wire " * 2
        _warm_member(host, member, prompt)
        before = self._fetch_counts(host).get("ok", 0)
        fetcher = host.prefix_fetcher
        old = fetcher.settings
        fetcher.settings = _dc.replace(old, wire_quant="int8")
        faults.install(faults.parse_spec("sched.fetch_decision:nth=1", 3))
        try:
            sink = _dispatch_request(host, "kvpf-int8", prompt)
        finally:
            faults.clear()
            fetcher.settings = old
        assert not sink.errors and sink.dones == 1
        assert self._fetch_counts(host).get("ok", 0) == before + 1
        ref = _serve_tokens(member.scheduler.engines()[0], "kvpf-ref8",
                            prompt, max_tokens=16)
        assert sink.toks == ref.toks and sink.text == ref.text

    def test_remote_source_death_degrades_to_recompute(self,
                                                       kv_fetch_pair):
        host, member, _ = kv_fetch_pair
        prompt = "the peer dies and the target recomputes " * 2
        _warm_member(host, member, prompt)
        before = self._fetch_counts(host).get("fallback", 0)
        faults.install(faults.parse_spec(
            "sched.fetch_decision:nth=1;fleet.kv_chunk:nth=1", 5))
        try:
            sink = _dispatch_request(host, "kvpf-dead", prompt)
        finally:
            faults.clear()
        assert not sink.errors and sink.dones == 1
        assert self._fetch_counts(host).get("fallback", 0) == before + 1
        ref = _serve_tokens(member.scheduler.engines()[0], "kvpf-refd",
                            prompt, max_tokens=16)
        assert sink.toks == ref.toks and sink.text == ref.text
        local = next(r for r in host.scheduler.engines()
                     if not getattr(r, "is_remote", False))
        assert local.audit() == []
        assert member.scheduler.engines()[0].audit() == []


class TestKvFleetConfig:
    def test_kv_settings_mapping(self):
        cfg = ServerConfig.load(environ={
            "DIS_TPU_FLEET__KV_DATA_PORT": "40100",
            "DIS_TPU_FLEET__KV_PAGE_COST": "0.9",
            "DIS_TPU_FLEET__KV_MAX_STREAMS": "2",
            "DIS_TPU_FLEET__KV_CONNECT_TIMEOUT_S": "2.5",
            "DIS_TPU_FLEET__KV_ENABLED": "false",
        })
        fs = cfg.fleet_settings()
        assert fs.kv_data_port == 40100
        assert fs.kv_max_streams == 2
        assert fs.kv_connect_timeout_s == 2.5
        assert fs.kv_enabled is False
        # the cross-host wire rate lands in the routing cost model
        assert cfg.fetch_costs().remote_page_cost == 0.9

    @pytest.mark.parametrize("env", [
        {"DIS_TPU_FLEET__KV_DATA_PORT": "70000"},
        {"DIS_TPU_FLEET__KV_PAGE_COST": "-1"},
        {"DIS_TPU_FLEET__KV_MAX_STREAMS": "0"},
        {"DIS_TPU_FLEET__KV_CONNECT_TIMEOUT_S": "0"},
    ])
    def test_kv_validation_rejects(self, env):
        with pytest.raises(ConfigError):
            ServerConfig.load(environ=env)

    def test_fleet_relaxes_single_sided_role_topologies(self):
        """A prefill-only registry host / decode-only worker is a LEGAL
        production config once the process is part of a fleet — the
        counterpart role lives on another member over the KV data
        plane. Standalone processes keep the strict check."""
        with pytest.raises(ConfigError):
            ServerConfig.load(environ={
                "DIS_TPU_SERVER__ENGINE_ROLES": "prefill"})
        with pytest.raises(ConfigError):
            ServerConfig.load(environ={
                "DIS_TPU_SERVER__ENGINE_ROLES": "decode"})
        host = ServerConfig.load(environ={
            "DIS_TPU_SERVER__ENGINE_ROLES": "prefill",
            "DIS_TPU_FLEET__ENABLED": "true"})
        assert host.engine_roles() == ["prefill"]
        worker = ServerConfig.load(environ={
            "DIS_TPU_SERVER__ENGINE_ROLES": "decode",
            "DIS_TPU_FLEET__CONNECT": "127.0.0.1:9999"})
        assert worker.engine_roles() == ["decode"]


# ---------------------------------------------------------------------------
# KvIntro broker fault (docs/RESILIENCE.md fleet.kv_intro)
# ---------------------------------------------------------------------------


class TestKvIntroBrokerFault:
    def test_injected_intro_drop_counts_dropped_and_recovers(self):
        """An armed ``fleet.kv_intro`` kills exactly one KvIntro on the
        control wire: the broker books it ``dropped`` (best-effort by
        design — the mesh route degrades to recompute, never to an
        error) and the next send goes through and books ``sent``."""
        from distributed_inference_server_tpu.serving.fleet import FleetServer

        sent = []

        class _Session:
            member_id = "m-intro"

            def send(self, name, obj):
                sent.append((name, obj))

        class _Broker:
            metrics = MetricsCollector()
            _send_intro = FleetServer._send_intro

        broker = _Broker()
        faults.install(faults.parse_spec("fleet.kv_intro:nth=1", seed=9))
        try:
            broker._send_intro(_Session(), {"member_id": "m2"})
            broker._send_intro(_Session(), {"member_id": "m2"})
        finally:
            faults.clear()
        assert len(sent) == 1
        counters = broker.metrics.fleet_counters()["kv_intros"]
        assert counters == {"dropped": 1, "sent": 1}


# ---------------------------------------------------------------------------
# Dial-path configure failure (distlint DL016 regression: a socket that
# dialed but cannot be configured must be closed, not leaked)
# ---------------------------------------------------------------------------


class _ConfigFailSock:
    """create_connection succeeded; configuring the socket then fails
    (EBADF/ENOTSOCK race with a concurrent close, resource limits)."""

    def __init__(self):
        self.closed = False

    def settimeout(self, t):
        raise OSError("bad fd")

    def setsockopt(self, *a):
        pass

    def close(self):
        self.closed = True


class TestDialConfigureFailure:
    def test_kv_channel_closes_sock_and_backs_off(self, monkeypatch):
        from distributed_inference_server_tpu.serving.fleet_kv import (
            KvDataChannel,
        )

        ch = KvDataChannel("m-cfg", "127.0.0.1", 1)
        fake = _ConfigFailSock()
        monkeypatch.setattr(socket, "create_connection",
                            lambda *a, **k: fake)
        before = ch._backoff_s
        with pytest.raises(OSError):
            ch._ensure_connected()
        assert fake.closed  # the dialed fd must not leak
        # the configure failure takes the same backoff a dial failure
        # would: the next attempt is deferred, not immediate
        assert ch._reconnecting
        assert ch._backoff_s == min(before * 2.0, 5.0)
        assert ch._not_before > time.monotonic() - 1.0
        assert ch._sock is None

    def test_fleet_worker_closes_sock_on_configure_failure(
            self, monkeypatch):
        from distributed_inference_server_tpu.serving.remote_runner import (
            FleetWorker,
        )

        class _Stub:
            class settings:
                connect = "127.0.0.1:9"

        fake = _ConfigFailSock()
        monkeypatch.setattr(socket, "create_connection",
                            lambda *a, **k: fake)
        with pytest.raises(OSError):
            FleetWorker._connect(_Stub(), 1.0)
        assert fake.closed  # the dialed fd must not leak


# ---------------------------------------------------------------------------
# Registry HA: lease-fenced failover (serving/fleet_ha.py)
# ---------------------------------------------------------------------------


class _StubFleetServer:
    def __init__(self):
        self.promotes = 0

    def on_ha_promote(self):
        self.promotes += 1


class _StubPeerLink:
    """Records frames instead of dialing; stands in for _PeerLink."""

    def __init__(self, endpoint):
        self.endpoint = endpoint
        self.frames = []

    def send(self, name, obj):
        self.frames.append((name, dict(obj)))
        return True

    def connected(self):
        return True

    def close(self):
        pass


REGS = ("127.0.0.1:7101", "127.0.0.1:7102", "127.0.0.1:7103")


def _ha(me=0, lease_s=3.0, lease_suspect_s=1.5):
    """A RegistryHA with its beat thread parked (60s interval) and its
    peer wires stubbed — tests drive _tick(now) / on_peer_frame by hand."""
    from distributed_inference_server_tpu.serving.fleet_ha import RegistryHA

    settings = FleetSettings(
        enabled=True, registries=REGS, lease_s=lease_s,
        lease_suspect_s=lease_suspect_s, heartbeat_interval_s=60.0,
    )
    srv = _StubFleetServer()
    ha = RegistryHA(srv, settings)
    ha.start(REGS[me])
    ha.stop()  # park the thread; state survives, ticks are now manual
    ha._peers = [_StubPeerLink(ep) for i, ep in enumerate(REGS) if i != me]
    return ha, srv


class TestRegistryHA:
    def test_boots_standby_and_respects_boot_grace(self):
        ha, srv = _ha(me=0)
        assert ha.role == "standby" and ha.epoch == 0
        now = time.monotonic()
        ha._tick(now)  # within the one-lease boot grace: no election
        assert ha.role == "standby" and srv.promotes == 0
        # ...and the standby beat announced itself to every peer
        assert all(link.frames[-1][0] == "RegistryState"
                   for link in ha._peers)

    def test_lowest_index_promotes_after_grace(self):
        ha, srv = _ha(me=0)
        now = time.monotonic()
        ha._tick(now + ha.settings.lease_s + 0.1)
        assert ha.is_primary() and ha.epoch == 1
        assert srv.promotes == 1
        assert ha.stats()["takeovers"] == {"lease_expired": 1}
        # the next tick beats an epoch-stamped lease to every peer
        ha._tick(now + ha.settings.lease_s + 0.2)
        for link in ha._peers:
            name, frame = link.frames[-1]
            assert name == "RegistryLease"
            assert frame["epoch"] == 1 and frame["role"] == "primary"

    def test_standby_defers_to_fresh_lower_index_peer(self):
        ha, srv = _ha(me=1)
        now = time.monotonic()
        # age the boot clock so the grace has lapsed, then observe a
        # FRESH frame from registries[0] (any kind): it defers us
        ha._lease_rx_at = now - ha.settings.lease_s - 0.1
        ha.on_peer_frame("RegistryState",
                         {"registry_id": REGS[0], "epoch": 0,
                          "role": "standby"})
        ha._tick(now)
        assert ha.role == "standby" and srv.promotes == 0
        # once that frame ages past one lease window, we stop deferring
        ha._tick(now + ha.settings.lease_s + 0.2)
        assert ha.is_primary() and ha.epoch == 1

    def test_lease_accept_then_expiry_promotes_above_learned_epoch(self):
        ha, srv = _ha(me=1)
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[0], "epoch": 5,
                          "role": "primary"})
        assert ha.epoch == 5 and ha.role == "standby"
        st = ha.stats()
        assert st["lease"]["holder"] == REGS[0]
        assert st["lease"]["state"] == MEMBER_ALIVE
        now = time.monotonic()
        ha._tick(now)  # lease alive: no election
        assert ha.role == "standby"
        # no beat for a full lease window: the watch ages the holder
        # dead, the deferral window lapses with it, and we take over
        ha._tick(now + ha.settings.lease_s + 0.1)
        assert ha.is_primary()
        assert ha.epoch == 6  # max(self, peer) + 1: fences the old primary
        assert srv.promotes == 1

    def test_primary_fenced_by_higher_epoch_lease(self):
        ha, srv = _ha(me=0)
        ha._tick(time.monotonic() + ha.settings.lease_s + 0.1)
        assert ha.is_primary() and ha.epoch == 1
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[1], "epoch": 3,
                          "role": "primary"})
        assert ha.role == "standby" and ha.epoch == 3
        assert ha.stats()["takeovers"].get("fenced") == 1
        # the fencing lease is also ACCEPTED: the demoted registry
        # immediately watches the new primary's lease
        assert ha.stats()["lease"]["holder"] == REGS[1]

    def test_same_epoch_tie_breaks_on_list_order(self):
        # the higher-index primary yields...
        ha, _ = _ha(me=1)
        ha._tick(time.monotonic() + 2 * ha.settings.lease_s + 0.2)
        assert ha.is_primary() and ha.epoch == 1
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[0], "epoch": 1,
                          "role": "primary"})
        assert ha.role == "standby"
        # ...and the lower-index primary holds its ground
        ha0, _ = _ha(me=0)
        ha0._tick(time.monotonic() + ha0.settings.lease_s + 0.1)
        assert ha0.is_primary() and ha0.epoch == 1
        ha0.on_peer_frame("RegistryLease",
                          {"registry_id": REGS[1], "epoch": 1,
                           "role": "primary"})
        assert ha0.is_primary() and ha0.epoch == 1

    def test_stale_lease_ignored(self):
        ha, _ = _ha(me=1)
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[0], "epoch": 5,
                          "role": "primary"})
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[2], "epoch": 3,
                          "role": "primary"})
        # the partitioned old primary's lease changes nothing here
        assert ha.epoch == 5
        assert ha.stats()["lease"]["holder"] == REGS[0]

    def test_registry_state_echo_fences_primary(self):
        ha, _ = _ha(me=0)
        ha._tick(time.monotonic() + ha.settings.lease_s + 0.1)
        assert ha.is_primary()
        # a standby that has already seen a newer primary than us
        ha.on_peer_frame("RegistryState",
                         {"registry_id": REGS[2], "epoch": 4,
                          "role": "standby"})
        assert ha.role == "standby" and ha.epoch == 4

    def test_restart_resets_election_state(self):
        ha, _ = _ha(me=0)
        ha._tick(time.monotonic() + ha.settings.lease_s + 0.1)
        assert ha.is_primary() and ha.epoch == 1
        ha.stop()
        ha.start(REGS[0])  # models a process restart
        ha.stop()
        assert ha.role == "standby" and ha.epoch == 0
        assert ha.stats()["takeovers"] == {}

    def test_injected_takeover_crash_is_atomic_or_absent(self):
        ha, srv = _ha(me=0)
        faults.install(faults.parse_spec("fleet.takeover:nth=1", seed=7))
        now = time.monotonic()
        with pytest.raises(faults.InjectedFault):
            ha._tick(now + ha.settings.lease_s + 0.1)
        # the crash fired BEFORE any state change: still a standby at
        # epoch 0, zero takeovers recorded, promote hook never ran
        assert ha.role == "standby" and ha.epoch == 0
        assert ha.stats()["takeovers"] == {} and srv.promotes == 0
        # the one-shot fault is spent: the retry tick promotes cleanly
        ha._tick(now + ha.settings.lease_s + 0.2)
        assert ha.is_primary() and ha.epoch == 1

    def test_stats_shape(self):
        ha, _ = _ha(me=2)
        ha.on_peer_frame("RegistryLease",
                         {"registry_id": REGS[0], "epoch": 2,
                          "role": "primary"})
        st = ha.stats()
        assert st["registry_id"] == REGS[2]
        assert st["role"] == "standby" and st["epoch"] == 2
        assert st["peers"][REGS[0]]["role"] == "primary"
        assert st["peers"][REGS[0]]["epoch"] == 2
        assert st["lease"]["age_s"] >= 0.0
