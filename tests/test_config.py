"""Config system tests: precedence (Property 26, design.md:836-840),
validation (Property 27, design.md:842-846), and hot-reload
(requirements.md:146)."""

from __future__ import annotations

import pytest

from distributed_inference_server_tpu.core.errors import ConfigError
from distributed_inference_server_tpu.serving.config import (
    ConfigWatcher,
    ServerConfig,
)
from distributed_inference_server_tpu.serving.scheduler import SchedulingStrategy


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


class TestPrecedence:
    def test_defaults(self):
        cfg = ServerConfig.load()
        assert cfg.get("server", "port") == 8000
        assert cfg.get("queue", "high_watermark") == 1000
        assert cfg.get("batcher", "window_ms") == 50.0
        assert cfg.get("batcher", "max_batch_size") == 32

    def test_file_overrides_defaults_toml(self, tmp_path):
        path = _write(
            tmp_path, "c.toml",
            "[server]\nport = 9100\n[queue]\nhigh_watermark = 1500\n",
        )
        cfg = ServerConfig.load(file_path=path)
        assert cfg.get("server", "port") == 9100
        assert cfg.get("queue", "high_watermark") == 1500
        assert cfg.get("queue", "low_watermark") == 500  # untouched default

    def test_file_overrides_defaults_yaml(self, tmp_path):
        path = _write(tmp_path, "c.yaml", "server:\n  port: 9200\n")
        cfg = ServerConfig.load(file_path=path)
        assert cfg.get("server", "port") == 9200

    def test_env_overrides_file(self, tmp_path):
        path = _write(tmp_path, "c.toml", "[server]\nport = 9100\n")
        cfg = ServerConfig.load(
            file_path=path, environ={"DIS_TPU_SERVER__PORT": "9300"}
        )
        assert cfg.get("server", "port") == 9300

    def test_cli_overrides_env_and_file(self, tmp_path):
        """Property 26: CLI > env > file."""
        path = _write(tmp_path, "c.toml", "[server]\nport = 9100\n")
        cfg = ServerConfig.load(
            file_path=path,
            environ={"DIS_TPU_SERVER__PORT": "9300"},
            cli_args=["--server-port", "9400"],
        )
        assert cfg.get("server", "port") == 9400

    def test_cli_config_file_flag(self, tmp_path):
        path = _write(tmp_path, "c.toml", "[server]\nport = 9500\n")
        cfg = ServerConfig.load(cli_args=["--config", path])
        assert cfg.get("server", "port") == 9500
        assert cfg.source_file == path

    def test_env_type_coercion(self):
        cfg = ServerConfig.load(
            environ={
                "DIS_TPU_SERVER__AUTO_RESTART": "false",
                "DIS_TPU_BATCHER__WINDOW_MS": "75.5",
                "DIS_TPU_ENGINE__PREFILL_BUCKETS": "16,64,256",
            }
        )
        assert cfg.get("server", "auto_restart") is False
        assert cfg.get("batcher", "window_ms") == 75.5
        assert cfg.get("engine", "prefill_buckets") == [16, 64, 256]

    def test_unknown_key_rejected(self, tmp_path):
        path = _write(tmp_path, "c.toml", "[server]\nbogus = 1\n")
        with pytest.raises(ConfigError):
            ServerConfig.load(file_path=path)

    def test_typed_views(self):
        cfg = ServerConfig.load()
        assert cfg.queue_config().high_watermark == 1000
        assert cfg.batcher_config().max_batch_size == 32
        assert cfg.validator_config().max_context_tokens == 8192
        assert cfg.strategy() is SchedulingStrategy.LEAST_LOADED


class TestValidation:
    """Property 27: invalid values rejected (the CLI maps this to a
    non-zero exit)."""

    @pytest.mark.parametrize(
        "environ",
        [
            {"DIS_TPU_SERVER__PORT": "0"},
            {"DIS_TPU_SERVER__PORT": "99999"},
            {"DIS_TPU_SERVER__PORT": "not-a-number"},
            {"DIS_TPU_QUEUE__HIGH_WATERMARK": "-5"},
            {"DIS_TPU_QUEUE__LOW_WATERMARK": "2000"},  # >= high
            {"DIS_TPU_QUEUE__HIGH_WATERMARK": "5000"},  # > max_queue_size
            {"DIS_TPU_SERVER__STRATEGY": "psychic"},
            {"DIS_TPU_MODEL__DTYPE": "int4"},
            {"DIS_TPU_ENGINE__MAX_BATCH": "0"},
            # mixed step: negative, and width not exceeding max_batch
            {"DIS_TPU_ENGINE__MIXED_STEP_TOKENS": "-1"},
            {"DIS_TPU_ENGINE__MIXED_STEP_TOKENS": "64"},  # == max_batch
        ],
    )
    def test_invalid_rejected(self, environ):
        with pytest.raises(ConfigError):
            ServerConfig.load(environ=environ)

    def test_mixed_step_tokens_valid_and_off(self):
        cfg = ServerConfig.load(
            environ={"DIS_TPU_ENGINE__MIXED_STEP_TOKENS": "128"}
        )
        assert cfg.get("engine", "mixed_step_tokens") == 128
        assert ServerConfig.load().get("engine", "mixed_step_tokens") == 0

    def test_cli_exit_nonzero_on_invalid(self):
        from distributed_inference_server_tpu.__main__ import main

        assert main(["--server-port", "0"]) != 0


class TestHotReload:
    def test_hot_diff_only_reloadable_keys(self):
        a = ServerConfig.load()
        b = ServerConfig.load(
            environ={
                "DIS_TPU_BATCHER__MAX_BATCH_SIZE": "16",
                "DIS_TPU_SERVER__PORT": "9999",  # not hot-reloadable
            }
        )
        diff = a.hot_diff(b)
        assert diff == {("batcher", "max_batch_size"): 16}

    def test_watcher_applies_file_change(self, tmp_path):
        path = _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 32\n")
        cfg = ServerConfig.load(file_path=path)
        watcher = ConfigWatcher(cfg)
        seen = []
        watcher.subscribe(lambda diff, new: seen.append(diff))

        import os

        _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 8\n")
        os.utime(path, (0, 0))  # force mtime change regardless of clock
        assert watcher.check_once() is True
        assert seen == [{("batcher", "max_batch_size"): 8}]
        assert watcher.current.get("batcher", "max_batch_size") == 8

    def test_reload_preserves_cli_overrides(self, tmp_path):
        """Property 26 must survive hot-reload: a file edit does not revert
        CLI-set keys, and passing --config inside cli_args is handled."""
        path = _write(tmp_path, "c.toml", "[queue]\nrequest_timeout_s = 10.0\n")
        cfg = ServerConfig.load(
            cli_args=["--config", path, "--batcher-window-ms", "10"]
        )
        assert cfg.get("batcher", "window_ms") == 10.0
        watcher = ConfigWatcher(cfg)

        import os

        _write(tmp_path, "c.toml", "[queue]\nrequest_timeout_s = 20.0\n")
        os.utime(path, (0, 0))
        assert watcher.check_once() is True
        # file change applied, CLI override NOT reverted
        assert watcher.current.get("queue", "request_timeout_s") == 20.0
        assert watcher.current.get("batcher", "window_ms") == 10.0

    def test_watcher_rejects_invalid_new_config(self, tmp_path):
        path = _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 32\n")
        cfg = ServerConfig.load(file_path=path)
        watcher = ConfigWatcher(cfg)

        import os

        _write(tmp_path, "c.toml", "[queue]\nhigh_watermark = -1\n")
        os.utime(path, (0, 0))
        assert watcher.check_once() is False
        assert watcher.current.get("batcher", "max_batch_size") == 32

    def test_server_applies_hot_config(self):
        """InferenceServer.apply_hot_config swaps live configs."""
        from distributed_inference_server_tpu.serving.server import InferenceServer

        srv = InferenceServer.__new__(InferenceServer)  # no engines needed
        from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        srv.scheduler = AdaptiveScheduler(SchedulingStrategy.ROUND_ROBIN)
        srv.dispatcher = Dispatcher(srv.scheduler)
        new = ServerConfig.load(
            environ={
                "DIS_TPU_BATCHER__MAX_BATCH_SIZE": "4",
                "DIS_TPU_QUEUE__HIGH_WATERMARK": "50",
                "DIS_TPU_QUEUE__LOW_WATERMARK": "10",
                "DIS_TPU_SERVER__STRATEGY": "memory_aware",
            }
        )
        diff = ServerConfig.load().hot_diff(new)
        srv.apply_hot_config(diff, new)
        assert srv.dispatcher.batcher.config.max_batch_size == 4
        assert srv.dispatcher.queue.config.high_watermark == 50
        assert srv.scheduler.strategy() is SchedulingStrategy.MEMORY_AWARE

    def test_non_hot_keys_do_not_leak_through_hot_apply(self):
        """A non-hot-reloadable key (queue.max_queue_size) changing alongside
        a hot key must not be applied to the live queue config."""
        from distributed_inference_server_tpu.serving.dispatcher import Dispatcher
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )
        from distributed_inference_server_tpu.serving.server import InferenceServer

        srv = InferenceServer.__new__(InferenceServer)
        srv.scheduler = AdaptiveScheduler()
        srv.dispatcher = Dispatcher(srv.scheduler)
        old_cap = srv.dispatcher.queue.config.max_queue_size
        new = ServerConfig.load(
            environ={
                "DIS_TPU_QUEUE__REQUEST_TIMEOUT_S": "5",
                "DIS_TPU_QUEUE__MAX_QUEUE_SIZE": "5000",
            }
        )
        diff = ServerConfig.load().hot_diff(new)
        srv.apply_hot_config(diff, new)
        assert srv.dispatcher.queue.config.request_timeout_s == 5
        assert srv.dispatcher.queue.config.max_queue_size == old_cap


class TestWatcherFailureModes:
    """Hot-reload watcher robustness (VERDICT r2 weak #7): atomic
    replace, parse errors mid-write, and the brief-ENOENT window of a
    rename-based writer."""

    def test_torn_write_then_same_mtime_completion_still_reloads(
        self, tmp_path
    ):
        """A parse failure must NOT advance the recorded mtime: if the
        writer completes within the same filesystem-timestamp tick, the
        completed file would otherwise be treated as already-seen and
        never reload."""
        import os

        path = _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 32\n")
        watcher = ConfigWatcher(ServerConfig.load(file_path=path))

        _write(tmp_path, "c.toml", "[batcher\nmax_batch")  # torn write
        os.utime(path, (5, 5))
        assert watcher.check_once() is False  # old config stays active
        assert watcher.current.get("batcher", "max_batch_size") == 32

        _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 8\n")
        os.utime(path, (5, 5))  # SAME mtime as the torn snapshot
        assert watcher.check_once() is True
        assert watcher.current.get("batcher", "max_batch_size") == 8

    def test_atomic_replace_applies(self, tmp_path):
        """os.replace (the atomic-writer idiom) is picked up like an
        in-place edit."""
        import os

        path = _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 32\n")
        watcher = ConfigWatcher(ServerConfig.load(file_path=path))
        tmp = _write(tmp_path, "c.toml.tmp",
                     "[batcher]\nmax_batch_size = 4\n")
        os.replace(tmp, path)
        os.utime(path, (9, 9))
        assert watcher.check_once() is True
        assert watcher.current.get("batcher", "max_batch_size") == 4

    def test_enoent_window_survives_and_recovers(self, tmp_path):
        """The file briefly missing (between a writer's unlink and its
        rename) must not kill the watcher; the reload lands once the
        file is back."""
        import os

        path = _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 32\n")
        watcher = ConfigWatcher(ServerConfig.load(file_path=path))
        os.unlink(path)
        assert watcher.check_once() is False  # ENOENT: old config active
        assert watcher.current.get("batcher", "max_batch_size") == 32
        _write(tmp_path, "c.toml", "[batcher]\nmax_batch_size = 16\n")
        os.utime(path, (7, 7))
        assert watcher.check_once() is True
        assert watcher.current.get("batcher", "max_batch_size") == 16
