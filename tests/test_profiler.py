"""Device-profiler tests (SURVEY §5 device-tracing bar; VERDICT r1 item
10): jax.profiler trace capture — wall-clock window and step-scoped via
the engine — and the /server/profile admin endpoint."""

from __future__ import annotations

import asyncio
import os

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving.server import InferenceServer
from distributed_inference_server_tpu.utils import profiler

_PAGED = PagedCacheConfig(num_pages=64, page_size=8, max_pages_per_seq=8)


def _make_engine():
    params = llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(max_batch=2, prefill_buckets=(16,), paged=_PAGED),
        dtype=jnp.float32,
    )


def test_capture_duration_produces_trace(tmp_path):
    # run some device work during the window so the trace is non-trivial
    out = profiler.capture_duration(0.05, base_dir=str(tmp_path))
    assert out["mode"] == "duration"
    assert os.path.isdir(out["trace_dir"])
    assert out["wall_s"] >= 0.05


def test_concurrent_capture_rejected(tmp_path):
    session = profiler.TraceSession(str(tmp_path))
    try:
        with pytest.raises(profiler.ProfileInProgress):
            profiler.TraceSession(str(tmp_path))
    finally:
        session.stop()


def test_engine_step_scoped_capture(tmp_path):
    eng = _make_engine()
    tok = ByteTokenizer()
    eng.add_request("r", tok.encode("profile me"),
                    SamplingParams(max_tokens=12, temperature=0.0))
    ev, holder = eng.profile_steps(3, base_dir=str(tmp_path))
    while eng.has_work():
        for out in eng.step():
            assert out.error is None
    assert ev.is_set()
    assert "error" not in holder, holder
    assert holder["mode"] == "steps"
    assert os.path.isdir(holder["trace_dir"])
    # trace viewer files land under the dir (plugins/profile/...)
    assert holder["files"], "capture produced no files"


def test_cancel_profile_disarms(tmp_path):
    eng = _make_engine()
    ev, holder = eng.profile_steps(2, base_dir=str(tmp_path))
    eng.cancel_profile(holder)
    tok = ByteTokenizer()
    eng.add_request("r", tok.encode("hi"),
                    SamplingParams(max_tokens=4, temperature=0.0))
    while eng.has_work():
        eng.step()
    assert not ev.is_set()  # never started
    # the global profiler lock is free: a fresh capture works
    out = profiler.capture_duration(0.01, base_dir=str(tmp_path))
    assert os.path.isdir(out["trace_dir"])


@pytest.fixture(scope="module")
def server():
    srv = InferenceServer(
        _make_engine, ByteTokenizer(), model_name="tiny-prof",
        num_engines=1, auto_restart=False,
    )
    srv.start()
    yield srv
    srv.shutdown(drain_timeout_s=5.0)


def _run(server, coro_fn):
    async def main():
        client = TestClient(TestServer(server.build_app()))
        await client.start_server()
        try:
            return await coro_fn(client)
        finally:
            await client.close()

    return asyncio.run(main())


def test_profile_endpoint_steps(server):
    async def go(client):
        gen = asyncio.create_task(client.post("/generate", json={
            "prompt": "trace this generation please",
            "max_tokens": 48, "temperature": 0.0,
        }))
        await asyncio.sleep(0)  # let the generation get queued
        resp = await client.post("/server/profile",
                                 json={"steps": 2, "timeout_s": 30})
        body = await resp.json()
        assert resp.status == 200, body
        assert body["mode"] == "steps"
        assert os.path.isdir(body["trace_dir"])
        assert body["engine_id"]
        g = await gen
        assert g.status == 200
    _run(server, go)


def test_profile_endpoint_duration(server):
    async def go(client):
        resp = await client.post("/server/profile",
                                 json={"duration_ms": 30})
        body = await resp.json()
        assert resp.status == 200, body
        assert body["mode"] == "duration"
        assert os.path.isdir(body["trace_dir"])
    _run(server, go)


def test_profile_endpoint_validation(server):
    async def go(client):
        r1 = await client.post("/server/profile", json={"steps": 0})
        assert r1.status == 400
        r2 = await client.post("/server/profile",
                               json={"duration_ms": 10**9})
        assert r2.status == 400
        r3 = await client.post("/server/profile",
                               json={"steps": 2, "engine_id": "nope"})
        assert r3.status == 400
    _run(server, go)
