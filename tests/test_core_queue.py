"""Conformance tests for the priority queue manager.

The reference has **no** queue tests despite spec'ing Properties 6-8
(design.md:716-732) — a gap SURVEY.md §4.1 calls out. This suite closes it:
strict priority ordering with FIFO within a level (**Property 6**),
backpressure hysteresis (**Property 7**), and timeout expiry (**Property 8**),
plus the absolute cap (queue.rs:110-113).
"""

import itertools

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core import (
    Priority,
    PriorityQueueManager,
    QueueConfig,
    QueueFull,
    QueuedRequest,
)

CASES = settings(max_examples=100, deadline=None)

arb_priority = st.sampled_from(list(Priority))


def make(i, priority):
    return QueuedRequest(id=f"req-{i}", data=i, priority=priority)


# -- Property 6: strict priority order, FIFO within level --------------------


@CASES
@given(priorities=st.lists(arb_priority, max_size=50))
def test_dequeue_order(priorities):
    q = PriorityQueueManager(QueueConfig(high_watermark=10_000, max_queue_size=20_000))
    for i, p in enumerate(priorities):
        q.enqueue(make(i, p))
    out = q.dequeue_batch(len(priorities) + 10)
    assert len(out) == len(priorities)
    # strict priority order: High block, then Normal, then Low
    levels = [r.priority for r in out]
    assert levels == sorted(levels, key=lambda p: -int(p))
    # FIFO within each level
    for level in Priority:
        ids = [r.data for r in out if r.priority == level]
        assert ids == sorted(ids)


@CASES
@given(
    priorities=st.lists(arb_priority, min_size=1, max_size=50),
    max_count=st.integers(min_value=0, max_value=60),
)
def test_dequeue_batch_size_cap(priorities, max_count):
    q = PriorityQueueManager(QueueConfig(high_watermark=10_000, max_queue_size=20_000))
    for i, p in enumerate(priorities):
        q.enqueue(make(i, p))
    out = q.dequeue_batch(max_count)
    assert len(out) == min(max_count, len(priorities))
    assert q.total_depth() == len(priorities) - len(out)


def test_dequeue_one_priority():
    q = PriorityQueueManager()
    q.enqueue(make(0, Priority.LOW))
    q.enqueue(make(1, Priority.HIGH))
    q.enqueue(make(2, Priority.NORMAL))
    assert q.dequeue_one().priority == Priority.HIGH
    assert q.dequeue_one().priority == Priority.NORMAL
    assert q.dequeue_one().priority == Priority.LOW
    assert q.dequeue_one() is None


# -- Property 7: backpressure hysteresis ------------------------------------


def test_backpressure_hysteresis_cycle():
    cfg = QueueConfig(high_watermark=10, low_watermark=5, max_queue_size=100)
    q = PriorityQueueManager(cfg)
    counter = itertools.count()

    # fill to the high watermark: still accepting (activation is strict >)
    for _ in range(10):
        q.enqueue(make(next(counter), Priority.NORMAL))
    assert q.is_accepting()
    # cross the high watermark -> backpressure activates
    q.enqueue(make(next(counter), Priority.NORMAL))
    assert not q.is_accepting()
    with pytest.raises(QueueFull):
        q.enqueue(make(next(counter), Priority.NORMAL))

    # drain to low watermark: still rejecting (release is strict <)
    q.dequeue_batch(6)  # 11 -> 5
    assert not q.is_accepting()
    # below the low watermark -> accepting again
    q.dequeue_batch(1)  # 5 -> 4
    assert q.is_accepting()
    q.enqueue(make(next(counter), Priority.NORMAL))


@CASES
@given(
    ops=st.lists(
        st.one_of(st.just("enq"), st.just("deq")), min_size=1, max_size=200
    )
)
def test_backpressure_invariants(ops):
    """After any op sequence: accepting implies depth could grow; rejecting
    implies depth >= low watermark (hysteresis band invariant)."""
    cfg = QueueConfig(high_watermark=20, low_watermark=10, max_queue_size=50)
    q = PriorityQueueManager(cfg)
    counter = itertools.count()
    for op in ops:
        if op == "enq":
            try:
                q.enqueue(make(next(counter), Priority.NORMAL))
            except QueueFull:
                pass
        else:
            q.dequeue_one()
        depth = q.total_depth()
        if depth > cfg.high_watermark:
            assert not q.is_accepting()
        if depth < cfg.low_watermark:
            assert q.is_accepting()


def test_absolute_cap():
    cfg = QueueConfig(high_watermark=1000, low_watermark=500, max_queue_size=5)
    q = PriorityQueueManager(cfg)
    for i in range(5):
        q.enqueue(make(i, Priority.NORMAL))
    with pytest.raises(QueueFull):
        q.enqueue(make(5, Priority.NORMAL))


# -- Property 8: timeout expiry ---------------------------------------------


def test_remove_expired():
    cfg = QueueConfig(request_timeout_s=10.0)
    q = PriorityQueueManager(cfg)
    import time

    now = time.monotonic()
    old = QueuedRequest(id="old", data=0, priority=Priority.NORMAL,
                        enqueued_at=now - 11.0)
    fresh = QueuedRequest(id="fresh", data=1, priority=Priority.NORMAL,
                          enqueued_at=now - 1.0)
    high_old = QueuedRequest(id="high-old", data=2, priority=Priority.HIGH,
                             enqueued_at=now - 30.0)
    q.enqueue(old)
    q.enqueue(fresh)
    q.enqueue(high_old)
    expired = q.remove_expired(now=now)
    assert {r.id for r in expired} == {"old", "high-old"}
    assert q.total_depth() == 1
    assert q.dequeue_one().id == "fresh"


def test_remove_expired_releases_backpressure():
    import time

    cfg = QueueConfig(
        high_watermark=4, low_watermark=2, max_queue_size=100, request_timeout_s=10.0
    )
    q = PriorityQueueManager(cfg)
    now = time.monotonic()
    for i in range(5):
        q.enqueue(
            QueuedRequest(id=str(i), data=i, priority=Priority.NORMAL,
                          enqueued_at=now - 60.0)
        )
    assert not q.is_accepting()
    expired = q.remove_expired(now=now)
    assert len(expired) == 5
    assert q.is_accepting()


# -- cancellation -----------------------------------------------------------


def test_cancel_removes_specific_request():
    q = PriorityQueueManager()
    for i in range(3):
        q.enqueue(make(i, Priority.NORMAL))
    removed = q.cancel("req-1")
    assert removed is not None and removed.data == 1
    assert q.cancel("req-1") is None
    remaining = [r.data for r in q.dequeue_batch(10)]
    assert remaining == [0, 2]


# -- per-tenant fair admission (docs/FLEET.md) -------------------------------


def fair_cfg(**kw):
    defaults = dict(high_watermark=10_000, max_queue_size=20_000,
                    tenant_fairness=True)
    defaults.update(kw)
    return QueueConfig(**defaults)


def make_t(i, tenant, priority=Priority.NORMAL):
    return QueuedRequest(id=f"req-{tenant}-{i}", data=i, priority=priority,
                         tenant=tenant)


def test_tenant_fair_round_robin_interleaves_equal_weights():
    """A saturating tenant cannot starve a trickling one: with equal
    weights, dequeues alternate 1:1 regardless of backlog skew."""
    q = PriorityQueueManager(fair_cfg())
    for i in range(100):
        q.enqueue(make_t(i, "hog"))
    for i in range(5):
        q.enqueue(make_t(i, "mouse"))
    out = q.dequeue_batch(10)
    assert sum(1 for r in out if r.tenant == "mouse") == 5
    # every mouse request lands within 2 positions of its fair slot
    mouse_positions = [j for j, r in enumerate(out) if r.tenant == "mouse"]
    for k, pos in enumerate(mouse_positions):
        assert pos <= 2 * (k + 1), (k, pos, [r.tenant for r in out])


def test_tenant_fair_bounded_wait_under_weight_ratio():
    """ACCEPTANCE (ISSUE 9): a saturating tenant cannot push another
    tenant's queue wait beyond the configured weight ratio — with
    weights hog=3, mouse=1, the mouse's k-th request dequeues within
    ~(1 + w_hog/w_mouse) * k positions."""
    q = PriorityQueueManager(fair_cfg(
        tenant_weights={"hog": 3.0, "mouse": 1.0}))
    for i in range(200):
        q.enqueue(make_t(i, "hog"))
    for i in range(8):
        q.enqueue(make_t(i, "mouse"))
    out = [q.dequeue_one() for _ in range(48)]
    positions = [j for j, r in enumerate(out) if r.tenant == "mouse"]
    assert len(positions) == 8  # all mouse requests served in the window
    ratio = 3.0 / 1.0
    for k, pos in enumerate(positions):
        assert pos <= (1 + ratio) * (k + 1) + 1, (k, pos)
    # the hog still gets its weight share, not merely the leftovers
    hogs = sum(1 for r in out if r.tenant == "hog")
    assert hogs >= 0.6 * len(out)


def test_tenant_fair_fifo_within_tenant_and_priority_across_levels():
    q = PriorityQueueManager(fair_cfg())
    q.enqueue(make_t(0, "a", Priority.LOW))
    q.enqueue(make_t(0, "b"))
    q.enqueue(make_t(1, "b"))
    q.enqueue(make_t(0, "c", Priority.HIGH))
    out = q.dequeue_batch(10)
    # strict priority first
    assert [r.priority for r in out] == [Priority.HIGH, Priority.NORMAL,
                                         Priority.NORMAL, Priority.LOW]
    # FIFO within tenant b
    b = [r.data for r in out if r.tenant == "b"]
    assert b == [0, 1]


def test_tenant_fair_single_tenant_is_plain_fifo():
    q = PriorityQueueManager(fair_cfg())
    for i in range(20):
        q.enqueue(make_t(i, "only"))
    assert [r.data for r in q.dequeue_batch(20)] == list(range(20))


def test_tenant_fair_expiry_cancel_and_depths():
    import time

    q = PriorityQueueManager(fair_cfg(request_timeout_s=10.0))
    now = time.monotonic()
    q.enqueue(QueuedRequest(id="old-a", data=0, tenant="a",
                            enqueued_at=now - 60.0))
    q.enqueue(make_t(1, "a"))
    q.enqueue(make_t(0, "b"))
    assert q.tenant_depths() == {"a": 2, "b": 1}
    expired = q.remove_expired(now=now)
    assert [r.id for r in expired] == ["old-a"]
    assert q.tenant_depths() == {"a": 1, "b": 1}
    assert q.cancel("req-b-0") is not None
    assert q.tenant_depths() == {"a": 1}
    assert q.dequeue_one().tenant == "a"
    assert q.tenant_depths() == {}


def test_tenant_fair_new_tenant_mid_stream_not_starved():
    q = PriorityQueueManager(fair_cfg())
    for i in range(50):
        q.enqueue(make_t(i, "hog"))
    q.dequeue_batch(10)  # hog is mid-drain with accumulated ring state
    q.enqueue(make_t(0, "late"))
    out = q.dequeue_batch(4)
    assert any(r.tenant == "late" for r in out), [r.tenant for r in out]


def test_tenant_default_when_unset():
    q = PriorityQueueManager(fair_cfg())
    q.enqueue(QueuedRequest(id="x", data=0))
    assert q.tenant_depths() == {"default": 1}


# -- backpressure re-evaluation on every mutation (ISSUE 9 satellite) --------
#
# The issue hypothesized that dequeue_batch partial drains under
# concurrent enqueue could leave the backpressure flag stale for a full
# poll interval. Not reproducible: every mutating method (enqueue,
# dequeue_one, dequeue_batch, remove_expired, cancel) recomputes
# _update_backpressure under the SAME lock hold as its mutation, so no
# interleaving can observe a flag that disagrees with the depth it was
# computed from. These regressions pin that property for both storage
# modes.


@pytest.mark.parametrize("fair", [False, True])
def test_backpressure_reevaluated_on_every_mutation(fair):
    import time

    cfg = QueueConfig(high_watermark=6, low_watermark=3, max_queue_size=100,
                      request_timeout_s=10.0, tenant_fairness=fair)
    now = time.monotonic()

    def fill(q, n, old=False):
        for i in range(n):
            q.enqueue(QueuedRequest(
                id=f"r{i}-{old}", data=i, priority=Priority.NORMAL,
                tenant="t", enqueued_at=now - (60.0 if old else 0.0)))

    # dequeue_batch partial drain releases the flag the moment depth
    # crosses the low watermark — not on the next poll
    q = PriorityQueueManager(cfg)
    fill(q, 7)
    assert not q.is_accepting()
    q.dequeue_batch(5)  # 7 -> 2 < low
    assert q.is_accepting()

    # dequeue_one, one mutation at a time
    q = PriorityQueueManager(cfg)
    fill(q, 7)
    for _ in range(5):
        q.dequeue_one()
    assert q.is_accepting()

    # cancel
    q = PriorityQueueManager(cfg)
    fill(q, 7)
    assert not q.is_accepting()
    for i in range(5):
        assert q.cancel(f"r{i}-False") is not None
    assert q.is_accepting()

    # remove_expired
    q = PriorityQueueManager(cfg)
    fill(q, 7, old=True)
    assert not q.is_accepting()
    q.remove_expired(now=now)
    assert q.is_accepting()


@CASES
@given(ops=st.lists(st.sampled_from(["enq", "deq", "batch", "cancel"]),
                    min_size=1, max_size=150))
def test_backpressure_invariants_fair_mode(ops):
    """The legacy hysteresis-band property holds verbatim in fair mode
    under arbitrary op interleavings."""
    cfg = QueueConfig(high_watermark=20, low_watermark=10, max_queue_size=50,
                      tenant_fairness=True,
                      tenant_weights={"a": 2.0, "b": 1.0})
    q = PriorityQueueManager(cfg)
    counter = itertools.count()
    live = []
    for op in ops:
        if op == "enq":
            i = next(counter)
            try:
                q.enqueue(make_t(i, "ab"[i % 2]))
                live.append(f"req-{'ab'[i % 2]}-{i}")
            except QueueFull:
                pass
        elif op == "deq":
            r = q.dequeue_one()
            if r is not None and r.id in live:
                live.remove(r.id)
        elif op == "batch":
            for r in q.dequeue_batch(3):
                if r.id in live:
                    live.remove(r.id)
        elif live:
            q.cancel(live.pop(0))
        depth = q.total_depth()
        if depth > cfg.high_watermark:
            assert not q.is_accepting()
        if depth < cfg.low_watermark:
            assert q.is_accepting()
