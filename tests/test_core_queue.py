"""Conformance tests for the priority queue manager.

The reference has **no** queue tests despite spec'ing Properties 6-8
(design.md:716-732) — a gap SURVEY.md §4.1 calls out. This suite closes it:
strict priority ordering with FIFO within a level (**Property 6**),
backpressure hysteresis (**Property 7**), and timeout expiry (**Property 8**),
plus the absolute cap (queue.rs:110-113).
"""

import itertools

import pytest

from _hypothesis_compat import given, settings, st

from distributed_inference_server_tpu.core import (
    Priority,
    PriorityQueueManager,
    QueueConfig,
    QueueFull,
    QueuedRequest,
)

CASES = settings(max_examples=100, deadline=None)

arb_priority = st.sampled_from(list(Priority))


def make(i, priority):
    return QueuedRequest(id=f"req-{i}", data=i, priority=priority)


# -- Property 6: strict priority order, FIFO within level --------------------


@CASES
@given(priorities=st.lists(arb_priority, max_size=50))
def test_dequeue_order(priorities):
    q = PriorityQueueManager(QueueConfig(high_watermark=10_000, max_queue_size=20_000))
    for i, p in enumerate(priorities):
        q.enqueue(make(i, p))
    out = q.dequeue_batch(len(priorities) + 10)
    assert len(out) == len(priorities)
    # strict priority order: High block, then Normal, then Low
    levels = [r.priority for r in out]
    assert levels == sorted(levels, key=lambda p: -int(p))
    # FIFO within each level
    for level in Priority:
        ids = [r.data for r in out if r.priority == level]
        assert ids == sorted(ids)


@CASES
@given(
    priorities=st.lists(arb_priority, min_size=1, max_size=50),
    max_count=st.integers(min_value=0, max_value=60),
)
def test_dequeue_batch_size_cap(priorities, max_count):
    q = PriorityQueueManager(QueueConfig(high_watermark=10_000, max_queue_size=20_000))
    for i, p in enumerate(priorities):
        q.enqueue(make(i, p))
    out = q.dequeue_batch(max_count)
    assert len(out) == min(max_count, len(priorities))
    assert q.total_depth() == len(priorities) - len(out)


def test_dequeue_one_priority():
    q = PriorityQueueManager()
    q.enqueue(make(0, Priority.LOW))
    q.enqueue(make(1, Priority.HIGH))
    q.enqueue(make(2, Priority.NORMAL))
    assert q.dequeue_one().priority == Priority.HIGH
    assert q.dequeue_one().priority == Priority.NORMAL
    assert q.dequeue_one().priority == Priority.LOW
    assert q.dequeue_one() is None


# -- Property 7: backpressure hysteresis ------------------------------------


def test_backpressure_hysteresis_cycle():
    cfg = QueueConfig(high_watermark=10, low_watermark=5, max_queue_size=100)
    q = PriorityQueueManager(cfg)
    counter = itertools.count()

    # fill to the high watermark: still accepting (activation is strict >)
    for _ in range(10):
        q.enqueue(make(next(counter), Priority.NORMAL))
    assert q.is_accepting()
    # cross the high watermark -> backpressure activates
    q.enqueue(make(next(counter), Priority.NORMAL))
    assert not q.is_accepting()
    with pytest.raises(QueueFull):
        q.enqueue(make(next(counter), Priority.NORMAL))

    # drain to low watermark: still rejecting (release is strict <)
    q.dequeue_batch(6)  # 11 -> 5
    assert not q.is_accepting()
    # below the low watermark -> accepting again
    q.dequeue_batch(1)  # 5 -> 4
    assert q.is_accepting()
    q.enqueue(make(next(counter), Priority.NORMAL))


@CASES
@given(
    ops=st.lists(
        st.one_of(st.just("enq"), st.just("deq")), min_size=1, max_size=200
    )
)
def test_backpressure_invariants(ops):
    """After any op sequence: accepting implies depth could grow; rejecting
    implies depth >= low watermark (hysteresis band invariant)."""
    cfg = QueueConfig(high_watermark=20, low_watermark=10, max_queue_size=50)
    q = PriorityQueueManager(cfg)
    counter = itertools.count()
    for op in ops:
        if op == "enq":
            try:
                q.enqueue(make(next(counter), Priority.NORMAL))
            except QueueFull:
                pass
        else:
            q.dequeue_one()
        depth = q.total_depth()
        if depth > cfg.high_watermark:
            assert not q.is_accepting()
        if depth < cfg.low_watermark:
            assert q.is_accepting()


def test_absolute_cap():
    cfg = QueueConfig(high_watermark=1000, low_watermark=500, max_queue_size=5)
    q = PriorityQueueManager(cfg)
    for i in range(5):
        q.enqueue(make(i, Priority.NORMAL))
    with pytest.raises(QueueFull):
        q.enqueue(make(5, Priority.NORMAL))


# -- Property 8: timeout expiry ---------------------------------------------


def test_remove_expired():
    cfg = QueueConfig(request_timeout_s=10.0)
    q = PriorityQueueManager(cfg)
    import time

    now = time.monotonic()
    old = QueuedRequest(id="old", data=0, priority=Priority.NORMAL,
                        enqueued_at=now - 11.0)
    fresh = QueuedRequest(id="fresh", data=1, priority=Priority.NORMAL,
                          enqueued_at=now - 1.0)
    high_old = QueuedRequest(id="high-old", data=2, priority=Priority.HIGH,
                             enqueued_at=now - 30.0)
    q.enqueue(old)
    q.enqueue(fresh)
    q.enqueue(high_old)
    expired = q.remove_expired(now=now)
    assert {r.id for r in expired} == {"old", "high-old"}
    assert q.total_depth() == 1
    assert q.dequeue_one().id == "fresh"


def test_remove_expired_releases_backpressure():
    import time

    cfg = QueueConfig(
        high_watermark=4, low_watermark=2, max_queue_size=100, request_timeout_s=10.0
    )
    q = PriorityQueueManager(cfg)
    now = time.monotonic()
    for i in range(5):
        q.enqueue(
            QueuedRequest(id=str(i), data=i, priority=Priority.NORMAL,
                          enqueued_at=now - 60.0)
        )
    assert not q.is_accepting()
    expired = q.remove_expired(now=now)
    assert len(expired) == 5
    assert q.is_accepting()


# -- cancellation -----------------------------------------------------------


def test_cancel_removes_specific_request():
    q = PriorityQueueManager()
    for i in range(3):
        q.enqueue(make(i, Priority.NORMAL))
    removed = q.cancel("req-1")
    assert removed is not None and removed.data == 1
    assert q.cancel("req-1") is None
    remaining = [r.data for r in q.dequeue_batch(10)]
    assert remaining == [0, 2]
