"""Member↔member KV mesh with telemetry-learned wire costs
(serving/fleet_mesh.py; docs/FLEET.md "KV mesh"). Covers the windowed
wire-rate estimator (cold prior fallback, window decay, lifetime
totals), the MeshWireRates registry (pricing band, disable switch,
bounded label sets, telemetry piggyback), MeshClient intro lifecycle
(add / unchanged / endpoint change / gone retraction), the MeshPeer
fail-fast arm, and the plan_route pricing matrix — including THE
acceptance pin: a fetch decision flips targets when a wire's learned
rate degrades."""

import pytest

from distributed_inference_server_tpu.engine.kv_cache import chain_hashes
from distributed_inference_server_tpu.serving.fleet_mesh import (
    _MAX_PAGE_COST,
    _MIN_PAGE_COST,
    WIRE_COUNTER_PREFIX,
    MeshClient,
    MeshPeer,
    MeshWireRates,
    WireRateEstimator,
)
from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.scheduler import (
    FetchCosts,
    plan_route,
)

T0 = 1_000_000.0  # deterministic wall clock for `now=` injection


# ---------------------------------------------------------------------------
# WireRateEstimator: the windowed learner
# ---------------------------------------------------------------------------


class TestWireRateEstimator:
    def test_cold_wire_has_no_rate(self):
        assert WireRateEstimator(window_s=30.0).rate(now=T0) is None

    def test_rate_is_window_bytes_over_seconds(self):
        est = WireRateEstimator(window_s=30.0)
        est.observe(1000, 0.5, chunks=2, now=T0)
        est.observe(3000, 1.5, chunks=1, now=T0 + 1.0)
        assert est.rate(now=T0 + 2.0) == pytest.approx(4000 / 2.0)
        assert est.totals() == (4000, 3)

    def test_window_decay_returns_to_cold(self):
        """An observation older than the window is pruned: the wire
        goes back to COLD (None) instead of trusting a stale rate —
        the caller re-prices at the prior."""
        est = WireRateEstimator(window_s=10.0)
        est.observe(8192, 0.25, now=T0)
        assert est.rate(now=T0 + 5.0) == pytest.approx(8192 / 0.25)
        assert est.rate(now=T0 + 60.0) is None
        # lifetime totals survive decay (the kv_wires stats table)
        assert est.totals() == (8192, 0)

    def test_degenerate_observations_ignored(self):
        est = WireRateEstimator(window_s=10.0)
        est.observe(0, 1.0, now=T0)
        est.observe(100, 0.0, now=T0)
        est.observe(-5, 1.0, now=T0)
        assert est.rate(now=T0) is None
        assert est.totals() == (0, 0)


# ---------------------------------------------------------------------------
# MeshWireRates: the (src, dst) registry and pricing
# ---------------------------------------------------------------------------


class _FakeMetrics:
    def __init__(self):
        self.set_calls = []
        self.removed = []

    def set_kv_wire_rate(self, src, dst, rate):
        self.set_calls.append((src, dst, rate))

    def remove_kv_wire_rate(self, src, dst):
        self.removed.append((src, dst))


class _FakePerf:
    def __init__(self):
        self.counters = {}

    def add_counter(self, name, value):
        self.counters[name] = self.counters.get(name, 0.0) + value


class TestMeshWireRates:
    def test_cold_wire_prices_at_the_prior(self):
        """page_cost is None for an unobserved wire: the caller falls
        back to the configured constant (the prior)."""
        rates = MeshWireRates(prior_rate=1000.0)
        assert rates.page_cost("a", "b", 0.6, now=T0) is None

    def test_wire_at_the_prior_rate_costs_the_constant(self):
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("a", "b", 2000, 2.0, now=T0)  # exactly the prior
        assert rates.page_cost("a", "b", 0.6, now=T0) == \
            pytest.approx(0.6)

    def test_slow_wire_dearer_fast_wire_cheaper_clamped(self):
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("slow", "b", 100, 1.0, now=T0)  # 10x under prior
        assert rates.page_cost("slow", "b", 0.6, now=T0) == \
            pytest.approx(6.0)
        rates.observe("fast", "b", 10_000, 1.0, now=T0)  # 10x over
        assert rates.page_cost("fast", "b", 0.6, now=T0) == \
            pytest.approx(0.06)
        rates.observe("crawl", "b", 1, 1e6, now=T0)
        assert rates.page_cost("crawl", "b", 0.6, now=T0) == \
            _MAX_PAGE_COST
        rates.observe("warp", "b", 10**15, 0.001, now=T0)
        assert rates.page_cost("warp", "b", 0.6, now=T0) == \
            _MIN_PAGE_COST

    def test_prior_zero_disables_learned_pricing(self):
        """fleet.kv_rate_prior <= 0: every wire prices at the constant
        (page_cost None) while rates keep flowing for observability."""
        rates = MeshWireRates(window_s=30.0, prior_rate=0.0)
        rates.observe("a", "b", 5000, 1.0, now=T0)
        assert rates.page_cost("a", "b", 0.6, now=T0) is None
        assert rates.rate("a", "b", now=T0) == pytest.approx(5000.0)

    def test_drop_member_clears_wires_and_gauge_series(self):
        """Dead members leave the label set (the tenant-gauge policy):
        every wire touching the member goes, both directions."""
        metrics = _FakeMetrics()
        rates = MeshWireRates(prior_rate=1000.0, metrics=metrics)
        rates.observe("m1", "m2", 100, 1.0, now=T0)
        rates.observe("m2", "m1", 200, 1.0, now=T0)
        rates.observe("registry", "m3", 300, 1.0, now=T0)
        rates.drop_member("m1")
        assert rates.rate("m1", "m2", now=T0) is None
        assert rates.rate("m2", "m1", now=T0) is None
        assert rates.rate("registry", "m3", now=T0) == pytest.approx(300)
        assert sorted(metrics.removed) == [("m1", "m2"), ("m2", "m1")]

    def test_snapshot_rows_are_stable_and_total(self):
        rates = MeshWireRates(window_s=10.0, prior_rate=1000.0)
        rates.observe("b", "a", 100, 1.0, chunks=1, now=T0)
        rates.observe("a", "b", 200, 1.0, chunks=2, now=T0)
        rows = rates.snapshot(now=T0 + 60.0)  # decayed: rate None
        assert [(r["src"], r["dst"]) for r in rows] == \
            [("a", "b"), ("b", "a")]
        assert rows[0]["bytes"] == 200 and rows[0]["chunks"] == 2
        assert rows[0]["rate_bytes_per_s"] is None

    def test_observations_piggyback_on_perf_telemetry(self):
        """Worker-side rates bump cumulative kvwire counters so the
        registry learns member↔member rates from the frames the
        heartbeat was shipping anyway."""
        perf = _FakePerf()
        rates = MeshWireRates(prior_rate=1000.0, perf=perf)
        rates.observe("w2", "w1", 4096, 0.5, chunks=3, now=T0)
        rates.observe("w2", "w1", 4096, 0.5, chunks=1, now=T0 + 1)
        base = f"{WIRE_COUNTER_PREFIX}w2|w1|"
        assert perf.counters[base + "bytes"] == pytest.approx(8192.0)
        assert perf.counters[base + "seconds"] == pytest.approx(1.0)
        assert perf.counters[base + "chunks"] == pytest.approx(4.0)

    def test_channel_handle_feeds_the_keyed_estimator(self):
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        handle = rates.estimator("w2", "w1")
        handle.observe(500, 0.5, now=T0)
        assert handle.rate(now=T0) == pytest.approx(1000.0)
        assert rates.rate("w2", "w1", now=T0) == pytest.approx(1000.0)


# ---------------------------------------------------------------------------
# MeshClient: intro lifecycle (no sockets — channels dial lazily)
# ---------------------------------------------------------------------------


def _client(member="w2"):
    return MeshClient(member, MeshWireRates(prior_rate=1000.0))


class TestMeshClientIntros:
    def test_intro_creates_a_lazy_channel(self):
        client = _client()
        try:
            client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                             "data_port": 19999, "max_streams": 4})
            ch = client.channel("w1")
            assert ch is not None and ch.address == ("127.0.0.1", 19999)
            assert client.channel("nobody") is None
            assert client.peer("nobody", "engine-0") is None
        finally:
            client.close()

    def test_unchanged_reintro_keeps_the_channel(self):
        """The broker resends intros every heartbeat; an unchanged
        endpoint must not churn the channel (breaker/backoff state
        lives there)."""
        client = _client()
        try:
            intro = {"member_id": "w1", "host": "127.0.0.1",
                     "data_port": 19999, "max_streams": 4}
            client.on_intro(intro)
            first = client.channel("w1")
            client.on_intro(dict(intro))
            assert client.channel("w1") is first
        finally:
            client.close()

    def test_changed_endpoint_replaces_the_channel(self):
        client = _client()
        try:
            client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                             "data_port": 19999, "max_streams": 4})
            first = client.channel("w1")
            client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                             "data_port": 20001, "max_streams": 4})
            second = client.channel("w1")
            assert second is not first
            assert second.address == ("127.0.0.1", 20001)
        finally:
            client.close()

    def test_gone_retracts_channel_and_learned_rates(self):
        client = _client()
        try:
            client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                             "data_port": 19999, "max_streams": 4})
            client.rates.observe("w2", "w1", 100, 1.0, now=T0)
            client.on_intro({"member_id": "w1", "gone": True})
            assert client.channel("w1") is None
            assert client.rates.rate("w2", "w1", now=T0) is None
        finally:
            client.close()

    def test_self_and_invalid_intros_ignored(self):
        client = _client()
        try:
            client.on_intro({"member_id": "w2", "host": "127.0.0.1",
                             "data_port": 19999})  # self
            client.on_intro({"member_id": "w1", "host": "",
                             "data_port": 19999})  # no host -> retract
            client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                             "data_port": 0})  # no port -> retract
            assert client.stats() == {}
        finally:
            client.close()

    def test_close_drops_everything_and_refuses_new_intros(self):
        client = _client()
        client.on_intro({"member_id": "w1", "host": "127.0.0.1",
                         "data_port": 19999, "max_streams": 4})
        client.close()
        assert client.channel("w1") is None
        client.on_intro({"member_id": "w3", "host": "127.0.0.1",
                         "data_port": 20002, "max_streams": 4})
        assert client.channel("w3") is None


class TestMeshPeerFailFast:
    def test_missing_wire_fails_the_export_immediately(self):
        """The exactly-once callback contract's fail-fast arm: no
        channel, or a breaker-open one, answers on_done(None, err)
        without touching a socket — the worker degrades to recompute."""
        done = []
        MeshPeer(None, "engine-0").submit_prefix_export(
            "r1", [1, 2], 2, "none", lambda c, e: done.append((c, e)))
        assert done == [(None, "mesh peer wire unavailable")]

    def test_breaker_open_wire_fails_fast(self):
        class _OpenBreakerChannel:
            def wire_available(self):
                return False

        done = []
        MeshPeer(_OpenBreakerChannel(), "engine-0").submit_prefix_export(
            "r1", [1, 2], 2, "none", lambda c, e: done.append((c, e)))
        assert done == [(None, "mesh peer wire unavailable")]


# ---------------------------------------------------------------------------
# plan_route pricing: learned wire rates steer the fetch target
# ---------------------------------------------------------------------------

PS = 4
PROMPT = list(range(33))  # 8 full pages + 1
HASHES = chain_hashes(PROMPT, PS, max_pages=8)
COSTS = FetchCosts(min_pages=2, page_cost=0.25, load_cost_pages=4.0,
                   remote_page_cost=0.6)


def _status(eid, healthy=True, active=0, waiting=0, digest=None,
            remote=False, data_plane=False):
    return EngineStatus(
        engine_id=eid, healthy=healthy, active_requests=active,
        waiting_requests=waiting, total_processed=0,
        memory_used_pages=0, memory_total_pages=100,
        prefix_digest=digest, page_size=PS, role="unified",
        digest_depth=8, remote=remote, data_plane=data_plane,
    )


def _wire_cost(rates):
    """The server wiring (serving/server.py): a status pair becomes a
    (src, dst) rate key — "registry" for this host, the member id for a
    remote proxy — and cold wires return None (charge the constant)."""
    def member_of(status):
        if status is None or not getattr(status, "remote", False):
            return "registry"
        return status.engine_id.rsplit(":", 1)[0]

    def cost(target, peer):
        src, dst = member_of(target), member_of(peer)
        if src == dst:
            return None
        if "registry" in (src, dst):
            member = dst if src == "registry" else src
            return rates.page_cost("registry", member,
                                   COSTS.remote_page_cost, now=T0)
        return rates.page_cost(src, dst, COSTS.remote_page_cost, now=T0)

    return cost


def _mesh_statuses():
    """A saturated warm remote peer, a cold remote mesh target, and a
    cold local engine: fetch beats route, and the (src, dst) wire
    prices decide WHICH target pulls the chain."""
    return [
        _status("engine-0"),
        _status("w1:engine-0", active=6, waiting=4,
                digest=frozenset(HASHES), remote=True, data_plane=True),
        _status("w2:engine-0", remote=True, data_plane=True),
    ]


class TestMeshRoutingMatrix:
    def test_cold_wires_price_at_the_prior_and_tie_break(self):
        """Every wire cold: both fetch options charge the constant
        (cold page_cost is None -> the static prior), so the decision
        falls to the deterministic engine-id tie-break — here the local
        relay, which sorts first. Identical pricing to passing no
        wire_cost at all."""
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        plan = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                          page_size=PS, wire_cost=_wire_cost(rates),
                          mesh_route=lambda t, p: True)
        assert plan.decision == "fetch"
        assert plan.engine_id == "engine-0"
        assert plan.peer_id == "w1:engine-0"
        bare = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                          page_size=PS,
                          mesh_route=lambda t, p: True)
        assert (bare.engine_id, bare.decision) == \
            (plan.engine_id, plan.decision)

    def test_fast_mesh_wire_beats_the_relay(self):
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("w2", "w1", 100_000, 1.0, now=T0)  # 100x prior
        plan = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                          page_size=PS, wire_cost=_wire_cost(rates),
                          mesh_route=lambda t, p: True)
        assert (plan.engine_id, plan.decision) == ("w2:engine-0", "fetch")

    def test_degraded_wire_rate_flips_the_fetch_target(self):
        """THE acceptance pin: the same fleet, the same request — when
        the member↔member wire's learned rate degrades, the fetch
        decision demonstrably flips off the mesh target onto the host
        (whose registry wire now prices better)."""
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("w2", "w1", 100_000, 1.0, now=T0)
        route = lambda t, p: True  # noqa: E731
        before = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                            page_size=PS, wire_cost=_wire_cost(rates),
                            mesh_route=route)
        assert before.engine_id == "w2:engine-0"
        # congestion: the wire now measures 100x SLOWER than the prior
        rates.observe("w2", "w1", 100_000, 10_000.0, now=T0 + 1)
        after = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                           page_size=PS, wire_cost=_wire_cost(rates),
                           mesh_route=route)
        assert after.decision == "fetch"
        assert after.engine_id == "engine-0"
        assert after.peer_id == "w1:engine-0"

    def test_mesh_gate_closed_excludes_the_remote_target(self):
        """Without an introduction (mesh_route False) the remote target
        has no admissible wire to the peer: the fetch stays on the
        host, however fast the member wire claims to be."""
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("w2", "w1", 100_000, 1.0, now=T0)
        plan = plan_route(_mesh_statuses(), HASHES, costs=COSTS,
                          page_size=PS, wire_cost=_wire_cost(rates),
                          mesh_route=lambda t, p: False)
        assert (plan.engine_id, plan.decision) == ("engine-0", "fetch")

    def test_no_data_plane_excludes_the_remote_target(self):
        """A remote target without a KV data plane cannot seat imported
        pages: its fetch option never exists (breaker-open wires land
        here too — data_plane clears while the breaker is open)."""
        statuses = _mesh_statuses()
        statuses[2] = _status("w2:engine-0", remote=True,
                              data_plane=False)
        rates = MeshWireRates(window_s=30.0, prior_rate=1000.0)
        rates.observe("w2", "w1", 100_000, 1.0, now=T0)
        plan = plan_route(statuses, HASHES, costs=COSTS, page_size=PS,
                          wire_cost=_wire_cost(rates),
                          mesh_route=lambda t, p: True)
        assert (plan.engine_id, plan.decision) == ("engine-0", "fetch")
