"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices so TP/PP/EP/CP mesh
code is exercised without TPU hardware (SURVEY.md §4.3). Must be set before
jax is imported anywhere.
"""

import os

# Hard override, not setdefault: the environment presets JAX_PLATFORMS=axon
# (single real TPU chip behind a one-process tunnel); tests must never claim
# it — they run on the CPU backend with 8 virtual devices.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Debug-mode precondition checks that are too hot for production (e.g.
# gather_kv_window's page-aligned-run assertion) fire throughout the suite.
os.environ.setdefault("DIS_TPU_DEBUG_GATHER", "1")

# The axon sitecustomize calls jax.config.update("jax_platforms", "axon,cpu")
# in every interpreter, overriding the env var — so the env override above is
# not enough: force the config back to cpu-only before any backend
# initialization (conftest imports before all test modules).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# ---------------------------------------------------------------------------
# Fast/slow test tiers (VERDICT r4 #9): tests listed in slow_tests.txt
# (>= 4s on a clean timing run — JAX-compile-heavy e2e/mesh tests) are
# marked `slow` at collection, and the DEFAULT run excludes them via
# pyproject addopts so the conformance tier finishes in < 5 min.
#   full suite:  python -m pytest tests/ -m "" -q
#   slow only:   python -m pytest tests/ -m slow -q
#   regenerate:  python tools/update_slowlist.py (see its docstring)
# A slowlisted test that no longer exists is ignored; NEW tests default
# to the fast tier until the next regeneration.
# ---------------------------------------------------------------------------
import os.path as _osp  # noqa: E402

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    # a test named explicitly (`pytest tests/foo.py::test_bar`) must RUN,
    # slowlisted or not — those ITEMS skip the marking so the default
    # `-m "not slow"` addopts has nothing to deselect there. Marking is
    # per-item: directory/file args in the same invocation keep their
    # tier split.
    named = tuple(a.split("[", 1)[0] for a in config.args if "::" in a)
    path = _osp.join(_osp.dirname(__file__), "slow_tests.txt")
    try:
        with open(path) as f:
            slow = {
                ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")
            }
    except OSError:
        return
    for item in items:
        explicit = any(
            item.nodeid == n or item.nodeid.startswith(n + "[")
            for n in named
        )
        if not explicit and item.nodeid in slow:
            item.add_marker(pytest.mark.slow)
