"""Test configuration.

Tests run on the XLA CPU backend with 8 virtual devices so TP/PP/EP/CP mesh
code is exercised without TPU hardware (SURVEY.md §4.3). Must be set before
jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
