"""Interpret-mode conformance for the fused Pallas kernels (RMSNorm,
RoPE, group-dequant matmul) against the XLA reference implementations
they can replace. Mirrors tests/test_pallas_paged_attention.py's
strategy: numerics off-TPU via interpret=True; Mosaic acceptance on the
real chip is tools/kernel_probe.py's job (r2 lesson: interpret-mode
green does not imply the kernel compiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_server_tpu.ops.norms import rms_norm
from distributed_inference_server_tpu.ops.pallas.fused import (
    apply_rope_pallas,
    quant_matmul_pallas,
    quant_matmul_supported,
    rms_norm_pallas,
)
from distributed_inference_server_tpu.ops.quant import (
    dequantize,
    quantize_int4,
    quantize_int8,
)
from distributed_inference_server_tpu.ops.rotary import (
    apply_rope,
    rope_frequencies,
)


@pytest.mark.parametrize("shape", [(8, 256), (3, 16, 512), (64, 2048)])
def test_rms_norm_matches_reference(shape):
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(k1, shape, jnp.float32)
    w = jax.random.normal(k2, shape[-1:], jnp.float32)
    ref = rms_norm(x, w, 1e-5)
    got = rms_norm_pallas(x, w, 1e-5, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_rms_norm_odd_rows():
    # M=5 < 8: single sub-8 row block (Mosaic pads sublanes)
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 128), jnp.float32)
    w = jnp.ones((128,))
    np.testing.assert_allclose(
        np.asarray(rms_norm_pallas(x, w, 1e-6, interpret=True)),
        np.asarray(rms_norm(x, w, 1e-6)), rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("D", [64, 128])
def test_rope_matches_reference(D):
    B, T, nh = 2, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(2), (B, T, nh, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)) + 7
    inv = rope_frequencies(D, theta=10000.0)
    ref = apply_rope(x, positions, inv)
    got = apply_rope_pallas(x, positions, inv, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_rope_bf16_dtype_preserved():
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 2, 64),
                          jnp.bfloat16)
    positions = jnp.arange(8)[None, :]
    inv = rope_frequencies(64, theta=500000.0)
    got = apply_rope_pallas(x, positions, inv, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(apply_rope(x, positions, inv), np.float32),
        rtol=2e-2, atol=2e-2,
    )


@pytest.mark.parametrize("M,K,N", [(64, 512, 256), (8, 1024, 128),
                                   (128, 2048, 512)])
def test_quant_matmul_int8(M, K, N):
    k1, k2 = jax.random.split(jax.random.PRNGKey(4))
    x = jax.random.normal(k1, (M, K), jnp.bfloat16)
    w = jax.random.normal(k2, (K, N), jnp.float32)
    qt = quantize_int8(w, group_size=128)
    assert quant_matmul_supported(M, K, N, 128, packed=False)
    ref = x @ dequantize(qt, jnp.bfloat16)
    got = quant_matmul_pallas(x, qt.q, qt.s, group=K // qt.s.shape[-2],
                              packed=False, interpret=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-1)


@pytest.mark.parametrize("M,K,N", [(64, 512, 256), (16, 1024, 512)])
def test_quant_matmul_int4(M, K, N):
    k1, k2 = jax.random.split(jax.random.PRNGKey(5))
    x = jax.random.normal(k1, (M, K), jnp.bfloat16)
    w = jax.random.normal(k2, (K, N), jnp.float32)
    qt = quantize_int4(w, group_size=64)
    assert quant_matmul_supported(M, K, N, 64, packed=True)
    ref = x @ dequantize(qt, jnp.bfloat16)
    got = quant_matmul_pallas(x, qt.q, qt.s, group=K // qt.s.shape[-2],
                              packed=True, interpret=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-1)


def test_dispatch_interpret_mode_end_to_end(monkeypatch):
    """DIS_TPU_PALLAS_FUSED=interpret drives the EXACT dispatch sites
    (norms.rms_norm, rotary.apply_rope, llama._mm) through the Pallas
    kernels off-TPU; outputs must match the default XLA path."""
    from distributed_inference_server_tpu.models import llama

    x = jax.random.normal(jax.random.PRNGKey(6), (16, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(7), (256,), jnp.float32)
    q4 = jax.random.normal(jax.random.PRNGKey(8), (2, 16, 4, 64),
                           jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(16)[None, :], (2, 16))
    inv = rope_frequencies(64, theta=10000.0)
    wq = quantize_int8(
        jax.random.normal(jax.random.PRNGKey(9), (256, 128), jnp.float32)
    )

    base_norm = rms_norm(x, w, 1e-6)
    base_rope = apply_rope(q4, pos, inv)
    base_mm = llama._mm(x.astype(jnp.bfloat16), wq)

    monkeypatch.setenv("DIS_TPU_PALLAS_FUSED", "interpret")
    np.testing.assert_allclose(
        np.asarray(rms_norm(x, w, 1e-6)), np.asarray(base_norm),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(apply_rope(q4, pos, inv)), np.asarray(base_rope),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(llama._mm(x.astype(jnp.bfloat16), wq), np.float32),
        np.asarray(base_mm, np.float32), rtol=5e-2, atol=5e-1,
    )


def test_quant_matmul_dispatch_rejects_misaligned():
    # N=100 has no 128-multiple tiling; K=300 not divisible by group
    assert not quant_matmul_supported(64, 512, 100, 128, packed=False)
    assert not quant_matmul_supported(64, 300, 256, 128, packed=False)
    # prime M > 8 has no multiple-of-8 row block
    assert not quant_matmul_supported(13, 512, 256, 128, packed=False)
