"""Paged KV cache conformance: Properties 9-12 (design.md:734-756) mapped
onto pages — prefix reuse, LRU eviction, access-clock refresh, and
serialize/deserialize round-trip."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.core.errors import CacheFull
from distributed_inference_server_tpu.engine.kv_cache import (
    PageAllocator,
    PagedCacheConfig,
    PagedKVState,
    deserialize_kv,
    flat_slots,
    serialize_kv,
)
from distributed_inference_server_tpu.models.configs import TINY

PCFG = PagedCacheConfig(num_pages=8, page_size=4, max_pages_per_seq=4)


@pytest.fixture(autouse=True)
def _audit_allocators(monkeypatch):
    """Every allocator this module constructs must end each test with
    self-consistent books (free list, content-address maps, LRU,
    refcounts — PageAllocator.audit, ISSUE 6 satellite). Conservation
    against live holders is the chaos harness's job; here the invariant
    is that no test path corrupts the allocator's internal structures."""
    created = []
    orig_init = PageAllocator.__init__

    def init(self, cfg):
        orig_init(self, cfg)
        created.append(self)

    monkeypatch.setattr(PageAllocator, "__init__", init)
    yield
    for a in created:
        assert a.audit() == [], a.audit()


def test_allocate_and_release_cycle():
    a = PageAllocator(PCFG)
    pages = a.allocate(8)
    assert sorted(pages) == list(range(8))
    with pytest.raises(CacheFull):
        a.allocate(1)
    a.release(pages)  # unpublished -> straight back to free list
    assert a.num_free() == 8


# -- Property 9: prefix reuse ------------------------------------------------


def test_prefix_match_shares_full_pages():
    a = PageAllocator(PCFG)
    tokens = list(range(10))  # 2 full pages + 2 tail tokens
    pages = a.allocate(3)
    a.publish(tokens, pages)
    a.release(pages)

    shared, matched = a.match_prefix(tokens)
    assert matched == 8  # only full pages participate
    assert shared == pages[:2]
    # a different suffix after one shared page
    shared2, matched2 = a.match_prefix(list(range(4)) + [99, 98, 97, 96])
    assert matched2 == 4
    assert shared2 == pages[:1]
    # no match for different first page
    shared3, matched3 = a.match_prefix([7, 7, 7, 7])
    assert (shared3, matched3) == ([], 0)
    a.release(shared + shared2)


def test_prefix_match_refcounts_protect_pages():
    a = PageAllocator(PCFG)
    tokens = list(range(8))
    pages = a.allocate(2)
    a.publish(tokens, pages)
    a.release(pages)  # cached, refcount 0

    shared, _ = a.match_prefix(tokens)  # refcount 1
    # exhaust the pool: only 6 free pages remain; the 2 shared must survive
    rest = a.allocate(6)
    with pytest.raises(CacheFull):
        a.allocate(1)
    a.release(shared)
    # now the shared pages are refcount-0 cached -> reclaimable
    more = a.allocate(2)
    assert set(more) == set(pages)
    a.release(rest + more)


# -- Property 10 / Property 11: LRU eviction & access clocks ----------------


def test_lru_eviction_order():
    """Property 10: the least-recently-used cached page is the eviction
    victim. Property 11: ``match_prefix`` (a cache access) updates the
    access clock — touching t1 here is what demotes t2 to LRU victim
    (design.md:740-750 [spec])."""
    a = PageAllocator(PCFG)
    t1 = [1] * 4
    t2 = [2] * 4
    p1 = a.allocate(1)
    a.publish(t1, p1)
    a.release(p1)
    p2 = a.allocate(1)
    a.publish(t2, p2)
    a.release(p2)

    # touch t1 so t2 becomes the LRU victim
    shared, _ = a.match_prefix(t1)
    a.release(shared)

    a.allocate(6)  # drain the free list
    got = a.allocate(1)  # must reclaim the LRU cached page: p2
    assert got == p2
    assert a.stats().evictions == 1
    # t2's content address is gone; t1 still matches
    assert a.match_prefix(t2) == ([], 0)
    s1, m1 = a.match_prefix(t1)
    assert m1 == 4


def test_evict_below_target():
    a = PageAllocator(PCFG)
    for i in range(4):
        p = a.allocate(1)
        a.publish([i] * 4, p)
        a.release(p)
    assert a.stats().pages_cached == 4
    reclaimed = a.evict_below(0.25)  # keep <= 2 pages in use
    assert reclaimed >= 2
    assert (PCFG.num_pages - a.stats().pages_free) / PCFG.num_pages <= 0.25 + 1e-9


def test_stats_hit_rate():
    a = PageAllocator(PCFG)
    p = a.allocate(1)
    a.publish([5] * 4, p)
    a.release(p)
    a.match_prefix([5] * 4 + [9])  # hit
    a.match_prefix([6] * 4)  # miss
    s = a.stats()
    assert s.hits == 1 and s.misses == 1
    assert a.hit_rate() == 0.5


# -- Property 12: serialize/deserialize round-trip --------------------------


def test_kv_serialize_roundtrip():
    state = PagedKVState.create(TINY, PCFG, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    content = rng.normal(size=(TINY.num_layers, 8, TINY.num_kv_heads,
                               TINY.head_dim)).astype(np.float32)
    slots = np.arange(4, 12)  # pages 1 and 2
    state.k = state.k.at[:, slots].set(jnp.asarray(content))
    state.v = state.v.at[:, slots].set(jnp.asarray(content * 2))

    blob = serialize_kv(state, [1, 2], PCFG.page_size, token_count=7)
    assert isinstance(blob, bytes) and len(blob) > 0

    fresh = PagedKVState.create(TINY, PCFG, dtype=jnp.float32)
    fresh, count = deserialize_kv(fresh, blob, [5, 6], PCFG.page_size)
    assert count == 7
    got_k = np.asarray(fresh.k[:, 20:28])
    np.testing.assert_array_equal(got_k, content)
    got_v = np.asarray(fresh.v[:, 20:28])
    np.testing.assert_array_equal(got_v, content * 2)


def test_kv_serialize_roundtrip_bfloat16():
    # the engine's default dtype; np.savez alone degrades bf16 to void
    state = PagedKVState.create(TINY, PCFG, dtype=jnp.bfloat16)
    rng = np.random.default_rng(1)
    content = jnp.asarray(
        rng.normal(size=(TINY.num_layers, 4, TINY.num_kv_heads, TINY.head_dim)),
        jnp.bfloat16,
    )
    state.k = state.k.at[:, 0:4].set(content)
    state.v = state.v.at[:, 0:4].set(content)
    blob = serialize_kv(state, [0], PCFG.page_size, token_count=4)
    fresh = PagedKVState.create(TINY, PCFG, dtype=jnp.bfloat16)
    fresh, count = deserialize_kv(fresh, blob, [3], PCFG.page_size)
    assert count == 4
    np.testing.assert_array_equal(
        np.asarray(fresh.k[:, 12:16]).view(np.uint16),
        np.asarray(content).view(np.uint16),
    )


def test_deserialize_garbage_raises_cache_error():
    from distributed_inference_server_tpu.core.errors import (
        CacheDeserializationError,
    )

    state = PagedKVState.create(TINY, PCFG, dtype=jnp.float32)
    with pytest.raises(CacheDeserializationError):
        deserialize_kv(state, b"not a valid payload", [0], PCFG.page_size)


def test_flat_slots_mapping():
    tables = jnp.asarray([[3, 1, 0, 0], [2, 0, 0, 0]], jnp.int32)
    positions = jnp.asarray([[0, 4, 5], [1, 2, 3]], jnp.int32)
    slots = flat_slots(tables, positions, page_size=4)
    np.testing.assert_array_equal(
        np.asarray(slots), [[12, 4, 5], [9, 10, 11]]
    )


def test_gather_kv_window_page_path_matches_slot_path():
    """The page-granular fast path must produce exactly the slot-granular
    gather's output when gather_slots rows are page-aligned runs of
    in-range pages (the engine's construction; rows past a sequence's
    live length reference real-but-stale pages and are masked by
    kv_valid_len downstream, so exact equality only needs in-range
    tables — out-of-range sentinels clamp differently per path and are
    likewise masked)."""
    import numpy as np
    import jax.numpy as jnp
    from distributed_inference_server_tpu.models import llama

    rng = np.random.default_rng(5)
    ps, num_pages, KV, D, B, P = 4, 12, 2, 8, 3, 5
    pool = rng.normal(size=(num_pages * ps, KV, D)).astype(np.float32)
    tables = rng.integers(0, num_pages, size=(B, P))
    offs = np.arange(P * ps)
    gather = (tables[:, offs // ps] * ps + offs % ps).astype(np.int32)
    k = jnp.asarray(pool)
    v = jnp.asarray(pool * 2.0)
    k_fast, v_fast = llama.gather_kv_window(k, v, jnp.asarray(gather), ps)
    k_slow, v_slow = llama.gather_kv_window(k, v, jnp.asarray(gather), 0)
    np.testing.assert_array_equal(np.asarray(k_fast), np.asarray(k_slow))
    np.testing.assert_array_equal(np.asarray(v_fast), np.asarray(v_slow))


# -- Device-held pages (kernel looping, ISSUE 19) ---------------------------
# A run-to-completion decode block draws pages onto an on-device
# free-list (draw_device); at block reconcile every drawn page comes
# back as either claimed (now live-held by a row) or returned (back to
# the free list). The DEVICE-HELD state participates in conservation.


def test_draw_device_prefers_free_then_evicts_lru():
    a = PageAllocator(PCFG)
    # publish 2 cached pages, keep 4 live, leaving 2 truly free
    live = a.allocate(4)
    for i in range(2):
        p = a.allocate(1)
        a.publish([100 + i] * 4, p)
        a.release(p)
    assert a.stats().pages_free == 2
    drawn = a.draw_device(4)  # 2 free + 2 reclaimed from LRU
    assert len(drawn) == 4
    assert a.device_held() == 4
    assert a.stats().evictions == 2
    # outstanding draw is NOT a leak: conservation counts device-held
    assert a.audit(live_pages=live) == []
    a.reconcile_device(claimed=[], returned=drawn)
    assert a.device_held() == 0
    assert a.num_free() == 4
    a.release(live)


def test_draw_device_partial_when_starved():
    a = PageAllocator(PCFG)
    live = a.allocate(7)  # one page left, nothing cached
    drawn = a.draw_device(3)
    assert len(drawn) == 1  # partial draw, no CacheFull
    assert a.draw_device(2) == []  # fully dry: empty, still no raise
    a.reconcile_device(claimed=[], returned=drawn)
    a.release(live)


def test_reconcile_claimed_pages_become_live_held():
    a = PageAllocator(PCFG)
    drawn = a.draw_device(2)
    a.reconcile_device(claimed=[drawn[0]], returned=[drawn[1]])
    # the claimed page is now an ordinary live-held page; the returned
    # one is free again
    assert a.device_held() == 0
    assert a.num_free() == PCFG.num_pages - 1
    assert a.audit(live_pages=[drawn[0]]) == []
    a.release([drawn[0]])
    assert a.num_free() == PCFG.num_pages


def test_audit_flags_live_page_still_device_held():
    a = PageAllocator(PCFG)
    drawn = a.draw_device(1)
    # a row's block table references the page before the host settled
    # the draw — audit must call out the unreconciled overlap
    issues = a.audit(live_pages=drawn)
    assert any("unreconciled device draw" in m for m in issues)
    a.reconcile_device(claimed=drawn, returned=[])
    assert a.audit(live_pages=drawn) == []
    a.release(drawn)


def test_reconcile_of_non_held_page_raises():
    a = PageAllocator(PCFG)
    drawn = a.draw_device(1)
    other = a.allocate(1)
    with pytest.raises(ValueError, match="not device-held"):
        a.reconcile_device(claimed=other, returned=[])
    with pytest.raises(ValueError, match="not device-held"):
        a.reconcile_device(claimed=[], returned=other)
    a.reconcile_device(claimed=[], returned=drawn)
    a.release(other)
