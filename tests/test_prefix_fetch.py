"""Fleet-wide prefix sharing (ISSUE 8): peer-to-peer KV prefix fetch
with the three-way route/fetch/recompute cost model. Covers the engine
export/import primitives (token identity over f32 and int8 wire, chunk
reorder/truncation/crc fuzz reusing the streamed-import validation
harness, registry staleness), the ``plan_route`` routing matrix under
load skew, configurable digest depth, the ``KvPrefixFetch`` wire
round-trip, and the serving path end-to-end (forced fetch, peer death
fallback, abort-mid-fetch)."""

import dataclasses
import random
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.core.errors import (
    CacheDeserializationError,
)
from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import (
    PagedCacheConfig,
    chain_hashes,
)
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.disagg import (
    InProcessChannel,
    ProtowireChannel,
)
from distributed_inference_server_tpu.serving.metrics import EngineStatus
from distributed_inference_server_tpu.serving.scheduler import (
    FetchCosts,
    plan_route,
)

TOK = ByteTokenizer()
PS = 4


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def make_engine(tiny_params, host_tier_bytes=0, host_tier_quant="none",
                num_pages=32, digest_depth=8):
    return LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=2,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(
                num_pages=num_pages, page_size=PS, max_pages_per_seq=16
            ),
            host_tier_bytes=host_tier_bytes,
            host_tier_quant=host_tier_quant,
            native_allocator=False,
            digest_depth=digest_depth,
        ),
        dtype=jnp.float32,
    )


def run_one(engine, rid, prompt, max_tokens=6):
    engine.add_request(rid, prompt, SamplingParams(max_tokens=max_tokens,
                                                   temperature=0.0))
    tokens = []
    for _ in range(500):
        if not engine.has_work():
            break
        for out in engine.step():
            if out.token_id is not None:
                tokens.append(out.token_id)
            assert out.error is None, out.error
    assert not engine.has_work()
    return tokens


PREFIX = list(range(40, 60))  # 5 full pages at PS=4
PROMPT = PREFIX + [7, 8]
HASHES = chain_hashes(PROMPT, PS, max_pages=(len(PROMPT) - 1) // PS)


# ---------------------------------------------------------------------------
# Engine primitives: export_prefix_chunks / import_prefix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("wire_quant", ["none", "int8"])
def test_peer_fetch_token_identity(tiny_params, wire_quant):
    """A peer-fetched prefix decodes byte-identically to recompute —
    the acceptance bar for the fetch path (f32 exactly; int8 wire
    asserts the same on this fixture, like the host tier and disagg
    wire)."""
    cold = make_engine(tiny_params)
    want = run_one(cold, "cold", PROMPT)

    warm = make_engine(tiny_params)
    run_one(warm, "warm", PROMPT)
    depth, chunks = warm.export_prefix_chunks(HASHES, chunk_pages=2,
                                              wire_quant=wire_quant)
    assert depth == len(HASHES)
    assert sum(c.page_count for c in chunks) == depth

    target = make_engine(tiny_params)
    seated = target.import_prefix(PROMPT[: depth * PS], chunks)
    assert seated == depth
    s0 = target.cache_stats()
    assert s0.pages_cached == depth  # seated as CACHED, nothing pinned
    got = run_one(target, "probe", PROMPT)
    assert got == want
    assert target.cache_stats().hits > s0.hits  # prefill matched them
    assert target.audit_pages() == []


def test_peer_fetch_from_host_tier(tiny_params):
    """A chain that churned out of the peer's HBM into its host tier
    still exports (stored int8 encoding ships as-is) and lands
    token-identically."""
    cold = make_engine(tiny_params)
    want = run_one(cold, "cold", PROMPT)

    warm = make_engine(tiny_params, host_tier_bytes=1 << 22,
                       host_tier_quant="int8", num_pages=10)
    run_one(warm, "warm", PROMPT)
    rng = np.random.default_rng(3)
    for i in range(8):  # cycle the 10-page pool: prefix demotes
        run_one(warm, f"churn{i}", rng.integers(100, 200, size=7).tolist(),
                max_tokens=2)
    warm.host_tier.flush()
    depth, chunks = warm.export_prefix_chunks(HASHES, chunk_pages=2)
    assert depth > 0
    target = make_engine(tiny_params)
    target.import_prefix(PROMPT[: depth * PS], chunks)
    assert run_one(target, "probe", PROMPT) == want
    assert target.audit_pages() == []


def test_registry_staleness_partial_and_full_eviction(tiny_params):
    """The peer evicted the chain between the routing score and the
    fetch: export serves whatever consecutive head it still holds —
    possibly nothing — and never errors (the caller falls back)."""
    warm = make_engine(tiny_params)
    run_one(warm, "warm", PROMPT)
    warm.evict_cache(0.0, drop_host_tier=True)  # full eviction, no tier
    depth, chunks = warm.export_prefix_chunks(HASHES)
    assert (depth, chunks) == (0, [])


def test_import_prefix_fuzz_reorder_truncation_crc(tiny_params):
    """The fetch import rides the KvImportSession validation harness:
    reordered chunks seat fine; a dropped chunk, corrupt crc, or
    duplicate index rejects the whole fetch with every reserved page
    released (allocator audit clean)."""
    warm = make_engine(tiny_params)
    want = run_one(warm, "warm", PROMPT)
    depth, chunks = warm.export_prefix_chunks(HASHES, chunk_pages=1)
    assert len(chunks) == depth >= 3
    tokens = PROMPT[: depth * PS]

    # any arrival order seats token-identically
    shuffled = list(chunks)
    random.Random(7).shuffle(shuffled)
    tgt = make_engine(tiny_params)
    tgt.import_prefix(tokens, shuffled)
    assert run_one(tgt, "probe", PROMPT) == want

    def rejects(bad):
        eng = make_engine(tiny_params)
        with pytest.raises(CacheDeserializationError):
            eng.import_prefix(tokens, bad)
        s = eng.cache_stats()
        assert s.pages_free == s.pages_total  # nothing leaked
        assert eng.audit_pages() == []

    rejects(chunks[:-1])  # truncation: coverage short of the tokens
    rejects([dataclasses.replace(chunks[0], crc32=chunks[0].crc32 ^ 1)]
            + chunks[1:])  # corrupt payload
    rejects([chunks[0]] + chunks)  # duplicate index
    rejects([dataclasses.replace(c, payload=c.payload[:-4],
                                 crc32=__import__("zlib").crc32(
                                     c.payload[:-4]) & 0xFFFFFFFF)
             if i == 0 else c for i, c in enumerate(chunks)])  # short payload


def test_import_prefix_validation(tiny_params):
    eng = make_engine(tiny_params)
    with pytest.raises(CacheDeserializationError):
        eng.import_prefix(PREFIX[:3], [])  # not whole pages
    with pytest.raises(CacheDeserializationError):
        eng.import_prefix([], [])


def test_digest_depth_configurable(tiny_params):
    """cache.digest_depth widens the published digest: a 12-page chain
    is fully visible at digest_depth=16 but flattens to 8 hashes at the
    default — exactly the window the cost model can score."""
    long_prefix = list(range(48))  # 12 full pages
    prompt = long_prefix + [7, 8]
    shallow = make_engine(tiny_params, digest_depth=8)
    deep = make_engine(tiny_params, digest_depth=16)
    run_one(shallow, "s", prompt)
    run_one(deep, "d", prompt)
    hashes = chain_hashes(prompt, PS, max_pages=12)
    assert sum(h in shallow.prefix_digest() for h in hashes) == 8
    assert sum(h in deep.prefix_digest() for h in hashes) == 12


def test_digest_depth_config_validation():
    from distributed_inference_server_tpu.core.errors import ConfigError
    from distributed_inference_server_tpu.serving.config import ServerConfig

    with pytest.raises(ConfigError):
        ServerConfig.load(environ={"DIS_TPU_CACHE__DIGEST_DEPTH": "0"})
    cfg = ServerConfig.load(environ={"DIS_TPU_CACHE__DIGEST_DEPTH": "16",
                                     "DIS_TPU_CACHE__FETCH_PAGE_COST":
                                     "0.1"})
    assert cfg.get("cache", "digest_depth") == 16
    costs = cfg.fetch_costs()
    assert costs.page_cost == 0.1 and costs.enabled


# ---------------------------------------------------------------------------
# Routing matrix: the three-way cost model under load skew
# ---------------------------------------------------------------------------


def _status(eid, healthy=True, active=0, waiting=0, digest=None,
            page_size=PS, role="unified", digest_depth=8):
    return EngineStatus(
        engine_id=eid, healthy=healthy, active_requests=active,
        waiting_requests=waiting, total_processed=0,
        memory_used_pages=0, memory_total_pages=100,
        prefix_digest=digest, page_size=page_size, role=role,
        digest_depth=digest_depth,
    )


RPROMPT = list(range(33))  # 8 full pages + 1
RHASHES = chain_hashes(RPROMPT, PS, max_pages=8)


class TestRoutingMatrix:
    def test_idle_warm_replica_routes_warm(self):
        plan = plan_route([
            _status("warm", digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES)
        assert (plan.engine_id, plan.decision) == ("warm", "warm")

    def test_saturated_warm_replica_fetches_to_cold(self):
        """THE acceptance case: the warm replica is saturated, so the
        cost model provably picks fetch-to-cold over route-to-warm."""
        plan = plan_route([
            _status("warm", active=6, waiting=4,
                    digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES)
        assert plan.decision == "fetch"
        assert plan.engine_id == "cold" and plan.peer_id == "warm"
        assert plan.peer_depth == len(RHASHES) and plan.depth == 0
        assert plan.prefix_hashes == tuple(RHASHES)

    def test_fetch_threshold_is_the_load_differential(self):
        """Fetch wins exactly when load_cost * (load_warm - load_cold)
        exceeds page_cost * fetched_pages (FetchCosts docstring)."""
        costs = FetchCosts(min_pages=2, page_cost=0.25, load_cost_pages=4.0)
        # gain 8 pages -> wire cost 2.0 -> needs a differential > 0.5
        # requests; load 1 vs 0 tips it
        warm1 = plan_route([
            _status("warm", active=1, digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES, costs=costs)
        assert warm1.decision == "fetch"
        warm0 = plan_route([
            _status("warm", digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES, costs=costs)
        assert warm0.decision == "warm"

    def test_no_match_recomputes_least_loaded(self):
        plan = plan_route([
            _status("busy", active=3),
            _status("idle"),
        ], RHASHES)
        assert (plan.engine_id, plan.decision) == ("idle", "recompute")

    def test_gain_below_min_pages_never_fetches(self):
        plan = plan_route([
            _status("warm", active=9, digest=frozenset(RHASHES[:1])),
            _status("cold"),
        ], RHASHES, costs=FetchCosts(min_pages=2))
        assert plan.decision in ("warm", "recompute")
        assert plan.peer_id is None

    def test_peer_fetch_disabled_routes_warm(self):
        plan = plan_route([
            _status("warm", active=9, waiting=9,
                    digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES, costs=FetchCosts(enabled=False))
        assert plan.decision != "fetch"

    def test_partial_local_match_still_fetches_whole_chain(self):
        """A target holding part of the chain still fetches when the
        peer is loaded; the plan records both depths (the fetch moves
        the whole chain — contiguous tiling — and the cost model
        charges it accordingly)."""
        plan = plan_route([
            _status("warm", active=6, digest=frozenset(RHASHES)),
            _status("cold", digest=frozenset(RHASHES[:3])),
        ], RHASHES)
        assert plan.decision == "fetch"
        assert plan.depth == 3 and plan.peer_depth == len(RHASHES)

    def test_decode_peer_can_source_but_not_take_the_request(self):
        """A decode-role replica holds the deepest match (a migrated
        sequence published there): it serves as the fetch SOURCE while
        the request lands on an admissible replica."""
        plan = plan_route([
            _status("dec", role="decode", digest=frozenset(RHASHES)),
            _status("pre", role="prefill"),
        ], RHASHES, roles=("prefill", "unified"))
        assert plan.engine_id == "pre"
        assert plan.decision == "fetch" and plan.peer_id == "dec"

    def test_unhealthy_peer_is_invisible(self):
        plan = plan_route([
            _status("dead", healthy=False, digest=frozenset(RHASHES)),
            _status("cold"),
        ], RHASHES)
        assert (plan.decision, plan.peer_id) == ("recompute", None)
        assert plan_route([_status("dead", healthy=False)], RHASHES) is None

    def test_forced_fetch_flag(self):
        """sched.fetch_decision forces the cheapest fetch option even
        when routing warm would be cheaper (the chaos lever)."""
        statuses = [
            _status("warm", digest=frozenset(RHASHES)),
            _status("cold"),
        ]
        faults.install(faults.parse_spec("sched.fetch_decision:nth=1", 1))
        try:
            plan = plan_route(statuses, RHASHES)
        finally:
            faults.clear()
        assert plan.decision == "fetch" and plan.engine_id == "cold"
        # disarmed: the same inputs route warm
        assert plan_route(statuses, RHASHES).decision == "warm"

    def test_deterministic_given_inputs(self):
        statuses = [
            _status("a", active=2, digest=frozenset(RHASHES[:4])),
            _status("b", active=1, digest=frozenset(RHASHES)),
            _status("c"),
        ]
        plans = {(p.engine_id, p.decision, p.peer_id)
                 for p in (plan_route(statuses, RHASHES)
                           for _ in range(5))}
        assert len(plans) == 1


# ---------------------------------------------------------------------------
# Wire: KvPrefixFetch round-trip
# ---------------------------------------------------------------------------


def test_fetch_request_wire_roundtrip():
    inproc = InProcessChannel().transfer_fetch_request(
        "r1", HASHES, 8, "int8")
    wired = ProtowireChannel().transfer_fetch_request(
        "r1", HASHES, 8, "int8")
    assert wired == ("r1", list(HASHES), 8, "int8", None)
    assert inproc == wired
    # empty wire_quant decodes to the canonical "none"
    assert ProtowireChannel().transfer_fetch_request(
        "r2", [], 4, "")[3] == "none"


def test_fetch_request_trace_context_roundtrip():
    """The KvPrefixFetch trace fields (docs/OBSERVABILITY.md) cross the
    protowire codec intact — the fetch span parents on the wire's
    round-tripped context, not on in-process state."""
    ctx = ("aaaabbbbccccdddd", "1111222233334444")
    wired = ProtowireChannel().transfer_fetch_request(
        "r1", HASHES, 8, "int8", trace=ctx)
    assert wired[:4] == ("r1", list(HASHES), 8, "int8")
    assert tuple(wired[4]) == ctx
    # untraced request: the fields stay off the wire, decode to None
    assert ProtowireChannel().transfer_fetch_request(
        "r1", HASHES, 8, "int8")[4] is None


# ---------------------------------------------------------------------------
# Serving path end-to-end (chaos-fleet topology, sans HTTP)
# ---------------------------------------------------------------------------


def _fetch_fleet(channel="protowire"):
    from tools import chaos_fleet

    chaos_fleet._env_setup()
    return chaos_fleet.build_fleet(
        strategy="cache_aware", channel=channel,
        engine_kwargs={"native_allocator": False},
    )


def _warm_and_probe(srv, prompt, spec, seed=0, max_tokens=8):
    """Warm one replica, arm ``spec``, probe; returns (warm_sink,
    probe_sink). Caller asserts on outcomes and metrics."""
    from tools import chaos_fleet

    warm = [chaos_fleet.submit(srv, f"w{i}-{seed}", prompt=prompt,
                               max_tokens=max_tokens) for i in range(2)]
    chaos_fleet.wait_terminal([s for s in warm if s is not None])
    time.sleep(0.35)  # digest refresh is rate-limited to 250 ms
    faults.install(faults.parse_spec(spec, seed))
    sinks = []
    chaos_fleet.submit(srv, f"probe-{seed}", prompt=prompt,
                       max_tokens=max_tokens, sinks=sinks)
    wedged = chaos_fleet.wait_terminal(sinks, 60)
    faults.clear()
    assert wedged == []
    return warm[0], sinks[0]


class TestServingFetch:
    def test_forced_fetch_end_to_end(self):
        """ACCEPTANCE: a repeated-prefix request lands on the cold
        replica via peer fetch (protowire channel), completes with the
        same token count as the warm run, and the fetch shows up in
        metrics as ok with bytes moved."""
        from tools import chaos_fleet

        srv = _fetch_fleet()
        try:
            warm_sink, probe = _warm_and_probe(
                srv, chaos_fleet._PROMPT + " e2e",
                "sched.fetch_decision:nth=1")
            assert probe.errors == [] and probe.dones == 1
            assert probe.tokens == warm_sink.tokens
            snap = srv.metrics.snapshot(
                tuple(srv.scheduler.statuses())).to_dict()
            pf = snap["cache"]["peer_fetch"]
            assert pf.get("ok") == 1 and pf["bytes"] > 0
            assert snap["cache"]["route_decisions"].get("fetch") == 1
            v = chaos_fleet.check_invariants(srv, [probe],
                                             require_success=True)
            assert v == []
        finally:
            faults.clear()
            srv.shutdown(drain_timeout_s=5.0)

    def test_peer_death_mid_fetch_falls_back_to_recompute(self):
        """ACCEPTANCE: kv.peer_fetch kills the wire mid-fetch — the
        request recomputes on its target, exactly once, with the fetch
        recorded as fallback and zero pages leaked."""
        from tools import chaos_fleet

        srv = _fetch_fleet()
        try:
            warm_sink, probe = _warm_and_probe(
                srv, chaos_fleet._PROMPT + " death",
                "sched.fetch_decision:nth=1;kv.peer_fetch:nth=1")
            assert probe.errors == [] and probe.dones == 1
            assert probe.tokens == warm_sink.tokens
            snap = srv.metrics.snapshot(
                tuple(srv.scheduler.statuses())).to_dict()
            pf = snap["cache"]["peer_fetch"]
            assert pf.get("fallback") == 1 and "ok" not in pf
            v = chaos_fleet.check_invariants(srv, [probe],
                                             require_success=True)
            assert v == []
        finally:
            faults.clear()
            srv.shutdown(drain_timeout_s=5.0)

    def test_export_on_dead_runner_resolves_callback(self, tiny_params):
        """submit_prefix_export on an unhealthy runner resolves its
        callback immediately (the fetcher falls back instead of waiting
        on a dead peer forever)."""
        from distributed_inference_server_tpu.serving.runner import (
            EngineRunner,
        )

        runner = EngineRunner("e0", lambda: make_engine(tiny_params))
        got = []
        runner.submit_prefix_export("r", HASHES, 8, "none",
                                    lambda res, err: got.append((res, err)))
        assert got and got[0][0] is None and got[0][1]

    def test_abort_mid_fetch_drops_the_request(self, tiny_params):
        """A client disconnect while the fetch is in flight drops the
        request (no submit into a closed sink), and the fetcher's
        in-flight map drains."""
        from distributed_inference_server_tpu.serving.disagg import (
            PrefixFetcher,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            PrefixRoutePlan,
        )

        class _Runner:
            engine_id = "x"

            def __init__(self):
                self.submitted = []
                self.export_cb = None

            def submit_prefix_export(self, rid, hashes, cp, wq, cb):
                self.export_cb = cb  # held: fetch stays in flight

            def submit(self, reqs):
                self.submitted.extend(reqs)

        class _Req:
            request_id = "r1"
            prompt_ids = PROMPT

        fetcher = PrefixFetcher()
        target, peer = _Runner(), _Runner()
        plan = PrefixRoutePlan("t", "fetch", peer_id="p", depth=0,
                               peer_depth=5, page_size=PS,
                               prefix_hashes=tuple(HASHES))
        fetcher.fetch_then_submit(target, peer, _Req(), plan)
        assert fetcher.pending_count() == 1
        assert fetcher.abort("r1") is True
        peer.export_cb(None, "peer gone")  # settle after the abort
        assert fetcher.pending_count() == 0
        assert target.submitted == []  # dropped, not submitted
        assert fetcher.abort("r1") is False  # nothing in flight anymore
