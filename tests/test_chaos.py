"""Chaos regressions (ISSUE 6): fixed-seed fault-injection scenarios for
the resilience layer — serving/faults.py, crash-safe redispatch, restart
backoff, the allocator audit — plus committed seeds of the
tools/chaos_fleet.py scenario matrix.

The acceptance property lives here as a tier-1 test: a fault-injected
runner crash whose in-flight requests streamed ZERO tokens completes
those requests successfully on another replica, token-identically and
invisibly to the client; token-emitting requests fail fast with the
distinct ``engine_crashed`` code.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer
from distributed_inference_server_tpu.serving import faults
from distributed_inference_server_tpu.serving.disagg import DisaggSettings
from distributed_inference_server_tpu.serving.faults import (
    FaultRule,
    FaultSet,
    FaultSpecError,
    InjectedFault,
    parse_spec,
)
from distributed_inference_server_tpu.serving.metrics import MetricsCollector
from distributed_inference_server_tpu.serving.runner import ServerRequest
from distributed_inference_server_tpu.serving.scheduler import AdaptiveScheduler
from distributed_inference_server_tpu.serving.server import InferenceServer

_PAGED = PagedCacheConfig(num_pages=192, page_size=8, max_pages_per_seq=32)
_PROMPT = "hello chaos engineering world"


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Fault injection is process-global; no test may leak an armed set."""
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def _engine(params):
    return LLMEngine(
        params, TINY, ByteTokenizer(),
        EngineConfig(max_batch=4, prefill_buckets=(16, 64), paged=_PAGED),
        dtype=jnp.float32,
    )


class _Sink:
    def __init__(self):
        self.toks, self.text = [], ""
        self.done = None
        self.errors = []
        self.terminals = 0
        self.first_token = threading.Event()
        self.ev = threading.Event()

    def on_token(self, token_id, text, token_index, logprob=None):
        if token_id is not None:
            self.toks.append(token_id)
            self.first_token.set()
        self.text += text

    def on_done(self, finish_reason, usage):
        self.done = (finish_reason, usage)
        self.terminals += 1
        self.ev.set()

    def on_error(self, message, code):
        self.errors.append((message, code))
        self.terminals += 1
        self.ev.set()


def _run_request(srv, rid, max_tokens=10, wait=True):
    sink = _Sink()
    srv.dispatcher.submit(ServerRequest(
        rid, ByteTokenizer().encode(_PROMPT),
        SamplingParams(max_tokens=max_tokens, temperature=0.0), sink,
    ))
    if wait:
        assert sink.ev.wait(90), "request did not complete"
    return sink


# ---------------------------------------------------------------------------
# FaultSet semantics (pure)
# ---------------------------------------------------------------------------


class TestFaultSet:
    def test_disabled_fire_is_noop(self):
        faults.clear()
        assert faults.fire("runner.step") is False
        assert faults.flag("sched.health_flap") is False

    def test_nth_fires_once_on_nth_hit(self):
        fs = FaultSet([FaultRule(point="p", nth=3)])
        fs.fire("p")
        fs.fire("p")
        with pytest.raises(InjectedFault):
            fs.fire("p")
        # nth rules are one-shot by default
        for _ in range(5):
            fs.fire("p")
        assert fs.fired_count("p") == 1

    def test_times_bounds_recurrence(self):
        fs = FaultSet([FaultRule(point="p", nth=1, times=2)])
        for _ in range(2):
            with pytest.raises(InjectedFault):
                fs.fire("p")
        fs.fire("p")
        assert fs.fired_count("p") == 2

    def test_prob_is_seed_deterministic(self):
        def burn(seed):
            fs = FaultSet([FaultRule(point="p", prob=0.5, times=None)],
                          seed=seed)
            out = []
            for _ in range(64):
                try:
                    fs.fire("p")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        assert burn(7) == burn(7)
        assert burn(7) != burn(8)
        assert sum(burn(7)) > 0

    def test_delay_rule_sleeps_not_raises(self):
        fs = FaultSet([FaultRule(point="p", nth=1, delay_ms=10.0)])
        t0 = time.monotonic()
        assert fs.fire("p") is True
        assert time.monotonic() - t0 >= 0.009

    def test_flag_never_raises(self):
        fs = FaultSet([FaultRule(point="p", nth=1)])
        assert fs.flag("p") is True
        assert fs.flag("p") is False  # one-shot consumed

    def test_parse_spec(self):
        fs = parse_spec(
            "runner.inbox:nth=1;disagg.chunk:prob=0.25,times=3;"
            "disagg.slow_peer:nth=2,delay_ms=5", seed=9,
        )
        assert set(fs._rules) == {"runner.inbox", "disagg.chunk",
                                  "disagg.slow_peer"}
        assert fs._rules["disagg.chunk"].times == 3
        assert fs._rules["disagg.slow_peer"].delay_ms == 5.0

    @pytest.mark.parametrize("bad", [
        "", "pointonly", "p:nth=x", "p:unknown=1", "p:prob=2.0", "p:",
        "p:nth=1;p:nth=2",
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(FaultSpecError):
            parse_spec(bad)

    def test_config_gates_and_validates_spec(self):
        from distributed_inference_server_tpu.core.errors import ConfigError
        from distributed_inference_server_tpu.serving.config import (
            ServerConfig,
        )

        cfg = ServerConfig.load(
            environ={"DIS_TPU_FAULTS__SPEC": "runner.step:nth=1",
                     "DIS_TPU_FAULTS__SEED": "5"})
        assert cfg.get("faults", "spec") == "runner.step:nth=1"
        assert cfg.get("faults", "seed") == 5
        with pytest.raises(ConfigError):
            ServerConfig.load(environ={"DIS_TPU_FAULTS__SPEC": "nonsense"})


# ---------------------------------------------------------------------------
# Restart backoff (satellite)
# ---------------------------------------------------------------------------


class _FlakyRunner:
    def __init__(self, eid="engine-x", fail=True):
        self.engine_id = eid
        self.fail = fail
        self.restarts = 0

    def is_healthy(self):
        return False

    def restart(self, wait_ready=True):
        self.restarts += 1
        if self.fail:
            raise RuntimeError("boom")


class TestRestartBackoff:
    def test_failed_restart_backs_off_exponentially(self):
        m = MetricsCollector()
        s = AdaptiveScheduler(auto_restart=True, metrics=m,
                              restart_backoff_s=10.0,
                              restart_backoff_max_s=25.0)
        r = _FlakyRunner()
        delays = []
        for _ in range(4):
            s._restart_one(r)
            not_before, delay = s._backoff[r.engine_id]
            delays.append(delay)
            assert not_before > time.monotonic()
            # jitter is bounded: delay <= wake <= 1.25 * delay
            assert not_before - time.monotonic() <= delay * 1.25 + 0.1
        assert delays == [10.0, 20.0, 25.0, 25.0]  # doubled, capped
        assert r.restarts == 4
        snap = m.snapshot().to_dict()
        assert snap["resilience"]["engine_restarts"] == {r.engine_id: 4}
        assert (b'engine_restarts_total{engine_id="engine-x"} 4.0'
                in m.prometheus_text())

    def test_successful_restart_resets_backoff(self):
        s = AdaptiveScheduler(auto_restart=True, restart_backoff_s=10.0)
        r = _FlakyRunner()
        s._restart_one(r)
        assert r.engine_id in s._backoff
        r.fail = False
        s._restart_one(r)
        assert r.engine_id not in s._backoff

    def test_health_loop_skips_engine_in_backoff(self):
        s = AdaptiveScheduler(auto_restart=True,
                              health_check_interval_s=0.01,
                              restart_backoff_s=30.0)
        r = _FlakyRunner()
        s.register(r)
        s.start_health_loop()
        try:
            deadline = time.monotonic() + 1.0
            while r.restarts == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            # one attempt happened; the 30s backoff holds every later
            # sweep back (~100 sweeps would fit in the window otherwise)
            time.sleep(0.3)
            assert r.restarts == 1
        finally:
            s.stop_health_loop()


# ---------------------------------------------------------------------------
# Allocator audit (satellite)
# ---------------------------------------------------------------------------


class TestAllocatorAudit:
    def _alloc(self):
        from distributed_inference_server_tpu.engine.kv_cache import (
            PageAllocator,
        )

        return PageAllocator(PagedCacheConfig(num_pages=8, page_size=4,
                                              max_pages_per_seq=4))

    def test_clean_books_audit_clean(self):
        a = self._alloc()
        pages = a.allocate(3)
        a.publish(list(range(12)), pages)
        assert a.audit() == []
        assert a.audit(pages) == []
        a.release(pages)
        assert a.audit([]) == []

    def test_leaked_page_detected(self):
        a = self._alloc()
        a.allocate(2)  # held by nobody we admit to -> leak
        issues = a.audit([])
        assert any("leaked" in i for i in issues), issues

    def test_refcount_holder_mismatch_detected(self):
        a = self._alloc()
        pages = a.allocate(2)
        a.publish(list(range(8)), pages)
        issues = a.audit(list(pages) + [pages[0]])  # phantom extra holder
        assert any("refcount" in i for i in issues), issues

    def test_use_after_free_detected(self):
        a = self._alloc()
        pages = a.allocate(1)
        a.release(pages)
        issues = a.audit(pages)
        assert any("free list" in i for i in issues), issues

    def test_corrupted_lru_detected(self):
        a = self._alloc()
        pages = a.allocate(1)
        a.publish(list(range(4)), pages)
        a.release(pages)
        a._lru[pages[0]] = 12345  # wrong hash
        assert any("hash mismatch" in i for i in a.audit())


# ---------------------------------------------------------------------------
# Crash-safe redispatch (tentpole acceptance)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="class")
def twin_server(tiny_params):
    srv = InferenceServer(
        lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
        num_engines=2, auto_restart=False,
    )
    srv.start()
    yield srv
    faults.clear()
    srv.shutdown(drain_timeout_s=5.0)


class TestRedispatch:
    def test_zero_token_inflight_completes_on_other_replica(
            self, twin_server):
        """ACCEPTANCE: the runner crashes between submit and inbox drain
        (zero tokens streamed) — the request must complete successfully,
        token-identically, on the other replica."""
        ref = _run_request(twin_server, "chaos-ref")
        assert not ref.errors, ref.errors

        faults.install(parse_spec("runner.inbox:nth=1", seed=1))
        got = _run_request(twin_server, "chaos-redispatch")
        faults.clear()

        assert not got.errors, got.errors
        assert got.terminals == 1
        assert got.toks == ref.toks
        assert got.text == ref.text
        # exactly one replica died; the survivor carried the request
        healthy = [r for r in twin_server.scheduler.engines()
                   if r.is_healthy()]
        assert len(healthy) == 1
        snap = twin_server.metrics.snapshot().to_dict()
        assert snap["resilience"]["redispatched"].get("ok", 0) >= 1
        assert ('requests_redispatched_total{outcome="ok"}'
                in twin_server.metrics.prometheus_text().decode())
        # no pages leaked anywhere (crashed replica audits vacuously)
        for r in twin_server.scheduler.engines():
            assert r.audit() == []
        # heal the fleet for subsequent tests
        for r in twin_server.scheduler.engines():
            if not r.is_healthy():
                r.restart()

    def test_redispatch_with_traced_request(self, twin_server):
        """Regression: the HTTP path attaches a root span to every
        request, and redispatch annotates it — a span-API mismatch here
        turned an invisible redispatch into a client-visible failure
        (the hook raised, _fail_all_of absorbed it, the sink got the
        crash error). Redispatch must succeed for traced requests too,
        and the span must carry the redispatch annotations."""
        span = twin_server.tracer.start("request", request_id="chaos-span")
        sink = _Sink()
        faults.install(parse_spec("runner.inbox:nth=1", seed=6))
        twin_server.dispatcher.submit(ServerRequest(
            "chaos-span", ByteTokenizer().encode(_PROMPT),
            SamplingParams(max_tokens=10, temperature=0.0), sink, span=span,
        ))
        assert sink.ev.wait(90), "traced request did not complete"
        faults.clear()
        assert not sink.errors, sink.errors
        assert sink.terminals == 1
        # events are structured 3-tuples (ts, name, attrs) since the
        # Span.event(name, **attrs) signature landed
        events = {n: a for _, n, a in span.events}
        assert "redispatched" in events
        assert events["redispatched"]["reason"]  # the hop carries why
        assert span.attributes["redispatch_to"]
        for r in twin_server.scheduler.engines():
            if not r.is_healthy():
                r.restart()

    def test_exhausted_attempts_fail_visibly_once(self, twin_server):
        """Both replicas crash on the redispatched request: bounded
        attempts end in ONE terminal error, never silence or a double
        event."""
        faults.install(parse_spec("runner.inbox:nth=1,times=10", seed=2))
        got = _run_request(twin_server, "chaos-exhaust")
        faults.clear()
        assert got.terminals == 1
        assert len(got.errors) == 1
        assert got.errors[0][1] == "worker_failure"
        snap = twin_server.metrics.snapshot().to_dict()
        assert snap["resilience"]["redispatched"].get("exhausted", 0) >= 1
        for r in twin_server.scheduler.engines():
            if not r.is_healthy():
                r.restart()

    def test_token_emitting_request_fails_fast_engine_crashed(
            self, twin_server):
        """A request that already streamed tokens cannot be re-run
        transparently — it must fail fast with the DISTINCT
        engine_crashed code."""
        sink = _run_request(twin_server, "chaos-midstream", max_tokens=64,
                            wait=False)
        assert sink.first_token.wait(60), "no first token"
        faults.install(parse_spec("runner.step:nth=1", seed=3))
        assert sink.ev.wait(60), "no terminal event after injected crash"
        faults.clear()
        assert sink.terminals == 1
        assert len(sink.errors) == 1
        assert sink.errors[0][1] == "engine_crashed"
        for r in twin_server.scheduler.engines():
            if not r.is_healthy():
                r.restart()


# ---------------------------------------------------------------------------
# Disagg chaos: crash-mid-handoff and import abort (satellite coverage)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="class")
def disagg_chaos_server(tiny_params):
    srv = InferenceServer(
        lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
        num_engines=2, auto_restart=False,
        engine_roles=["prefill", "decode"],
        disagg_settings=DisaggSettings(handoff_timeout_s=30.0),
    )
    srv.start()
    yield srv
    faults.clear()
    srv.shutdown(drain_timeout_s=5.0)


@pytest.fixture(scope="class")
def mono_chaos_server(tiny_params):
    """stream=False: the monolithic stop-the-world handoff, whose
    channel error is the ``disagg.transfer`` fault point (the streamed
    path fires disagg.chunk/disagg.commit instead)."""
    srv = InferenceServer(
        lambda: _engine(tiny_params), ByteTokenizer(), "tiny",
        num_engines=2, auto_restart=False,
        engine_roles=["prefill", "decode"],
        disagg_settings=DisaggSettings(
            stream=False, handoff_timeout_s=30.0),
    )
    srv.start()
    yield srv
    faults.clear()
    srv.shutdown(drain_timeout_s=5.0)


class TestMonolithicTransferChaos:
    def test_transfer_fault_retries_and_still_lands(self, mono_chaos_server):
        """Monolithic handoff channel death: the first transfer attempt
        dies on the channel, the migration worker records a retry, and
        the request still reaches a single clean terminal (retry or
        decode-in-place fallback — never a client-visible error)."""
        srv = mono_chaos_server
        faults.install(parse_spec("disagg.transfer:nth=1", seed=8))
        got = _run_request(srv, "chaos-transfer", max_tokens=48)
        faults.clear()
        assert not got.errors, got.errors
        assert got.terminals == 1
        snap = srv.metrics.snapshot().to_dict()
        handoffs = snap["disagg"]["handoffs"]
        assert handoffs.get("retry", 0) >= 1, handoffs
        for r in srv.scheduler.engines():
            assert r.audit() == []


class TestDisaggChaos:
    def test_commit_drop_decodes_in_place(self, disagg_chaos_server):
        """Crash-mid-handoff: the switchover commit dies on the channel;
        the source keeps the request and the client sees nothing."""
        srv = disagg_chaos_server
        faults.install(parse_spec("disagg.commit:nth=1", seed=4))
        got = _run_request(srv, "chaos-commit", max_tokens=48)
        faults.clear()
        assert not got.errors, got.errors
        assert got.terminals == 1
        snap = srv.metrics.snapshot().to_dict()
        assert snap["disagg"]["handoffs"].get("fallback", 0) >= 1
        for r in srv.scheduler.engines():
            assert r.audit() == []

    def test_import_abort_releases_every_page(self, disagg_chaos_server):
        """Crash-mid-import: chunk validation fails on the decode side —
        the session aborts, the request decodes in place, and the
        decode engine's pool holds ZERO stray pages (the audit proves
        conservation)."""
        srv = disagg_chaos_server
        faults.install(parse_spec("kv.import_chunk:nth=1", seed=5))
        got = _run_request(srv, "chaos-import", max_tokens=48)
        faults.clear()
        assert not got.errors, got.errors
        assert got.terminals == 1
        # allow the phase-1 abort submitted to the decode runner to drain
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(r.audit() == [] for r in srv.scheduler.engines()):
                break
            time.sleep(0.05)
        for r in srv.scheduler.engines():
            assert r.audit() == [], r.engine_id


# ---------------------------------------------------------------------------
# Committed chaos-fleet seeds (the harness's own scenario matrix)
# ---------------------------------------------------------------------------


class TestChaosFleetSeeds:
    @pytest.mark.parametrize("scenario,seed", [
        ("redispatch", 11),
        ("crash_mid_handoff", 12),
        ("degradation_flap", 13),
        # fleet prefix sharing (docs/CACHING.md): peer dies mid-fetch →
        # recompute fallback, exactly-once, zero page leak. Seeds 21/24
        # crash the peer runner outright (runner.inbox); 22 drops a
        # chunk on the wire (kv.peer_fetch).
        ("warm_peer_fetch_death", 21),
        ("warm_peer_fetch_death", 22),
        ("warm_peer_fetch_death", 24),
        # deadline-aware admission under synthetic overload
        # (docs/RESILIENCE.md "Gray failures and overload"): shed
        # requests get the distinct admission_shed terminal fast,
        # admitted traffic completes, zero pages leak, and admission
        # recovers as the short window decays
        ("overload_shed", 51),
        ("overload_shed", 52),
        ("overload_shed", 53),
    ])
    def test_scenario_clean(self, scenario, seed):
        from tools import chaos_fleet

        violations, srv = chaos_fleet.run_scenario(scenario, seed)
        try:
            assert violations == []
        finally:
            srv.shutdown(drain_timeout_s=5.0)


@pytest.fixture(scope="module")
def fleet_chaos_cache():
    """One fleet per FLEET scenario, reused across its committed seeds
    (the chaos harness's own main loop does exactly this; scenarios
    self-heal crashed members between iterations via _ensure_worker) —
    a fresh two-server fleet per seed would cost tier-1 ~2 minutes of
    pure engine builds."""
    cache = {}
    yield cache
    faults.clear()
    for srv in cache.values():
        srv.shutdown(drain_timeout_s=5.0)


class TestFleetChaosSeeds:
    """Committed seeds of the fleet control-plane scenarios
    (docs/FLEET.md): registry partition -> suspect -> dead -> rejoin
    reconvergence; remote member death mid-zero-token-request (seed 31
    kills the forwarded submit on the registry host's wire, 34/35 crash
    the worker on receipt) -> exactly-once redispatch; and rerole
    hysteresis holding under an oscillating signal."""

    @pytest.mark.parametrize("scenario,seed", [
        ("registry_partition", 31),
        ("registry_partition", 32),
        ("registry_partition", 33),
        ("remote_runner_crash_mid_request", 31),
        ("remote_runner_crash_mid_request", 34),
        ("remote_runner_crash_mid_request", 35),
        ("rerole_flap", 31),
        ("rerole_flap", 32),
        ("rerole_flap", 33),
        # fleet KV data plane (docs/FLEET.md "KV data plane"): the
        # cross-host handoff stream dies — dial failure (41), member
        # crash on the import command (43), wire torn at the Nth chunk
        # (45) — and the request decodes in place, exactly once, zero
        # pages leaked on either side.
        ("cross_host_handoff_death", 41),
        ("cross_host_handoff_death", 43),
        ("cross_host_handoff_death", 45),
        # the remote warm peer dies under a forced fetch — dial failure
        # (41), response chunk torn (42, 45) — and the request degrades
        # to recompute on its local target, exactly once.
        ("remote_fetch_source_death", 41),
        ("remote_fetch_source_death", 42),
        ("remote_fetch_source_death", 45),
        # gray-failure defense (docs/RESILIENCE.md "Gray failures and
        # overload"): a fleet.slow_member-delayed member is demoted by
        # the latency-scored HealthScorer and drained without a client
        # error, then recovers through the two-sided hysteresis
        ("slow_member_brownout", 51),
        ("slow_member_brownout", 52),
        ("slow_member_brownout", 53),
        # a flapping data wire (fleet.wire_timeout): the channel
        # breaker opens, probes no earlier than the cooldown, and
        # re-closes once the wire heals — every stream exactly-once
        ("breaker_flap", 51),
        ("breaker_flap", 52),
        ("breaker_flap", 53),
        # KV mesh (docs/FLEET.md "KV mesh"): a delegated fetch's direct
        # member-to-member wire dies — w2's import session rejects a
        # chunk (61), the peer dial fails (62), a chunk tears off the
        # response stream (63) — and the hinted request degrades to
        # recompute ON THE MEMBER, exactly once, zero pages leaked on
        # any of the three processes; each seed asserts the fetch hint
        # actually left the host (a delegation that silently relays or
        # recomputes host-side is a violation, not a degradation).
        ("mesh_peer_wire_death", 61),
        ("mesh_peer_wire_death", 62),
        ("mesh_peer_wire_death", 63),
        # registry HA (docs/FLEET.md "Registry HA"): the primary dies
        # in-process and the warm standby promotes within the lease
        # window at a bumped epoch, serves through its own ingress, and
        # the restarted old primary rejoins as a fenced standby. Odd
        # seeds (71, 73) also crash the first promotion attempt
        # (fleet.takeover) — takeover must be atomic-or-absent.
        ("registry_failover", 71),
        ("registry_failover", 72),
        ("registry_failover", 73),
        # a registry<->registry partition (fleet.lease_beat) makes two
        # primaries; the member fences the stale epoch's control, and
        # on heal the old primary demotes — exactly one primary, epochs
        # converged, every request exactly-once.
        ("registry_split_brain", 71),
        ("registry_split_brain", 72),
        ("registry_split_brain", 73),
    ])
    def test_scenario_clean(self, scenario, seed, fleet_chaos_cache):
        from tools import chaos_fleet

        violations, srv = chaos_fleet.run_scenario(
            scenario, seed, srv=fleet_chaos_cache.get(scenario))
        fleet_chaos_cache[scenario] = srv
        assert violations == []
