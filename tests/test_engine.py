"""Continuous-batching engine tests: correctness against the static
generation path, batching isolation, prefix reuse, preemption recovery,
stop handling, and failure isolation (Properties 9, 21, 22)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_inference_server_tpu.core.models import FinishReason
from distributed_inference_server_tpu.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distributed_inference_server_tpu.engine.kv_cache import PagedCacheConfig
from distributed_inference_server_tpu.models import llama
from distributed_inference_server_tpu.models.configs import TINY
from distributed_inference_server_tpu.models.generate import greedy_generate
from distributed_inference_server_tpu.models.tokenizer import ByteTokenizer

TOK = ByteTokenizer()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), TINY, dtype=jnp.float32)


def make_engine(tiny_params, num_pages=32, page_size=4, max_pages_per_seq=8,
                max_batch=4):
    return LLMEngine(
        tiny_params,
        TINY,
        TOK,
        EngineConfig(
            max_batch=max_batch,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(
                num_pages=num_pages,
                page_size=page_size,
                max_pages_per_seq=max_pages_per_seq,
            ),
        ),
        dtype=jnp.float32,
    )


def run_to_completion(engine, max_steps=500):
    """Drive step() until idle; returns per-request aggregated results."""
    results = {}
    for _ in range(max_steps):
        if not engine.has_work():
            break
        for out in engine.step():
            r = results.setdefault(
                out.request_id,
                {"text": "", "tokens": [], "finish": None, "error": None,
                 "usage": None},
            )
            r["text"] += out.text
            if out.token_id is not None:
                r["tokens"].append(out.token_id)
            if out.finished:
                r["finish"] = out.finish_reason
                r["error"] = out.error
                r["usage"] = out.usage
    assert not engine.has_work(), "engine did not drain"
    return results


GREEDY = SamplingParams(max_tokens=8, temperature=0.0)


def test_engine_matches_static_generate(tiny_params):
    engine = make_engine(tiny_params)
    prompt = TOK.encode("hello")
    engine.add_request("r1", prompt, GREEDY)
    results = run_to_completion(engine)
    expected = greedy_generate(
        tiny_params, TINY, prompt, max_new_tokens=8, max_seq=32,
        eos_ids=TOK.eos_ids,
    )
    assert results["r1"]["tokens"] == expected
    assert results["r1"]["finish"] == FinishReason.LENGTH
    assert results["r1"]["usage"].prompt_tokens == len(prompt)
    assert results["r1"]["usage"].completion_tokens == 8


def test_concurrent_requests_isolated(tiny_params):
    # batch-mates must not affect each other's tokens (Property 21/22 analog)
    engine = make_engine(tiny_params)
    prompts = {f"r{i}": TOK.encode(f"prompt number {i}") for i in range(4)}
    for rid, ids in prompts.items():
        engine.add_request(rid, ids, GREEDY)
    results = run_to_completion(engine)
    for rid, ids in prompts.items():
        solo = greedy_generate(
            tiny_params, TINY, ids, max_new_tokens=8, max_seq=32,
            eos_ids=TOK.eos_ids,
        )
        assert results[rid]["tokens"] == solo, rid


def test_more_requests_than_slots(tiny_params):
    engine = make_engine(tiny_params, max_batch=2)
    for i in range(5):
        engine.add_request(f"r{i}", TOK.encode(f"req {i}"), GREEDY)
    results = run_to_completion(engine)
    assert len(results) == 5
    for rid, r in results.items():
        assert r["finish"] == FinishReason.LENGTH and len(r["tokens"]) == 8


def test_prefix_reuse_hits_and_same_output(tiny_params):
    engine = make_engine(tiny_params)
    prompt = TOK.encode("shared prefix, reuse")  # 21 ids: > 1 full page
    engine.add_request("first", prompt, GREEDY)
    first = run_to_completion(engine)["first"]
    assert engine.allocator.stats().pages_cached > 0

    engine.add_request("second", prompt, GREEDY)
    second = run_to_completion(engine)["second"]
    assert engine.allocator.stats().hits > 0  # shared pages (Property 9)
    assert second["tokens"] == first["tokens"]  # numerically identical path


def test_preemption_under_page_pressure(tiny_params):
    # tiny pool: 2 concurrent requests cannot both hold their full length
    engine = make_engine(tiny_params, num_pages=8, page_size=4,
                        max_pages_per_seq=6, max_batch=2)
    p1 = TOK.encode("abcdefgh")  # 9 ids incl BOS
    p2 = TOK.encode("12345678")
    engine.add_request("a", p1, SamplingParams(max_tokens=10, temperature=0.0))
    engine.add_request("b", p2, SamplingParams(max_tokens=10, temperature=0.0))
    results = run_to_completion(engine)
    for rid, prompt in (("a", p1), ("b", p2)):
        solo = greedy_generate(
            tiny_params, TINY, prompt, max_new_tokens=10, max_seq=24,
            eos_ids=TOK.eos_ids,
        )
        assert results[rid]["tokens"] == solo, rid
        assert results[rid]["error"] is None
    # preemption must not leak pages (every page free or cached afterwards)
    s = engine.allocator.stats()
    assert s.pages_free + s.pages_cached == s.pages_total


def test_stop_sequence_truncates_and_finishes(tiny_params):
    engine = make_engine(tiny_params)
    prompt = TOK.encode("hello")
    # discover the greedy text first
    engine.add_request("probe", prompt, GREEDY)
    text = run_to_completion(engine)["probe"]["text"]
    assert len(text) >= 3
    stop = text[1:3]  # a substring that will occur
    engine.add_request(
        "s", prompt,
        SamplingParams(max_tokens=8, temperature=0.0, stop_sequences=(stop,)),
    )
    r = run_to_completion(engine)["s"]
    assert r["finish"] == FinishReason.STOP_SEQUENCE
    assert stop not in r["text"]
    assert r["text"] == text[: text.find(stop)]


def test_eos_finishes_with_stop(tiny_params):
    engine = make_engine(tiny_params)
    prompt = TOK.encode("hello")
    engine.add_request("probe", prompt, SamplingParams(max_tokens=1, temperature=0.0))
    first_tok = run_to_completion(engine)["probe"]["tokens"][0]

    class EosTok(ByteTokenizer):
        def __init__(self, eos):
            super().__init__()
            self.eos_ids = (eos,)

    engine2 = LLMEngine(
        tiny_params, TINY, EosTok(first_tok),
        EngineConfig(max_batch=2, prefill_buckets=(8, 32),
                     paged=PagedCacheConfig(num_pages=32, page_size=4,
                                            max_pages_per_seq=8)),
        dtype=jnp.float32,
    )
    engine2.add_request("e", prompt, GREEDY)
    r = run_to_completion(engine2)["e"]
    assert r["finish"] == FinishReason.STOP
    assert r["tokens"] == []
    assert r["usage"].completion_tokens == 0


def test_oversized_prompt_rejected_with_error(tiny_params):
    engine = make_engine(tiny_params, num_pages=8, max_pages_per_seq=2)
    engine.add_request("big", list(range(1, 40)), GREEDY)
    r = run_to_completion(engine)["big"]
    assert r["error"] is not None and "exceeds" in r["error"]


def test_abort_releases_resources(tiny_params):
    engine = make_engine(tiny_params)
    prompt = TOK.encode("hello world")
    engine.add_request("gone", prompt, SamplingParams(max_tokens=50, temperature=0.0))
    engine.step()  # prefill + first decode
    assert engine.num_active() == 1
    assert engine.abort("gone")
    assert engine.num_active() == 0
    assert not engine.has_work()
    s = engine.allocator.stats()
    assert s.pages_free + s.pages_cached == s.pages_total


def test_failure_isolation_bad_request(tiny_params):
    # a request whose processing explodes must not take down batch-mates
    engine = make_engine(tiny_params)
    good = TOK.encode("good")
    engine.add_request("ok", good, GREEDY)

    bad = TOK.encode("bad")
    engine.add_request("boom", bad, GREEDY)
    seq = engine._by_id["boom"]

    class Exploding(tuple):
        def __iter__(self):  # poison the stop-sequence scan
            raise RuntimeError("injected failure")

    seq.params = SamplingParams(max_tokens=8, temperature=0.0)
    object.__setattr__(seq.params, "stop_sequences", Exploding(("zzz",)))

    results = run_to_completion(engine)
    assert results["boom"]["error"] is not None
    solo = greedy_generate(
        tiny_params, TINY, good, max_new_tokens=8, max_seq=32,
        eos_ids=TOK.eos_ids,
    )
    assert results["ok"]["tokens"] == solo
    s = engine.allocator.stats()
    assert s.pages_free + s.pages_cached == s.pages_total


def test_embeddings_path(tiny_params):
    engine = make_engine(tiny_params)
    vecs = engine.embed_ids([TOK.encode("alpha"), TOK.encode("beta gamma")])
    assert vecs.shape == (2, TINY.hidden_size)
    norms = np.linalg.norm(vecs, axis=-1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)
    # deterministic
    vecs2 = engine.embed_ids([TOK.encode("alpha"), TOK.encode("beta gamma")])
    np.testing.assert_allclose(vecs, vecs2, atol=1e-6)


def test_embeddings_long_input_not_truncated(tiny_params):
    # longer than the largest prefill bucket (32): chunk-pooled, not cut
    engine = make_engine(tiny_params)
    long_ids = [1 + (i % 200) for i in range(75)]
    vec_full = engine.embed_ids([long_ids])[0]
    vec_prefix = engine.embed_ids([long_ids[:32]])[0]
    # the tail must influence the embedding
    assert not np.allclose(vec_full, vec_prefix, atol=1e-4)
    # and the chunked pooling must be deterministic
    np.testing.assert_allclose(
        vec_full, engine.embed_ids([long_ids])[0], atol=1e-6
    )


def test_chunked_prefill_interleaves_with_decode(tiny_params):
    """A long prompt prefilling in budgeted quanta must not starve decode:
    seated sequences keep emitting tokens in steps where the long prompt is
    still prefilling, and the long prompt's output is unaffected."""
    engine = LLMEngine(
        tiny_params, TINY, TOK,
        EngineConfig(
            max_batch=2,
            prefill_buckets=(8, 32),
            paged=PagedCacheConfig(num_pages=64, page_size=4,
                                   max_pages_per_seq=16),
            decode_block_size=2,
            prefill_batch=2,
            prefill_token_budget=8,  # one 8-token chunk per step
        ),
        dtype=jnp.float32,
    )
    short = TOK.encode("hi")
    engine.add_request("short", short,
                       SamplingParams(max_tokens=40, temperature=0.0))
    results = {}
    for out in engine.step():  # seat + prefill short, start decoding
        results.setdefault(out.request_id, {"tokens": [], "finish": None})[
            "tokens"].append(out.token_id)
    long_ids = [1 + (i % 200) for i in range(40)]  # 5 chunks of 8
    engine.add_request("long", long_ids, GREEDY)

    interleaved = False
    for _ in range(300):
        if not engine.has_work():
            break
        outs = engine.step()
        long_seq = engine._by_id.get("long")
        long_prefilling = long_seq is not None and long_seq.next_token is None
        for out in outs:
            r = results.setdefault(out.request_id,
                                   {"tokens": [], "finish": None})
            if out.token_id is not None:
                r["tokens"].append(out.token_id)
                if out.request_id == "short" and long_prefilling:
                    interleaved = True
            if out.finished:
                r["finish"] = out.finish_reason
    assert not engine.has_work()
    assert interleaved, "short request made no progress during long prefill"
    # chunked, budget-limited prefill must not change the long prompt's output
    solo = greedy_generate(
        tiny_params, TINY, long_ids, max_new_tokens=8, max_seq=64,
        eos_ids=TOK.eos_ids,
    )
    assert results["long"]["tokens"] == solo
    assert len(results["short"]["tokens"]) == 40


def test_engine_pallas_attention_matches_xla(tiny_params):
    """End-to-end decode with the Pallas ragged paged-attention kernel
    (interpret mode on CPU) produces the same greedy tokens as the XLA
    gather path."""
    prompt = TOK.encode("pallas")
    results = {}
    for impl in ("xla", "pallas"):
        engine = LLMEngine(
            tiny_params,
            TINY,
            TOK,
            EngineConfig(
                max_batch=2,
                prefill_buckets=(8, 32),
                paged=PagedCacheConfig(
                    num_pages=32, page_size=4, max_pages_per_seq=8
                ),
                attention_impl=impl,
            ),
            dtype=jnp.float32,
        )
        engine.add_request("r1", prompt, GREEDY)
        results[impl] = run_to_completion(engine)["r1"]
    assert results["pallas"]["tokens"] == results["xla"]["tokens"]
    assert results["pallas"]["finish"] == results["xla"]["finish"]


def test_auto_impl_probe_downgrades_gracefully(tiny_params):
    """"auto" resolution never crashes the engine: on backends where the
    Pallas kernels cannot compile (Mosaic is TPU-only — interpret=False on
    the CPU backend is such a rejection), the probe catches the failure
    and downgrades to the XLA gather path per kernel."""
    engine = make_engine(tiny_params)
    # CPU backend short-circuits without probing
    assert engine._resolved_impl() == ("xla", "xla")
    # the probe itself must swallow lowering/compile failures, not raise
    assert engine._probe_pallas() == (False, False)


def test_auto_impl_prefill_demoted_to_opt_in(tiny_params, monkeypatch):
    """VERDICT r4 #3 "win or demote": even when Mosaic accepts BOTH
    kernels, auto serves prefill on XLA (the one silicon datapoint has
    the prefill kernel at 0.66x XLA) unless DIS_TPU_PALLAS_PREFILL=1
    opts back in for crossover sweeps. Decode keeps pallas-if-compiles."""
    import jax as jax_mod

    from distributed_inference_server_tpu.engine.engine import LLMEngine

    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    monkeypatch.setattr(LLMEngine, "_probe_pallas",
                        lambda self: (True, True))
    monkeypatch.delenv("DIS_TPU_PALLAS_PREFILL", raising=False)
    assert make_engine(tiny_params)._resolved_impl() == ("pallas", "xla")
    monkeypatch.setenv("DIS_TPU_PALLAS_PREFILL", "1")
    assert make_engine(tiny_params)._resolved_impl() == ("pallas", "pallas")


class TestWarmup:
    """Startup warm-compilation (engine.warmup): every serving program
    compiles before the first real request, so first-request TTFT never
    pays tracing + XLA compile."""

    def test_warmup_compiles_all_buckets_and_decode(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.models import llama as _llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )

        params = _llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        eng = LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(
                max_batch=2, prefill_buckets=(8, 16),
                paged=PagedCacheConfig(num_pages=64, page_size=8,
                                       max_pages_per_seq=8),
                warmup_compile=True,
            ),
            dtype=jnp.float32,
        )
        eng.warmup()
        assert not eng.has_work()  # warmup requests fully drained
        # every bucket's prefill program is compiled and cached
        assert {k[1] for k in eng._prefill_fns} == {8, 16}
        # the decode-block carry exists => the block program ran
        assert eng._carry is not None
        # and real serving still works afterwards
        tok = ByteTokenizer()
        eng.add_request("r", tok.encode("after warmup"),
                        SamplingParams(max_tokens=4, temperature=0.0))
        n = 0
        while eng.has_work():
            for o in eng.step():
                assert o.error is None, o.error
                n += o.token_id is not None
        assert n == 4

    def test_warmup_covers_cp_program(self):
        import jax
        import jax.numpy as jnp

        from distributed_inference_server_tpu.models import llama as _llama
        from distributed_inference_server_tpu.models.configs import TINY
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.parallel import (
            MeshSpec,
            make_mesh,
        )

        params = _llama.init_params(jax.random.PRNGKey(0), TINY, jnp.float32)
        eng = LLMEngine(
            params, TINY, ByteTokenizer(),
            EngineConfig(
                max_batch=2, prefill_buckets=(16,),
                paged=PagedCacheConfig(num_pages=64, page_size=8,
                                       max_pages_per_seq=8),
            ),
            dtype=jnp.float32, mesh=make_mesh(MeshSpec(seq=4)),
        )
        eng.warmup()
        assert eng._cp_fns  # ring-prefill program compiled


class TestGatherBucketing:
    """Decode/prefill gather windows track the LIVE page bucket, not the
    configured capacity — a huge max_pages_per_seq must neither change
    outputs nor widen the per-step gather beyond the next bucket."""

    def test_bucket_math(self, tiny_params):
        eng = make_engine(tiny_params, num_pages=80, max_pages_per_seq=64)
        assert eng._pages_bucket(1) == 8
        assert eng._pages_bucket(8) == 8
        assert eng._pages_bucket(9) == 16
        assert eng._pages_bucket(33) == 64
        # capped at the configured capacity
        eng2 = make_engine(tiny_params, max_pages_per_seq=6)
        assert eng2._pages_bucket(100) == 6

    def test_outputs_identical_with_oversized_capacity(self, tiny_params):
        prompt = TOK.encode("bucketed gather windows")
        results = {}
        for cap in (8, 64):  # 64 pages >> needed (~3)
            eng = make_engine(tiny_params, num_pages=80, page_size=4,
                              max_pages_per_seq=cap)
            eng.add_request("r", prompt, GREEDY)
            results[cap] = run_to_completion(eng)["r"]["tokens"]
        assert results[8] == results[64]

    def test_bucket_growth_across_boundary(self, tiny_params):
        # prompt + output spans > 8 pages (page_size 4): the engine must
        # cross the 8->16 bucket boundary mid-generation and stay exact
        prompt = TOK.encode("x" * 30)
        eng = make_engine(tiny_params, num_pages=64, page_size=4,
                          max_pages_per_seq=16)
        eng.add_request("r", prompt, SamplingParams(max_tokens=24,
                                                    temperature=0.0))
        out = run_to_completion(eng)["r"]
        assert len(out["tokens"]) == 24

        from distributed_inference_server_tpu.models.generate import (
            greedy_generate,
        )

        want = greedy_generate(
            tiny_params, TINY, prompt, max_new_tokens=24, max_seq=64,
            eos_ids=TOK.eos_ids,
        )
        assert out["tokens"] == list(want)


class TestLogprobs:
    """Streaming logprob emission (the reference's optional TokenEvent
    logprob, models.rs:272-277): every emitted token carries the model-
    distribution log-probability of the sampled id — raw-logit
    log-softmax, temperature/top-p independent."""

    def test_greedy_logprobs_match_reference_forward(self, tiny_params):
        engine = make_engine(tiny_params)
        prompt = TOK.encode("logprobs!")
        engine.add_request("r", prompt, GREEDY)
        events = []
        while engine.has_work():
            for o in engine.step():
                if o.token_id is not None:
                    events.append((o.token_id, o.logprob))
        assert len(events) == 8
        assert all(lp is not None and lp <= 0.0 for _, lp in events)

        # reference: teacher-forced forward over prompt+output
        ids = prompt + [t for t, _ in events]
        T = len(ids)
        cache = llama.KVCache.create(TINY, 1, T, dtype=jnp.float32)
        pos = jnp.arange(T)[None]
        logits, _ = llama.forward(
            tiny_params, TINY, jnp.asarray([ids], jnp.int32), pos, cache,
            pos, jnp.full((1,), T, jnp.int32),
        )
        lsm = jax.nn.log_softmax(np.asarray(logits)[0], axis=-1)
        for i, (tok, lp) in enumerate(events):
            want = float(lsm[len(prompt) - 1 + i, tok])
            assert abs(lp - want) < 1e-4, (i, lp, want)

    def test_spec_logprobs_match_plain_decode(self, tiny_params):
        draft = llama.init_params(jax.random.PRNGKey(9), TINY,
                                  dtype=jnp.float32)
        from distributed_inference_server_tpu.engine.speculative import (
            SpecConfig,
        )

        def run(spec):
            eng = LLMEngine(
                tiny_params, TINY, TOK,
                EngineConfig(max_batch=2, prefill_buckets=(8, 32),
                             paged=PagedCacheConfig(num_pages=64,
                                                    page_size=4,
                                                    max_pages_per_seq=16)),
                dtype=jnp.float32,
                draft_params=draft if spec else None,
                draft_cfg=TINY if spec else None,
                spec=SpecConfig(num_draft_tokens=3) if spec else None,
            )
            eng.add_request("r", TOK.encode("spec lp"), GREEDY)
            out = []
            while eng.has_work():
                for o in eng.step():
                    if o.token_id is not None:
                        out.append((o.token_id, o.logprob))
            return out

        spec, plain = run(True), run(False)
        assert [t for t, _ in spec] == [t for t, _ in plain]
        for (_, a), (_, b) in zip(spec, plain):
            assert abs(a - b) < 1e-4, (a, b)


def test_greedy_row_identical_across_sample_modes(tiny_params):
    """A greedy request's tokens must not depend on which sampler branch
    the LAUNCH takes: solo (all-greedy launch, pure-argmax mode) vs
    co-seated with a nucleus-sampled batch-mate (full-machinery mode).
    Greedy rows are argmax in every branch by construction — this pins
    the launcher's sample_mode wiring."""
    engine = make_engine(tiny_params)
    prompt = TOK.encode("mode check")
    engine.add_request("solo", prompt, GREEDY)
    solo = run_to_completion(engine)["solo"]["tokens"]

    engine2 = make_engine(tiny_params)
    engine2.add_request("greedy", prompt, GREEDY)
    engine2.add_request(
        "nucleus", TOK.encode("other"),
        SamplingParams(max_tokens=8, temperature=0.9, top_p=0.7),
    )
    mixed = run_to_completion(engine2)
    assert mixed["greedy"]["tokens"] == solo
    # the sampled row just has to produce SOMETHING (its token count
    # depends on the PRNG bit-stream — an EOS draw may end it early)
    assert mixed["nucleus"]["tokens"]
