"""Per-family chat templates (tasks.md:259-262 [spec]; VERDICT r2 missing
#6: /chat applied the Llama-3 header format to every model family).
Mistral gets [INST] pairs, Qwen2 gets ChatML, Gemma-2 gets start_of_turn
blocks with the assistant role renamed to 'model'."""

from distributed_inference_server_tpu.core.models import ChatMessage, Role
from distributed_inference_server_tpu.models.tokenizer import (
    apply_chat_template,
    chat_template_family,
)

CONVO = [
    ChatMessage(role=Role.SYSTEM, content="be brief"),
    ChatMessage(role=Role.USER, content="hi"),
    ChatMessage(role=Role.ASSISTANT, content="hello"),
    ChatMessage(role=Role.USER, content="bye"),
]


class TestFamilyDetection:
    def test_model_names_map_to_families(self):
        assert chat_template_family("llama-3-8b") == "llama3"
        assert chat_template_family("llama-3.2-1b") == "llama3"
        assert chat_template_family("mistral-7b") == "mistral"
        assert chat_template_family("mixtral-8x7b") == "mistral"
        assert chat_template_family("qwen2-7b") == "chatml"
        assert chat_template_family("gemma2-9b") == "gemma"
        assert chat_template_family("tiny") == "llama3"  # default
        assert chat_template_family("") == "llama3"


class TestTemplates:
    def test_llama3_headers(self):
        out = apply_chat_template(CONVO, "llama3")
        assert out.startswith("<|begin_of_text|>")
        assert "<|start_header_id|>system<|end_header_id|>\n\nbe brief<|eot_id|>" in out
        assert out.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")

    def test_mistral_inst_pairs_fold_system(self):
        out = apply_chat_template(CONVO, "mistral")
        # system folds into the FIRST user turn; assistant closes with
        # </s> and follows "[/INST] " with a space (HF chat_template)
        assert out == (
            "<s>[INST] be brief\n\nhi [/INST] hello</s>[INST] bye [/INST]"
        )

    def test_chatml_blocks(self):
        out = apply_chat_template(CONVO, "chatml")
        assert out == (
            "<|im_start|>system\nbe brief<|im_end|>\n"
            "<|im_start|>user\nhi<|im_end|>\n"
            "<|im_start|>assistant\nhello<|im_end|>\n"
            "<|im_start|>user\nbye<|im_end|>\n"
            "<|im_start|>assistant\n"
        )

    def test_gemma_turns_rename_assistant_to_model(self):
        out = apply_chat_template(CONVO, "gemma")
        assert out == (
            "<bos><start_of_turn>user\nbe brief\n\nhi<end_of_turn>\n"
            "<start_of_turn>model\nhello<end_of_turn>\n"
            "<start_of_turn>user\nbye<end_of_turn>\n"
            "<start_of_turn>model\n"
        )

    def test_default_family_is_llama3(self):
        assert apply_chat_template(CONVO) == apply_chat_template(
            CONVO, "llama3"
        )


class TestHandlerWiring:
    def test_handler_family_follows_model_name(self):
        """The handler derives the family from its CURRENT model name, so
        hot-swap retemplates /chat automatically."""
        from distributed_inference_server_tpu.models.tokenizer import (
            ByteTokenizer,
        )
        from distributed_inference_server_tpu.serving.dispatcher import (
            Dispatcher,
        )
        from distributed_inference_server_tpu.serving.handler import (
            InferenceHandler,
        )
        from distributed_inference_server_tpu.serving.scheduler import (
            AdaptiveScheduler,
        )

        h = InferenceHandler(
            Dispatcher(AdaptiveScheduler()), ByteTokenizer(), "qwen2-7b"
        )
        assert h.chat_family == "chatml"
        h.model_name = "gemma2-9b"  # what server.swap_model assigns
        assert h.chat_family == "gemma"


class TestSystemFolding:
    """System content must never silently vanish (review finding): late
    or multiple system messages still reach the model in families with
    no native system slot."""

    def test_mistral_trailing_system_not_dropped(self):
        msgs = [
            ChatMessage(role=Role.USER, content="hi"),
            ChatMessage(role=Role.SYSTEM, content="be brief"),
        ]
        out = apply_chat_template(msgs, "mistral")
        assert out == "<s>[INST] hi [/INST][INST] be brief [/INST]"

    def test_mistral_multiple_systems_accumulate(self):
        msgs = [
            ChatMessage(role=Role.SYSTEM, content="one"),
            ChatMessage(role=Role.SYSTEM, content="two"),
            ChatMessage(role=Role.USER, content="hi"),
        ]
        out = apply_chat_template(msgs, "mistral")
        assert out == "<s>[INST] one\n\ntwo\n\nhi [/INST]"

    def test_gemma_trailing_system_becomes_user_turn(self):
        msgs = [
            ChatMessage(role=Role.USER, content="hi"),
            ChatMessage(role=Role.SYSTEM, content="be brief"),
        ]
        out = apply_chat_template(msgs, "gemma")
        assert out == (
            "<bos><start_of_turn>user\nhi<end_of_turn>\n"
            "<start_of_turn>user\nbe brief<end_of_turn>\n"
            "<start_of_turn>model\n"
        )


# Qwen2-style ChatML template as checkpoints actually ship it
# (tokenizer_config.json "chat_template" key, Jinja)
CHATML_JINJA = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\\n' + message['content'] "
    "+ '<|im_end|>' + '\\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\\n' }}"
    "{% endif %}"
)

CHATML_RENDERED = (
    "<|im_start|>system\nbe brief<|im_end|>\n"
    "<|im_start|>user\nhi<|im_end|>\n"
    "<|im_start|>assistant\nhello<|im_end|>\n"
    "<|im_start|>user\nbye<|im_end|>\n"
    "<|im_start|>assistant\n"
)


def _write_cfg(tmp_path, cfg: dict) -> str:
    import json

    (tmp_path / "tokenizer_config.json").write_text(json.dumps(cfg))
    return str(tmp_path)


class TestCheckpointTemplate:
    """The checkpoint's own tokenizer_config.json chat_template is the
    authority (VERDICT r3 weak #4: name sniffing gave a finetune named
    'my-assistant-v2' over Qwen2 weights the Llama-3 template)."""

    def test_template_from_file_beats_name_sniffing(self, tmp_path):
        from distributed_inference_server_tpu.models.tokenizer import (
            load_tokenizer,
            render_chat,
        )

        d = tmp_path / "my-assistant-v2"  # sniffs as llama3
        d.mkdir()
        _write_cfg(d, {"chat_template": CHATML_JINJA})
        tok = load_tokenizer(str(d))  # no tokenizer.json -> ByteTokenizer
        assert chat_template_family("my-assistant-v2") == "llama3"
        assert render_chat(CONVO, tok, "my-assistant-v2") == CHATML_RENDERED

    def test_no_config_falls_back_to_family(self, tmp_path):
        from distributed_inference_server_tpu.models.tokenizer import (
            load_tokenizer,
            render_chat,
        )

        tok = load_tokenizer(str(tmp_path))
        assert render_chat(CONVO, tok, "qwen2-7b") == apply_chat_template(
            CONVO, "chatml"
        )

    def test_list_form_picks_default_entry(self, tmp_path):
        from distributed_inference_server_tpu.models.tokenizer import (
            load_chat_template,
        )

        _write_cfg(tmp_path, {
            "chat_template": [
                {"name": "tool_use", "template": "TOOLS"},
                {"name": "default", "template": CHATML_JINJA},
            ],
        })
        tpl = load_chat_template(str(tmp_path))
        assert tpl is not None
        assert tpl(CONVO) == CHATML_RENDERED

    def test_special_tokens_rendered_from_config(self, tmp_path):
        from distributed_inference_server_tpu.models.tokenizer import (
            load_chat_template,
        )

        _write_cfg(tmp_path, {
            "chat_template": (
                "{{ bos_token }}{% for m in messages %}{{ m['content'] }}"
                "{{ eos_token }}{% endfor %}"
            ),
            # AddedToken-dict and plain-string spellings both appear in
            # real checkpoints
            "bos_token": {"content": "<s>"},
            "eos_token": "</s>",
        })
        tpl = load_chat_template(str(tmp_path))
        out = tpl([ChatMessage(role=Role.USER, content="hi")])
        assert out == "<s>hi</s>"

    def test_list_form_without_default_treated_as_absent(self, tmp_path):
        """No 'default' entry means the chat format is unknowable (the
        named entries are rag/tool_use/...); guessing one would render
        every /chat in a wrong prompt format."""
        from distributed_inference_server_tpu.models.tokenizer import (
            load_chat_template,
        )

        _write_cfg(tmp_path, {
            "chat_template": [
                {"name": "rag", "template": "RAG"},
                {"name": "tool_use", "template": "TOOLS"},
            ],
        })
        assert load_chat_template(str(tmp_path)) is None

    def test_broken_template_treated_as_absent(self, tmp_path):
        from distributed_inference_server_tpu.models.tokenizer import (
            load_chat_template,
        )

        _write_cfg(tmp_path, {"chat_template": "{% for m in %}broken"})
        assert load_chat_template(str(tmp_path)) is None

    def test_render_time_error_falls_back_to_family(self, tmp_path):
        """Templates that reject conversations via raise_exception (e.g.
        Mistral's no-system-message guard) must not 500 the request."""
        from distributed_inference_server_tpu.models.tokenizer import (
            load_tokenizer,
            render_chat,
        )

        _write_cfg(tmp_path, {
            "chat_template": (
                "{% for m in messages %}"
                "{% if m['role'] == 'system' %}"
                "{{ raise_exception('no system role') }}{% endif %}"
                "{{ m['content'] }}{% endfor %}"
            ),
        })
        tok = load_tokenizer(str(tmp_path))
        # CONVO opens with a system message -> template raises -> family
        assert render_chat(CONVO, tok, "qwen2-7b") == apply_chat_template(
            CONVO, "chatml"
        )
        # a conversation the template accepts renders via the template
        ok = [ChatMessage(role=Role.USER, content="hi")]
        assert render_chat(ok, tok, "qwen2-7b") == "hi"
