"""Reference (pure-XLA) grouped-query attention over a contiguous KV cache.

This is the numerics ground truth the Pallas kernels (ops/pallas/) are tested
against, and the fallback path on the CPU backend. It avoids materializing
repeated KV heads by folding the GQA group into the einsum, keeps softmax in
f32, and handles ragged batches with an explicit per-row valid length — the
same (q_positions, kv_valid_len) contract the paged-attention kernel uses.

Replaces the reference's planned llama.cpp attention (design.md:7 [spec]).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

_NEG_INF = -1e30  # large-negative instead of -inf so fully-masked rows stay finite


def gqa_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    sliding_window=None,
    attn_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Causal GQA attention of new queries against a contiguous KV cache.

    Args:
      q: [B, T, H, D] new queries (T=1 for decode).
      k_cache, v_cache: [B, S, KV, D] cache contents (padded to S slots);
        must already contain the K/V of the new tokens.
      q_positions: [B, T] absolute position of each query token. Padding
        queries may hold any in-range value; their outputs are discarded
        downstream.
      kv_valid_len: [B] number of valid cache slots per row.
      sliding_window: Mistral-style window — each query attends only the
        last ``sliding_window`` positions. None = full causal. May be a
        TRACED int scalar (Gemma-2 per-layer windows flow through the
        layer scan), where <= 0 means full causal.
      attn_softcap: Gemma-2 score soft-capping — scores pass through
        ``tanh(s / cap) * cap`` before masking (None = off; static).

    Returns: [B, T, H, D] attention outputs in q.dtype.
    """
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    KV = k_cache.shape[2]
    G = H // KV

    qg = q.reshape(B, T, KV, G, D)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k_cache, preferred_element_type=jnp.float32
    )
    scores = scores * (1.0 / jnp.sqrt(D).astype(jnp.float32))
    if attn_softcap is not None:
        scores = jnp.tanh(scores / attn_softcap) * attn_softcap

    kv_pos = jnp.arange(S)
    causal = kv_pos[None, None, :] <= q_positions[:, :, None]  # [B, T, S]
    valid = kv_pos[None, None, :] < kv_valid_len[:, None, None]  # [B, 1->T, S]
    if sliding_window is None:
        window_ok = causal
    else:
        w = jnp.asarray(sliding_window, jnp.int32)
        window_ok = causal & (
            (w <= 0)
            | (kv_pos[None, None, :] > q_positions[:, :, None] - w)
        )
    mask = (window_ok & valid)[:, None, None, :, :]  # [B, 1, 1, T, S]

    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)

    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs, v_cache, preferred_element_type=jnp.float32
    )
    return out.reshape(B, T, H, D).astype(q.dtype)


def ragged_gqa_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    tok_row: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_valid_len: jnp.ndarray,
    sliding_window=None,
    attn_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Causal GQA attention of a PACKED ragged batch against per-row caches.

    The mixed-step contract (engine/engine.py ``_mixed_step``): one flat
    token axis carries every row's new tokens back-to-back — decode rows
    contribute one token each, prefill-chunk rows up to the chunk budget —
    and each packed token attends its OWN row's KV. This is the numerics
    ground truth the Pallas ragged kernel
    (ops/pallas/paged_attention.py:paged_attention_ragged) is tested
    against, and the CPU/fallback serving path.

    Args:
      q: [S, H, D] packed query tokens (S = the mixed-step token budget;
        padding slots carry ``tok_row`` -1 and any q values).
      k_cache, v_cache: [Bm, S_max, KV, D] per-row gathered cache windows
        (the XLA gather path's dense form; must already contain the new
        tokens' K/V).
      tok_row: [S] row index of each packed token (-1 = padding; padding
        outputs are garbage and discarded by the caller).
      q_positions: [S] absolute position of each packed token in its row.
      kv_valid_len: [Bm] valid cache slots per row.
      sliding_window / attn_softcap: as in ``gqa_attention``.

    Returns: [S, H, D] attention outputs in q.dtype.
    """
    S, H, D = q.shape
    Bm, Smax, KV, _ = k_cache.shape
    G = H // KV

    row = jnp.clip(tok_row, 0, Bm - 1)
    k_tok = jnp.take(k_cache, row, axis=0)  # [S, Smax, KV, D]
    v_tok = jnp.take(v_cache, row, axis=0)
    qg = q.reshape(S, KV, G, D)
    scores = jnp.einsum(
        "tkgd,tskd->tkgs", qg, k_tok, preferred_element_type=jnp.float32
    )
    scores = scores * (1.0 / jnp.sqrt(D).astype(jnp.float32))
    if attn_softcap is not None:
        scores = jnp.tanh(scores / attn_softcap) * attn_softcap

    kv_pos = jnp.arange(Smax)
    causal = kv_pos[None, :] <= q_positions[:, None]  # [S, Smax]
    valid = kv_pos[None, :] < jnp.take(kv_valid_len, row)[:, None]
    mask = causal & valid & (tok_row >= 0)[:, None]
    if sliding_window is not None:
        w = jnp.asarray(sliding_window, jnp.int32)
        mask &= (w <= 0) | (kv_pos[None, :] > q_positions[:, None] - w)

    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "tkgs,tskd->tkgd", probs, v_tok, preferred_element_type=jnp.float32
    )
    return out.reshape(S, H, D).astype(q.dtype)
